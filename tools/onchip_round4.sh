#!/bin/bash
# Round-4 on-chip runbook, ordered by VERDICT r3's mandates:
#   1. FIRST, a bare no-flag `python bench.py` exactly as the driver runs
#      it, committed as BENCH_r04_local.json + raw log — before any
#      exploratory row can crash the worker (three rounds of 0.0 driver
#      benches is the round's #1 item).
#   2. Exact-precision trained parity (tools/trained_parity.py, highest).
#   3. The round-3e decision ladder rows that never got silicon: fused
#      subpixel loss with batch 10/8, softsel whole-step, clean trainer
#      steps/s, serving re-measure after the mask-carry rework.
#   4. Re-pick BENCH_DEFAULTS.json from measured rows; if it changed,
#      reproduce the new default with a second bare run.
#   5. Fresh trace at the winning config (next-bottleneck discipline).
#   6. The crash bisect LAST — it deliberately pokes the crash mode.
# Marker-guarded: safe to re-run across chip windows.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round4.out}
MARK=/root/.cache/raft_tpu/r4_markers
LADDER=/root/.cache/raft_tpu/r4_ladder
mkdir -p "$MARK" "$LADDER"
# seed with round-3's measured rows so a slow r4 set can't downgrade the
# defaults pick below what is already proven
cp -n /root/.cache/raft_tpu/r3_ladder/*.json "$LADDER"/ 2>/dev/null || true
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
snap() { cp "$OUT" /root/repo/ONCHIP_r04.log 2>/dev/null || true; }
wait_chip() {
    for _ in 1 2 3 4 5; do
        if timeout -k 10 120 python -c \
            "import jax; assert jax.devices()[0].platform != 'cpu'" \
            >/dev/null 2>&1; then return 0; fi
        log "chip not answering; waiting 60s"
        sleep 60
    done
    return 1
}
step() {
    local name=$1 tmo=$2; shift 2
    if [ -e "$MARK/$name" ]; then log "skip $name (done)"; return 0; fi
    wait_chip || { log "SKIP $name (chip unavailable)"; return 1; }
    log "begin $name"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        touch "$MARK/$name"; log "done $name"
    else
        local rc=$?
        log "retry $name after 90s (rc=$rc)"
        sleep 90
        if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
            touch "$MARK/$name"; log "done $name (retry)"
        else
            log "FAILED rc=$? $name"
        fi
    fi
    snap
}
bench_cfg() {
    local tag=$1 tmo=$2; shift 2
    if [ -e "$MARK/bench_$tag" ]; then log "skip bench_$tag"; return 0; fi
    wait_chip || { log "SKIP bench_$tag (chip unavailable)"; return 1; }
    log "begin bench_$tag: $*"
    if timeout "$tmo" python bench.py --steps 10 "$@" \
            > "$LADDER/$tag.json" 2>> "$OUT"; then
        cat "$LADDER/$tag.json" >> "$OUT"
        touch "$MARK/bench_$tag"; log "done bench_$tag"
    else
        log "FAILED bench_$tag rc=$?"; cat "$LADDER/$tag.json" >> "$OUT"
    fi
    snap
}
commit_msmt() {  # measurement artifacts only — no source changes
    local msg=$1; shift
    for f in "$@"; do git add "$f" 2>/dev/null || true; done
    git diff --cached --quiet || git commit -q -m "$msg" -m \
        "No-Verification-Needed: measurement logs and records only"
}

# ---- 1. the driver-style bare bench, FIRST ----------------------------
if [ ! -e "$MARK/bare_bench" ]; then
    if wait_chip; then
        log "begin bare_bench (no flags, exactly as the driver runs it)"
        if timeout 2700 python bench.py \
                > "$LADDER/bare.json" 2>> "$OUT"; then
            cat "$LADDER/bare.json" >> "$OUT"
            # only a real nonzero number counts as done
            if python - "$LADDER/bare.json" <<'EOF'
import json, sys
row = json.load(open(sys.argv[1]))
sys.exit(0 if row.get("value", 0) > 0 else 1)
EOF
            then
                touch "$MARK/bare_bench"
                cp "$LADDER/bare.json" /root/repo/BENCH_r04_local.json
                snap
                commit_msmt \
                    "Record driver-style bare bench.py run for round 4" \
                    BENCH_r04_local.json ONCHIP_r04.log
                log "bare_bench committed"
            else
                log "bare_bench emitted a zero/failed row; will retry \
next window"
            fi
        else
            log "FAILED bare_bench rc=$?"
        fi
        snap
    fi
fi

# ---- 2. exact-precision trained parity --------------------------------
step trained_parity_exact 2400 python tools/trained_parity.py
if [ -e "$MARK/trained_parity_exact" ] \
        && [ ! -e "$MARK/trained_parity_committed" ]; then
    cp /root/.cache/raft_tpu/ref_ckpt/trained_parity.json \
        /root/repo/TRAINED_PARITY_onchip.json 2>/dev/null || true
    commit_msmt \
        "On-chip trained-weights parity at exact fp32 matmul precision" \
        TRAINED_PARITY_onchip.json ONCHIP_r04.log
    touch "$MARK/trained_parity_committed"
fi

# ---- 3. the decision ladder the round-3 window never reached ----------
# fused subpixel-domain loss frees the ~560 MB prediction stack +
# cotangent: try batch 10 FIRST (the stack was part of why b10 OOM'd)
bench_cfg j_fused 2700 --batches 12 10 8 --corr-dtype bfloat16 --no-remat \
    --fused-loss
bench_cfg i_softsel_b8 1800 --batches 8 --corr-dtype bfloat16 --no-remat \
    --corr-impl softsel
# scan-unroll: replicate the refinement body so XLA can pipeline across
# iteration boundaries; compile cost grows with the factor, so bounded
# timeouts and the mid factors only
bench_cfg k_unroll2 2400 --batches 8 --corr-dtype bfloat16 --no-remat \
    --scan-unroll 2
bench_cfg k_unroll4 2700 --batches 8 --corr-dtype bfloat16 --no-remat \
    --scan-unroll 4
# compositions: the levers are independent (memory, lerp-chain, pipeline)
# so if two singles win, their product is the candidate default — measure
# it in THIS window instead of waiting a round
bench_cfg m_fused_softsel 2700 --batches 10 8 --corr-dtype bfloat16 \
    --no-remat --fused-loss --corr-impl softsel
bench_cfg n_fused_unroll2 2700 --batches 10 8 --corr-dtype bfloat16 \
    --no-remat --fused-loss --scan-unroll 2
# isolated softsel rows give the per-lookup story for BENCH_NOTES
step s_bf16 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot softsel --grad --corr-dtype bfloat16
# the materialized-pyramid Pallas kernel's hypothesized regime is
# large-resolution serving (VERDICT r3 weak #6): measure it at the
# sintel serving geometry or demote it to documented insurance
step pallas_regime 1800 python -m raft_tpu.cli.corr_bench --batch 1 \
    --hw 55 128 --iters 20 --impls onehot pallas

# ---- 4. re-pick defaults; reproduce bare if they changed --------------
step pick_defaults_r4 120 python tools/pick_bench_defaults.py "$LADDER"
if [ -e "$MARK/pick_defaults_r4" ] && [ ! -e "$MARK/bare_bench_final" ] \
        && ! git diff --quiet BENCH_DEFAULTS.json; then
    if wait_chip; then
        log "defaults changed - reproducing with a bare run"
        if timeout 2700 python bench.py \
                > "$LADDER/bare_final.json" 2>> "$OUT"; then
            cat "$LADDER/bare_final.json" >> "$OUT"
            if python - "$LADDER/bare_final.json" <<'EOF'
import json, sys
row = json.load(open(sys.argv[1]))
sys.exit(0 if row.get("value", 0) > 0 else 1)
EOF
            then
                touch "$MARK/bare_bench_final"
                cp "$LADDER/bare_final.json" /root/repo/BENCH_r04_local.json
                snap
                commit_msmt \
                    "Bare bench reproduction at the re-picked defaults" \
                    BENCH_r04_local.json BENCH_DEFAULTS.json ONCHIP_r04.log
            fi
        else
            log "FAILED bare_bench_final rc=$?"
        fi
        snap
    fi
fi

# ---- 5. clean trainer steps/s + serving re-measure --------------------
step train_rate 1800 python -m raft_tpu.cli.train --name r4rate \
    --stage chairs --mixed_precision --synthetic 64 --num_steps 220 \
    --val_freq 1000 --batch_size 8 --num_workers 4 \
    --checkpoint_dir /root/.cache/raft_tpu/r4_rate --log_dir runs
step infer_bf16_v2 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 \
    --corr_dtype bfloat16
step infer_fp32_v2 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024
# serving-side unroll probe: fwd-only, 20 iters — pipelining has more
# boundaries to cross here than in the 12-iter train step
step infer_bf16_unroll2 2400 python -m raft_tpu.cli.infer_bench \
    --hw 440 1024 --corr_dtype bfloat16 --scan_unroll 2
# softsel accuracy at trained weights (ADVICE r3: its bf16 selection
# GEMMs round the bilinear weights — pin the cost in the same window
# that measures its speed; torch flows come from the r3 cache)
step trained_parity_softsel 2400 python tools/trained_parity.py \
    --corr_impl softsel
# guard added retroactively (r5): only an on-chip result may carry the
# _onchip label — the unguarded cp once published CPU rehearsal numbers
if [ -e "$MARK/trained_parity_softsel" ]; then
    cp /root/.cache/raft_tpu/ref_ckpt/trained_parity_softsel.json \
        /root/repo/TRAINED_PARITY_softsel_onchip.json 2>/dev/null || true
fi

# ---- 6. fresh trace at the current winner (next-bottleneck hunt) ------
# profile exactly the config BENCH_DEFAULTS.json now pins
TRACE_FLAGS=$(python - <<'EOF'
import json
try:
    d = json.load(open("BENCH_DEFAULTS.json"))
except Exception:
    d = {}
flags = ["--batch", str(d.get("batches", [8])[0])]
if d.get("corr_dtype"):
    flags += ["--corr_dtype", d["corr_dtype"]]
if d.get("corr_impl"):
    flags += ["--corr_impl", d["corr_impl"]]
if d.get("fused_loss"):
    flags.append("--fused_loss")
if d.get("scan_unroll", 1) != 1:
    flags += ["--scan_unroll", str(d["scan_unroll"])]
print(" ".join(flags))
EOF
)
step trace_r4 2400 python -m raft_tpu.cli.profile_step $TRACE_FLAGS \
    --steps 10 --trace-dir /tmp/raft_trace_r4
step trace_summary_r4 1200 python -m raft_tpu.cli.trace_summary \
    /tmp/raft_trace_r4

# ---- 7. the crash bisect, LAST ----------------------------------------
step crash_bisect 5400 bash tools/crash_bisect.sh /tmp/crash_bisect.out

log "round4 runbook complete"
snap
commit_msmt "On-chip round-4 artifacts: ladder rows, parity, bisect" \
    ONCHIP_r04.log CRASH_BISECT_r04.log TRAINED_PARITY_onchip.json \
    TRAINED_PARITY_softsel_onchip.json BENCH_DEFAULTS.json
