"""graftexport: the serialized-executable static-analysis tier.

Fifth tier of the gate family — graftlint reads source, graftaudit
reads single-device compiled artifacts, graftthread reads
thread-safety declarations, graftshard reads partitioned programs,
graftexport reads SERIALIZED ARTIFACTS: the real serve programs
(plain f32, u8 warm-start, feature-cache, ragged) round-tripped
through the AOT executable cache (``raft_tpu/serving/aot.py``,
``jax.experimental.serialize_executable``) and audited on BOTH sides
of the disk boundary against rules E1–E6, each a concrete
cached-artifact bug class:

- E1 ``incomplete-cache-key``: a manifest key missing/empty a
  required provenance component — the stale-load hazard;
- E2 ``donation-dropped-by-serialization``: ``input_output_alias``
  entries present in the live compile but absent from the
  deserialized executable;
- E3 ``baked-weight-literal``: multi-MB constants serialized into the
  blob — weights belong in arguments, keyed by fingerprint;
- E4 ``non-portable-artifact``: custom-call targets that pin the blob
  to the writing process/platform; dishonest platform claims;
- E5 ``calling-convention-drift``: manifest signature vs the loading
  engine's live recipe;
- E6 ``integrity-check-bypassed``: fault-injected corruption / skew /
  stale-key probes that the load path SURVIVES instead of routing to
  miss-and-recompile.

Same surface as the siblings: ``python -m tools.graftexport --json``,
shrink-only (and EMPTY) ``baseline.json``, per-finding ``Waiver`` with
required justification, lintcache-backed warm repeats. The meta-gate
``python -m tools.graft --json`` runs all five tiers.
"""

from .core import (apply_baseline, audit_targets,  # noqa: F401
                   load_baseline, load_fixture_targets, main,
                   write_baseline)
from .finding import ExportFinding  # noqa: F401
from .spec import ExportArtifacts, ExportTarget, Waiver  # noqa: F401
