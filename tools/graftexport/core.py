"""graftexport driver: round-trip the serve programs, run E1–E6,
baseline.

Usage (from the repo root; this exact bare invocation is the tier-1
gate, ``tests/test_graftexport.py``)::

    python -m tools.graftexport --json

Exit codes mirror the sibling tiers: 0 clean (modulo baseline), 1 new
findings or stale baseline entries, 2 usage error. The baseline
(``tools/graftexport/baseline.json``) is SHRINK-ONLY and ships EMPTY —
the first scan's findings were fixed at the site (aot.py's store/load
grew checks, not waivers), and new ones are fixed or waived with
justification, never grandfathered.

Suppression: serialized artifacts have no source line, so the pragma
analog is a :class:`~tools.graftexport.spec.Waiver` on the target
declaration — rule id + detail substring + REQUIRED justification.

Caching: compiling + serializing + reloading + fault-probing the four
serve programs costs tens of seconds; repeats are served from the
shared ``tools/lintcache.py`` cache. Entries are keyed on the artifact
key (a content hash over every ``raft_tpu/**/*.py`` — the sources that
decide the serialized artifacts — plus the jax version) and the active
rule set, under a package signature covering this tool and lintcache
itself; editing any serving/model source, rule, or the cache machinery
rebuilds, an untouched tree answers warm in seconds with no jax import
at all. ``--no-cache`` forces a rebuild.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from collections import Counter
from dataclasses import asdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools import lintcache

from .finding import ExportFinding
from .spec import ExportTarget

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
DEFAULT_BASELINE = os.path.join(_HERE, "baseline.json")
_TARGETS_PY = os.path.join(_HERE, "targets.py")
CACHE_ENV = "RAFT_GRAFTEXPORT_CACHE"
CACHE_FILE = "graftexport_cache.json"


# -- audit ----------------------------------------------------------------

def audit_one(target: ExportTarget, rules
              ) -> Tuple[List[ExportFinding], float]:
    """Build one target's round-trip artifacts and run ``rules`` over
    them. Waivers are applied here — a waived finding never reaches
    the baseline logic (or the cache), same as a pragma'd graftlint
    finding."""
    from .artifacts import build_artifacts

    art = build_artifacts(target)
    findings: List[ExportFinding] = []
    for mod in rules:
        for f in mod.check(target, art):
            if not target.waived(f.rule, f.detail):
                findings.append(f)
    return findings, art.seconds


def audit_targets(targets: Sequence[ExportTarget], rules=None
                  ) -> Tuple[List[ExportFinding], Dict[str, float]]:
    """Uncached audit over ``targets`` (fixtures, library callers).
    Returns ``(findings, seconds per target)``."""
    from .rules import ALL_RULES

    rules = ALL_RULES if rules is None else rules
    findings: List[ExportFinding] = []
    seconds: Dict[str, float] = {}
    for t in targets:
        got, dt = audit_one(t, rules)
        findings.extend(got)
        seconds[t.name] = dt
    return findings, seconds


# -- cache ----------------------------------------------------------------

def artifact_key() -> str:
    """Content hash over the sources that decide the serialized
    artifacts (every ``raft_tpu/**/*.py``) + the jax version — cheap
    enough for the warm path (no jax import), strong enough that any
    change to the serving, model, or cache-seam code rebuilds."""
    sig = lintcache.package_signature(os.path.join(_REPO, "raft_tpu"))
    try:
        from importlib.metadata import version
        jver = version("jax")
    except Exception:
        jver = "?"
    return f"{sig}-jax{jver}"


def _tool_signature() -> str:
    # every module the findings depend on: this package, the cache
    # machinery, AND the shared alias parser E2 calls out to — a fixed
    # regex in hlo_lib's input_output_alias scan must invalidate cached
    # findings, or the warm gate would answer clean from code that no
    # longer exists ("a cache must never outlive the code that
    # produced it")
    return lintcache.package_signature(
        _HERE,
        os.path.join(_REPO, "tools", "lintcache.py"),
        os.path.join(_REPO, "tools", "hlo_lib.py"))


def cached_audit(targets: Sequence[ExportTarget], rules, cache_path: str
                 ) -> Tuple[List[ExportFinding], Dict[str, float],
                            Dict[str, bool]]:
    """Repo-target audit through the lintcache file: per-target entries
    keyed on (targets.py, artifact key, target name + rule ids).
    Returns ``(findings, seconds, hit map)``."""
    rule_key = ",".join(m.RULE for m in rules)
    digest = artifact_key()
    cache = lintcache.load_cache(cache_path, _tool_signature())
    findings: List[ExportFinding] = []
    seconds: Dict[str, float] = {}
    hits: Dict[str, bool] = {}
    dirty = False
    for t in targets:
        key = lintcache.cache_key(_TARGETS_PY, digest,
                                  f"{t.name}|{rule_key}")
        entry = cache["files"].get(key)
        if entry is not None:
            findings.extend(ExportFinding(**f)
                            for f in entry["findings"])
            seconds[t.name] = 0.0
            hits[t.name] = True
            continue
        got, dt = audit_one(t, rules)
        findings.extend(got)
        seconds[t.name] = dt
        hits[t.name] = False
        cache["files"][key] = {"findings": [asdict(f) for f in got],
                               "built_s": round(dt, 2)}
        dirty = True
    if dirty:
        lintcache.evict_dead_entries(cache, {_TARGETS_PY: digest})
        lintcache.save_cache(cache_path, cache)
    return findings, seconds, hits


# -- fixtures -------------------------------------------------------------

def load_fixture_targets(path: str) -> List[ExportTarget]:
    """TARGETS from a fixture module file (tests/graftexport_fixtures)."""
    name = "graftexport_fixture_" + \
        os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise OSError(f"cannot import fixture module {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return list(mod.TARGETS)


# -- baseline (same shrink-only semantics as the sibling tiers') ----------

def load_baseline(path: str) -> Counter:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Counter((e["target"], e["rule"], e["detail"])
                   for e in data.get("findings", []))


def write_baseline(path: str, findings: Iterable[ExportFinding]) -> None:
    entries = [{"target": k[0], "rule": k[1], "detail": k[2]}
               for k in sorted(f.key() for f in findings)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "comment": "graftexport grandfathered findings — burn down, "
                       "never grow; regenerate with --write-baseline "
                       "after fixing one. Ships EMPTY: the first scan's "
                       "findings were fixed at the site (aot.py grew "
                       "checks), and tests/test_graftexport.py pins "
                       "it empty.",
            "findings": entries,
        }, f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: List[ExportFinding], baseline: Counter,
                   audited_targets: Optional[Iterable[str]] = None,
                   ) -> Tuple[List[ExportFinding],
                              List[Tuple[str, str, str]]]:
    """(new findings, stale keys). An unconsumed entry whose target WAS
    audited is stale and fails the run — it would silently grandfather
    the next reintroduction; an entry for a target outside this run
    (--targets subset) is merely unchecked."""
    remaining = Counter(baseline)
    new: List[ExportFinding] = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    if audited_targets is not None:
        audited = set(audited_targets)
        checked = (lambda k: k[0] in audited)
    else:
        checked = (lambda k: True)
    stale = sorted(k for k, n in remaining.items() if checked(k)
                   for _ in range(n))
    return new, stale


# -- CLI ------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftexport",
        description="Serialized-executable invariant checker (rules "
                    "E1-E6 over the serialize→deserialize round trip "
                    "of the real serve programs through the AOT "
                    "artifact cache; see tools/graftexport/rules/).")
    p.add_argument("--baseline", metavar="JSON", default=DEFAULT_BASELINE,
                   help="grandfather file (default: the committed "
                        "tools/graftexport/baseline.json — pinned EMPTY)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (list of findings)")
    p.add_argument("--write-baseline", metavar="JSON",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--targets", metavar="T1,T2",
                   help="audit only these targets")
    p.add_argument("--rules", metavar="E1,E2,...",
                   help="run only these rule ids")
    p.add_argument("--fixture", metavar="PY",
                   help="audit the TARGETS of this fixture module "
                        "instead of the repo registry (no default "
                        "baseline, no cache)")
    p.add_argument("--no-cache", action="store_true",
                   help="rebuild artifacts even on a warm cache")
    p.add_argument("--cache", metavar="JSON",
                   default=lintcache.default_cache_path(CACHE_ENV,
                                                        CACHE_FILE),
                   help="findings cache file (default: the shared "
                        f"user cache, override with ${CACHE_ENV})")
    args = p.parse_args(argv)

    from .rules import ALL_RULES

    rules = ALL_RULES
    if args.rules:
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [m for m in ALL_RULES if m.RULE in want]
        unknown = want - {m.RULE for m in rules}
        if unknown:
            print(f"graftexport: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    if args.write_baseline and (args.rules or args.targets):
        print("graftexport: refusing --write-baseline with --rules/"
              "--targets — regenerate from a full run",
              file=sys.stderr)
        return 2

    fixture_run = bool(args.fixture)
    if fixture_run:
        # fixtures import jax at module scope (sibling-tier idiom):
        # point a fresh interpreter at the CPU backend FIRST
        from .artifacts import prepare_env
        prepare_env()
        try:
            targets = load_fixture_targets(args.fixture)
        # exec_module can raise anything (ImportError, a jax error at
        # module scope) — all of it is "unloadable fixture", exit 2
        except Exception as exc:  # noqa: BLE001
            print(f"graftexport: unloadable fixture {args.fixture}: "
                  f"{exc}", file=sys.stderr)
            return 2
        if args.baseline == DEFAULT_BASELINE:
            args.baseline = None
    else:
        from .targets import export_targets
        targets = export_targets()
    if args.targets:
        want_t = {t.strip() for t in args.targets.split(",")}
        unknown_t = want_t - {t.name for t in targets}
        if unknown_t:
            print(f"graftexport: unknown target(s): {sorted(unknown_t)}",
                  file=sys.stderr)
            return 2
        targets = [t for t in targets if t.name in want_t]

    if fixture_run or args.no_cache:
        findings, seconds = audit_targets(targets, rules=rules)
        hits = {}
    else:
        findings, seconds, hits = cached_audit(targets, rules,
                                               args.cache)
    for tname, dt in seconds.items():
        how = "cache" if hits.get(tname) else f"{dt:.1f}s"
        print(f"graftexport: {tname} audited in {how}", file=sys.stderr)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"graftexport: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}", file=sys.stderr)
        return 0

    stale: List[Tuple[str, str, str]] = []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as exc:
            print(f"graftexport: unreadable baseline "
                  f"{args.baseline}: {exc}", file=sys.stderr)
            return 2
        active = {m.RULE for m in rules}
        baseline = Counter({k: v for k, v in baseline.items()
                            if k[1] in active})
        findings, stale = apply_baseline(
            findings, baseline,
            audited_targets=[t.name for t in targets])

    if args.as_json:
        print(json.dumps([{
            "target": f.target, "rule": f.rule, "name": f.name,
            "detail": f.detail, "message": f.message,
        } for f in findings] + [{
            "target": k[0], "rule": "B0", "name": "stale-baseline",
            "detail": k[2],
            "message": f"stale baseline entry for {k[1]}: {k[2]!r} — "
                       "regenerate with --write-baseline",
        } for k in stale], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftexport: {len(findings)} new finding(s)",
                  file=sys.stderr)
    if stale:
        for k in stale:
            print(f"graftexport: stale baseline entry {k[0]} [{k[1]}] "
                  f"{k[2]!r}", file=sys.stderr)
        print(f"graftexport: {len(stale)} stale baseline entr(y/ies) — "
              "the finding was fixed (good!) but the entry must go: "
              "regenerate with --write-baseline so it cannot "
              "grandfather a future reintroduction", file=sys.stderr)
    return 1 if (findings or stale) else 0
