"""The repo's real export-audit targets: the four serve programs.

Shapes are deliberately tiny (32x32, batch 1, iters 1, small model) —
every invariant the export rules check (key completeness, alias
survival, baked-literal budget, custom-call portability, signature
match, miss-routing) is decided by program/artifact STRUCTURE, which
is shape-independent; tiny shapes just keep four CPU compiles inside
the tier-1 budget.

Module scope is jax-free on purpose: the warm-cache path of the gate
answers without importing jax at all (tests pin that with a poisoned
``jax`` shim on PYTHONPATH); everything heavy lives inside ``build``
closures.
"""

from __future__ import annotations

import tempfile
from typing import List

from .spec import ExportTarget

_IMAGE_HW = (32, 32)
_ITERS = 1

_ENGINE_WEIGHTS = []   # [(variables, cfg)] — one real init, all targets


def _engine_weights():
    from .artifacts import ensure_cpu

    jax = ensure_cpu()
    import jax.numpy as jnp
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT

    if not _ENGINE_WEIGHTS:
        # small model: the audit exercises the serialize/load SEAM,
        # not the net, and the small init/compile is ~4x cheaper
        cfg = RAFTConfig(small=True)
        model = RAFT(cfg)
        h, w = _IMAGE_HW
        img = jnp.zeros((1, h, w, 3))
        variables = model.init(jax.random.PRNGKey(0), img, img,
                               iters=1)
        _ENGINE_WEIGHTS.append((variables, cfg))
    return _ENGINE_WEIGHTS[0]


def _build_engine(**engine_kw):
    flags = {"cached": bool(engine_kw.pop("_cached", False)),
             "ragged": bool(engine_kw.pop("_ragged", False))}

    def build():
        from .artifacts import ensure_cpu

        ensure_cpu()
        from raft_tpu.serving.engine import RAFTEngine

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        # one throwaway cache dir per audit run: the audit writes the
        # entry through the engine's own store path, then reloads and
        # fault-probes it
        root = tempfile.mkdtemp(prefix="graftexport-aot-")
        eng = RAFTEngine(variables, cfg, iters=_ITERS,
                         precompile=False, aot_cache=root,
                         **engine_kw)
        return eng, (1, h, w), flags
    return build


def export_targets() -> List[ExportTarget]:
    return [
        ExportTarget(
            name="serve",
            build=_build_engine(),
            notes="plain f32 bucket — the default serve artifact"),
        ExportTarget(
            name="serve_u8_warm",
            build=_build_engine(warm_start=True, wire="u8"),
            notes="u8 wire + warm-start donation — the production "
                  "wire config; E2's alias-survival check has real "
                  "donations to lose here"),
        ExportTarget(
            name="serve_cached",
            build=_build_engine(warm_start=True, wire="u8",
                                feature_cache=True, _cached=True),
            notes="feature-cache signature (fmap1/fmap2 operands + "
                  "donations) — the widest calling convention E5 "
                  "guards"),
        ExportTarget(
            name="serve_ragged",
            build=_build_engine(warm_start=True, wire="u8",
                                ragged=True, ragged_grain=32,
                                _ragged=True),
            notes="ragged rows program — grain 32 so the 32x32 audit "
                  "shape is itself a capacity class"),
    ]
