"""E1: incomplete cache key — the stale-load hazard.

A serialized executable is only as trustworthy as the key that names
it. Every component in ``REQUIRED_KEY_FIELDS`` exists because two
programs differing ONLY in that component would otherwise collide on
one digest and the second process would load the first's bytes: a
weights fingerprint missing means a promoted model serves the old
model's artifact; a missing jax/jaxlib version means an executable
deserializes into a runtime with a different calling convention; a
missing partition hash means a 4-device blob loads into an 8-device
assembly. The production store refuses incomplete keys by
construction (``aot.store`` raises) — this rule audits the MANIFESTS
actually on disk, which is what catches entries written by an older
writer, a third-party exporter, or a hand-edited artifact dir.

An empty/falsy value is as bad as an absent field: ``"weights": ""``
hashes fine and collides just the same.
"""

from __future__ import annotations

from typing import List

from ..finding import ExportFinding
from ..spec import REQUIRED_KEY_FIELDS, ExportArtifacts, ExportTarget

RULE = "E1"
NAME = "incomplete-cache-key"

#: fields where 0/[] is a legitimate value (a program with no
#: donations donates []; iters could legitimately be absent from a
#: fixture at 0)
_FALSY_OK = frozenset({"donations", "geometry", "iters"})


def check(target: ExportTarget, art: ExportArtifacts
          ) -> List[ExportFinding]:
    if art.serialize_error or not art.manifest:
        return []
    key = art.manifest.get("key")
    if not isinstance(key, dict):
        return [ExportFinding(
            target.name, RULE, NAME, "no key",
            "manifest carries no key dict at all — the entry cannot "
            "be verified against anything; any blob parked at this "
            "digest would load")]
    out: List[ExportFinding] = []
    for field_name in sorted(REQUIRED_KEY_FIELDS - set(key)):
        out.append(ExportFinding(
            target.name, RULE, NAME, f"missing {field_name}",
            f"cache key omits '{field_name}' — two programs differing "
            f"only in {field_name} collide on one digest and the "
            "loser serves the winner's executable"))
    for field_name in sorted(set(key) & REQUIRED_KEY_FIELDS):
        v = key[field_name]
        if not v and field_name not in _FALSY_OK and not isinstance(
                v, (int, float)):
            out.append(ExportFinding(
                target.name, RULE, NAME, f"empty {field_name}",
                f"cache key component '{field_name}' is empty — an "
                "empty value hashes fine and collides exactly like a "
                "missing one"))
    return out
