"""E6: cache-integrity discipline, proven by fault injection.

Rules E1–E5 audit what a writer PUT on disk; this rule audits what a
loader will ACCEPT. The driver copies the freshly-written entry and
damages each copy one way — blob zero-fill, truncation, a single bit
flip, a torn manifest, a jax-version skew, a swapped weights key, a
stale-key probe — then runs the load path. The contract
(``aot.AOTCache.load``'s docstring, drilled by the ``aot.load`` chaos
site) is that EVERY one of these reads as a clean miss. A probe the
loader SURVIVES is the finding: some integrity check is missing or
bypassed, and real bit rot / version skew / artifact swaps would
serve a wrong or corrupt executable instead of recompiling.

A target opting into ``naive_loader=True`` (fixtures only) probes a
manifest-ignoring loader instead — the counterfactual that shows
what each check protects against.

For engine targets a failed serialize round-trip is ALSO reported
here: the production store is expected to round-trip its own
programs, and a silent serialize failure means every replica
recompiles while believing it has a warm cache.
"""

from __future__ import annotations

from typing import List

from ..finding import ExportFinding
from ..spec import ExportArtifacts, ExportTarget

RULE = "E6"
NAME = "integrity-check-bypassed"


def check(target: ExportTarget, art: ExportArtifacts
          ) -> List[ExportFinding]:
    out: List[ExportFinding] = []
    if art.serialize_error and target.kind == "engine":
        out.append(ExportFinding(
            target.name, RULE, NAME, "serialize round-trip",
            "the production store failed to round-trip this engine "
            f"program: {art.serialize_error} — replicas would "
            "recompile on every start while believing the cache is "
            "warm"))
    for probe in art.probes:
        if not probe.get("survived"):
            continue
        tamper = probe.get("tamper", "?")
        loader = probe.get("loader", "verified")
        out.append(ExportFinding(
            target.name, RULE, NAME, f"{loader}:{tamper}",
            f"a {tamper!r}-damaged entry LOADED through the {loader} "
            "load path — the integrity check that should route this "
            "to miss-and-recompile is missing or bypassed, so real "
            "corruption/skew would serve a wrong executable"))
    return out
