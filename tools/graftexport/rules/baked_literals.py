"""E3: multi-MB literals baked into a serialized program.

An artifact's size discipline: the engine passes weights as ARGUMENTS
(the cache key carries their fingerprint, the blob carries none of
their bytes) — so a serve artifact's constants are coordinate grids
and norm epsilons, a few KiB. A closure-captured weight tree instead
shows up as multi-MB ``stablehlo.constant`` payloads, which triples
artifact size, bloats every replica's download, and — worse — bakes a
SPECIFIC checkpoint into a blob whose key claims weights-independence
via the fingerprint field: update_weights would swap the key while
the old weights ride along inside the program.

Detection runs on the LOWERED StableHLO (constants are explicit
``stablehlo.constant dense<...> : tensor<...>`` ops there; the
optimized module re-encodes them) and prices each constant from its
tensor type — the dense payload in text form is elided for large
literals, the type never is.
"""

from __future__ import annotations

import re
from typing import List

from ..finding import ExportFinding
from ..spec import ExportArtifacts, ExportTarget

RULE = "E3"
NAME = "baked-weight-literal"

_CONST_RE = re.compile(
    r"stablehlo\.constant[^\n]*?:\s*tensor<([^>]+)>")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3B11FNUZ": 1,
}


def _tensor_bytes(spec: str) -> int:
    """``"1x768x768xf32"`` -> 2359296. Unknown dtypes price at 4."""
    parts = spec.split("x")
    dtype = parts[-1]
    n = 1
    for d in parts[:-1]:
        if d.isdigit():
            n *= int(d)
        elif d == "?":          # dynamic dim: price what we can see
            continue
        else:
            return 0            # not a ranked numeric tensor
    return n * _DTYPE_BYTES.get(dtype, 4)


def check(target: ExportTarget, art: ExportArtifacts
          ) -> List[ExportFinding]:
    if not art.lowered_text:
        return []
    budget = target.baked_literal_bytes_max
    out: List[ExportFinding] = []
    seen = set()
    for m in _CONST_RE.finditer(art.lowered_text):
        spec = m.group(1).strip()
        size = _tensor_bytes(spec)
        if size <= budget or spec in seen:
            continue
        seen.add(spec)
        out.append(ExportFinding(
            target.name, RULE, NAME, f"tensor<{spec}>",
            f"constant tensor<{spec}> bakes {size:,} bytes into the "
            f"serialized program ({budget:,}-byte budget) — weights "
            "belong in ARGUMENTS keyed by the weights fingerprint, "
            "not inside the blob where update_weights can't reach "
            "them"))
    return out
