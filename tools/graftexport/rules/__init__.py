"""graftexport rules E1–E6, one module per export bug class.

Every module exports ``RULE`` (the id), ``NAME`` (kebab-case), and
``check(target, art) -> List[ExportFinding]``. Waivers are applied by
the driver, not here.
"""

from . import cache_key               # noqa: F401  (E1)
from . import donation_serialize      # noqa: F401  (E2)
from . import baked_literals          # noqa: F401  (E3)
from . import portability             # noqa: F401  (E4)
from . import signature_drift         # noqa: F401  (E5)
from . import integrity               # noqa: F401  (E6)

ALL_RULES = [cache_key, donation_serialize, baked_literals,
             portability, signature_drift, integrity]
