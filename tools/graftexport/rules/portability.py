"""E4: non-portable artifact — custom calls and dishonest platform
claims.

The artifact dir's whole value is that ANOTHER process loads the
blob. Two things quietly break that: (1) custom-call targets that
resolve against the writing process — host callbacks hold a pointer
into the writer's Python heap; platform kernels resolve only on the
backend that registered them. A blob carrying one either fails to
deserialize elsewhere (best case) or calls into garbage. (2) a
manifest whose ``platform`` claim differs from the backend that
actually compiled the blob: the key then routes a CPU-compiled
executable to TPU replicas, and the load-time version checks can't
save you because the key LIES.

Sharding annotations (``Sharding``/``SPMDFullToShardShape``/...) are
allowlisted: they are partitioner metadata the loading runtime
re-resolves, present in every mesh program by construction.
"""

from __future__ import annotations

import re
from typing import List

from ..finding import ExportFinding
from ..spec import ExportArtifacts, ExportTarget

RULE = "E4"
NAME = "non-portable-artifact"

_STABLEHLO_CC = re.compile(r"stablehlo\.custom_call\s+@([\w.$~-]+)")
_HLO_CC = re.compile(r'custom_call_target="([^"]+)"')


def check(target: ExportTarget, art: ExportArtifacts
          ) -> List[ExportFinding]:
    out: List[ExportFinding] = []
    allow = set(target.custom_call_allowlist)
    seen = set()
    for text, where in ((art.lowered_text, "lowered"),
                        (art.live_hlo, "optimized")):
        if not text:
            continue
        regex = _STABLEHLO_CC if where == "lowered" else _HLO_CC
        for m in regex.finditer(text):
            name = m.group(1)
            if name in allow or name in seen:
                continue
            seen.add(name)
            out.append(ExportFinding(
                target.name, RULE, NAME, f"custom_call {name}",
                f"custom call '{name}' ({where} module) pins the "
                "artifact to the process/platform that wrote it — a "
                "loading replica resolves it against nothing (or "
                "worse, against a stale pointer); keep host "
                "callbacks out of serialized programs or allowlist "
                "the target with a justification"))
    claimed = ""
    if isinstance(art.manifest.get("key"), dict):
        claimed = str(art.manifest["key"].get("platform", ""))
    if claimed and art.platform and claimed != art.platform:
        out.append(ExportFinding(
            target.name, RULE, NAME, "platform-claim",
            f"manifest claims platform '{claimed}' but the blob was "
            f"compiled on '{art.platform}' — the key routes this "
            "executable to replicas whose backend never produced it, "
            "and load-time verification trusts the claim"))
    return out
