"""E5: calling-convention drift between the artifact and the engine.

The manifest records the signature the blob was serialized under:
flat input avals, flat output avals, donated flat params. The engine,
loading, dispatches against its OWN live recipe
(``bucket_program`` → args + lowering). If the two drift — a config
rename reorders operands, a wire-dtype change flips an input aval, a
donation list changes — the loaded executable either throws at
dispatch (best case) or reinterprets buffers (worst). The cache key
catches most drift by construction (config/wire/donations are key
components); this rule is the belt-and-braces audit that the
SIGNATURE a writer recorded actually matches the recipe the loading
engine would feed it, catching writers whose key was complete but
whose recorded convention is wrong (or tampered).
"""

from __future__ import annotations

from typing import List

from ..finding import ExportFinding
from ..spec import ExportArtifacts, ExportTarget

RULE = "E5"
NAME = "calling-convention-drift"


def _diff(kind: str, live, recorded, target, out):
    if recorded is None:
        return
    live = list(live or [])
    recorded = list(recorded or [])
    if live == recorded:
        return
    n = max(len(live), len(recorded))
    for i in range(n):
        lv = live[i] if i < len(live) else "(absent)"
        rv = recorded[i] if i < len(recorded) else "(absent)"
        if lv == rv:
            continue
        out.append(ExportFinding(
            target.name, RULE, NAME, f"{kind}[{i}]",
            f"signature {kind}[{i}] drifted: engine's live recipe "
            f"says {lv!r}, artifact manifest recorded {rv!r} — the "
            "loaded executable would be dispatched with buffers it "
            "was not compiled for"))


def check(target: ExportTarget, art: ExportArtifacts
          ) -> List[ExportFinding]:
    if art.serialize_error or not art.manifest:
        return []
    recorded = art.manifest.get("signature")
    live = art.engine_signature
    if not isinstance(recorded, dict) or not live:
        return []
    out: List[ExportFinding] = []
    _diff("in", live.get("in"), recorded.get("in"), target, out)
    _diff("out", live.get("out"), recorded.get("out"), target, out)
    ld = sorted(live.get("donations") or [])
    rd = recorded.get("donations")
    if rd is not None and sorted(rd) != ld:
        out.append(ExportFinding(
            target.name, RULE, NAME, "donations",
            f"donation signature drifted: live recipe donates {ld}, "
            f"artifact recorded {sorted(rd)} — a loading engine "
            "would free (or fail to free) the wrong input buffers"))
    return out
