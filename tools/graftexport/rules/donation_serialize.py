"""E2: donation dropped by serialization.

graftaudit H4 proves XLA honors the engine's donations in the LIVE
compile; graftshard S6 proves they survive partitioning. This rule
closes the last gap: the serialize→deserialize round trip. The whole
zero-copy warm-start story (donated flow_init → flow_low, donated
cache rows) is an ``input_output_alias`` map inside the executable —
if the serialized artifact loses it, every replica that LOADS instead
of compiles silently pays an input-sized copy per call, and the fleet
regresses exactly where the cache was supposed to help most.

Detection: flat params aliased in the live optimized module
(``parse_aliased_params``) must be aliased in the RELOADED
executable's module too. The live module is the ground truth — params
XLA already declined (shape-mismatch etc.) are H4's finding, not
ours.
"""

from __future__ import annotations

from typing import List

from ..finding import ExportFinding
from ..spec import ExportArtifacts, ExportTarget

RULE = "E2"
NAME = "donation-dropped-by-serialization"


def check(target: ExportTarget, art: ExportArtifacts
          ) -> List[ExportFinding]:
    if art.serialize_error or not (art.live_hlo and art.loaded_hlo):
        return []
    from tools import hlo_lib

    live = hlo_lib.parse_aliased_params(art.live_hlo)
    loaded = hlo_lib.parse_aliased_params(art.loaded_hlo)
    out: List[ExportFinding] = []
    for ix in sorted(live - loaded):
        out.append(ExportFinding(
            target.name, RULE, NAME, f"param {ix}",
            f"flat param {ix} is input_output_alias'd in the live "
            "compile but NOT in the deserialized executable — the "
            "serialized artifact lost the donation and every loading "
            "replica pays an input-sized copy per call"))
    return out
