"""The one record type every graftexport rule emits.

Identical shape to its siblings' (graftaudit/graftshard): an export
finding anchors to a *target* (one serve program round-tripped through
the AOT serialize/load seam) plus a stable ``detail`` string (key
component name, flat-arg index, constant type, tamper mode) — the
detail IS the baseline identity, since serialized artifacts have no
line numbers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class ExportFinding:
    target: str    # export target name, e.g. "serve_u8_warm"
    rule: str      # "E1".."E6"
    name: str      # kebab-case rule name, e.g. "incomplete-cache-key"
    detail: str    # stable identity inside the artifact (key field,
                   # param index, constant type, tamper mode)
    message: str

    def render(self) -> str:
        return (f"{self.target}: {self.rule}[{self.name}] "
                f"{self.message}")

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: details derive from key-field names,
        flat param indices and tamper-mode names, which survive
        recompiles of the same program."""
        return (self.target, self.rule, self.detail)
