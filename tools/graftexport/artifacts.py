"""Build export-audit artifacts: round-trip a program through the AOT
serialize/load seam and record both halves.

The cold path drives the REAL seam (``raft_tpu/serving/aot.py``): an
engine target serializes through ``RAFTEngine._get_executable``'s own
store, the entry is reloaded through the verified ``AOTCache.load``,
and tampered COPIES of the entry are probed to prove every corruption
routes to miss (E6). Fixture (``kind="fn"``) targets write through a
low-level raw writer instead, so they can plant exactly the defect a
rule exists to catch — the production store refuses most of them by
construction.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Dict, List, Optional

from .spec import ExportArtifacts, ExportTarget

_MANIFEST = "manifest.json"
_BLOB = "executable.bin"


def prepare_env() -> None:
    """Env-only half of :func:`ensure_cpu`: pin the CPU backend before
    jax is imported, WITHOUT importing jax. The driver calls this
    before loading fixture modules (which, like the sibling tiers'
    fixtures, import jax at module scope)."""
    import sys

    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"


def ensure_cpu():
    """Force the CPU backend exactly the way tests/conftest.py does:
    the image's sitecustomize registers the 'axon' remote-TPU plugin in
    every interpreter and jax would initialize it even under
    JAX_PLATFORMS=cpu — an audit must never dial (or block on) the
    tunnel. Safe to call when jax is already imported/configured."""
    prepare_env()
    import jax

    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")
    return jax


# -- low-level entry IO (fixture writers + E6 probes) ---------------------

def _write_entry_raw(root: str, components: Dict, compiled, lowered,
                     args, *, platform_claim: str = "",
                     tamper_signature: bool = False) -> str:
    """Write one cache entry WITHOUT the production store's key
    completeness check — the fixture stand-in for an older or
    third-party writer. Layout and manifest shape are byte-compatible
    with ``aot.store`` so the verified loader (and the rules) read
    both the same way."""
    from raft_tpu.serving import aot
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    blob = pickle.dumps((payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)
    if platform_claim:
        components = dict(components, platform=platform_claim)
    signature = aot.build_signature(args, lowered)
    if tamper_signature and signature.get("in"):
        signature["in"] = ["tampered[0]"] + signature["in"][1:]
    manifest = {
        "format": aot.AOT_FORMAT,
        "key": components,
        "sha256": hashlib.sha256(blob).hexdigest(),
        "blob_bytes": len(blob),
        "signature": signature,
    }
    edir = os.path.join(root, "objects", aot.key_digest(components))
    os.makedirs(edir, exist_ok=True)
    with open(os.path.join(edir, _BLOB), "wb") as f:
        f.write(blob)
    with open(os.path.join(edir, _MANIFEST), "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    return edir


def _naive_load(edir: str):
    """The E6 counterfactual: a loader that skips every manifest check
    — reads the blob and deserializes it, nothing else. A probe run
    through THIS loader shows what each integrity check is protecting
    against; the production path (``aot.AOTCache.load``) must never
    behave like it."""
    from jax.experimental import serialize_executable as se

    with open(os.path.join(edir, _BLOB), "rb") as f:
        payload, in_tree, out_tree = pickle.loads(f.read())
    return se.deserialize_and_load(payload, in_tree, out_tree)


#: tamper modes probed through the VERIFIED loader — every one must
#: route to miss. Bit-level blob damage is only probed here (the hash
#: check rejects it before a byte is unpickled); the naive loader is
#: never pointed at a damaged pickle stream.
VERIFIED_TAMPERS = ("blob-zero-fill", "blob-truncate", "blob-bit-flip",
                    "manifest-torn", "manifest-version-skew",
                    "manifest-key-swap", "stale-weights-key")

#: manifest-level tampers probed through the NAIVE loader (fixtures
#: only): it ignores the manifest, so it survives all of them — the
#: E6 findings a checks-skipping loader earns.
NAIVE_TAMPERS = ("manifest-torn", "manifest-version-skew",
                 "manifest-key-swap")


def _apply_tamper(edir: str, tamper: str, components: Dict) -> Dict:
    """Damage one aspect of the entry copy at ``edir``; returns the
    components the probe should LOAD WITH (differs only for the
    stale-key probe)."""
    bpath = os.path.join(edir, _BLOB)
    mpath = os.path.join(edir, _MANIFEST)
    if tamper == "blob-zero-fill":
        n = os.path.getsize(bpath)
        with open(bpath, "wb") as f:
            f.write(b"\0" * n)
    elif tamper == "blob-truncate":
        with open(bpath, "rb") as f:
            raw = f.read()
        with open(bpath, "wb") as f:
            f.write(raw[:len(raw) // 2])
    elif tamper == "blob-bit-flip":
        with open(bpath, "rb") as f:
            raw = bytearray(f.read())
        raw[len(raw) // 2] ^= 0xFF
        with open(bpath, "wb") as f:
            f.write(bytes(raw))
    elif tamper == "manifest-torn":
        with open(mpath, encoding="utf-8") as f:
            text = f.read()
        with open(mpath, "w", encoding="utf-8") as f:
            f.write(text[:len(text) // 2])
    elif tamper == "manifest-version-skew":
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
        manifest.setdefault("key", {})["jax"] = "0.0.0-skewed"
        with open(mpath, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
    elif tamper == "manifest-key-swap":
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
        manifest.setdefault("key", {})["weights"] = "0" * 16
        with open(mpath, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
    elif tamper == "stale-weights-key":
        # the entry is untouched; the PROBE asks for a different
        # weights fingerprint — the loader must miss (different digest,
        # and even a relocated entry fails the verbatim key check)
        return dict(components, weights="f" * 16)
    else:
        raise ValueError(f"unknown tamper {tamper!r}")
    return components


def integrity_probes(root: str, components: Dict,
                     naive: bool = False) -> List[Dict]:
    """Fault-inject COPIES of the entry and record whether any load
    path survives. ``survived=True`` is an E6 finding. The entry at
    ``root`` itself is never touched."""
    from raft_tpu.serving import aot

    src = aot.AOTCache(root).entry_dir(components)
    probes: List[Dict] = []
    tampers = NAIVE_TAMPERS if naive else VERIFIED_TAMPERS
    for tamper in tampers:
        tmp = tempfile.mkdtemp(prefix="graftexport-probe-")
        try:
            cache = aot.AOTCache(tmp)
            edir = cache.entry_dir(components)
            os.makedirs(os.path.dirname(edir), exist_ok=True)
            shutil.copytree(src, edir)
            load_with = _apply_tamper(edir, tamper, components)
            if naive:
                try:
                    survived = _naive_load(edir) is not None
                    note = "naive loader ignored the manifest"
                except Exception as exc:  # noqa: BLE001
                    survived = False
                    note = f"{type(exc).__name__}"
            else:
                exe = cache.load(load_with)
                survived = exe is not None
                note = cache.last_miss if not survived else "LOADED"
            probes.append({"tamper": tamper,
                           "loader": "naive" if naive else "verified",
                           "survived": bool(survived), "note": note})
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return probes


# -- the builder ----------------------------------------------------------

def _read_entry(root: str, components: Dict) -> Dict:
    from raft_tpu.serving import aot

    edir = aot.AOTCache(root).entry_dir(components)
    out = {"manifest": {}, "blob_bytes": 0}
    try:
        with open(os.path.join(edir, _MANIFEST), encoding="utf-8") as f:
            out["manifest"] = json.load(f)
        out["blob_bytes"] = os.path.getsize(os.path.join(edir, _BLOB))
    except OSError:
        pass
    return out


def _fixture_components(target: ExportTarget, donations,
                        platform: str) -> Dict:
    """A complete key for a fixture program, minus the fields the
    fixture deliberately omits."""
    from raft_tpu.serving import aot
    import jax
    import jaxlib

    components = {
        "format": aot.AOT_FORMAT,
        "program": target.name,
        "weights": "fixture-" + ("0" * 8),
        "geometry": [],
        "wire": "f32",
        "iters": 0,
        "config": "fixture",
        "donations": sorted(int(i) for i in donations),
        "partition": "single",
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": platform,
    }
    for field_name in target.omit_key_fields:
        components.pop(field_name, None)
    return components


def build_artifacts(target: ExportTarget) -> ExportArtifacts:
    """Round-trip one target through serialize→deserialize and bundle
    what the rules need."""
    jax = ensure_cpu()
    from raft_tpu.serving import aot

    t0 = time.perf_counter()
    art = ExportArtifacts()
    art.platform = jax.default_backend()
    tmp_root: Optional[str] = None
    try:
        if target.kind == "engine":
            engine, (b, h, w), flags = target.build()
            cache = engine._aot
            if cache is None:
                raise ValueError(f"target {target.name}: engine has no "
                                 "aot_cache — nothing to audit")
            if flags.get("ragged"):
                bucket = engine.ensure_ragged(b, h, w)
                live_exe = engine._compiled_ragged[bucket]
            elif flags.get("cached"):
                bucket = engine.ensure_bucket(b, h, w, cached=True)
                live_exe = engine._compiled_cached[bucket]
            else:
                bucket = engine.ensure_bucket(b, h, w)
                live_exe = engine._compiled[bucket]
            art.key = engine._aot_key(bucket, **flags)
            art.live_hlo = live_exe.as_text()
            # the live half, re-derived from the SAME recipe the
            # engine compiled (bucket_program is the public seam)
            fn, args = engine.bucket_program(bucket, **flags)
            lowered = fn.lower(*args)
            art.lowered_text = lowered.as_text()
            art.engine_signature = aot.build_signature(args, lowered)
            entry = _read_entry(cache.root, art.key)
            art.manifest = entry["manifest"]
            art.blob_bytes = entry["blob_bytes"]
            probe_cache = aot.AOTCache(cache.root)
            loaded = probe_cache.load(art.key)
            if loaded is None:
                art.serialize_error = ("verified load of the freshly "
                                       "stored entry failed: "
                                       f"{probe_cache.last_miss}")
            else:
                art.loaded_hlo = loaded.as_text()
            art.probes = integrity_probes(cache.root, art.key,
                                          naive=target.naive_loader)
        elif target.kind == "fn":
            fn, args, donate = target.build()
            jitted = jax.jit(fn, donate_argnums=tuple(donate))
            lowered = jitted.lower(*args)
            art.lowered_text = lowered.as_text()
            # fresh_compile: these executables feed _write_entry_raw —
            # a jax-persistent-cache-deserialized executable serializes
            # to a payload that can never load back, and the test
            # processes run with that cache enabled
            with aot.fresh_compile():
                compiled = lowered.compile()
            art.live_hlo = compiled.as_text()
            art.engine_signature = aot.build_signature(args, lowered)
            art.key = _fixture_components(target, donate, art.platform)
            if target.drop_donation_on_serialize:
                # a serialization path that loses the alias map: the
                # blob comes from a NON-donating compile of the same fn
                with aot.fresh_compile():
                    to_store = jax.jit(fn).lower(*args).compile()
            else:
                to_store = compiled
            tmp_root = tempfile.mkdtemp(prefix="graftexport-fix-")
            try:
                _write_entry_raw(
                    tmp_root, art.key, to_store, lowered, tuple(args),
                    platform_claim=target.platform_claim,
                    tamper_signature=target.tamper_signature)
            except Exception as exc:  # noqa: BLE001 — e.g. callbacks
                art.serialize_error = f"{type(exc).__name__}: {exc}"
            if not art.serialize_error:
                if target.platform_claim:
                    art.key = dict(art.key,
                                   platform=target.platform_claim)
                entry = _read_entry(tmp_root, art.key)
                art.manifest = entry["manifest"]
                art.blob_bytes = entry["blob_bytes"]
                probe_cache = aot.AOTCache(tmp_root)
                loaded = probe_cache.load(art.key)
                if loaded is not None:
                    art.loaded_hlo = loaded.as_text()
                art.probes = integrity_probes(
                    tmp_root, art.key, naive=target.naive_loader)
        else:
            raise ValueError(f"target {target.name}: unknown kind "
                             f"{target.kind!r} (engine|fn)")
    finally:
        if tmp_root is not None:
            shutil.rmtree(tmp_root, ignore_errors=True)
    art.seconds = time.perf_counter() - t0
    return art
