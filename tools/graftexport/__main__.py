import sys

from tools.graftexport.core import main

if __name__ == "__main__":
    sys.exit(main())
