"""Export-audit target declarations: what round-trips, and what is
waived.

An ``ExportTarget`` names one program that goes through the AOT
serialize→deserialize cycle (``raft_tpu/serving/aot.py``) plus the
declared discipline the audit holds the ARTIFACT to: a complete cache
key (E1), donations surviving serialization (E2), no baked weight
literals (E3), portable custom calls and an honest platform claim
(E4), a manifest signature matching the engine's live recipe (E5), and
every corruption/skew probe routed to miss (E6).

``Waiver`` is the sibling tiers' pragma analog, verbatim: rule id + a
substring of the finding's ``detail`` + a REQUIRED justification,
reviewed where the target is declared.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Tuple

#: 1 MiB: ceiling for one literal baked into a serialized program
#: (E3). The engine's serve programs carry weights as ARGUMENTS — their
#: baked constants are coordinate grids and norm epsilons, well under
#: 100 KiB at audit shapes — while a closure-captured weight tree shows
#: up as multi-MB ``stablehlo.constant`` payloads. An artifact whose
#: key claims weights-independence must never ship one.
DEFAULT_BAKED_LITERAL_BYTES_MAX = 1 << 20

#: custom-call targets a serialized artifact may carry and still load
#: anywhere its key claims (sharding annotations are partitioner
#: metadata, resolved by the loading runtime). Anything else — host
#: callbacks, platform-specific kernels — pins the blob to the
#: process/platform that wrote it (E4).
PORTABLE_CUSTOM_CALLS = (
    "Sharding",
    "SPMDFullToShardShape",
    "SPMDShardToFullShape",
)

#: literal mirror of ``raft_tpu.serving.aot.REQUIRED_KEY_FIELDS`` — on
#: purpose: the warm cache path answers with no jax import (and no
#: raft_tpu import) at all. Drift between the mirror and the live set
#: is itself a gate failure — tests/test_graftexport.py pins both
#: halves equal.
REQUIRED_KEY_FIELDS = frozenset({
    "format", "program", "weights", "geometry", "wire", "iters",
    "config", "donations", "partition", "jax", "jaxlib", "platform",
})


@dataclass(frozen=True)
class Waiver:
    rule: str      # "E4"
    match: str     # substring of the finding's detail
    reason: str    # justification — empty reasons are rejected

    def __post_init__(self):
        if not self.reason.strip():
            raise ValueError(
                f"waiver for {self.rule} ({self.match!r}) has no "
                "justification — waivers document intent or they are "
                "just silent baselining")


@dataclass(frozen=True)
class ExportTarget:
    """One audited serialize→deserialize round trip.

    ``kind="engine"``: ``build()`` returns ``(engine, (b, h, w),
    flags)`` — a ``RAFTEngine`` constructed with an ``aot_cache`` and
    ``flags`` naming the program table (``{"cached": bool, "ragged":
    bool}``). The driver ensures the bucket/class (the engine itself
    serializes through the production store path), re-lowers the same
    recipe via ``engine.bucket_program`` for the live half, reloads
    the written entry through the verified loader, and fault-injects
    copies of the entry for E6.

    ``kind="fn"``: ``build()`` returns ``(fn, args, donate_argnums)``
    — a raw program the driver jits/compiles and writes through a
    LOW-LEVEL entry writer so fixtures can plant exactly one defect
    via the knobs below (the production ``aot.store`` refuses most of
    them by construction, which is the point).
    """

    name: str
    build: Callable
    kind: str = "engine"
    #: key components to DROP from the written manifest (E1 fixtures —
    #: models an older/third-party writer with an incomplete key)
    omit_key_fields: Tuple[str, ...] = ()
    #: serialize a non-donating compile of the same fn while the live
    #: trace keeps its donations (E2 fixtures — models a serialization
    #: path that loses the alias map)
    drop_donation_on_serialize: bool = False
    #: write this platform into the manifest key regardless of the
    #: compiling backend (E4 fixtures — a dishonest platform claim)
    platform_claim: str = ""
    #: corrupt the manifest's signature block after the write (E5
    #: fixtures — calling-convention drift between artifact and engine)
    tamper_signature: bool = False
    #: run the E6 probes through a NAIVE loader that ignores the
    #: manifest (E6 fixtures — models a loader missing the integrity
    #: checks; the real targets always probe the verified loader)
    naive_loader: bool = False
    baked_literal_bytes_max: int = DEFAULT_BAKED_LITERAL_BYTES_MAX
    custom_call_allowlist: Tuple[str, ...] = PORTABLE_CUSTOM_CALLS
    waivers: Tuple[Waiver, ...] = ()
    notes: str = ""

    def waived(self, rule: str, detail: str) -> bool:
        return any(w.rule == rule and w.match in detail
                   for w in self.waivers)


@dataclass
class ExportArtifacts:
    """Everything the rules see for one target: the live lowering +
    optimized HLO, the RELOADED executable's HLO, the manifest as
    written to disk, the engine's live calling-convention record, and
    the E6 probe outcomes. ``serialize_error`` is non-empty when the
    round trip itself failed (some programs — host callbacks — cannot
    serialize; rules that need the loaded half skip, E6 reports it for
    engine targets)."""

    key: Dict = field(default_factory=dict)        # components as used
    lowered_text: str = ""                         # live StableHLO
    live_hlo: str = ""                             # live optimized HLO
    loaded_hlo: str = ""                           # reloaded exe's HLO
    manifest: Dict = field(default_factory=dict)   # manifest.json
    blob_bytes: int = 0
    serialize_error: str = ""
    #: live calling convention: {"in": [...], "out": [...],
    #: "donations": [...]} — what E5 diffs the manifest against
    engine_signature: Dict = field(default_factory=dict)
    platform: str = ""                             # actual backend
    #: E6 outcomes: {"tamper": ..., "loader": ..., "survived": bool,
    #: "note": ...} — a surviving load IS the finding
    probes: List[Dict] = field(default_factory=list)
    seconds: float = 0.0
