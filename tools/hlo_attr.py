"""Map XLA fusion names from a trace/HLO dump back to source ops.

`cli/trace_summary.py` names the top ops (`fusion.1989`,
`convolution_add_fusion.49`, ...) but an XProf trace carries no source
attribution, which left the round-5 scan-body band (six conv fusions at
20-80 GB/s effective, ~44 ms/step — PROFILE.md tail) unattributable.
This closes the loop: run the same step with
``XLA_FLAGS="--xla_dump_to=DIR --xla_dump_hlo_as_text"`` alongside the
trace capture, then::

    python tools/hlo_attr.py DIR fusion.1989 convolution_add_fusion.49
    python tools/hlo_attr.py DIR --top 25       # largest fusions by body size

For each fusion the tool prints its root op kind, operand/result shapes
and the ``metadata.op_name`` JAX path (e.g.
``jit(train_step)/transpose(jvp(...))/while/body/...``), which names the
model-source op the fusion came from.  Reference analog: the profiling
story nvprof/nsys gives the CUDA reference for free via kernel names
(alt_cuda_corr/correlation_kernel.cu:19 names its own kernels); XLA
fusions need this mapping step instead.

The parsing itself lives in ``tools/hlo_lib.py`` (shared with
``tools/graftaudit``, which audits the same artifacts mechanically);
this module is the human-facing CLI and re-exports the entry points its
tests pin.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

try:                      # repo-root `python tools/hlo_attr.py` / pytest
    from tools import hlo_lib
except ImportError:       # tools/ itself on sys.path
    import hlo_lib

# pinned legacy surface (tests/test_hlo_attr.py; external callers)
parse_fusions = hlo_lib.parse_fusions
_pick_module = hlo_lib.pick_module


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump_dir", help="--xla_dump_to directory")
    ap.add_argument("names", nargs="*",
                    help="fusion names from trace_summary (suffix match)")
    ap.add_argument("--top", type=int, default=0,
                    help="also print the N largest fusions by body size")
    args = ap.parse_args(argv)

    mod = _pick_module(args.dump_dir)
    if mod is None:
        print(f"no *after_optimizations*.txt under {args.dump_dir}; "
              "run with XLA_FLAGS='--xla_dump_to=DIR "
              "--xla_dump_hlo_as_text'", file=sys.stderr)
        return 1
    print(f"# module: {os.path.basename(mod)}")
    fusions = parse_fusions(mod)

    def show(name: str, info: dict) -> None:
        print(f"{name}  {info['kind']:>8}  {info['shape']:<28} "
              f"body={info['body_lines']:<4} {info['op_name']}")

    for want in args.names:
        # substring match: trace_summary truncates hlo_op_name to 48
        # chars, so a pasted name may be missing its tail (.N suffix)
        hits = {n: i for n, i in fusions.items() if want in n}
        if not hits:
            print(f"{want}  NOT FOUND (fusion names are per-compile; "
                  "dump and trace must come from the same run)")
        for n, i in sorted(hits.items()):
            show(n, i)

    if args.top:
        print(f"# top {args.top} fusions by body size")
        ranked = sorted(fusions.items(),
                        key=lambda kv: -kv[1]["body_lines"])[:args.top]
        for n, i in ranked:
            show(n, i)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
