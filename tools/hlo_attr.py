"""Map XLA fusion names from a trace/HLO dump back to source ops.

`cli/trace_summary.py` names the top ops (`fusion.1989`,
`convolution_add_fusion.49`, ...) but an XProf trace carries no source
attribution, which left the round-5 scan-body band (six conv fusions at
20-80 GB/s effective, ~44 ms/step — PROFILE.md tail) unattributable.
This closes the loop: run the same step with
``XLA_FLAGS="--xla_dump_to=DIR --xla_dump_hlo_as_text"`` alongside the
trace capture, then::

    python tools/hlo_attr.py DIR fusion.1989 convolution_add_fusion.49
    python tools/hlo_attr.py DIR --top 25       # largest fusions by body size

For each fusion the tool prints its root op kind, operand/result shapes
and the ``metadata.op_name`` JAX path (e.g.
``jit(train_step)/transpose(jvp(...))/while/body/...``), which names the
model-source op the fusion came from.  Reference analog: the profiling
story nvprof/nsys gives the CUDA reference for free via kernel names
(alt_cuda_corr/correlation_kernel.cu:19 names its own kernels); XLA
fusions need this mapping step instead.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|\S+)\s+fusion\(")
_META_RE = re.compile(r'op_name="(?P<op>[^"]*)"')
_CALLS_RE = re.compile(r"calls=%(?P<comp>[\w.\-]+)")
_KIND_RE = re.compile(r"kind=(?P<kind>k\w+)")


def _pick_module(dump_dir: str) -> Optional[str]:
    """Largest after-optimizations HLO text in the dump (the main jit)."""
    cands: List[Tuple[int, str]] = []
    if not os.path.isdir(dump_dir):
        return None
    for fn in os.listdir(dump_dir):
        if fn.endswith("after_optimizations.txt"):
            p = os.path.join(dump_dir, fn)
            cands.append((os.path.getsize(p), p))
    return max(cands)[1] if cands else None


def parse_fusions(path: str) -> Dict[str, dict]:
    """name -> {shape, kind, op_name, calls, body_lines} for every fusion."""
    fusions: Dict[str, dict] = {}
    comp_sizes: Dict[str, int] = {}
    comp_ops: Dict[str, List[str]] = {}
    cur_comp = None
    with open(path) as f:
        for line in f:
            m = re.match(r"^(?:ENTRY\s+)?%(?P<comp>[\w.\-]+)\s+\(", line)
            if m:
                # ENTRY opens the top-level computation: stop attributing
                # lines to the previous fused computation
                cur_comp = None if line.startswith("ENTRY") \
                    else m.group("comp")
                if cur_comp is not None:
                    comp_sizes[cur_comp] = 0
                    comp_ops[cur_comp] = []
                continue
            if line.strip() == "}":
                cur_comp = None
            elif cur_comp is not None and line.strip():
                comp_sizes[cur_comp] += 1
                bm = _META_RE.search(line)
                if bm:
                    comp_ops[cur_comp].append(bm.group("op"))
            d = _DEF_RE.match(line)
            if d:
                meta = _META_RE.search(line)
                calls = _CALLS_RE.search(line)
                kind = _KIND_RE.search(line)
                fusions[d.group("name")] = {
                    "shape": d.group("shape"),
                    "kind": kind.group("kind") if kind else "?",
                    "op_name": meta.group("op") if meta else "(no metadata)",
                    "calls": calls.group("comp") if calls else None,
                }
    for info in fusions.values():
        info["body_lines"] = comp_sizes.get(info["calls"] or "", 0)
        if info["op_name"] == "(no metadata)":
            # fall back to the fused computation's own ops: report the
            # most frequent op_name in the body
            ops = comp_ops.get(info["calls"] or "", [])
            if ops:
                # max over the list: first-seen wins ties (deterministic)
                best = max(ops, key=ops.count)
                info["op_name"] = f"(body) {best}"
    return fusions


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump_dir", help="--xla_dump_to directory")
    ap.add_argument("names", nargs="*",
                    help="fusion names from trace_summary (suffix match)")
    ap.add_argument("--top", type=int, default=0,
                    help="also print the N largest fusions by body size")
    args = ap.parse_args(argv)

    mod = _pick_module(args.dump_dir)
    if mod is None:
        print(f"no *after_optimizations*.txt under {args.dump_dir}; "
              "run with XLA_FLAGS='--xla_dump_to=DIR "
              "--xla_dump_hlo_as_text'", file=sys.stderr)
        return 1
    print(f"# module: {os.path.basename(mod)}")
    fusions = parse_fusions(mod)

    def show(name: str, info: dict) -> None:
        print(f"{name}  {info['kind']:>8}  {info['shape']:<28} "
              f"body={info['body_lines']:<4} {info['op_name']}")

    for want in args.names:
        # substring match: trace_summary truncates hlo_op_name to 48
        # chars, so a pasted name may be missing its tail (.N suffix)
        hits = {n: i for n, i in fusions.items() if want in n}
        if not hits:
            print(f"{want}  NOT FOUND (fusion names are per-compile; "
                  "dump and trace must come from the same run)")
        for n, i in sorted(hits.items()):
            show(n, i)

    if args.top:
        print(f"# top {args.top} fusions by body size")
        ranked = sorted(fusions.items(),
                        key=lambda kv: -kv[1]["body_lines"])[:args.top]
        for n, i in ranked:
            show(n, i)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
