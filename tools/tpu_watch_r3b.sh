#!/bin/bash
# Round-3 late-session watchdog: poll the axon tunnel; on each window run
# the remaining marker-guarded follow-ups (round3e: bf16 shootout row +
# defaults re-pick; round3d: exact-precision trained parity) and finish
# with one bare bench.py so the freshest headline is reproduced with
# zero flags. Exits when everything is done.
set -u
cd /root/repo
LOG=/tmp/tpu_watch_r3b.log
MARK=/root/.cache/raft_tpu/r3_markers
while true; do
    if [ -e "$MARK/t_bf16" ] && [ -e "$MARK/trained_parity_exact" ] \
            && [ -e "$MARK/final_bare_bench" ]; then
        echo "$(date -u +%H:%M:%S) r3 follow-ups fully done" >> "$LOG"
        exit 0
    fi
    if timeout -k 10 180 python -c \
        "import jax; assert jax.devices()[0].platform != 'cpu'" \
        >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) chip up — running follow-ups" >> "$LOG"
        bash tools/onchip_round3e.sh /tmp/onchip_round3e.out
        bash tools/onchip_round3d.sh /tmp/onchip_round3d.out
        if [ ! -e "$MARK/final_bare_bench" ]; then
            if timeout 1800 python bench.py --steps 10 \
                    > /tmp/final_bare_bench.json 2>>"$LOG"; then
                touch "$MARK/final_bare_bench"
                cp /tmp/final_bare_bench.json /root/repo/BENCH_r03_local.json
                cd /root/repo && git add BENCH_r03_local.json \
                    && git commit -q -m \
                    "Record bare-flag bench reproduction for round 3" -m \
                    "No-Verification-Needed: measurement record only" || true
            fi
        fi
        echo "$(date -u +%H:%M:%S) follow-up pass ended" >> "$LOG"
    else
        echo "$(date -u +%H:%M:%S) chip unavailable" >> "$LOG"
    fi
    sleep 300
done
