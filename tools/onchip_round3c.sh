#!/bin/bash
# Round-3c: decide the transposed-volume lookup (corr_impl=onehot_t) on
# MEASURED numbers, rerun the bf16 shootout row that the pass-1 worker
# crash swallowed, and redo the train450 -> resume pair cleanly (pass-2's
# train450 hit a live-edit import race; train500_resume trained 0->500
# with nothing to resume from). Marker-guarded like the main runbook.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round3c.out}
MARK=/root/.cache/raft_tpu/r3_markers
LADDER=/root/.cache/raft_tpu/r3_ladder
mkdir -p "$MARK" "$LADDER"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
step() {
    local name=$1 tmo=$2; shift 2
    if [ -e "$MARK/$name" ]; then log "skip $name (done)"; return 0; fi
    log "begin $name"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        touch "$MARK/$name"; log "done $name"
    else
        log "FAILED rc=$? $name"
    fi
    cp "$OUT" /root/repo/ONCHIP_r03c.log 2>/dev/null || true
}
bench_cfg() {
    local tag=$1 tmo=$2; shift 2
    if [ -e "$MARK/bench_$tag" ]; then log "skip bench_$tag"; return 0; fi
    log "begin bench_$tag: $*"
    if timeout "$tmo" python bench.py --steps 10 "$@" \
            > "$LADDER/$tag.json" 2>> "$OUT"; then
        cat "$LADDER/$tag.json" >> "$OUT"
        touch "$MARK/bench_$tag"; log "done bench_$tag"
    else
        log "FAILED bench_$tag rc=$?"; cat "$LADDER/$tag.json" >> "$OUT"
    fi
    cp "$OUT" /root/repo/ONCHIP_r03c.log 2>/dev/null || true
}

# ---- 1. onehot_t lookup decision (isolated, then whole-step) -----------
step t_fwd 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot onehot_t
step t_grad 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot onehot_t --grad
# the missing bf16 row (pass-1 worker crash) + the onehot_t bf16 variant
step t_bf16 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls gather onehot onehot_t --grad --corr-dtype bfloat16
bench_cfg h_onehot_t_b8 1800 --batches 8 --corr-dtype bfloat16 --no-remat \
    --corr-impl onehot_t
step pick_defaults_c 120 python tools/pick_bench_defaults.py "$LADDER"

# ---- 2. clean train450 -> resume pair (quiet host, fixed code) ---------
rm -rf /root/.cache/raft_tpu/r3_ck
step train450c 2400 python -m raft_tpu.cli.train --name r3synth \
    --stage chairs --mixed_precision --synthetic 64 --num_steps 450 \
    --val_freq 200 --batch_size 6 --num_workers 4 \
    --checkpoint_dir /root/.cache/raft_tpu/r3_ck --log_dir runs
step train500c_resume 1800 python -m raft_tpu.cli.train --name r3synth \
    --stage chairs --mixed_precision --synthetic 64 --num_steps 500 \
    --val_freq 200 --batch_size 6 --num_workers 4 --resume \
    --checkpoint_dir /root/.cache/raft_tpu/r3_ck --log_dir runs

log "round3c complete"
cp "$OUT" /root/repo/ONCHIP_r03c.log 2>/dev/null || true
for f in ONCHIP_r03c.log BENCH_DEFAULTS.json runs/r3synth/metrics.jsonl; do
    git add "$f" 2>/dev/null || true
done
git diff --cached --quiet || git commit -q -m \
    "On-chip round-3c artifacts: onehot_t shootout, clean train/resume pair" \
    -m "No-Verification-Needed: measurement logs and recorded defaults only"
