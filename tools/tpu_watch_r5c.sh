#!/bin/bash
# Round-5 combined watchdog (supersedes tpu_watch_r5b.sh): poll the
# tunnel every ~3 min with the real-execute probe; on a window run the
# 5b musts (bare HEAD bench refresh, 3k-step sustained train, --resume
# proof) then the 5c attribution pass. Exits when every marker exists.
set -u
cd /root/repo
LOG=/root/repo/OUTAGE_r05.log
MARK=${RAFT_R5B_MARK:-/root/.cache/raft_tpu/r5b_markers}
while true; do
    if [ -e "$MARK/bare_final_head" ] && [ -e "$MARK/sustained_train" ] \
            && [ -e "$MARK/resume_check" ] && [ -e "$MARK/recorded" ] \
            && [ -e "$MARK/trace_attr" ]; then
        echo "$(date -u +%H:%M:%S) r5b+r5c runbooks fully done" >> "$LOG"
        exit 0
    fi
    # Half-up tunnel (devices() OK, execute hangs) must read as down.
    if bash tools/chip_probe.sh 180; then
        echo "$(date -u +%H:%M:%S) chip up — running r5b+r5c runbooks" \
            >> "$LOG"
        bash tools/onchip_round5b.sh /tmp/onchip_round5b.out
        bash tools/onchip_round5c.sh /tmp/onchip_round5c.out
        echo "$(date -u +%H:%M:%S) runbook pass ended" >> "$LOG"
    else
        echo "$(date -u +%H:%M:%S) chip unavailable" >> "$LOG"
    fi
    sleep 180
done
