#!/bin/bash
# Round-3 follow-up ladder, informed by the first ladder's measurements:
#   b_bf16_b8  (bf16 vol, b8, no remat)  16.04 pairs/s  <- best
#   a_fp32_b8  (fp32 vol, b8, no remat)  12.63
#   c_bf16_dots(bf16 vol, b12, remat)    13.77          <- remat hurts
# Untried: larger batch WITHOUT remat (bf16 volumes cut temp memory, so
# b10/b12 may fit where r2's fp32 b8 was borderline). Run after the main
# runbook so the chip is never double-booked.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round3b.out}
MARK=/root/.cache/raft_tpu/r3_markers
LADDER=/root/.cache/raft_tpu/r3_ladder
mkdir -p "$MARK" "$LADDER"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
bench_cfg() {
    local tag=$1 tmo=$2; shift 2
    if [ -e "$MARK/bench_$tag" ]; then log "skip bench_$tag"; return 0; fi
    log "begin bench_$tag: $*"
    if timeout "$tmo" python bench.py --steps 10 "$@" \
            > "$LADDER/$tag.json" 2>> "$OUT"; then
        cat "$LADDER/$tag.json" >> "$OUT"
        touch "$MARK/bench_$tag"; log "done bench_$tag"
    else
        log "FAILED bench_$tag rc=$?"; cat "$LADDER/$tag.json" >> "$OUT"
    fi
    cp "$OUT" /root/repo/ONCHIP_r03b.log 2>/dev/null || true
}

# g_*: re-measure after moving convex upsampling out of the scan into one
# batched lane-tiled op (ops/flow_ops.convex_upsample_batched) — the XProf
# trace attributed ~35% of the 500 ms step to the per-iteration form's
# (…,9,8,8) tile padding. Same flags as b_bf16_b8 for apples-to-apples.
bench_cfg g_upsample_b8  1800 --batches 8 --corr-dtype bfloat16 --no-remat
bench_cfg f_bf16_b12     1800 --batches 12 10 --corr-dtype bfloat16 --no-remat
step_pick() {
    python tools/pick_bench_defaults.py "$LADDER" >> "$OUT" 2>&1
    cp "$OUT" /root/repo/ONCHIP_r03b.log 2>/dev/null || true
}
step_pick
log "round3b complete"
# artifacts-only commit so a round-end snapshot can't lose the evidence
for f in ONCHIP_r03b.log BENCH_DEFAULTS.json; do
    git add "$f" 2>/dev/null || true
done
git diff --cached --quiet || git commit -q -m \
    "On-chip round-3b ladder artifacts" \
    -m "No-Verification-Needed: measurement logs and recorded defaults only"
