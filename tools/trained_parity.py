"""Trained-weights parity: genuine .pth -> convert -> diff vs live torch.

Closes VERDICT r2 missing #1 as far as the sandbox allows (no egress, so
the artifact is the CPU-trained reference checkpoint from
``tools/train_reference_ckpt.py`` rather than the released one): load the
``.pth`` exactly as a user would (``tools/convert.load_pth``), run BOTH
implementations at full demo-frame resolution with the reference's demo
iteration count, and report the flow diff. Unlike the random-init parity
suite this exercises (a) the converter on a real torch-SAVED artifact,
(b) BatchNorm running statistics that have moved off init (eval-mode BN
uses them), and (c) trained-weight flow magnitudes.

Also measures the ``corr_dtype=bfloat16`` flow delta at the same weights
(VERDICT r2 next #4): the bf16-volume step is the single biggest traffic
lever, gated on exactly this number.
"""

import argparse
import json
import os
import os.path as osp
import sys

import numpy as np

REF = "/root/reference"
# runnable as `python tools/trained_parity.py` — put the repo root on the
# path so raft_tpu imports without an install step
sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))


def torch_flow_cached(pth, img1, img2, small, iters, cache_dir):
    """torch_flow with an on-disk cache: the torch reference forward at
    full demo resolution costs minutes per model on the 1-core host and is
    bit-deterministic for a given (checkpoint, crop, iters) — rerunning
    the tool (e.g. after TPU-side changes) should not repay it."""
    st = os.stat(pth)  # fingerprint: same-named but replaced ckpt files
    #                    must not reuse a stale cached reference flow;
    #                    st_mtime_ns (not integer seconds) so a same-size
    #                    replacement within one second still misses
    key = (f"torchflow_{osp.basename(pth)}_{st.st_size}_{st.st_mtime_ns}"
           f"_{iters}_{img1.shape[0]}x{img1.shape[1]}.npy")
    subdir = osp.join(cache_dir, "torchflow_cache")  # don't litter ckpt_dir
    os.makedirs(subdir, exist_ok=True)
    path = osp.join(subdir, key)
    if osp.exists(path):
        return np.load(path)
    # migrate a round-3 cache hit (legacy key: integer-second mtime, flat
    # in cache_dir) instead of re-paying minutes of torch forwards
    legacy = osp.join(cache_dir, (
        f"torchflow_{osp.basename(pth)}_{st.st_size}_{int(st.st_mtime)}"
        f"_{iters}_{img1.shape[0]}x{img1.shape[1]}.npy"))
    if osp.exists(legacy):
        os.replace(legacy, path)
        return np.load(path)
    out = torch_flow(pth, img1, img2, small, iters)
    np.save(path, out)
    return out


def torch_flow(pth, img1, img2, small, iters):
    import torch

    sys.path.insert(0, osp.join(REF, "core"))
    from raft import RAFT as TorchRAFT

    targs = argparse.Namespace(small=small, mixed_precision=False,
                               alternate_corr=False, dropout=0.0)
    model = TorchRAFT(targs)
    sd = torch.load(pth, map_location="cpu")
    model.load_state_dict({k.removeprefix("module."): v
                           for k, v in sd.items()})
    model.eval()
    with torch.no_grad():
        t1 = torch.from_numpy(img1).permute(2, 0, 1)[None]
        t2 = torch.from_numpy(img2).permute(2, 0, 1)[None]
        flow = model(t1, t2, iters=iters, test_mode=True)
    return flow[0].permute(1, 2, 0).numpy()


def jax_flow(pth, img1, img2, small, iters, corr_dtype="float32",
             corr_impl=None):
    import jax.numpy as jnp

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT
    from raft_tpu.tools.convert import load_pth

    extra = {"corr_impl": corr_impl} if corr_impl else {}
    cfg = RAFTConfig(small=small, corr_dtype=corr_dtype, **extra)
    variables = load_pth(pth, cfg)
    model = RAFT(cfg)
    _, flow = model.apply(variables, jnp.asarray(img1[None]),
                          jnp.asarray(img2[None]), iters=iters,
                          test_mode=True)
    return np.asarray(flow)[0]


def main():
    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", default="/root/.cache/raft_tpu/ref_ckpt")
    p.add_argument("--iters", type=int, default=20,
                   help="the reference demo count (demo.py:62)")
    p.add_argument("--hw", type=int, nargs=2, default=[368, 768],
                   help="center-crop of the 436x1024 demo frames; must be "
                        "/8 with H/64>=2 (both implementations need it)")
    p.add_argument("--corr_impl", "--corr-impl", default=None,
                   # no choices=: RAFTConfig.__post_init__ is the single
                   # validator, so a new backend needs no edit here
                   help="lookup backend for the jax side — lets any "
                        "backend's accuracy be pinned at TRAINED weights "
                        "(e.g. softsel's bf16 selection-weight rounding) "
                        "in the same chip window that measures its speed")
    p.add_argument("--matmul-precision", default="highest",
                   choices=["default", "highest"],
                   help="'highest' forces exact fp32 MXU passes for convs/"
                        "dots on TPU (XLA's default fp32 conv runs multi-"
                        "pass bf16, which costs ~0.1 px through 20 "
                        "recurrent iterations); parity measurement wants "
                        "the exact mode")
    args = p.parse_args()

    if args.matmul_precision == "highest":
        import jax
        jax.config.update("jax_default_matmul_precision", "highest")

    from PIL import Image

    f1 = np.asarray(Image.open(osp.join(REF, "demo-frames",
                                        "frame_0020.png")))
    f2 = np.asarray(Image.open(osp.join(REF, "demo-frames",
                                        "frame_0021.png")))
    h, w = args.hw
    y0 = (f1.shape[0] - h) // 2
    x0 = (f1.shape[1] - w) // 2
    img1 = f1[y0:y0 + h, x0:x0 + w].astype(np.float32)
    img2 = f2[y0:y0 + h, x0:x0 + w].astype(np.float32)

    results = {}
    for name, small in [("basic", False), ("small", True)]:
        pth = osp.join(args.ckpt_dir, f"raft-{name}-cputrained.pth")
        if not osp.exists(pth):
            print(f"{name}: checkpoint missing at {pth}, skipped")
            continue
        ft = torch_flow_cached(pth, img1, img2, small, args.iters,
                               args.ckpt_dir)
        fj = jax_flow(pth, img1, img2, small, args.iters,
                      corr_impl=args.corr_impl)
        diff = np.abs(ft - fj)
        rec = {"flow_mag_max": round(float(np.abs(ft).max()), 2),
               "max_diff_px": float(diff.max()),
               "mean_diff_px": float(diff.mean())}
        if not small:
            fb = jax_flow(pth, img1, img2, small, args.iters,
                          corr_dtype="bfloat16", corr_impl=args.corr_impl)
            epe = np.linalg.norm(fb - fj, axis=-1)
            # EPE of bf16-volume flow against the fp32-volume flow: the
            # accuracy cost of halving the dominant HBM traffic
            rec["bf16_volume_epe_vs_fp32"] = float(epe.mean())
            rec["bf16_volume_epe_max"] = float(epe.max())
        results[name] = rec
        print(name, json.dumps(rec), flush=True)

    # impl variants get their own file — the canonical (default-impl)
    # record must not be clobbered by a backend-accuracy follow-up
    tag = f"_{args.corr_impl}" if args.corr_impl else ""
    out = osp.join(args.ckpt_dir, f"trained_parity{tag}.json")
    with open(out, "w") as f:
        json.dump({"iters": args.iters, "hw": args.hw,
                   "corr_impl": args.corr_impl or "default", **results}, f,
                  indent=1)
    print("wrote", out)
    return 0 if results else 1


if __name__ == "__main__":
    sys.exit(main())
