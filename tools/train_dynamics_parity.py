"""Training-dynamics parity: identical init + batches, torch vs raft_tpu.

BASELINE config 4's acceptance is a FlyingChairs loss-curve match; real
FlyingChairs can't be staged (zero egress), so this is the substitute at
the same level of rigor as PARITY.md's trained-weights check: start BOTH
implementations from the SAME weights (torch init -> tools/convert), feed
them the SAME batch sequence (warped real Sintel frames), with the
reference's exact training recipe — AdamW(lr, wdecay, eps) + OneCycleLR
(num_steps+100, pct_start 0.05, linear anneal) (train.py:79-86), global
norm clip 1.0 (train.py:196), gamma-weighted masked sequence loss
(train.py:47-72) — and record both loss trajectories.

What forward parity can't see, this does: train-mode BatchNorm batch
statistics, the optimizer's step/bias-correction off-by-ones, the
schedule's warmup shape, the loss mask arithmetic, and gradient flow
through the scanned (vs unrolled) refinement loop. fp32 both sides;
per-step divergence beyond float-noise growth indicates a semantic
mismatch, not rounding.

Writes train_dynamics.json: per-step (loss_torch, loss_jax) + summary.
"""

import argparse
import json
import os
import os.path as osp
import sys

import numpy as np

# the comparison target is torch on CPU; fp32 CPU-vs-CPU is the clean
# setting and must not block dialing the (possibly down) TPU tunnel
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REF = "/root/reference"
sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))
sys.path.insert(0, osp.dirname(osp.abspath(__file__)))  # train_reference_ckpt
sys.path.insert(0, osp.join(REF, "core"))


def torch_run(batches, hw, steps, iters, lr, wdecay, eps, seed):
    import torch

    from raft import RAFT as TorchRAFT

    targs = argparse.Namespace(small=False, mixed_precision=False,
                               alternate_corr=False, dropout=0.0)
    torch.manual_seed(seed)
    model = TorchRAFT(targs)
    sd0 = {f"module.{k}": v.clone() for k, v in model.state_dict().items()}
    model.train()
    opt = torch.optim.AdamW(model.parameters(), lr=lr, weight_decay=wdecay,
                            eps=eps)
    sched = torch.optim.lr_scheduler.OneCycleLR(
        opt, lr, steps + 100, pct_start=0.05, cycle_momentum=False,
        anneal_strategy="linear")
    losses = []
    for i1, i2, gt, valid in batches:
        t1 = torch.from_numpy(i1).permute(0, 3, 1, 2)
        t2 = torch.from_numpy(i2).permute(0, 3, 1, 2)
        tgt = torch.from_numpy(gt).permute(0, 3, 1, 2)
        tv = torch.from_numpy(valid)
        preds = model(t1, t2, iters=iters)
        # the reference's sequence_loss (train.py:47-72), verbatim math
        mag = torch.sum(tgt ** 2, dim=1).sqrt()
        vmask = (tv >= 0.5) & (mag < 400.0)
        loss = 0.0
        for j, pred in enumerate(preds):
            w = 0.8 ** (len(preds) - j - 1)
            i_loss = (pred - tgt).abs()
            loss = loss + w * (vmask[:, None] * i_loss).mean()
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
        opt.step()
        sched.step()
        losses.append(float(loss.item()))
    return sd0, losses


def jax_run(sd0, batches, hw, steps, iters, lr, wdecay, eps):
    import jax
    import jax.numpy as jnp

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.tools.convert import convert_state_dict
    from raft_tpu.models import RAFT
    from raft_tpu.training.train_step import (create_train_state,
                                              make_train_step)

    model_cfg = RAFTConfig(small=False, mixed_precision=False)
    train_cfg = TrainConfig(stage="chairs", num_steps=steps, batch_size=
                            batches[0][0].shape[0], iters=iters, lr=lr,
                            wdecay=wdecay, epsilon=eps, add_noise=False,
                            # bit-level torch matching wants the
                            # reference-exact full-resolution loss, not
                            # the (value-equivalent) fused subpixel form
                            fused_loss=False)
    rng = jax.random.PRNGKey(0)
    model = RAFT(model_cfg)
    img = jnp.zeros((1, *hw, 3))
    template = model.init(rng, img, img, iters=1)
    variables = convert_state_dict(
        {k: np.asarray(v) for k, v in sd0.items()}, template)
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=hw,
                               init_variables=variables)
    step_fn = jax.jit(make_train_step(model_cfg, train_cfg),
                      donate_argnums=(0,))
    # keep per-step losses ON DEVICE and fetch once after the loop: the
    # comparison needs every step's value but not per-step, and a
    # float() in the loop body serializes host and device every
    # iteration (graftlint R1; the ROADMAP burn-down's batched-fetch
    # candidate). The trajectory is a few hundred scalars — holding the
    # handles costs nothing next to one D2H round trip per step.
    device_losses = []
    for i1, i2, gt, valid in batches:
        batch = {"image1": jnp.asarray(i1), "image2": jnp.asarray(i2),
                 "flow": jnp.asarray(gt), "valid": jnp.asarray(valid)}
        state, metrics = step_fn(state, batch, rng)
        device_losses.append(metrics["loss"])
    return [float(v) for v in jax.device_get(device_losses)]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--hw", type=int, nargs=2, default=[184, 248])
    p.add_argument("--lr", type=float, default=4e-4)      # chairs recipe
    p.add_argument("--wdecay", type=float, default=1e-4)  # train_standard.sh
    p.add_argument("--eps", type=float, default=1e-8)
    p.add_argument("--out",
                   default="/root/.cache/raft_tpu/train_dynamics.json")
    args = p.parse_args()

    from raft_tpu.utils.platform import setup_cli

    setup_cli()
    from train_reference_ckpt import make_pairs  # same data generator

    rng = np.random.RandomState(7)
    pairs = make_pairs(24, tuple(args.hw), rng)
    batches = []
    for _ in range(args.steps):
        sel = [pairs[rng.randint(len(pairs))] for _ in range(args.batch)]
        i1 = np.stack([s[0] for s in sel])
        i2 = np.stack([s[1] for s in sel])
        gt = np.stack([s[2] for s in sel])
        valid = np.ones(gt.shape[:-1], np.float32)
        batches.append((i1, i2, gt, valid))

    sd0, loss_t = torch_run(batches, tuple(args.hw), args.steps, args.iters,
                            args.lr, args.wdecay, args.eps, seed=1234)
    print("torch done:", [round(v, 4) for v in loss_t[:5]], "...",
          round(loss_t[-1], 4), flush=True)
    loss_j = jax_run(sd0, batches, tuple(args.hw), args.steps, args.iters,
                     args.lr, args.wdecay, args.eps)
    print("jax done:  ", [round(v, 4) for v in loss_j[:5]], "...",
          round(loss_j[-1], 4), flush=True)

    lt, lj = np.asarray(loss_t), np.asarray(loss_j)
    rel = np.abs(lt - lj) / np.maximum(np.abs(lt), 1e-9)
    tail = max(1, args.steps // 4)
    summary = {
        "steps": args.steps,
        "step0_rel": float(rel[0]),
        "first10_max_rel": float(rel[:10].max()),
        "tail_mean_torch": float(lt[-tail:].mean()),
        "tail_mean_jax": float(lj[-tail:].mean()),
        "tail_mean_rel": float(abs(lt[-tail:].mean() - lj[-tail:].mean())
                               / lt[-tail:].mean()),
    }
    with open(args.out, "w") as f:
        json.dump({"summary": summary,
                   "loss_torch": loss_t, "loss_jax": loss_j}, f, indent=1)
    print(json.dumps(summary), flush=True)
    print("wrote", args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
