#!/bin/bash
# Round-6 on-chip runbook: the gru_impl whole-step A/B.
#
# PR 2 built a second update-block implementation (RAFTConfig.gru_impl
# = 'fused': lane-major scan-body convs + Pallas gate/blend epilogues,
# see PROFILE.md round 6). Promotion is decided HERE, by whole-step
# rungs at the proven r5 defaults — never by isolated kernel benches
# (they steered the repo wrong for two rounds; PROFILE round 5).
#
# Rung design: the pair differs ONLY in the gru_impl knob, both pinned
# to the current BENCH_DEFAULTS winner config (softsel + bf16 volumes +
# fused loss, b8 first). The explicit _gruxla control re-measures the
# incumbent in the SAME window so the A/B is same-day, same-tunnel —
# cross-window comparisons have been off by more than the effects we
# chase. bench.py itself provides OOM laddering and the one-shot
# crash-retry re-exec (RAFT_BENCH_* env), so a worker death resumes at
# the crashed rung instead of zeroing the pair.
#
# Marker-resumable across windows like round 5; ladder rows feed
# tools/pick_bench_defaults.py, and a re-picked BENCH_DEFAULTS.json is
# only committed after a bare-run reproduction.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round6.out}
MARK=${RAFT_R6_MARK:-/root/.cache/raft_tpu/r6_markers}
LADDER=${RAFT_R6_LADDER:-/root/.cache/raft_tpu/r6_ladder}
mkdir -p "$MARK" "$LADDER"
# seed with the r5 rows so a slow r6 set can't downgrade the pick below
# what is already proven
cp -n /root/.cache/raft_tpu/r5_ladder/*.json "$LADDER"/ 2>/dev/null || true
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
snap() { cp "$OUT" /root/repo/ONCHIP_r06.log 2>/dev/null || true; }
wait_chip() {
    for _ in 1 2 3 4 5; do
        if timeout -k 10 120 python -c \
            "import jax; assert jax.devices()[0].platform != 'cpu'" \
            >/dev/null 2>&1; then return 0; fi
        log "chip not answering; waiting 60s"
        sleep 60
    done
    return 1
}
step() {
    local name=$1 tmo=$2; shift 2
    if [ -e "$MARK/$name" ]; then log "skip $name (done)"; return 0; fi
    wait_chip || { log "SKIP $name (chip unavailable)"; return 1; }
    log "begin $name"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        touch "$MARK/$name"; log "done $name"
    else
        local rc=$?
        log "retry $name after 90s (rc=$rc)"
        sleep 90
        if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
            touch "$MARK/$name"; log "done $name (retry)"
        else
            log "FAILED rc=$? $name"
        fi
    fi
    snap
}
bench_cfg() {
    local tag=$1 tmo=$2; shift 2
    if [ -e "$MARK/bench_$tag" ]; then log "skip bench_$tag"; return 0; fi
    wait_chip || { log "SKIP bench_$tag (chip unavailable)"; return 1; }
    log "begin bench_$tag: $*"
    if timeout "$tmo" python bench.py --steps 10 "$@" \
            > "$LADDER/$tag.json" 2>> "$OUT"; then
        cat "$LADDER/$tag.json" >> "$OUT"
        touch "$MARK/bench_$tag"; log "done bench_$tag"
    else
        log "FAILED bench_$tag rc=$?"; cat "$LADDER/$tag.json" >> "$OUT"
    fi
    snap
}
commit_msmt() {  # measurement artifacts only — no source changes
    local msg=$1; shift
    for f in "$@"; do git add "$f" 2>/dev/null || true; done
    git diff --cached --quiet || git commit -q -m "$msg" -m \
        "No-Verification-Needed: measurement logs and records only"
}

# ---- the A/B pair: identical config, only gru_impl differs ------------
R5_WINNER="--corr-dtype bfloat16 --no-remat --fused-loss --corr-impl softsel"
# shellcheck disable=SC2086
bench_cfg g_gruxla 2400 --batches 8 6 $R5_WINNER --gru-impl xla
# fused first compile is new HLO territory: generous cap, same rungs
# shellcheck disable=SC2086
bench_cfg g_grufused 2700 --batches 8 6 $R5_WINNER --gru-impl fused
commit_msmt "r6 gru_impl A/B ladder rows" ONCHIP_r06.log

# ---- HLO capture for graftaudit budget re-anchoring -------------------
# tools/graftaudit/budgets.json pins the H5 scan-body/whole-step bands
# with CPU-anchored byte counts; this dump gives the next PR a real TPU
# module to re-anchor them from (tools/hlo_lib.pick_module +
# band_traffic read an --xla_dump_to directory directly). Kept OUT of
# the A/B rungs: dumping is compile-time-only but the measurement pair
# stays env-identical on principle. Compile-only cost: 2 steps.
HLO_DUMP=${RAFT_R6_HLO_DUMP:-/root/.cache/raft_tpu/r6_hlo_dump}
mkdir -p "$HLO_DUMP"
# shellcheck disable=SC2086
step hlo_dump_r6 1500 env \
    XLA_FLAGS="--xla_dump_to=$HLO_DUMP --xla_dump_hlo_as_text" \
    python bench.py --steps 2 --batches 8 $R5_WINNER --gru-impl xla
if [ -e "$MARK/hlo_dump_r6" ]; then
    log "hlo dump module: $(python -c \
        "from tools.hlo_lib import pick_module as p; \
print(p('$HLO_DUMP'))" 2>/dev/null || echo unreadable)"
fi

# ---- sharded HLO capture for graftshard re-anchoring (PR 15) ----------
# Compiles the two graftshard mesh programs on the REAL devices and
# answers: does the TPU pipeline sink the backward scan's grad
# all-reduces (S1 waiver evidence)? What are the real collective sizes
# (S2) / shard extents (S5)? A single-chip window self-reports and
# no-ops — the rung only earns its slot on a slice. Compile-only.
step shard_audit_r6 1500 python tools/shard_audit_onchip.py \
    --out /root/.cache/raft_tpu/r6_shard_audit --image-hw 64,64

# ---- secondary: fused at the b10 memory edge (the Pallas epilogues
# drop gate intermediates from the scan's saved-residual stack, so the
# fused path may fit a batch the xla path OOMs at) -----------------------
# shellcheck disable=SC2086
bench_cfg g_grufused_b10 2700 --batches 10 $R5_WINNER --gru-impl fused

# ---- defaults decision (same discipline as r5: a re-picked
# BENCH_DEFAULTS.json is only committed after a bare reproduction) ------
step pick_defaults_r6 120 python tools/pick_bench_defaults.py "$LADDER"
if [ -e "$MARK/pick_defaults_r6" ] && [ ! -e "$MARK/defaults_decided" ] \
        && [ ! -e "$MARK/defaults_changed" ]; then
    if git diff --quiet BENCH_DEFAULTS.json; then
        touch "$MARK/defaults_decided"  # pick kept the proven defaults
    else
        touch "$MARK/defaults_changed"
        log "defaults re-picked - bare reproduction owed"
    fi
fi
if [ -e "$MARK/defaults_changed" ] && [ ! -e "$MARK/bare_bench_final" ]; then
    if wait_chip; then
        log "reproducing re-picked defaults with a bare run"
        if timeout 2700 python bench.py \
                > "$LADDER/bare_final.json" 2>> "$OUT"; then
            cat "$LADDER/bare_final.json" >> "$OUT"
            if python - "$LADDER/bare_final.json" <<'EOF'
import json, sys
row = json.load(open(sys.argv[1]))
sys.exit(0 if row.get("value", 0) > 0 else 1)
EOF
            then
                touch "$MARK/bare_bench_final" "$MARK/defaults_decided"
                cp "$LADDER/bare_final.json" /root/repo/BENCH_r06_local.json
                snap
                commit_msmt \
                    "Bare bench reproduction at the re-picked defaults" \
                    BENCH_r06_local.json BENCH_DEFAULTS.json ONCHIP_r06.log
            fi
        else
            log "FAILED bare_bench_final rc=$?"
        fi
        snap
    fi
fi
if [ -e "$MARK/defaults_decided" ]; then
    commit_msmt "r6 ladder rows + defaults" ONCHIP_r06.log \
        BENCH_DEFAULTS.json
else
    commit_msmt "r6 ladder rows" ONCHIP_r06.log
fi

# ---- serving front-end: scheduler ragged-traffic drill (PR 6) --------
# two concurrent submitters + two warm-start sessions over cvt2trt-ish
# shapes with ragged per-shape totals; the JSON line records occupancy
# vs the one-request-per-dispatch baseline and real-hardware latency
# histograms (the CPU tier-1 drill proves the ROUTING — executable
# count == documented buckets — but its latency numbers mean nothing).
# The metrics.jsonl snapshot lands in $OUT's dir for the PROFILE entry.
step serve_bench_r6 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 48 --submitters 2 \
    --bucket-batch 4 --sessions 2 --session-frames 4 \
    --deadline-ms 30000 --gather-ms 20 --log-dir /tmp/raft_serve_r6

# ---- replica fleet: data-parallel fan-out A/B (PR 17) ----------------
# serve_bench_r6's EXACT traffic again, fanned across a 4-replica
# fleet behind the same scheduler (least-loaded dispatch, per-replica
# breaker boards). Compare the two JSON lines: pairs_per_s (the
# data-parallel win is THE number), dispatch_gap_* (the fleet overlaps
# device time across lanes), and the fleet block's per-replica
# dispatches/occupancy (balance within ~2x is the placement contract).
# Replicas 2..4 warm from the AOT store the primary populates in this
# same run — the summary's compiles must equal documented_buckets
# (primary only; each added lane is an I/O-bound deserialize, the
# replica-rollout cold-start story serve_export_r6 measures end-to-end).
rm -rf /tmp/raft_aot_fleet_r6
step serve_fleet_r6 2400 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 48 --submitters 2 \
    --bucket-batch 4 --sessions 2 --session-frames 4 \
    --deadline-ms 30000 --gather-ms 20 \
    --replicas 4 --aot-cache /tmp/raft_aot_fleet_r6 \
    --log-dir /tmp/raft_serve_fleet_r6

# ---- multi-host fleet: kill-one failover drill (PR 18) ---------------
# serve_bench_r6's traffic across 2 loopback host lanes behind the
# transport seam: both hosts admitted via the sha256-verified artifact
# push + prewarm BEFORE traffic (the hosts block's push_entries /
# push_bytes prove the ship; prewarm rides the same AOT store — zero
# extra XLA compiles per host), then h0's transport is poisoned while
# the queue drains (--hosts-kill-one). The JSON line must show every
# request settled (stranded 0, accounting_ok true, abandoned_inflight
# 0) with the hosts block recording h0's missed-beat walk and the
# failover; metrics.jsonl in the log dir carries the host_suspect /
# host_dead / failover event evidence. The big shapes matter: seconds
# of drain per dispatch is the in-flight window the verdict lands in.
rm -rf /tmp/raft_aot_hosts_r6
step serve_hosts_r6 2400 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 48 --submitters 2 \
    --bucket-batch 4 --deadline-ms 60000 --gather-ms 20 \
    --dispatch-timeout-ms 60000 --breaker-failures 2 \
    --hosts 2 --hosts-kill-one \
    --aot-cache /tmp/raft_aot_hosts_r6 \
    --log-dir /tmp/raft_serve_hosts_r6

# ---- request tracing: REAL tail exemplars + phase attribution (PR 14)
# serve_bench_r6's traffic with the span ledger armed (full sampling —
# this window wants every span): spans.jsonl lands beside the metrics,
# the summary's tail_exemplars block names the top-bucket trace ids,
# and serve_trace prints WHERE the on-chip p99 actually went (queue vs
# assembly vs device vs fetch — the CPU drills can only fake these
# proportions). Runs at depth 2 + u8 so the attribution covers the
# pipelined fetch stage; feed the numbers to PROFILE.md and size the
# production --trace-sample from the ledger's written/opened ratio.
step serve_trace_r6 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 48 --submitters 2 \
    --bucket-batch 4 --sessions 2 --session-frames 4 \
    --deadline-ms 30000 --gather-ms 20 \
    --wire u8 --pipeline-depth 2 \
    --log-dir /tmp/raft_serve_trace_r6 --trace-sample 1.0
step serve_trace_r6_report 600 python -m raft_tpu.cli.serve_trace \
    /tmp/raft_serve_trace_r6/spans.jsonl --top 10

# ---- serving hot path: wire/pipeline A/B on the same traffic (PR 8) --
# serve_bench_r6 above is the f32/depth-1 baseline; this rung re-runs
# the SAME traffic with the u8 wire + depth-2 pipelined dispatch (and
# device-resident session state). Compare the two JSON lines'
# h2d_bytes_per_req (expect ~0.25x) and dispatch_gap_* (expect ~0 at
# depth 2 under load) — the on-chip numbers PROFILE.md round 7 wants.
# Warm-up leg first: the u8 buckets are NEW programs (and the
# device-state splat/embed jits compile mid-traffic on a cold cache),
# which would pollute the measured rung's gap histogram with one-off
# multi-second on-chip compiles; the warm-up populates the persistent
# compile cache so the measured run is steady-state.
step serve_wire_r6_warm 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 8 --submitters 2 \
    --bucket-batch 4 --sessions 2 --session-frames 2 \
    --deadline-ms 60000 --gather-ms 20 \
    --wire u8 --pipeline-depth 2 --device-state
step serve_wire_r6 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 48 --submitters 2 \
    --bucket-batch 4 --sessions 2 --session-frames 4 \
    --deadline-ms 30000 --gather-ms 20 \
    --wire u8 --pipeline-depth 2 --device-state \
    --log-dir /tmp/raft_serve_wire_r6

# ---- ragged single-executable serving: mixed-shape A/B (PR 13) -------
# serve_bench_r6's EXACT traffic again, served through ONE ragged
# capacity-class executable (440x1024 box covers both shapes) instead
# of one bucket per shape. Compare the two JSON lines: executables
# (1 vs 2), capacity_fill / cross_shape_coalesce_rate (the bucketed
# line can never coalesce across shapes), padding_waste_ratio (the
# honest cost: the 368x496 requests run in the 440x1024 box), and
# pairs_per_s — the fill-from-the-whole-queue win vs the capacity
# padding cost is THE number this rung exists to measure. Warm-up leg
# first: the ragged program is new HLO (one compile, which is the
# point — a cold mixed-shape fleet pays ONE compile, not O(shapes)).
step serve_ragged_r6_warm 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 8 --submitters 2 \
    --bucket-batch 4 --sessions 2 --session-frames 2 \
    --deadline-ms 60000 --gather-ms 20 \
    --ragged --capacity-classes 440x1024
step serve_ragged_r6 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 48 --submitters 2 \
    --bucket-batch 4 --sessions 2 --session-frames 4 \
    --deadline-ms 30000 --gather-ms 20 \
    --ragged --capacity-classes 440x1024 \
    --log-dir /tmp/raft_serve_ragged_r6

# ---- cross-frame feature cache: warm-video A/B (PR 12) ---------------
# same hot-path recipe + video-heavy traffic (long streams), A/B'd
# against serve_wire_r6's configuration on the SAME session traffic:
# the cached rung serves steady-state pairs with ONE encoder pass and
# ONE frame of H2D each (warm_pairs_per_s / cache_hit_rate /
# dispatch-gap in the summary line; hit_rate should sit >= 0.9 —
# anything lower means the pool capacity is too small for the stream
# population or streams are cold-restarting). Warm-up leg compiles the
# cached-signature buckets (new programs) outside the measured window.
step serve_cache_r6_base 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 0 --submitters 1 \
    --bucket-batch 4 --sessions 4 --session-frames 16 \
    --deadline-ms 60000 --gather-ms 20 \
    --wire u8 --pipeline-depth 2 --device-state \
    --log-dir /tmp/raft_serve_cache_r6_base
step serve_cache_r6_warm 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 0 --submitters 1 \
    --bucket-batch 4 --sessions 2 --session-frames 2 \
    --deadline-ms 60000 --gather-ms 20 \
    --wire u8 --pipeline-depth 2 --feature-cache
step serve_cache_r6 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 0 --submitters 1 \
    --bucket-batch 4 --sessions 4 --session-frames 16 \
    --deadline-ms 60000 --gather-ms 20 \
    --wire u8 --pipeline-depth 2 --feature-cache \
    --log-dir /tmp/raft_serve_cache_r6

# ---- serving resilience: chaos drill against the real device (PR 7) --
# randomized raise/hang plans at serve.request / serve.dispatch_exec /
# engine.compile through the dispatch watchdog + per-bucket breakers +
# drop/recompile recovery, then a clean round; exits nonzero on any
# invariant violation (stranded futures, accounting identity, health
# vs breaker board, leaked duplicate buckets). The CPU tier-1 soak
# proves the LOGIC; this proves it against real device hangs and real
# recompile times. Timeout/hang are sized for on-chip compiles (a
# wedge verdict must not fire on a legitimate minutes-long compile);
# runs AFTER the measurement rungs — a quarantined device thread must
# not share a window with the A/B pair.
step serve_chaos_r6 1800 python -m raft_tpu.cli.serve_bench \
    --shapes 368x496 --requests 24 --submitters 2 --bucket-batch 4 \
    --chaos 2 --dispatch-timeout-ms 120000 --hang-ms 180000 \
    --breaker-backoff-ms 5000 --breaker-backoff-max-ms 600000 \
    --recover-s 300 --gather-ms 20 --log-dir /tmp/raft_serve_chaos_r6

# ---- AOT executable cache: load-vs-compile cold-start A/B (PR 16) ----
# the serialized-artifact seam against real-chip compile times: the
# cold leg compiles both hot-path buckets and STORES their serialized
# executables (summary: compiles=N, aot_misses=N); the warm leg is a
# fresh process against the same dir and must report compiles=0,
# aot_hits=N, compiles_avoided=N — the replica-rollout cold-start
# number is the wall_s delta between the two legs (on-chip compiles
# are minutes; the load is an I/O-bound deserialize). The chaos leg
# re-runs the corruption drill against the warm dir: every round
# corrupts the cached artifact before a recompiling bucket's load, and
# the drill must exit clean (miss-and-recompile, entry re-stored).
rm -rf /tmp/raft_aot_r6
step serve_export_r6_cold 2400 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 24 --submitters 2 \
    --bucket-batch 4 --deadline-ms 30000 --gather-ms 20 \
    --wire u8 --aot-cache /tmp/raft_aot_r6 \
    --log-dir /tmp/raft_serve_export_r6_cold
step serve_export_r6 2400 python -m raft_tpu.cli.serve_bench \
    --shapes 440x1024,368x496 --requests 24 --submitters 2 \
    --bucket-batch 4 --deadline-ms 30000 --gather-ms 20 \
    --wire u8 --aot-cache /tmp/raft_aot_r6 \
    --log-dir /tmp/raft_serve_export_r6
step serve_export_r6_chaos 2400 python -m raft_tpu.cli.serve_bench \
    --shapes 368x496 --requests 24 --submitters 2 --bucket-batch 4 \
    --chaos 2 --dispatch-timeout-ms 120000 --hang-ms 180000 \
    --breaker-backoff-ms 5000 --breaker-backoff-max-ms 600000 \
    --recover-s 300 --gather-ms 20 --aot-cache /tmp/raft_aot_r6
# the production round trip at the envelope shape: store through
# AOTCache, reload through the verified path, run, diff vs live jit
# (bitwise pin) — the refactored export cycle check (VERDICT r2 #7)
step export_cycle_r6 2400 python tools/export_cycle_check.py

# ---- multi-model registry: basic+small mixed-priority drill (PR 9) ---
# the two paper archs served side by side behind the ModelRegistry:
# basic is the accurate live tier, small the fast tier, traffic split
# 3:1 interactive:batch, plus a same-arch canary rollout on basic at
# 25% that promotes after traffic (update_weights swap — watch the
# summary's canary block report resolution=weights_swap and the
# per-model executables_live stay at the documented bucket counts).
# The per-model p50/p99 blocks are the REAL basic-vs-small latency
# tiering numbers the fast-tier case (Rethinking RAFT) needs in
# PROFILE.md; the CPU tier-1 drill only proves routing/accounting.
# Deadline sized for on-chip compiles of BOTH models' buckets plus
# the canary's (three envelopes compile in this window).
step serve_registry_r6 2400 python -m raft_tpu.cli.serve_bench \
    --models basic,small --shapes 440x1024,368x496 --requests 48 \
    --submitters 2 --bucket-batch 4 --priority-mix 3:1 --canary 0.25 \
    --deadline-ms 120000 --gather-ms 20 --iters 20 \
    --log-dir /tmp/raft_serve_registry_r6

# ---- SLO guardian: unattended rollout + admission budget (PR 10) -----
# the serve_registry_r6 traffic again, but the rollout verdict belongs
# to the SLOGuardian: the same-arch canary bakes for 30s against the
# live variant's window metrics (p99 ratio 2x + 500ms slack and a 5%
# error-rate margin absorb on-chip compile jitter; real breach = real
# rollback) and must auto-promote via weights_swap — watch the
# summary's guardian block for the decision + evidence windows, and
# the canary block for resolution=guardian_promote. The 32-token
# admission budget (8 reserved interactive) also gets its first
# real-hardware numbers: admission_rejected per model in the
# per-model blocks. Bake sized ABOVE the traffic run so the window
# sees the whole drill.
step serve_guardian_r6 2400 python -m raft_tpu.cli.serve_bench \
    --models basic,small --shapes 440x1024,368x496 --requests 48 \
    --submitters 2 --bucket-batch 4 --priority-mix 3:1 --canary 0.25 \
    --guardian \
    --slo p99_ratio:2.0,p99_slack_ms:500,err_rate:0.05,min_requests:5 \
    --bake-ms 30000 --admission-budget 32 --admission-reserve 8 \
    --deadline-ms 120000 --gather-ms 20 --iters 20 \
    --log-dir /tmp/raft_serve_guardian_r6

# ---- trace the loser's question: where did the fused step's time go ---
# (only worth a window slot once both A/B rungs have numbers)
if [ -e "$MARK/bench_g_gruxla" ] && [ -e "$MARK/bench_g_grufused" ]; then
    step trace_grufused 2400 python -m raft_tpu.cli.profile_step \
        --batch 8 --corr_impl softsel --corr_dtype bfloat16 --fused-loss \
        --gru_impl fused --steps 10 --trace-dir /tmp/raft_trace_r6
    step trace_summary_r6 1200 python -m raft_tpu.cli.trace_summary \
        /tmp/raft_trace_r6
fi

log "round6 runbook complete"
snap
commit_msmt "On-chip round-6 artifacts: gru_impl A/B ladder" ONCHIP_r06.log
