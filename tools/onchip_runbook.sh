#!/bin/bash
# On-chip measurement runbook — run when the axon tunnel is up.
# Executes everything "owed to the hardware" (BENCH_NOTES.md) in priority
# order, appending to /tmp/onchip_runbook.out. Each step is independently
# useful; a tunnel drop mid-way loses only the remaining steps.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_runbook.out}
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }

log "0 envelope"
timeout 2400 python -m raft_tpu.cli.envelope >> "$OUT" 2>&1

log "1 corr_bench chairs fwd"
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 --iters 20 >> "$OUT" 2>&1
log "2 corr_bench chairs grad"
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 --iters 20 --grad >> "$OUT" 2>&1

log "3 bench.py corr-impl shootout (winner becomes default)"
timeout 2400 python bench.py --steps 10 --corr-impl pallas >> "$OUT" 2>&1
timeout 2400 python bench.py --steps 10 --corr-impl onehot >> "$OUT" 2>&1

log "4 corr_bench 128x128 fwd+grad"
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 1 --hw 128 128 --iters 10 >> "$OUT" 2>&1
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 1 --hw 128 128 --iters 10 --grad >> "$OUT" 2>&1

log "5 profile_step trace"
timeout 2400 python -m raft_tpu.cli.profile_step --batch 6 --steps 10 --corr-impl pallas --trace-dir /tmp/raft_trace >> "$OUT" 2>&1

log "6 bench.py batch ladder with winner (edit default first if clear)"
timeout 2400 python bench.py --steps 10 --batches 8 6 --corr-impl pallas >> "$OUT" 2>&1

log "done"
