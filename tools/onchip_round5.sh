#!/bin/bash
# Round-5 on-chip runbook — the round-4 runbook re-armed for r5, now
# TIERED against the historical ~100-minute chip window (VERDICT r4
# item 7). Markers make every step resumable across windows; tiers just
# order the work and add commit points so even a short window ends with
# committed artifacts.
#
# Window-budget arithmetic (expected warm-cache durations from the r3
# logs; caps are worst-case timeouts, not estimates):
#   Tier A (the round's mandate, expect ~50 min, caps sum 95 min):
#     bare_bench        expect ~15-20 min  cap 2700 s
#     trained_parity    expect ~15-20 min  cap 2400 s
#     j_fused ladder    expect ~10-15 min  cap 2700 s  (b12/10/8)
#     -> commit after EACH of these (bench + parity already committed
#        individually; ladder rows committed at the tier boundary)
#   Tier B (defaults decision, expect ~35 min, caps sum 120 min):
#     i_softsel_b8      expect ~8 min      cap 1800 s
#     k_unroll2         expect ~10 min     cap 2400 s  (compile grows
#                                                       with factor)
#     m_fused_softsel   expect ~10 min     cap 2700 s
#     n_fused_unroll2   expect ~10 min     cap 2700 s
#     pick_defaults+bare reproduction      cap 2700 s (only if changed)
#   Tier C (secondary numbers, expect ~45 min, caps sum 160 min):
#     train_rate, infer_bf16/fp32/unroll2, softsel parity, corr_bench
#     s_bf16 + pallas_regime, trace + summary
#   Tier D (speculative / crash-poking, LAST):
#     k_unroll4 (no hardware signal yet suggests it helps), then the
#     crash bisect (deliberately reproduces the crash-on-exit mode).
# A single ~100-min window at expected durations lands A + most of B;
# markers carry the rest to the next window.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round5.out}
# env-overridable so the control flow can be dry-run in a scratch clone
MARK=${RAFT_R5_MARK:-/root/.cache/raft_tpu/r5_markers}
LADDER=${RAFT_R5_LADDER:-/root/.cache/raft_tpu/r5_ladder}
mkdir -p "$MARK" "$LADDER"
# seed with earlier measured rows so a slow r5 set can't downgrade the
# defaults pick below what is already proven
cp -n /root/.cache/raft_tpu/r3_ladder/*.json "$LADDER"/ 2>/dev/null || true
cp -n /root/.cache/raft_tpu/r4_ladder/*.json "$LADDER"/ 2>/dev/null || true
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
snap() { cp "$OUT" /root/repo/ONCHIP_r05.log 2>/dev/null || true; }
wait_chip() {
    for _ in 1 2 3 4 5; do
        if timeout -k 10 120 python -c \
            "import jax; assert jax.devices()[0].platform != 'cpu'" \
            >/dev/null 2>&1; then return 0; fi
        log "chip not answering; waiting 60s"
        sleep 60
    done
    return 1
}
step() {
    local name=$1 tmo=$2; shift 2
    if [ -e "$MARK/$name" ]; then log "skip $name (done)"; return 0; fi
    wait_chip || { log "SKIP $name (chip unavailable)"; return 1; }
    log "begin $name"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        touch "$MARK/$name"; log "done $name"
    else
        local rc=$?
        log "retry $name after 90s (rc=$rc)"
        sleep 90
        if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
            touch "$MARK/$name"; log "done $name (retry)"
        else
            log "FAILED rc=$? $name"
        fi
    fi
    snap
}
bench_cfg() {
    local tag=$1 tmo=$2; shift 2
    if [ -e "$MARK/bench_$tag" ]; then log "skip bench_$tag"; return 0; fi
    wait_chip || { log "SKIP bench_$tag (chip unavailable)"; return 1; }
    log "begin bench_$tag: $*"
    if timeout "$tmo" python bench.py --steps 10 "$@" \
            > "$LADDER/$tag.json" 2>> "$OUT"; then
        cat "$LADDER/$tag.json" >> "$OUT"
        touch "$MARK/bench_$tag"; log "done bench_$tag"
    else
        log "FAILED bench_$tag rc=$?"; cat "$LADDER/$tag.json" >> "$OUT"
    fi
    snap
}
commit_msmt() {  # measurement artifacts only — no source changes
    local msg=$1; shift
    for f in "$@"; do git add "$f" 2>/dev/null || true; done
    git diff --cached --quiet || git commit -q -m "$msg" -m \
        "No-Verification-Needed: measurement logs and records only"
}

# ======================= TIER A =======================================
# ---- A1. the driver-style bare bench, FIRST ---------------------------
if [ ! -e "$MARK/bare_bench" ]; then
    if wait_chip; then
        log "begin bare_bench (no flags, exactly as the driver runs it)"
        if timeout 2700 python bench.py \
                > "$LADDER/bare.json" 2>> "$OUT"; then
            cat "$LADDER/bare.json" >> "$OUT"
            # only a real nonzero number counts as done
            if python - "$LADDER/bare.json" <<'EOF'
import json, sys
row = json.load(open(sys.argv[1]))
sys.exit(0 if row.get("value", 0) > 0 else 1)
EOF
            then
                touch "$MARK/bare_bench"
                cp "$LADDER/bare.json" /root/repo/BENCH_r05_local.json
                snap
                commit_msmt \
                    "Record driver-style bare bench.py run for round 5" \
                    BENCH_r05_local.json ONCHIP_r05.log
                log "bare_bench committed"
            else
                log "bare_bench emitted a zero/failed row; will retry \
next window"
            fi
        else
            log "FAILED bare_bench rc=$?"
        fi
        snap
    fi
fi

# ---- A2. exact-precision trained parity -------------------------------
step trained_parity_exact 2400 python tools/trained_parity.py
if [ -e "$MARK/trained_parity_exact" ] \
        && [ ! -e "$MARK/trained_parity_committed" ]; then
    cp /root/.cache/raft_tpu/ref_ckpt/trained_parity.json \
        /root/repo/TRAINED_PARITY_onchip.json 2>/dev/null || true
    commit_msmt \
        "On-chip trained-weights parity at exact fp32 matmul precision" \
        TRAINED_PARITY_onchip.json ONCHIP_r05.log
    touch "$MARK/trained_parity_committed"
fi

# ---- A3. fused subpixel-domain loss: the highest-leverage ladder row --
# (frees the ~560 MB prediction stack + cotangent; b10 was the stack's
# OOM casualty, so try 12/10 before the proven 8)
bench_cfg j_fused 2700 --batches 12 10 8 --corr-dtype bfloat16 --no-remat \
    --fused-loss
commit_msmt "r5 tier A ladder rows" ONCHIP_r05.log

# ======================= TIER B =======================================
bench_cfg i_softsel_b8 1800 --batches 8 --corr-dtype bfloat16 --no-remat \
    --corr-impl softsel
bench_cfg k_unroll2 2400 --batches 8 --corr-dtype bfloat16 --no-remat \
    --scan-unroll 2
# compositions: the levers are independent (memory, lerp-chain,
# pipeline) so if two singles win, their product is the candidate
# default — measure it in THIS window instead of waiting a round
bench_cfg m_fused_softsel 2700 --batches 10 8 --corr-dtype bfloat16 \
    --no-remat --fused-loss --corr-impl softsel
bench_cfg n_fused_unroll2 2700 --batches 10 8 --corr-dtype bfloat16 \
    --no-remat --fused-loss --scan-unroll 2

# re-pick defaults; reproduce bare if they changed. The changed/decided
# state lives in MARKERS, not `git diff` (a tier commit would clear the
# diff and silently skip the reproduction the comment promises):
#   defaults_changed  = the pick rewrote BENCH_DEFAULTS.json; a bare
#                       reproduction is owed before it may be committed
#   defaults_decided  = committed-state is settled (unchanged pick, or
#                       reproduction landed)
step pick_defaults_r5 120 python tools/pick_bench_defaults.py "$LADDER"
if [ -e "$MARK/pick_defaults_r5" ] && [ ! -e "$MARK/defaults_decided" ] \
        && [ ! -e "$MARK/defaults_changed" ]; then
    if git diff --quiet BENCH_DEFAULTS.json; then
        touch "$MARK/defaults_decided"  # pick kept the proven defaults
    else
        touch "$MARK/defaults_changed"
        log "defaults re-picked - bare reproduction owed"
    fi
fi
if [ -e "$MARK/defaults_changed" ] && [ ! -e "$MARK/bare_bench_final" ]; then
    if wait_chip; then
        log "reproducing re-picked defaults with a bare run"
        if timeout 2700 python bench.py \
                > "$LADDER/bare_final.json" 2>> "$OUT"; then
            cat "$LADDER/bare_final.json" >> "$OUT"
            if python - "$LADDER/bare_final.json" <<'EOF'
import json, sys
row = json.load(open(sys.argv[1]))
sys.exit(0 if row.get("value", 0) > 0 else 1)
EOF
            then
                touch "$MARK/bare_bench_final" "$MARK/defaults_decided"
                cp "$LADDER/bare_final.json" /root/repo/BENCH_r05_local.json
                snap
                commit_msmt \
                    "Bare bench reproduction at the re-picked defaults" \
                    BENCH_r05_local.json BENCH_DEFAULTS.json ONCHIP_r05.log
            fi
        else
            log "FAILED bare_bench_final rc=$?"
        fi
        snap
    fi
fi
# only commit BENCH_DEFAULTS.json once its state is settled — re-picked
# defaults must never ship without their bare-run reproduction
if [ -e "$MARK/defaults_decided" ]; then
    commit_msmt "r5 tier B ladder rows + defaults" ONCHIP_r05.log \
        BENCH_DEFAULTS.json
else
    commit_msmt "r5 tier B ladder rows" ONCHIP_r05.log
fi

# ======================= TIER C =======================================
# ---- clean trainer steps/s + serving re-measure -----------------------
step train_rate 1800 python -m raft_tpu.cli.train --name r5rate \
    --stage chairs --mixed_precision --synthetic 64 --num_steps 220 \
    --val_freq 1000 --batch_size 8 --num_workers 4 \
    --checkpoint_dir /root/.cache/raft_tpu/r5_rate --log_dir runs
step infer_bf16_v2 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 \
    --corr_dtype bfloat16
step infer_fp32_v2 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024
# serving-side unroll probe: fwd-only, 20 iters — pipelining has more
# boundaries to cross here than in the 12-iter train step
step infer_bf16_unroll2 2400 python -m raft_tpu.cli.infer_bench \
    --hw 440 1024 --corr_dtype bfloat16 --scan_unroll 2
# softsel accuracy at trained weights (its bf16 selection GEMMs round
# the bilinear weights — pin the cost in the same window that measures
# its speed; torch flows come from the r3 cache)
step trained_parity_softsel 2400 python tools/trained_parity.py \
    --corr_impl softsel
# only a result the ON-CHIP step above actually produced may be labeled
# _onchip (an unguarded cp here once published CPU rehearsal numbers
# under this name — caught and reverted in r5)
if [ -e "$MARK/trained_parity_softsel" ]; then
    cp /root/.cache/raft_tpu/ref_ckpt/trained_parity_softsel.json \
        /root/repo/TRAINED_PARITY_softsel_onchip.json 2>/dev/null || true
fi
# isolated softsel rows give the per-lookup story for BENCH_NOTES
step s_bf16 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot softsel --grad --corr-dtype bfloat16
# the materialized-pyramid Pallas kernel's hypothesized regime is
# large-resolution serving: measure it at the sintel serving geometry
# or demote it to documented insurance (VERDICT item 6)
step pallas_regime 1800 python -m raft_tpu.cli.corr_bench --batch 1 \
    --hw 55 128 --iters 20 --impls onehot pallas

# ---- fresh trace at the current winner (next-bottleneck hunt) ---------
if ! TRACE_FLAGS=$(python tools/bench_default_flags.py --with-batch); then
    # tracing the wrong config would burn the window on a misleading
    # measurement — surface the failure and pin the known default
    log "bench_default_flags.py FAILED - tracing at --batch 8 fallback"
    TRACE_FLAGS="--batch 8"
fi
step trace_r5 2400 python -m raft_tpu.cli.profile_step $TRACE_FLAGS \
    --steps 10 --trace-dir /tmp/raft_trace_r5
step trace_summary_r5 1200 python -m raft_tpu.cli.trace_summary \
    /tmp/raft_trace_r5
commit_msmt "r5 tier C: trainer rate, serving rows, softsel parity, \
trace" ONCHIP_r05.log TRAINED_PARITY_softsel_onchip.json

# ======================= TIER D =======================================
# unroll4 is two speculative rungs past any hardware signal — only
# spend a window slot on it after everything above has numbers
bench_cfg k_unroll4 2700 --batches 8 --corr-dtype bfloat16 --no-remat \
    --scan-unroll 4
# the crash bisect LAST — it deliberately pokes the crash mode
step crash_bisect 5400 bash tools/crash_bisect.sh /tmp/crash_bisect.out
# (crash_bisect.sh shares the same marker dir via RAFT_R5_MARK)

log "round5 runbook complete"
snap
FINAL_FILES="ONCHIP_r05.log CRASH_BISECT_r05.log TRAINED_PARITY_onchip.json \
TRAINED_PARITY_softsel_onchip.json"
if [ -e "$MARK/defaults_decided" ]; then
    FINAL_FILES="$FINAL_FILES BENCH_DEFAULTS.json"
fi
commit_msmt "On-chip round-5 artifacts: ladder rows, parity, bisect" \
    $FINAL_FILES
