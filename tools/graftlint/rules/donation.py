"""R4 donation-discipline.

A train/optimizer state threaded through a jitted step WITHOUT
``donate_argnums`` doubles peak HBM: XLA must keep the input state
alive while materializing the output state. On the 15.75 GB v5e-1 that
is the difference between batch 8 fitting and an OOM ladder (bench.py's
survivability rules exist because of exactly this). The rule fires only
where the wrapped callable's signature is visible (a lambda or a
same-file def) and its first parameter is state-like — opaque
factory-call results (``jax.jit(make_train_step(...))``) are skipped
rather than guessed at.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..finding import Finding
from ..jitctx import Analysis, is_jit_callable, jit_call_kwargs

RULE = "R4"
NAME = "donation-discipline"

_STATE_NAMES = {"state", "train_state", "opt_state", "optimizer_state"}


def _first_param(fn: ast.AST) -> Optional[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return None
    params = args.posonlyargs + args.args
    if not params:
        return None
    first = params[0]
    if first.arg in ("self", "cls") and len(params) > 1:
        first = params[1]
    return first.arg


def _is_statelike(name: Optional[str]) -> bool:
    return name is not None and (
        name in _STATE_NAMES or name.endswith("_state"))


def _donates(kwargs) -> bool:
    return "donate_argnums" in kwargs or "donate_argnames" in kwargs


def check(a: Analysis) -> List[Finding]:
    out: List[Finding] = []
    # jax.jit(fn_or_lambda, ...) where the signature is visible
    for call in a.jit_calls:
        if not call.args:
            continue
        fn = call.args[0]
        target: Optional[ast.AST] = None
        if isinstance(fn, ast.Lambda):
            target = fn
        else:
            target = a.resolve_def(fn, call)
        if target is None:
            continue
        first = _first_param(target)
        if _is_statelike(first) and not _donates(jit_call_kwargs(call)):
            out.append(Finding(
                a.path, call.lineno, call.col_offset, RULE, NAME,
                f"jit wraps a function whose first parameter "
                f"'{first}' looks like a train/optimizer state but "
                "passes no donate_argnums — the old state stays live "
                "and peak HBM doubles; add donate_argnums=(0,) (or "
                "donate_argnames)"))
    # @jax.jit-decorated defs with a state-like first parameter
    for node in ast.walk(a.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if not is_jit_callable(dec):
                continue
            kwargs = (jit_call_kwargs(dec)
                      if isinstance(dec, ast.Call) else {})
            first = _first_param(node)
            if _is_statelike(first) and not _donates(kwargs):
                out.append(Finding(
                    a.path, node.lineno, node.col_offset, RULE, NAME,
                    f"@jit function '{node.name}' takes state-like "
                    f"first parameter '{first}' without "
                    "donate_argnums — add donate_argnums=(0,) or "
                    "rename if it is not a consumed state"))
    return out
