"""Rule registry: each module exposes RULE, NAME, and check(analysis)."""

from __future__ import annotations

from . import donation, exit_code, host_sync, lifecycle, retrace, \
    tracer_leak

ALL_RULES = (host_sync, tracer_leak, retrace, donation, lifecycle,
             exit_code)

RULE_IDS = {mod.RULE for mod in ALL_RULES}
