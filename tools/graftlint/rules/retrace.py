"""R3 retrace-hazard.

``jax.jit`` caches compiled programs on the IDENTITY of the wrapped
callable plus the hash of static arguments. Calling ``jax.jit`` inside
a loop body (or on a fresh ``lambda`` per iteration) creates a new
callable each pass, so every iteration pays a full trace+compile —
multi-minute on a remote TPU backend. Passing an unhashable value
(list/dict/set) in a ``static_argnums`` position raises at call time,
after the code already shipped.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..finding import Finding
from ..jitctx import Analysis, dotted, jit_call_kwargs

RULE = "R3"
NAME = "retrace-hazard"

_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)


def _static_positions(call: ast.Call) -> Tuple[int, ...]:
    """Literal int positions named by ``static_argnums``, else ()."""
    kw = jit_call_kwargs(call).get("static_argnums")
    if kw is None:
        return ()
    nodes = kw.elts if isinstance(kw, (ast.Tuple, ast.List)) else [kw]
    pos = []
    for n in nodes:
        if isinstance(n, ast.Constant) and isinstance(n.value, int):
            pos.append(n.value)
    return tuple(pos)


def _in_comprehension(a: Analysis, node: ast.AST) -> bool:
    cur = a.parents.get(node)
    while cur is not None:
        if isinstance(cur, _COMPREHENSIONS):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return False
        cur = a.parents.get(cur)
    return False


_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


def check(a: Analysis) -> List[Finding]:
    out: List[Finding] = []
    # (a) jit created inside a loop or comprehension body
    for call in a.jit_calls:
        if (a.enclosing_loop_same_scope(call) is not None
                or _in_comprehension(a, call)):
            what = ("a fresh lambda" if call.args
                    and isinstance(call.args[0], ast.Lambda)
                    else "the wrapped callable")
            out.append(Finding(
                a.path, call.lineno, call.col_offset, RULE, NAME,
                f"jax.jit called inside a loop: {what} is a new cache "
                "key every iteration, so each pass re-traces and "
                "re-compiles — hoist the jit out of the loop"))
        # (b) direct-invoke jit(f, static_argnums=...)(args...) with an
        # unhashable literal in a static position
        parent = a.parents.get(call)
        if isinstance(parent, ast.Call) and parent.func is call:
            for pos in _static_positions(call):
                if (pos < len(parent.args)
                        and isinstance(parent.args[pos], _UNHASHABLE)):
                    # anchor to the CALL line, not the argument's own
                    # line — pragmas live on the statement's first line
                    out.append(Finding(
                        a.path, parent.lineno, parent.col_offset,
                        RULE, NAME,
                        f"argument {pos} is marked static but is an "
                        "unhashable literal — jit static args are "
                        "cache keys and must hash"))
    # (c) call sites of names bound to jit(..., static_argnums=...)
    static_by_name: Dict[Tuple[ast.AST, str], Tuple[int, ...]] = {}
    for scope, bound in a.jit_bound.items():
        for name, call in bound.items():
            pos = _static_positions(call)
            if pos:
                static_by_name[(scope, name)] = pos
    if static_by_name:
        for node in ast.walk(a.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            for scope in a.scope_chain(node):
                pos = static_by_name.get((scope, name))
                if pos is None:
                    continue
                for p in pos:
                    if (p < len(node.args)
                            and isinstance(node.args[p], _UNHASHABLE)):
                        out.append(Finding(
                            a.path, node.lineno, node.col_offset,
                            RULE, NAME,
                            f"argument {p} of {name}(...) is static "
                            "but unhashable (list/dict/set) — this "
                            "raises at call time; pass a tuple or "
                            "hashable config"))
                break
    return out
