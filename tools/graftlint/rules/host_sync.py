"""R1 host-sync-in-hot-path.

A host sync (``block_until_ready``, ``jax.device_get``, ``np.asarray``,
``float(...)``/``int(...)``, ``.item()``) inside a jit-traced body is a
trace-time error waiting to happen; inside the training/bench step loop
it serializes host and device every iteration — on a remote backend that
caps the loop at ~1/RTT steps/s regardless of how fast the chip is
(BENCH_NOTES.md round 5 measured 0.72 steps/s against a ~3 steps/s
device from exactly this).

Hot-loop findings are limited to syncs that run UNCONDITIONALLY in the
loop body: a fetch guarded by ``if step % sum_freq == ...`` is the
sanctioned periodic-flush pattern (trainer.flush_metrics), not a bug.
"""

from __future__ import annotations

import ast
from typing import List

from ..finding import Finding
from ..jitctx import Analysis, dotted

RULE = "R1"
NAME = "host-sync-in-hot-path"

#: full dotted names that force a device->host round trip
SYNC_CALLS = {
    "jax.block_until_ready", "jax.device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
}
#: method names that do the same on any receiver
SYNC_METHODS = {"block_until_ready", "item", "tolist"}
#: builtins that concretize an array value
SYNC_BUILTINS = {"float", "int", "bool"}


def _sync_reason(node: ast.Call) -> str:
    name = dotted(node.func)
    if name in SYNC_CALLS:
        return f"{name}(...)"
    if (isinstance(node.func, ast.Attribute)
            and node.func.attr in SYNC_METHODS):
        return f".{node.func.attr}()"
    if (isinstance(node.func, ast.Name)
            and node.func.id in SYNC_BUILTINS and node.args
            and not isinstance(node.args[0], ast.Constant)):
        return f"{node.func.id}(...) on a non-literal"
    return ""


def check(a: Analysis) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(a.tree):
        if not isinstance(node, ast.Call):
            continue
        reason = _sync_reason(node)
        if not reason:
            continue
        if a.in_jitted_body(node):
            out.append(Finding(
                a.path, node.lineno, node.col_offset, RULE, NAME,
                f"host sync {reason} inside a jit-traced body — "
                "concretizes a tracer (or silently falls back to "
                "trace-time constants)"))
            continue
        loop = a.enclosing_hot_loop(node)
        if loop is not None and not a.under_if_within(node, loop):
            if reason.startswith(("np.", "numpy.")):
                # np.array/asarray on a HOST value is not a device
                # sync, but it is host work serialized into the step
                # loop (and a D2H fetch when the value is on device)
                detail = ("materializes on host every iteration — a "
                          "D2H sync if the value is a device array, "
                          "stalled dispatch either way; hoist it out "
                          "of the loop or guard it on a cadence")
            else:
                detail = ("serializes host and device every "
                          "iteration; fetch periodically under an "
                          "`if step % freq` guard instead")
            out.append(Finding(
                a.path, node.lineno, node.col_offset, RULE, NAME,
                f"unconditional host sync {reason} inside a loop that "
                f"drives a jit-compiled step — {detail}"))
    return out
