"""R2 tracer-leak.

``np.*`` math on a traced value inside a jit body either crashes at
trace time or — worse — silently evaluates once on trace-time constants
and bakes the result into the compiled program. Python ``print`` inside
a jit body runs at TRACE time only: it prints tracer reprs during the
first call and nothing ever again, which reads like a working log line
until retracing stops. Use ``jnp.*`` and ``jax.debug.print`` instead.
"""

from __future__ import annotations

import ast
from typing import List

from ..finding import Finding
from ..jitctx import Analysis, dotted

RULE = "R2"
NAME = "tracer-leak"

#: np attributes that are fine at trace time: dtypes, constants, and
#: introspection that works on tracers
_NP_ALLOWED = {
    "float16", "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "dtype", "finfo",
    "iinfo", "shape", "ndim", "pi", "inf", "nan", "newaxis", "e",
}
#: handled (and better diagnosed) by R1
_NP_R1 = {"asarray", "array"}


def check(a: Analysis) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(a.tree):
        if not isinstance(node, ast.Call) or not a.in_jitted_body(node):
            continue
        name = dotted(node.func)
        if name and name.split(".", 1)[0] in ("np", "numpy"):
            attr = name.split(".")[-1]
            if attr not in _NP_ALLOWED and attr not in _NP_R1:
                out.append(Finding(
                    a.path, node.lineno, node.col_offset, RULE, NAME,
                    f"{name}(...) inside a jit-traced body runs on the "
                    "host at trace time — use the jnp equivalent so it "
                    "stays in the compiled program"))
        elif isinstance(node.func, ast.Name) and node.func.id == "print":
            out.append(Finding(
                a.path, node.lineno, node.col_offset, RULE, NAME,
                "print(...) inside a jit-traced body fires at trace "
                "time only (tracer reprs once, then silence) — use "
                "jax.debug.print for runtime values"))
    return out
