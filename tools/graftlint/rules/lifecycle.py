"""R5 resource-lifecycle.

The round-5 advisor finding this rule generalizes: a watchdog daemon
armed with ``.start()`` whose ``.stop()`` runs only on the
normal-return path survives any exception — and later hard-kills the
host process with ``os._exit`` once its heartbeat goes stale
(ADVICE.md, trainer.py). Two checks:

- **paired start/stop**: if a function both ``X.start()``s and
  ``X.stop()``s the same object, at least one ``X.stop()`` must sit in
  a ``finally:`` suite (or the start must itself be inside a ``try``
  whose finally stops it) so the exception path disarms the resource;
- **daemon threads**: arming ``threading.Thread(..., daemon=True)`` in
  a function with no ``finally:`` at all leaks a live thread past every
  exception. Lifecycle-owning classes (defining ``stop``/``close``/
  ``shutdown``/``__exit__``) are exempt — the caller-side check above
  covers their users.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..finding import Finding
from ..jitctx import Analysis, dotted

RULE = "R5"
NAME = "resource-lifecycle"

_STOPPISH = {"stop", "close", "shutdown", "__exit__", "join"}


def _recv_name(call: ast.Call) -> Optional[str]:
    """Dotted receiver of a method call: ``a.b.start()`` -> "a.b"."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return None


def _is_daemon_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    if dotted(node.func) not in ("threading.Thread", "Thread"):
        return False
    for kw in node.keywords:
        if (kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True):
            return True
    return False


def _class_owns_lifecycle(cls: Optional[ast.ClassDef]) -> bool:
    if cls is None:
        return False
    names = {n.name for n in cls.body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    return bool(names & _STOPPISH)


def _finally_covers_arming(a, fn: ast.AST, call: ast.Call) -> bool:
    """A try/finally only excuses arming a daemon if it can actually
    shut it down: the arming must be inside the try, or the try must
    come after it (the loader.py pattern — threads started, then the
    consume loop's finally signals stop). A finally that completed
    BEFORE the arming covers nothing."""
    cur = a.parents.get(call)
    while cur is not None and cur is not fn:
        if isinstance(cur, ast.Try) and cur.finalbody:
            return True
        cur = a.parents.get(cur)
    # same-scope walk only: a finally inside a NESTED function can
    # never run the outer thread's shutdown
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if (isinstance(node, ast.Try) and node.finalbody
                and node.lineno >= call.lineno):
            return True
        todo.extend(ast.iter_child_nodes(node))
    return False


def check(a: Analysis) -> List[Finding]:
    out: List[Finding] = []
    # group method calls per enclosing function scope
    per_scope: Dict[ast.AST, List[ast.Call]] = {}
    for node in ast.walk(a.tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            per_scope.setdefault(a.scope_of(node), []).append(node)

    for scope, calls in per_scope.items():
        starts: Dict[str, List[ast.Call]] = {}
        stops: Dict[str, List[ast.Call]] = {}
        for call in calls:
            recv = _recv_name(call)
            if recv is None:
                continue
            if call.func.attr == "start":
                starts.setdefault(recv, []).append(call)
            elif call.func.attr == "stop":
                stops.setdefault(recv, []).append(call)
        for recv, start_calls in starts.items():
            if recv not in stops:
                continue
            if any(a.in_finally(s) for s in stops[recv]):
                continue
            for s in start_calls:
                out.append(Finding(
                    a.path, s.lineno, s.col_offset, RULE, NAME,
                    f"{recv}.start() is armed but every {recv}.stop() "
                    "is on the normal-return path only — an exception "
                    "leaves the resource live (a watchdog will later "
                    "hard-kill the process); move stop() into a "
                    "try/finally"))

    # daemon-thread arming outside any try/finally
    daemon_names: Dict[ast.AST, Set[str]] = {}
    for node in ast.walk(a.tree):
        if isinstance(node, ast.Assign) and _is_daemon_thread_ctor(
                node.value):
            for tgt in node.targets:
                name = tgt.id if isinstance(tgt, ast.Name) else dotted(tgt)
                if name:
                    daemon_names.setdefault(
                        a.scope_of(node), set()).add(name)

    def _flag_daemon(call: ast.Call) -> None:
        scope = a.scope_of(call)
        if isinstance(scope, ast.Module):
            return  # module-level arming is process-lifetime by intent
        if _class_owns_lifecycle(a.enclosing_class(scope)):
            return
        if _finally_covers_arming(a, scope, call):
            return
        out.append(Finding(
            a.path, call.lineno, call.col_offset, RULE, NAME,
            "daemon thread armed in a function with no try/finally — "
            "an exception after this point leaks a live watcher "
            "thread; arm it inside try/finally (or own it in a class "
            "with a stop())"))

    for node in ast.walk(a.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "start"):
            continue
        if _is_daemon_thread_ctor(node.func.value):
            _flag_daemon(node)  # threading.Thread(daemon=True).start()
            continue
        recv = _recv_name(node)
        if recv is None:
            continue
        for scope in a.scope_chain(node):
            if recv in daemon_names.get(scope, set()):
                _flag_daemon(node)
                break
    return out
