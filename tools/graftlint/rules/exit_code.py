"""R6 exit-code-discipline.

Runbooks and drivers branch on exit codes: ``WEDGED_EXIT_CODE`` (3,
utils/watchdog.py) means "backend wedged — re-probe, don't sleep out
your timeout". The round-5 advisor caught bench.py exiting 2 for the
SAME failure mode, splitting one condition across two codes. Any raw
integer to ``os._exit`` (and any distinctive code >= 2 to
``sys.exit``) must be a named, shared constant; ``sys.exit(0)`` /
``sys.exit(1)`` stay the conventional success/failure idiom.
"""

from __future__ import annotations

import ast
from typing import List

from ..finding import Finding
from ..jitctx import Analysis, dotted

RULE = "R6"
NAME = "exit-code-discipline"

_EXITS = {"os._exit", "sys.exit", "exit", "_exit"}


def check(a: Analysis) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(a.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name not in _EXITS or not node.args:
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, int)
                and not isinstance(arg.value, bool)):
            continue
        code = arg.value
        hard = name.endswith("_exit")
        if hard or code >= 2:
            out.append(Finding(
                a.path, node.lineno, node.col_offset, RULE, NAME,
                f"{name}({code}) uses a raw integer exit code — "
                "runbooks branch on these; use the shared named "
                "constant (e.g. raft_tpu.utils.watchdog."
                "WEDGED_EXIT_CODE) so one failure mode maps to one "
                "code"))
    return out
