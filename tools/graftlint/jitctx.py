"""Shared AST analysis for graftlint rules: jit contexts and hot loops.

Every rule needs the same three questions answered about a node:

1. is it inside a function body that XLA will trace (``@jax.jit``,
   ``jax.jit(fn)``, ``jax.jit(lambda ...)``, ``pjit``,
   ``partial(jax.jit, ...)``)?
2. is it inside a loop that drives a jit-compiled step function (the
   trainer/bench hot loop, where one stray host sync serializes the
   whole pipeline)?
3. what name does a call target resolve to, dotted ("jax.device_get",
   "hang_watch.stop")?

This module computes all of that once per file into an :class:`Analysis`
object the rule modules share. Pure stdlib ``ast`` — graftlint must lint
files that import jax without importing jax itself.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

#: callables whose invocation means "trace this function with XLA"
JIT_NAMES = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "jax.experimental.pjit.pjit",
}
PARTIAL_NAMES = {"partial", "functools.partial"}

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_LOOPS = (ast.For, ast.AsyncFor, ast.While)


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> "a.b.c"; None for anything not a plain name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_callable(node: ast.AST) -> bool:
    """True if ``node`` itself names a jit transform (decorator form)."""
    name = dotted(node)
    if name in JIT_NAMES:
        return True
    # partial(jax.jit, static_argnums=...) used as a decorator factory
    if isinstance(node, ast.Call) and dotted(node.func) in PARTIAL_NAMES:
        return bool(node.args) and dotted(node.args[0]) in JIT_NAMES
    return False


def is_jit_call(node: ast.AST) -> bool:
    """True for a ``Call`` node that invokes a jit transform on a fn."""
    if not isinstance(node, ast.Call):
        return False
    if dotted(node.func) in JIT_NAMES:
        return True
    # partial(jax.jit, ...)(fn) — the outer call's func is the partial
    return is_jit_callable(node.func) and not (
        dotted(node.func) in JIT_NAMES)


def jit_call_kwargs(call: ast.Call) -> Dict[str, ast.expr]:
    """Keyword args of a jit call, folding in a partial's keywords."""
    kws = {kw.arg: kw.value for kw in call.keywords if kw.arg}
    if isinstance(call.func, ast.Call):  # partial(jax.jit, kw=..)(fn)
        for kw in call.func.keywords:
            if kw.arg:
                kws.setdefault(kw.arg, kw.value)
    return kws


class Analysis:
    """One-pass per-file analysis shared by all rules."""

    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.source = source
        self.path = path
        self.lines = source.splitlines()
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        #: function/lambda nodes whose body is traced by jit
        self.jitted_bodies: Set[ast.AST] = set()
        #: jit Call nodes (``jax.jit(...)`` invocations, not decorators)
        self.jit_calls: List[ast.Call] = []
        #: scope node -> {name: jit Call} for ``name = jax.jit(...)``
        self.jit_bound: Dict[ast.AST, Dict[str, ast.Call]] = {}
        #: loops whose body invokes a jit-bound callable
        self.hot_loops: Set[ast.AST] = set()

        self._collect_defs()
        self._collect_jit()
        self._collect_hot_loops()

    # -- scopes -----------------------------------------------------------

    def scope_of(self, node: ast.AST) -> ast.AST:
        """Nearest enclosing function/lambda, else the module."""
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, _SCOPES):
            cur = self.parents.get(cur)
        return cur if cur is not None else self.tree

    def scope_chain(self, node: ast.AST) -> List[ast.AST]:
        """[innermost function, ..., module] enclosing ``node``."""
        chain = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = self.parents.get(cur)
            if cur is None:
                break
            if isinstance(cur, _SCOPES) or isinstance(cur, ast.Module):
                chain.append(cur)
        if not chain or not isinstance(chain[-1], ast.Module):
            chain.append(self.tree)
        return chain

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_loop_same_scope(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest For/While around ``node`` not crossing a function."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _LOOPS):
                return cur
            if isinstance(cur, _SCOPES):
                return None
            cur = self.parents.get(cur)
        return None

    def under_if_within(self, node: ast.AST, stop: ast.AST) -> bool:
        """Is ``node`` guarded by an ``if`` somewhere below ``stop``?"""
        cur = self.parents.get(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, ast.If):
                return True
            cur = self.parents.get(cur)
        return False

    def in_finally(self, node: ast.AST) -> bool:
        """Is ``node`` inside some ``finally:`` suite?"""
        cur, child = self.parents.get(node), node
        while cur is not None:
            if isinstance(cur, ast.Try):
                probe: Optional[ast.AST] = child
                while probe is not None and probe is not cur:
                    if probe in cur.finalbody:
                        return True
                    probe = self.parents.get(probe)
            child, cur = cur, self.parents.get(cur)
        return False

    # -- jit discovery ----------------------------------------------------

    def _collect_defs(self) -> None:
        # name -> FunctionDef, indexed per scope, for jit(Name) resolution
        self._defs: Dict[ast.AST, Dict[str, ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self.scope_of(node)
                self._defs.setdefault(scope, {})[node.name] = node

    def resolve_def(self, name_node: ast.AST,
                    at: ast.AST) -> Optional[ast.AST]:
        """Resolve a ``Name`` to a FunctionDef visible from ``at``."""
        if not isinstance(name_node, ast.Name):
            return None
        for scope in self.scope_chain(at):
            hit = self._defs.get(scope, {}).get(name_node.id)
            if hit is not None:
                return hit
        return None

    def _collect_jit(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(is_jit_callable(d) for d in node.decorator_list):
                    self.jitted_bodies.add(node)
            if is_jit_call(node):
                self.jit_calls.append(node)
                if node.args:
                    fn = node.args[0]
                    if isinstance(fn, ast.Lambda):
                        self.jitted_bodies.add(fn)
                    else:
                        target = self.resolve_def(fn, node)
                        if target is not None:
                            self.jitted_bodies.add(target)
        # names bound to jit results: step_fn = jax.jit(...), incl.
        # dotted targets (self._fn = jax.jit(serve))
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and is_jit_call(node.value):
                for tgt in node.targets:
                    name = tgt.id if isinstance(tgt, ast.Name) \
                        else dotted(tgt)
                    if name:
                        scope = self.scope_of(node)
                        self.jit_bound.setdefault(scope, {})[name] = \
                            node.value

    def jitted_fn_of(self, node: ast.AST) -> Optional[ast.AST]:
        """Innermost enclosing function whose body jit traces, if any."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = self.parents.get(cur)
            if cur in self.jitted_bodies:
                return cur
        return None

    def in_jitted_body(self, node: ast.AST) -> bool:
        return self.jitted_fn_of(node) is not None

    # -- hot loops --------------------------------------------------------

    def _visible_jit_names(self, node: ast.AST) -> Set[str]:
        names: Set[str] = set()
        for scope in self.scope_chain(node):
            names.update(self.jit_bound.get(scope, {}))
        return names

    def _collect_hot_loops(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, _LOOPS):
                continue
            jit_names = self._visible_jit_names(node)
            if not jit_names:
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and dotted(sub.func) in jit_names):
                    self.hot_loops.add(node)
                    break

    def enclosing_hot_loop(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest hot loop around ``node`` within the same function."""
        cur = self.parents.get(node)
        while cur is not None:
            if cur in self.hot_loops:
                return cur
            if isinstance(cur, _SCOPES):
                return None
            cur = self.parents.get(cur)
        return None


def analyze(source: str, path: str) -> Analysis:
    return Analysis(ast.parse(source, filename=path), source, path)
