"""graftlint — AST-based JAX/TPU invariant checker for this repo.

Six rules, all pure-stdlib ``ast`` (linting files that import jax must
not itself import jax):

- R1 host-sync-in-hot-path   — D2H syncs in jit bodies / step loops
- R2 tracer-leak             — np.* math or print on traced values
- R3 retrace-hazard          — jit-in-loop, unhashable static args
- R4 donation-discipline     — state-threading jits w/o donate_argnums
- R5 resource-lifecycle      — start()/daemon threads w/o try/finally
- R6 exit-code-discipline    — raw integer exit codes

Run ``python -m tools.graftlint --help`` from the repo root; the tier-1
gate is ``tests/test_graftlint.py``.
"""

from .core import (apply_baseline, lint_file, lint_paths, load_baseline,
                   main, write_baseline)
from .finding import Finding

__all__ = ["Finding", "apply_baseline", "lint_file", "lint_paths",
           "load_baseline", "main", "write_baseline"]
