"""graftlint driver: walk files, run rules, apply pragmas + baseline.

Usage (from the repo root)::

    python -m tools.graftlint raft_tpu bench.py tools tests \
        --baseline tools/graftlint/baseline.json

Exit codes: 0 clean (modulo baseline), 1 new findings, 2 usage/parse
error. ``--json`` prints a machine-readable findings list instead of
the human one; ``--write-baseline`` regenerates the grandfather file
from the current findings (the burn-down workflow: fix a finding, then
regenerate — the baseline only ever shrinks).

Suppression: a ``# graftlint: disable=R1,R5`` comment on the line a
finding anchors to (the statement's FIRST line for multi-line
statements) suppresses those rules there; ``disable=all`` suppresses
every rule on that line. Directories named in ``_EXCLUDED_DIRS``
(intentionally-violating lint fixtures, caches) are skipped when
walking, but a file passed explicitly on the command line is always
linted — that is how the fixture tests exercise the rules.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .finding import Finding
from .jitctx import Analysis

#: directory basenames never entered when walking a directory argument
#: (graftaudit_fixtures: graftaudit's intentionally-violating audit
#: fixtures, the artifact-tier analog of graftlint_fixtures)
_EXCLUDED_DIRS = {"__pycache__", ".git", "graftlint_fixtures",
                  "graftaudit_fixtures", "node_modules", ".venv"}

# rule list only — a trailing bare-word justification ("disable=R5
# process-lifetime by design") must not be swallowed into the rule id
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable="
    r"(all|[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand dir args to ``**/*.py`` (minus excluded dirs); keep
    explicit file args verbatim (even non-.py: caller's choice)."""
    out: List[str] = []
    seen = set()

    def add(path: str) -> None:
        key = os.path.normpath(path)
        if key not in seen:   # a file named explicitly AND reached by a
            seen.add(key)     # dir walk must lint once, not twice
            out.append(path)

    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _EXCLUDED_DIRS)
                for f in sorted(files):
                    if f.endswith(".py"):
                        add(os.path.join(root, f))
        else:
            add(p)
    return out


def parse_pragmas(source: str) -> Dict[int, Optional[set]]:
    """line number -> set of disabled rule ids (None = all rules).

    Tokenized, not regexed over raw lines: the pragma must live in an
    actual COMMENT token — a string literal that merely CONTAINS
    "graftlint: disable=..." must not suppress findings on its line."""
    import io
    import tokenize

    pragmas: Dict[int, Optional[set]] = {}
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return pragmas   # unparsable files already yield E1 findings
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        spec = m.group(1).strip()
        line = tok.start[0]
        if spec.lower() == "all":
            pragmas[line] = None
        else:
            pragmas[line] = {r.strip().upper() for r in spec.split(",")
                             if r.strip()}
    return pragmas


def lint_file(path: str, rules=None) -> List[Finding]:
    """All findings for one file, pragma-filtered, sorted by position."""
    from .rules import ALL_RULES
    rules = ALL_RULES if rules is None else rules
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as exc:
        return [Finding(path, 0, 0, "E0", "unreadable", str(exc))]
    try:
        analysis = Analysis(ast.parse(source, filename=path), source,
                            path)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, exc.offset or 0, "E1",
                        "syntax-error", exc.msg or "syntax error")]
    pragmas = parse_pragmas(source)
    findings: List[Finding] = []
    for mod in rules:
        findings.extend(mod.check(analysis))
    kept = []
    for f in findings:
        disabled = pragmas.get(f.line)
        if f.line in pragmas and (disabled is None or f.rule in disabled):
            continue
        kept.append(f)
    return sorted(kept, key=lambda f: (f.line, f.col, f.rule))


# -- parse cache + parallel walk ------------------------------------------

_SIG_CACHE: List[str] = []


def _rules_signature() -> str:
    """Content hash of the whole graftlint package: editing any rule
    (or this driver) invalidates every cache entry — a cache must never
    outlive the code that produced it."""
    if not _SIG_CACHE:
        import hashlib

        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.abspath(__file__))
        for root, dirs, files in os.walk(pkg):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for f in sorted(files):
                if f.endswith(".py"):
                    with open(os.path.join(root, f), "rb") as fh:
                        h.update(f.encode() + b"\0" + fh.read())
        _SIG_CACHE.append(h.hexdigest()[:16])
    return _SIG_CACHE[0]


def default_cache_path() -> str:
    root = os.environ.get("RAFT_GRAFTLINT_CACHE")
    if root:
        return root
    home = os.path.expanduser("~")
    base = (os.path.join(home, ".cache") if home != "~"
            else os.path.join(os.sep, "tmp"))
    return os.path.join(base, "raft_tpu", "graftlint_cache.json")


def _load_cache(path: str) -> Dict:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("sig") == _rules_signature():
            return data
    except (OSError, ValueError):
        pass
    return {"sig": _rules_signature(), "files": {}}


def _save_cache(path: str, cache: Dict) -> None:
    """Atomic, last-writer-wins: concurrent gate runs (pytest spawns
    several) may each write; any complete file is a valid cache."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cache, f)
        os.replace(tmp, path)
    except OSError:
        pass     # a cache is an accelerator, never a correctness gate


def _rule_ids(rules) -> Optional[List[str]]:
    return None if rules is None else sorted(m.RULE for m in rules)


def _lint_one(job: Tuple[str, Optional[List[str]]]) -> List[Finding]:
    """Pool worker: rule MODULES don't pickle, ids do."""
    path, ids = job
    rules = None
    if ids is not None:
        from .rules import ALL_RULES
        rules = [m for m in ALL_RULES if m.RULE in set(ids)]
    return lint_file(path, rules=rules)


def lint_paths(paths: Sequence[str], rules=None,
               cache_path: Optional[str] = None,
               jobs: int = 1) -> List[Finding]:
    """Lint, optionally with a content-hash parse cache and a process
    pool over the cache misses. Cache entries key on (path, sha256 of
    the file bytes, active rule ids) under the package-wide rules
    signature, so an edit to a file, a rule filter, or the linter
    itself can never replay stale findings."""
    import hashlib

    files = collect_files(paths)
    findings_by_file: Dict[str, List[Finding]] = {}
    misses: List[str] = []
    cache = hashes = None
    ids = _rule_ids(rules)
    if cache_path:
        cache = _load_cache(cache_path)
        hashes = {}
        rkey = ",".join(ids) if ids is not None else "*"
        for path in files:
            try:
                with open(path, "rb") as f:
                    digest = hashlib.sha256(f.read()).hexdigest()
            except OSError:
                misses.append(path)   # unreadable: E0 via lint_file
                continue
            hashes[path] = digest
            # ABSOLUTE key paths: the default cache is user-global, so
            # cwd-relative keys from two working directories would
            # collide and evict each other
            entry = cache["files"].get(
                f"{os.path.abspath(path)}|{digest}|{rkey}")
            if entry is None:
                misses.append(path)
            else:
                findings_by_file[path] = [Finding(**d) for d in entry]
    else:
        misses = list(files)

    if jobs > 1 and len(misses) > 1:
        import multiprocessing

        with multiprocessing.Pool(min(jobs, len(misses))) as pool:
            linted = pool.map(_lint_one, [(p, ids) for p in misses])
    else:
        linted = [lint_file(p, rules=rules) for p in misses]
    for path, fs in zip(misses, linted):
        findings_by_file[path] = fs

    if cache is not None:
        rkey = ",".join(ids) if ids is not None else "*"
        for path, fs in zip(misses, linted):
            digest = hashes.get(path)
            if digest is not None:
                cache["files"][
                    f"{os.path.abspath(path)}|{digest}|{rkey}"
                ] = [f.__dict__ for f in fs]
        # evict dead weight — without this the shared user-level file
        # grows forever: entries for a file seen this run under a
        # superseded digest (any rule filter), and entries whose file
        # no longer exists at all (deleted/renamed paths; keys are
        # absolute, so the exists() check is cwd-independent)
        current = {os.path.abspath(p): d for p, d in hashes.items()}
        alive: Dict[str, bool] = {}
        for key in list(cache["files"]):
            path, digest = key.split("|", 2)[:2]
            if path in current:
                if digest != current[path]:
                    del cache["files"][key]
            else:
                if path not in alive:
                    alive[path] = os.path.exists(path)
                if not alive[path]:
                    del cache["files"][key]
        _save_cache(cache_path, cache)

    out: List[Finding] = []
    for path in files:
        out.extend(findings_by_file.get(path, []))
    return out


# -- baseline -------------------------------------------------------------

# keyed on (mtime, size) so library users that lint across edits (a
# pytest process, an editor integration) never key a baseline entry
# off stale content
_LINES_CACHE: Dict[str, Tuple[Tuple[float, int], List[str]]] = {}


def _code_line(finding: Finding) -> str:
    try:
        st = os.stat(finding.path)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        return ""
    cached = _LINES_CACHE.get(finding.path)
    if cached is None or cached[0] != stamp:
        try:
            with open(finding.path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            lines = []
        _LINES_CACHE[finding.path] = (stamp, lines)
    else:
        lines = cached[1]
    if 1 <= finding.line <= len(lines):
        return lines[finding.line - 1].strip()
    return ""


def finding_key(finding: Finding) -> Tuple[str, str, str]:
    return finding.key(_code_line(finding))


def load_baseline(path: str) -> Counter:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Counter(
        (e["path"].replace("\\", "/"), e["rule"], e["code"])
        for e in data.get("findings", []))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    entries = [{"path": k[0], "rule": k[1], "code": k[2]}
               for k in sorted(finding_key(f) for f in findings)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "comment": "graftlint grandfathered findings — burn down, "
                       "never grow; regenerate with --write-baseline "
                       "after fixing one",
            "findings": entries,
        }, f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: List[Finding], baseline: Counter,
                   linted_paths: Optional[Iterable[str]] = None,
                   ) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """Returns (new findings, stale baseline keys).

    Stale entries are NOT a free pass: an unconsumed entry would
    silently grandfather the next reintroduction of that exact line,
    so the CLI fails on them and demands a regenerate (the baseline
    must only ever shrink, and shrink EXPLICITLY). An entry whose file
    was not in ``linted_paths`` at all (a partial run) is merely
    unchecked, not stale; ``linted_paths=None`` treats every
    unconsumed entry as stale."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        k = finding_key(f)
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    if linted_paths is not None:
        linted = {os.path.normpath(p).replace("\\", "/")
                  for p in linted_paths}
        checked = (lambda k: os.path.normpath(k[0]).replace("\\", "/")
                   in linted)
    else:
        checked = (lambda k: True)
    stale = sorted(k for k, n in remaining.items() if checked(k)
                   for _ in range(n))
    return new, stale


# -- CLI ------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based JAX/TPU invariant checker (rules R1-R6; "
                    "see tools/graftlint/rules/).")
    p.add_argument("paths", nargs="+",
                   help="files and/or directories to lint")
    p.add_argument("--baseline", metavar="JSON",
                   help="grandfather file: matching findings don't fail "
                        "the run (burn-down workflow)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (list of findings)")
    p.add_argument("--write-baseline", metavar="JSON",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--rules", metavar="R1,R2,...",
                   help="run only these rule ids")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="parse/lint cache misses across N processes "
                        "(default 1: in-process)")
    p.add_argument("--cache", metavar="JSON", default=None,
                   help="parse-cache file (default: "
                        "$RAFT_GRAFTLINT_CACHE or "
                        "~/.cache/raft_tpu/graftlint_cache.json); "
                        "entries key on file content hash + active "
                        "rules + a hash of the linter itself, so the "
                        "cache can never replay stale findings")
    p.add_argument("--no-cache", action="store_true",
                   help="lint every file from scratch")
    args = p.parse_args(argv)

    if args.jobs < 1:
        print("graftlint: --jobs must be >= 1", file=sys.stderr)
        return 2
    cache_path = None if args.no_cache \
        else (args.cache or default_cache_path())

    rules = None
    if args.rules:
        from .rules import ALL_RULES
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [m for m in ALL_RULES if m.RULE in want]
        unknown = want - {m.RULE for m in rules}
        if unknown:
            print(f"graftlint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    if args.write_baseline and args.rules:
        # a rule-filtered regenerate would silently drop every other
        # rule's grandfathered entries and fail the next full gate run
        print("graftlint: refusing --write-baseline with --rules — "
              "regenerate from a full-rule run over the gate's paths",
              file=sys.stderr)
        return 2

    findings = lint_paths(args.paths, rules=rules,
                          cache_path=cache_path, jobs=args.jobs)
    hard_errors = [f for f in findings if f.rule.startswith("E")]

    if args.write_baseline:
        write_baseline(args.write_baseline,
                       [f for f in findings
                        if not f.rule.startswith("E")])
        print(f"graftlint: wrote {len(findings) - len(hard_errors)} "
              f"finding(s) to {args.write_baseline}; pass the SAME "
              "paths as the tier-1 gate (raft_tpu bench.py tools "
              "tests) or the gate will fail on entries this run "
              "never saw", file=sys.stderr)
        return 0

    stale: List[Tuple[str, str, str]] = []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as exc:
            print(f"graftlint: unreadable baseline "
                  f"{args.baseline}: {exc}", file=sys.stderr)
            return 2
        if rules is not None:
            # entries for rules outside the active filter can neither
            # be consumed nor meaningfully checked — a --rules R5 run
            # must not call the untouched R1 entries stale
            active = {m.RULE for m in rules}
            baseline = Counter({k: v for k, v in baseline.items()
                                if k[1] in active})
        findings, stale = apply_baseline(
            findings, baseline, linted_paths=collect_files(args.paths))

    if args.as_json:
        # stale entries ride in the same list (rule B0) so a machine
        # consumer sees WHY the run failed, not `[]` with rc=1
        print(json.dumps([{
            "path": f.path, "line": f.line, "col": f.col,
            "rule": f.rule, "name": f.name, "message": f.message,
        } for f in findings] + [{
            "path": k[0], "line": 0, "col": 0, "rule": "B0",
            "name": "stale-baseline",
            "message": f"stale baseline entry for {k[1]}: {k[2]!r} — "
                       "regenerate with --write-baseline",
        } for k in stale], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftlint: {len(findings)} new finding(s)",
                  file=sys.stderr)
    if stale:
        # a lingering entry would grandfather the NEXT reintroduction
        # of that exact line — fail until the baseline is regenerated
        for k in stale:
            print(f"graftlint: stale baseline entry {k[0]} [{k[1]}] "
                  f"{k[2]!r}", file=sys.stderr)
        print(f"graftlint: {len(stale)} stale baseline entr(y/ies) — "
              "the finding was fixed (good!) but the entry must go: "
              "regenerate with --write-baseline so it cannot "
              "grandfather a future reintroduction", file=sys.stderr)
    return 1 if (findings or stale) else 0
