"""graftlint driver: walk files, run rules, apply pragmas + baseline.

Usage (from the repo root)::

    python -m tools.graftlint raft_tpu bench.py tools tests \
        --baseline tools/graftlint/baseline.json

Exit codes: 0 clean (modulo baseline), 1 new findings, 2 usage/parse
error. ``--json`` prints a machine-readable findings list instead of
the human one; ``--write-baseline`` regenerates the grandfather file
from the current findings (the burn-down workflow: fix a finding, then
regenerate — the baseline only ever shrinks).

Suppression: a ``# graftlint: disable=R1,R5`` comment on the line a
finding anchors to (the statement's FIRST line for multi-line
statements) suppresses those rules there; ``disable=all`` suppresses
every rule on that line. Directories named in ``_EXCLUDED_DIRS``
(intentionally-violating lint fixtures, caches) are skipped when
walking, but a file passed explicitly on the command line is always
linted — that is how the fixture tests exercise the rules.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

try:
    from tools import lintcache
except ImportError:          # invoked as a top-level package (tests
    import lintcache         # insert the repo root on sys.path)

from .finding import Finding
from .jitctx import Analysis


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand dir args to ``**/*.py`` (minus the shared excluded dirs:
    the intentionally-violating *_fixtures trees, caches); keep
    explicit file args verbatim (even non-.py: caller's choice)."""
    return lintcache.collect_files(paths)


def parse_pragmas(source: str) -> Dict[int, Optional[set]]:
    """line number -> set of disabled rule ids (None = all rules).

    Tokenized, not regexed over raw lines: the pragma must live in an
    actual COMMENT token — a string literal that merely CONTAINS
    "graftlint: disable=..." must not suppress findings on its line."""
    return lintcache.parse_pragmas(source, "graftlint")


def lint_file(path: str, rules=None) -> List[Finding]:
    """All findings for one file, pragma-filtered, sorted by position."""
    from .rules import ALL_RULES
    rules = ALL_RULES if rules is None else rules
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as exc:
        return [Finding(path, 0, 0, "E0", "unreadable", str(exc))]
    try:
        analysis = Analysis(ast.parse(source, filename=path), source,
                            path)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, exc.offset or 0, "E1",
                        "syntax-error", exc.msg or "syntax error")]
    pragmas = parse_pragmas(source)
    findings: List[Finding] = []
    for mod in rules:
        findings.extend(mod.check(analysis))
    kept = []
    for f in findings:
        disabled = pragmas.get(f.line)
        if f.line in pragmas and (disabled is None or f.rule in disabled):
            continue
        kept.append(f)
    return sorted(kept, key=lambda f: (f.line, f.col, f.rule))


# -- parse cache + parallel walk (tools/lintcache machinery) --------------

def _rules_signature() -> str:
    """Content hash of the whole graftlint package PLUS the shared
    lintcache module: editing any rule, this driver, or the cache
    machinery itself invalidates every cache entry — a cache must never
    outlive the code that produced it."""
    return lintcache.package_signature(
        os.path.dirname(os.path.abspath(__file__)),
        lintcache.__file__)


def default_cache_path() -> str:
    return lintcache.default_cache_path("RAFT_GRAFTLINT_CACHE",
                                        "graftlint_cache.json")


def _rule_ids(rules) -> Optional[List[str]]:
    return None if rules is None else sorted(m.RULE for m in rules)


def _lint_one(job: Tuple[str, Optional[List[str]]]) -> List[Finding]:
    """Pool worker: rule MODULES don't pickle, ids do."""
    path, ids = job
    rules = None
    if ids is not None:
        from .rules import ALL_RULES
        rules = [m for m in ALL_RULES if m.RULE in set(ids)]
    return lint_file(path, rules=rules)


def lint_paths(paths: Sequence[str], rules=None,
               cache_path: Optional[str] = None,
               jobs: int = 1) -> List[Finding]:
    """Lint, optionally with a content-hash parse cache and a process
    pool over the cache misses. Cache entries key on (path, sha256 of
    the file bytes, active rule ids) under the package-wide rules
    signature, so an edit to a file, a rule filter, or the linter
    itself can never replay stale findings."""
    files = collect_files(paths)
    findings_by_file: Dict[str, List[Finding]] = {}
    misses: List[str] = []
    cache = hashes = None
    ids = _rule_ids(rules)
    rkey = ",".join(ids) if ids is not None else "*"
    if cache_path:
        cache = lintcache.load_cache(cache_path, _rules_signature())
        hashes = {}
        for path in files:
            digest = lintcache.file_digest(path)
            if digest is None:
                misses.append(path)   # unreadable: E0 via lint_file
                continue
            hashes[path] = digest
            entry = cache["files"].get(
                lintcache.cache_key(path, digest, rkey))
            if entry is None:
                misses.append(path)
            else:
                findings_by_file[path] = [Finding(**d) for d in entry]
    else:
        misses = list(files)

    if jobs > 1 and len(misses) > 1:
        linted = lintcache.map_jobs(_lint_one,
                                    [(p, ids) for p in misses], jobs)
    else:
        # serial path uses the caller's actual rule MODULES — a custom
        # rule object outside ALL_RULES must run, not silently resolve
        # to nothing through the id round-trip the pool needs
        linted = [lint_file(p, rules=rules) for p in misses]
    for path, fs in zip(misses, linted):
        findings_by_file[path] = fs

    if cache is not None:
        for path, fs in zip(misses, linted):
            digest = hashes.get(path)
            if digest is not None:
                cache["files"][lintcache.cache_key(path, digest, rkey)] \
                    = [f.__dict__ for f in fs]
        lintcache.evict_dead_entries(cache, hashes)
        lintcache.save_cache(cache_path, cache)

    out: List[Finding] = []
    for path in files:
        out.extend(findings_by_file.get(path, []))
    return out


# -- baseline (tools/lintcache machinery) ---------------------------------

def finding_key(finding: Finding) -> Tuple[str, str, str]:
    return finding.key(lintcache.code_line(finding.path, finding.line))


def load_baseline(path: str) -> Counter:
    return lintcache.load_baseline(path)


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    lintcache.write_baseline(path, (finding_key(f) for f in findings),
                             "graftlint")


def apply_baseline(findings: List[Finding], baseline: Counter,
                   linted_paths: Optional[Iterable[str]] = None,
                   ) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """Returns (new findings, stale baseline keys) — see
    :func:`tools.lintcache.apply_baseline` for the shrink-only
    discipline this enforces."""
    return lintcache.apply_baseline(findings, baseline, finding_key,
                                    linted_paths=linted_paths)


# -- CLI ------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based JAX/TPU invariant checker (rules R1-R6; "
                    "see tools/graftlint/rules/).")
    p.add_argument("paths", nargs="+",
                   help="files and/or directories to lint")
    p.add_argument("--baseline", metavar="JSON",
                   help="grandfather file: matching findings don't fail "
                        "the run (burn-down workflow)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (list of findings)")
    p.add_argument("--write-baseline", metavar="JSON",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--rules", metavar="R1,R2,...",
                   help="run only these rule ids")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="parse/lint cache misses across N processes "
                        "(default 1: in-process)")
    p.add_argument("--cache", metavar="JSON", default=None,
                   help="parse-cache file (default: "
                        "$RAFT_GRAFTLINT_CACHE or "
                        "~/.cache/raft_tpu/graftlint_cache.json); "
                        "entries key on file content hash + active "
                        "rules + a hash of the linter itself, so the "
                        "cache can never replay stale findings")
    p.add_argument("--no-cache", action="store_true",
                   help="lint every file from scratch")
    args = p.parse_args(argv)

    if args.jobs < 1:
        print("graftlint: --jobs must be >= 1", file=sys.stderr)
        return 2
    cache_path = None if args.no_cache \
        else (args.cache or default_cache_path())

    rules = None
    if args.rules:
        from .rules import ALL_RULES
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [m for m in ALL_RULES if m.RULE in want]
        unknown = want - {m.RULE for m in rules}
        if unknown:
            print(f"graftlint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    if args.write_baseline and args.rules:
        # a rule-filtered regenerate would silently drop every other
        # rule's grandfathered entries and fail the next full gate run
        print("graftlint: refusing --write-baseline with --rules — "
              "regenerate from a full-rule run over the gate's paths",
              file=sys.stderr)
        return 2

    findings = lint_paths(args.paths, rules=rules,
                          cache_path=cache_path, jobs=args.jobs)
    hard_errors = [f for f in findings if f.rule.startswith("E")]

    if args.write_baseline:
        write_baseline(args.write_baseline,
                       [f for f in findings
                        if not f.rule.startswith("E")])
        print(f"graftlint: wrote {len(findings) - len(hard_errors)} "
              f"finding(s) to {args.write_baseline}; pass the SAME "
              "paths as the tier-1 gate (raft_tpu bench.py tools "
              "tests) or the gate will fail on entries this run "
              "never saw", file=sys.stderr)
        return 0

    stale: List[Tuple[str, str, str]] = []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as exc:
            print(f"graftlint: unreadable baseline "
                  f"{args.baseline}: {exc}", file=sys.stderr)
            return 2
        if rules is not None:
            # entries for rules outside the active filter can neither
            # be consumed nor meaningfully checked — a --rules R5 run
            # must not call the untouched R1 entries stale
            active = {m.RULE for m in rules}
            baseline = Counter({k: v for k, v in baseline.items()
                                if k[1] in active})
        findings, stale = apply_baseline(
            findings, baseline, linted_paths=collect_files(args.paths))

    if args.as_json:
        # stale entries ride in the same list (rule B0) so a machine
        # consumer sees WHY the run failed, not `[]` with rc=1
        print(json.dumps([{
            "path": f.path, "line": f.line, "col": f.col,
            "rule": f.rule, "name": f.name, "message": f.message,
        } for f in findings] + [{
            "path": k[0], "line": 0, "col": 0, "rule": "B0",
            "name": "stale-baseline",
            "message": f"stale baseline entry for {k[1]}: {k[2]!r} — "
                       "regenerate with --write-baseline",
        } for k in stale], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftlint: {len(findings)} new finding(s)",
                  file=sys.stderr)
    if stale:
        # a lingering entry would grandfather the NEXT reintroduction
        # of that exact line — fail until the baseline is regenerated
        for k in stale:
            print(f"graftlint: stale baseline entry {k[0]} [{k[1]}] "
                  f"{k[2]!r}", file=sys.stderr)
        print(f"graftlint: {len(stale)} stale baseline entr(y/ies) — "
              "the finding was fixed (good!) but the entry must go: "
              "regenerate with --write-baseline so it cannot "
              "grandfather a future reintroduction", file=sys.stderr)
    return 1 if (findings or stale) else 0
