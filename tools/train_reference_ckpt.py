"""Produce a GENUINE torch reference checkpoint by training on CPU.

The sandbox has no egress (wget of the released models.zip fails — see
BENCH_NOTES round 3), so the released ``raft-*.pth`` can't be fetched.
This is the closest substitute that still exercises everything random-init
parity cannot: run the ACTUAL reference implementation
(``/root/reference/core``) through real optimizer steps so its weights
move off init and its cnet BatchNorm accumulates genuine running stats
(``core/extractor.py`` norm_fn='batch'), then save a ``.pth`` in the
reference's own on-disk format (``module.``-prefixed state_dict,
train.py:187) for ``raft_tpu.tools.convert`` to consume.

Training data: crops of the reference's bundled Sintel demo frames warped
by smooth random flow fields (img2 = warp(img1, flow) via cv2.remap), so
images are real and flow GT is exact with realistic magnitudes — not
random noise. Loss is the reference's sequence loss (train.py:57-82):
gamma-weighted L1 over the iteration outputs.

Outputs (under --out, default /root/.cache/raft_tpu/ref_ckpt):
    raft-basic-cputrained.pth   genuine torch artifact, module.* keys
    raft-small-cputrained.pth   (with --small too)
    train_log.jsonl             loss per step, for the committed record
"""

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

import cv2

cv2.setNumThreads(0)

REF = "/root/reference"
sys.path.insert(0, os.path.join(REF, "core"))


def smooth_flow(h, w, rng, max_mag=24.0):
    """Low-frequency random flow: upsampled coarse gaussian noise."""
    coarse = rng.randn(2, 6, 8).astype(np.float32)
    flow = np.stack([
        cv2.resize(c, (w, h), interpolation=cv2.INTER_CUBIC) for c in coarse
    ], axis=-1)
    mag = rng.uniform(2.0, max_mag)
    scale = mag / (np.abs(flow).max() + 1e-6)
    return flow * scale


def make_pairs(n, hw, rng):
    """(img1, img2, flow) with img2 = backward-warp of img1 by flow.

    grid_sample semantics: img2(x) = img1(x + flow(x)) makes ``flow`` the
    forward flow img1->img2 up to the warp's own occlusion error, which a
    few hundred CPU steps never resolve anyway — the point is realistic
    image statistics and flow magnitudes, not a converged model.
    """
    frames = sorted(glob.glob(os.path.join(REF, "demo-frames", "*.png")))
    imgs = [cv2.cvtColor(cv2.imread(f), cv2.COLOR_BGR2RGB) for f in frames]
    h, w = hw
    out = []
    for _ in range(n):
        src = imgs[rng.randint(len(imgs))]
        y0 = rng.randint(0, src.shape[0] - h + 1)
        x0 = rng.randint(0, src.shape[1] - w + 1)
        img1 = src[y0:y0 + h, x0:x0 + w].astype(np.float32)
        flow = smooth_flow(h, w, rng)
        gx, gy = np.meshgrid(np.arange(w, dtype=np.float32),
                             np.arange(h, dtype=np.float32))
        img2 = cv2.remap(img1, gx + flow[..., 0], gy + flow[..., 1],
                         cv2.INTER_LINEAR, borderMode=cv2.BORDER_REFLECT)
        out.append((img1, img2, flow))
    return out


def sequence_loss(flow_preds, flow_gt, gamma=0.8):
    import torch

    n = len(flow_preds)
    loss = 0.0
    for i, pred in enumerate(flow_preds):
        loss = loss + gamma ** (n - i - 1) * (pred - flow_gt).abs().mean()
    return loss


def train_one(small, args, rng):
    import torch

    from raft import RAFT as TorchRAFT

    name = "small" if small else "basic"
    targs = argparse.Namespace(small=small, mixed_precision=False,
                               alternate_corr=False, dropout=0.0)
    torch.manual_seed(1234)
    model = TorchRAFT(targs)
    resume_path = os.path.join(args.out, f"raft-{name}-cputrained.pth")
    if args.resume and os.path.exists(resume_path):
        sd = torch.load(resume_path, map_location="cpu")
        model.load_state_dict({k.removeprefix("module."): v
                               for k, v in sd.items()})
        print(f"[{name}] resumed from {resume_path}", flush=True)
    model.train()  # BN stats accumulate (chairs stage leaves BN unfrozen,
    #                train.py:148 only freezes for later stages)
    opt = torch.optim.AdamW(model.parameters(), lr=args.lr,
                            weight_decay=1e-5)
    pairs = make_pairs(args.pairs, tuple(args.hw), rng)
    log_path = os.path.join(args.out, f"train_log_{name}.jsonl")
    t0 = time.time()
    with open(log_path, "a" if args.resume else "w") as logf:
        for step in range(args.steps):
            batch = [pairs[rng.randint(len(pairs))]
                     for _ in range(args.batch)]
            i1 = torch.from_numpy(
                np.stack([b[0] for b in batch])).permute(0, 3, 1, 2)
            i2 = torch.from_numpy(
                np.stack([b[1] for b in batch])).permute(0, 3, 1, 2)
            gt = torch.from_numpy(
                np.stack([b[2] for b in batch])).permute(0, 3, 1, 2)
            preds = model(i1, i2, iters=args.iters)
            loss = sequence_loss(preds, gt)
            opt.zero_grad()
            loss.backward()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            opt.step()
            rec = {"step": step, "loss": float(loss.item()),
                   "epe": float((preds[-1] - gt).norm(dim=1).mean().item()),
                   "t": round(time.time() - t0, 1)}
            logf.write(json.dumps(rec) + "\n")
            logf.flush()
            if step % 10 == 0:
                print(f"[{name}] step {step} loss {rec['loss']:.3f} "
                      f"epe {rec['epe']:.2f} ({rec['t']}s)", flush=True)
            if step and step % 200 == 0:
                _save(model, args.out, name)  # survive an arbitrary kill

    return _save(model, args.out, name)


def _save(model, out, name):
    import torch

    # the reference saves through nn.DataParallel, so consumers expect
    # module.-prefixed keys (train.py:187, demo.py:27); atomic rename so a
    # kill mid-write can't corrupt the only copy
    sd = {f"module.{k}": v for k, v in model.state_dict().items()}
    path = os.path.join(out, f"raft-{name}-cputrained.pth")
    torch.save(sd, path + ".tmp")
    os.replace(path + ".tmp", path)
    print(f"saved {path}", flush=True)
    return path


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="/root/.cache/raft_tpu/ref_ckpt")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--hw", type=int, nargs=2, default=[184, 248],
                   help="crop; H and W must keep every corr-pyramid level "
                        ">= 2 px (H/64 >= 2), else the REFERENCE's own "
                        "align_corners bilinear_sampler divides by zero "
                        "(utils.py bilinear_sampler, H-1 in the "
                        "denominator) — measured NaN at 96x128")
    p.add_argument("--pairs", type=int, default=48)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--small", action="store_true", help="also train small")
    p.add_argument("--resume", action="store_true",
                   help="continue from the existing cputrained .pth")
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)
    rng = np.random.RandomState(0)
    train_one(False, args, rng)
    if args.small:
        train_one(True, args, rng)
    return 0


if __name__ == "__main__":
    sys.exit(main())
