"""graftwire driver: walk files, run W-rules, global union pass, CLI.

Usage (from the repo root; the argument-less form is the tier-1
gate)::

    python -m tools.graftwire --json
    python -m tools.graftwire raft_tpu/serving some_file.py \
        --baseline tools/graftwire/baseline.json

With no paths the scan covers :data:`DEFAULT_PATHS` — the wire-facing
serving stack, the placement/parallel layer, and the fault-injection
seam. Exit codes: 0 clean (modulo baseline), 1 new findings, 2
usage/parse error. ``--json`` prints a machine-readable findings list;
``--write-baseline`` regenerates the grandfather file (shrink-only
discipline; the SHIPPED baseline is EMPTY and must stay that way —
findings are fixed or pragma-waived with justification, never
silently baselined).

Suppression: ``# graftwire: disable=W1,W6   (justification)`` on the
finding's anchor line.

Three passes per run: the per-file rules (W3-W6 in ``scan_file``),
then — in ``lint_paths`` — the GLOBAL W1/W2 pass over the union of
every file's wire facts (client call sites vs worker handler tables
live in different modules, so drift only closes here, like
graftthread's T3 union graph), and the repo-level W7 fault-coverage
cross-reference whenever the scanned set includes
``raft_tpu/testing/faults.py``. The content-hash parse cache
(tools/lintcache, shared with the other tiers) stores each file's
findings, facts, and pragma lines; the union and W7 passes re-run
every time (dict walks, not parses) so a cache hit can never hide
cross-file drift. The cache signature folds in the schema registry's
digest — editing ``serving/schema.py`` invalidates cached W6 results.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

try:
    from tools import lintcache
except ImportError:          # invoked as a top-level package (tests
    import lintcache         # insert the repo root on sys.path)

from .declarations import WireAnalysis, WireFacts
from .finding import Finding
from . import schema_registry

#: the argument-less scan: everything that touches the wire — the
#: serving stack (transport/hosts/scheduler/registry), the placement
#: and parallel layer, and the fault-injection seam W7 audits
DEFAULT_PATHS = ("raft_tpu/serving",
                 "raft_tpu/parallel",
                 os.path.join("raft_tpu", "testing", "faults.py"))

FAULTS_SUFFIX = os.path.join("raft_tpu", "testing", "faults.py")


def collect_files(paths: Sequence[str]) -> List[str]:
    return lintcache.collect_files(paths)


def parse_pragmas(source: str) -> Dict[int, Optional[set]]:
    return lintcache.parse_pragmas(source, "graftwire")


def _apply_pragmas(findings: List[Finding],
                   pragmas: Dict[int, Optional[set]]) -> List[Finding]:
    kept = []
    for f in findings:
        disabled = pragmas.get(f.line)
        if f.line in pragmas and (disabled is None or f.rule in disabled):
            continue
        kept.append(f)
    return sorted(kept, key=lambda f: (f.line, f.col, f.rule))


def scan_file(path: str, rules=None) -> Dict:
    """One file's full scan: ``{"findings": [per-file findings, pragma-
    filtered], "facts": WireFacts, "pragmas": {line: rules}}``. The
    cross-file rules (W1/W2) run over the facts ONLY in
    :func:`lint_file` / :func:`lint_paths`; here they are returned raw
    for the driver's union pass."""
    from .rules import PER_FILE_RULES
    rules = None if rules is None else list(rules)
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as exc:
        return {"findings": [Finding(path, 0, 0, "E0", "unreadable",
                                     str(exc))],
                "facts": WireFacts(), "pragmas": {}}
    try:
        analysis = WireAnalysis(path, ast.parse(source, filename=path))
    except SyntaxError as exc:
        return {"findings": [Finding(path, exc.lineno or 0,
                                     exc.offset or 0, "E1",
                                     "syntax-error",
                                     exc.msg or "syntax error")],
                "facts": WireFacts(), "pragmas": {}}
    pragmas = parse_pragmas(source)
    registry = schema_registry.registry_for(path)
    findings: List[Finding] = list(analysis.errors)
    for mod in PER_FILE_RULES:
        if rules is not None and mod not in rules:
            continue
        findings.extend(mod.check(analysis, registry))
    return {"findings": _apply_pragmas(findings, pragmas),
            "facts": analysis.facts(), "pragmas": pragmas}


def _union_findings(entries: Dict[str, Dict], files: Sequence[str],
                    rules=None) -> List[Finding]:
    """The global W1/W2 pass over every scanned file's facts, each
    finding pragma-filtered against its ANCHOR file's pragma lines."""
    from .rules import GLOBAL_RULES
    facts_by_path = {path: entries[path]["facts"]
                     for path in files if path in entries}
    out: List[Finding] = []
    for mod in GLOBAL_RULES:
        if rules is not None and mod not in rules:
            continue
        for finding in mod.check_union(facts_by_path):
            pragmas = entries.get(finding.path, {}).get("pragmas", {})
            out.extend(_apply_pragmas([finding], pragmas))
    return out


def lint_file(path: str, rules=None) -> List[Finding]:
    """All findings for ONE file — per-file rules plus W1/W2 over the
    file's own facts (the fixture/unit mode; the repo gate's verdict
    is the union, via :func:`lint_paths`)."""
    entry = scan_file(path, rules)
    findings = list(entry["findings"])
    findings.extend(_union_findings({path: entry}, [path], rules))
    return sorted(findings, key=lambda f: (f.line, f.col, f.rule))


# -- parse cache + parallel walk (tools/lintcache machinery) --------------

def _rules_signature() -> str:
    """Content hash of the graftwire package PLUS the shared lintcache
    module PLUS the schema registry the W6 verdicts were made against
    — a cache must never outlive the code OR the schema that produced
    it."""
    sig = lintcache.package_signature(
        os.path.dirname(os.path.abspath(__file__)),
        lintcache.__file__)
    schema_path = schema_registry.find_schema(
        os.path.join(os.getcwd(), "_probe_"))
    digest = (lintcache.file_digest(schema_path)
              if schema_path else None)
    return f"{sig}:{digest or 'no-schema'}"


def default_cache_path() -> str:
    return lintcache.default_cache_path("RAFT_GRAFTWIRE_CACHE",
                                        "graftwire_cache.json")


def _rule_ids(rules) -> Optional[List[str]]:
    return None if rules is None else sorted(m.RULE for m in rules)


def _rules_from_ids(ids: Optional[List[str]]):
    if ids is None:
        return None
    from .rules import ALL_RULES
    return [m for m in ALL_RULES if m.RULE in set(ids)]


def _entry_to_json(entry: Dict) -> Dict:
    return {"findings": [f.__dict__ for f in entry["findings"]],
            "facts": entry["facts"].to_json(),
            "pragmas": {str(k): (sorted(v) if v is not None else None)
                        for k, v in entry["pragmas"].items()}}


def _entry_from_json(data: Dict) -> Dict:
    return {"findings": [Finding(**d) for d in data["findings"]],
            "facts": WireFacts.from_json(data["facts"]),
            "pragmas": {int(k): (set(v) if v is not None else None)
                        for k, v in data["pragmas"].items()}}


def _scan_one(job: Tuple[str, Optional[List[str]]]) -> Dict:
    """Pool worker: rule MODULES don't pickle, ids do."""
    path, ids = job
    return scan_file(path, rules=_rules_from_ids(ids))


def _w7_findings(files: Sequence[str], entries: Dict[str, Dict],
                 rules=None) -> List[Finding]:
    """Repo-level W7 whenever the scanned set includes the fault
    seam; findings pragma-filter against their anchor file (which may
    be OUTSIDE the scanned set, e.g. cli/serve_bench.py — parse its
    pragmas fresh)."""
    from .rules import fault_coverage
    if rules is not None and fault_coverage not in rules:
        return []
    trigger = next((p for p in files
                    if os.path.normpath(p).endswith(FAULTS_SUFFIX)), None)
    if trigger is None:
        return []
    repo_root = os.path.normpath(trigger)
    for _ in range(3):
        repo_root = os.path.dirname(repo_root)
    out: List[Finding] = []
    for finding in fault_coverage.check_repo(repo_root or "."):
        pragmas = entries.get(finding.path, {}).get("pragmas")
        if pragmas is None:
            try:
                with open(finding.path, encoding="utf-8") as f:
                    pragmas = parse_pragmas(f.read())
            except OSError:
                pragmas = {}
        out.extend(_apply_pragmas([finding], pragmas))
    return out


def lint_paths(paths: Sequence[str], rules=None,
               cache_path: Optional[str] = None,
               jobs: int = 1) -> List[Finding]:
    """Scan, optionally with the shared content-hash parse cache and a
    process pool over cache misses (cache entries key on file hash +
    active rule ids under the package+schema signature — identical
    discipline to graftlint's). Per-file findings come first in path
    order, then the global W1/W2 union findings, then W7."""
    files = collect_files(paths)
    entries: Dict[str, Dict] = {}
    misses: List[str] = []
    cache = hashes = None
    ids = _rule_ids(rules)
    rkey = ",".join(ids) if ids is not None else "*"
    if cache_path:
        cache = lintcache.load_cache(cache_path, _rules_signature())
        hashes = {}
        for path in files:
            digest = lintcache.file_digest(path)
            if digest is None:
                misses.append(path)   # unreadable: E0 via scan_file
                continue
            hashes[path] = digest
            stored = cache["files"].get(
                lintcache.cache_key(path, digest, rkey))
            if stored is None:
                misses.append(path)
            else:
                entries[path] = _entry_from_json(stored)
    else:
        misses = list(files)

    if jobs > 1 and len(misses) > 1:
        scanned = lintcache.map_jobs(_scan_one,
                                     [(p, ids) for p in misses], jobs)
    else:
        # serial path uses the caller's actual rule MODULES — a custom
        # rule object outside ALL_RULES must run, not silently resolve
        # to nothing through the id round-trip the pool needs
        scanned = [scan_file(p, rules=rules) for p in misses]
    for path, entry in zip(misses, scanned):
        entries[path] = entry

    if cache is not None:
        for path, entry in zip(misses, scanned):
            digest = hashes.get(path)
            if digest is not None:
                cache["files"][lintcache.cache_key(path, digest, rkey)] \
                    = _entry_to_json(entry)
        lintcache.evict_dead_entries(cache, hashes)
        lintcache.save_cache(cache_path, cache)

    out: List[Finding] = []
    for path in files:
        out.extend(entries.get(path, {}).get("findings", []))
    out.extend(_union_findings(entries, files, rules))
    out.extend(_w7_findings(files, entries, rules))
    return out


# -- baseline (tools/lintcache machinery) ---------------------------------

def finding_key(finding: Finding) -> Tuple[str, str, str]:
    return finding.key(lintcache.code_line(finding.path, finding.line))


def load_baseline(path: str) -> Counter:
    return lintcache.load_baseline(path)


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    lintcache.write_baseline(path, (finding_key(f) for f in findings),
                             "graftwire")


def apply_baseline(findings: List[Finding], baseline: Counter,
                   linted_paths: Optional[Iterable[str]] = None,
                   ) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """Returns (new findings, stale baseline keys) — the shrink-only
    discipline of :func:`tools.lintcache.apply_baseline`."""
    return lintcache.apply_baseline(findings, baseline, finding_key,
                                    linted_paths=linted_paths)


# -- CLI ------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftwire",
        description="Wire-protocol static analysis for the multi-host "
                    "fleet (rules W1-W7; see tools/graftwire/rules/). "
                    "With no paths, scans the serving stack + parallel "
                    "layer + fault seam against the shipped baseline.")
    p.add_argument("paths", nargs="*",
                   help="files and/or directories to check (default: "
                        f"{' '.join(DEFAULT_PATHS)}, with the shipped "
                        "baseline applied)")
    p.add_argument("--baseline", metavar="JSON",
                   help="grandfather file: matching findings don't "
                        "fail the run (burn-down workflow)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (list of findings)")
    p.add_argument("--write-baseline", metavar="JSON",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--rules", metavar="W1,W3,...",
                   help="run only these rule ids")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="scan cache misses across N processes "
                        "(default 1: in-process)")
    p.add_argument("--cache", metavar="JSON", default=None,
                   help="parse-cache file (default: "
                        "$RAFT_GRAFTWIRE_CACHE or "
                        "~/.cache/raft_tpu/graftwire_cache.json); "
                        "same content-hash + package-signature "
                        "discipline as the other tiers' caches")
    p.add_argument("--no-cache", action="store_true",
                   help="scan every file from scratch")
    args = p.parse_args(argv)

    if args.jobs < 1:
        print("graftwire: --jobs must be >= 1", file=sys.stderr)
        return 2
    cache_path = None if args.no_cache \
        else (args.cache or default_cache_path())

    paths = list(args.paths)
    baseline_path = args.baseline
    if not paths:
        paths = list(DEFAULT_PATHS)
        if baseline_path is None and not args.write_baseline:
            # the argument-less gate applies the shipped baseline, so
            # `python -m tools.graftwire --json` IS the tier-1 gate
            baseline_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "baseline.json")

    rules = None
    if args.rules:
        from .rules import ALL_RULES
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [m for m in ALL_RULES if m.RULE in want]
        unknown = want - {m.RULE for m in rules}
        if unknown:
            print(f"graftwire: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    if args.write_baseline and args.rules:
        # a rule-filtered regenerate would silently drop every other
        # rule's grandfathered entries and fail the next full gate run
        print("graftwire: refusing --write-baseline with --rules — "
              "regenerate from a full-rule run over the gate's paths",
              file=sys.stderr)
        return 2

    findings = lint_paths(paths, rules=rules,
                          cache_path=cache_path, jobs=args.jobs)
    hard_errors = [f for f in findings if f.rule.startswith("E")]

    if args.write_baseline:
        write_baseline(args.write_baseline,
                       [f for f in findings
                        if not f.rule.startswith("E")])
        print(f"graftwire: wrote {len(findings) - len(hard_errors)} "
              f"finding(s) to {args.write_baseline} — remember the "
              "discipline: the SHIPPED baseline stays EMPTY (fix or "
              "pragma-with-justification instead)", file=sys.stderr)
        return 0

    stale: List[Tuple[str, str, str]] = []
    if baseline_path:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as exc:
            print(f"graftwire: unreadable baseline "
                  f"{baseline_path}: {exc}", file=sys.stderr)
            return 2
        if rules is not None:
            active = {m.RULE for m in rules}
            baseline = Counter({k: v for k, v in baseline.items()
                                if k[1] in active})
        findings, stale = apply_baseline(
            findings, baseline, linted_paths=collect_files(paths))

    if args.as_json:
        # stale entries ride in the same list (rule B0) so a machine
        # consumer sees WHY the run failed, not `[]` with rc=1
        print(json.dumps([{
            "path": f.path, "line": f.line, "col": f.col,
            "rule": f.rule, "name": f.name, "message": f.message,
        } for f in findings] + [{
            "path": k[0], "line": 0, "col": 0, "rule": "B0",
            "name": "stale-baseline",
            "message": f"stale baseline entry for {k[1]}: {k[2]!r} — "
                       "regenerate with --write-baseline",
        } for k in stale], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftwire: {len(findings)} new finding(s)",
                  file=sys.stderr)
    if stale:
        for k in stale:
            print(f"graftwire: stale baseline entry {k[0]} [{k[1]}] "
                  f"{k[2]!r}", file=sys.stderr)
        print(f"graftwire: {len(stale)} stale baseline entr(y/ies) — "
              "regenerate with --write-baseline so it cannot "
              "grandfather a future reintroduction", file=sys.stderr)
    return 1 if (findings or stale) else 0
