"""Static view of `raft_tpu/serving/schema.py` — the wire/metrics
registry W6 checks emissions and method names against.

The registry values are `frozenset({...})` calls, so the file is not
`literal_eval`-able; we walk the module AST and pull the KEY constants
out of the `EVENT_FIELDS` / `WIRE_METHODS` dict literals. Parsed once
per (path, digest) and memoized — schema edits change the digest,
which also feeds the tier's cache signature so stale cached W6 results
die with it.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

from tools import lintcache

SCHEMA_REL = os.path.join("raft_tpu", "serving", "schema.py")

_memo: Dict[str, "SchemaRegistry"] = {}


@dataclass
class SchemaRegistry:
    path: str
    digest: str
    events: Set[str] = field(default_factory=set)
    methods: Set[str] = field(default_factory=set)

    def event_declared(self, match) -> bool:
        """`match` is WireAnalysis's ("exact", name) / ("prefix", p)."""
        kind, value = match
        if kind == "exact":
            return value in self.events
        return any(e.startswith(value) for e in self.events)


def find_schema(start: str) -> Optional[str]:
    """Walk up from `start` (a scanned file) to the repo root holding
    serving/schema.py; fall back to the current working directory so
    fixture copies under tmp dirs still resolve the REAL registry."""
    cur = os.path.dirname(os.path.abspath(start))
    for _ in range(12):
        cand = os.path.join(cur, SCHEMA_REL)
        if os.path.isfile(cand):
            return cand
        nxt = os.path.dirname(cur)
        if nxt == cur:
            break
        cur = nxt
    cand = os.path.join(os.getcwd(), SCHEMA_REL)
    return cand if os.path.isfile(cand) else None


def load(path: Optional[str]) -> Optional[SchemaRegistry]:
    if path is None:
        return None
    digest = lintcache.file_digest(path)
    key = f"{os.path.abspath(path)}:{digest}"
    if key in _memo:
        return _memo[key]
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    reg = SchemaRegistry(path=path, digest=digest)
    for node in tree.body:
        # both plain and annotated assignments (`EVENT_FIELDS: Dict[
        # str, frozenset] = {...}` is an AnnAssign)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            name = node.target.id
        else:
            continue
        if name not in ("EVENT_FIELDS", "WIRE_METHODS"):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        keys = {k.value for k in node.value.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)}
        if name == "EVENT_FIELDS":
            reg.events = keys
        else:
            reg.methods = keys
    _memo[key] = reg
    return reg


def registry_for(start: str) -> Optional[SchemaRegistry]:
    return load(find_schema(start))
