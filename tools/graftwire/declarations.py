"""Per-module wire model: the `GRAFTWIRE` declaration literal plus the
facts every W-rule consumes (client calls, worker handler tables, lock
scopes, event emissions, raw socket touches).

The declaration is the module's side of the wire contract — the same
move as graftthread's `GRAFTTHREAD` literal: the analyzer trusts what
the module SAYS about itself, then checks that the code matches.

```python
GRAFTWIRE = {
    "idempotent": ("ping", "stats"),       # safe to re-send (W2)
    "wire_locks": ("_lock",),              # lock IS the per-conn
                                           #   serialization (W3)
    "locks": ("_reg_lock",),               # extra lock-ish attrs (W3)
    "verdicts": ("_wedge_host",),          # host-verdict fns (W4)
    "consequences": ("poison",),           # must precede settles (W4)
    "settles": ("_failover_requeue",),     # extra future-settlers (W4)
    "framed_helpers": ("_send_msg",),      # blessed raw-socket fns (W6)
    "event_emitters": ("_emit",),          # record_event wrappers (W6)
}
```
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.graftwire.finding import Finding

#: every key GRAFTWIRE accepts, with its empty default
DECL_DEFAULTS = {
    "idempotent": (),
    "wire_locks": (),
    "locks": (),
    "verdicts": (),
    "consequences": (),
    "settles": (),
    "framed_helpers": (),
    "event_emitters": (),
}

#: attribute names that look like a lock/serialization guard when used
#: as a context manager (`with self._lock:`), mirroring graftthread
LOCKISH = re.compile(r"(^|_)(lock|mutex|guard|sem|semaphore|cond)s?$",
                     re.IGNORECASE)

#: calls that settle a caller-visible future (W4's "too early" side)
SETTLE_NAMES = {"settle_future", "set_result", "set_exception"}

#: raw-socket verbs that put bytes on / pull bytes off the wire (W6);
#: shutdown/close/bind are lifecycle, not framing, and stay legal
SOCKET_VERBS = {"send", "sendall", "sendto", "recv", "recvfrom",
                "recv_into"}

#: receiver name segments that mark a socket object
SOCKETISH = re.compile(r"(^|_)(sock|socket|conn|connection)s?(_|$)",
                       re.IGNORECASE)

#: subprocess-ish blocking waits for W3
SUBPROCESS_WAITS = {"run", "communicate", "check_output", "check_call",
                    "wait"}
PROCESSISH = re.compile(r"(^|_)(proc|process|popen|child|worker)s?(_|$)",
                        re.IGNORECASE)


def dotted(node: ast.AST) -> Optional[str]:
    """`self.fleet._lock` -> "self.fleet._lock"; None for anything that
    is not a plain Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def segments(name: str) -> List[str]:
    return name.split(".")


@dataclass
class WireCall:
    """One `<recv>.call("method", payload...)` (or `_call`) client-side
    wire invocation with a string-constant method name."""
    method: str
    line: int
    col: int
    has_request_id: bool
    func: str          # enclosing function qualname ("" at module level)


@dataclass
class Handler:
    """One `_m_<method>` entry in a worker handler table (a class that
    also defines `handle`)."""
    method: str
    line: int
    col: int
    cls: str


@dataclass
class EventEmit:
    """One record_event(...) / declared-emitter call. `match` is
    ("exact", name) for a string constant, ("prefix", p) for
    `"p" + expr` first args."""
    match: Tuple[str, str]
    line: int
    col: int


@dataclass
class WireFacts:
    """The cacheable per-file summary the cross-file rules (W1/W2)
    union over — plain JSON-able payload, like graftthread's edges."""
    calls: List[dict] = field(default_factory=list)
    handlers: List[dict] = field(default_factory=list)
    idempotent: List[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"calls": self.calls, "handlers": self.handlers,
                "idempotent": self.idempotent}

    @classmethod
    def from_json(cls, blob: dict) -> "WireFacts":
        return cls(calls=list(blob.get("calls", ())),
                   handlers=list(blob.get("handlers", ())),
                   idempotent=list(blob.get("idempotent", ())))


class WireAnalysis:
    """One parsed module + its GRAFTWIRE declarations + extracted wire
    facts. Rule modules stay thin: they read these tables."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.errors: List[Finding] = []
        self.decl: Dict[str, Tuple[str, ...]] = {
            k: tuple(v) for k, v in DECL_DEFAULTS.items()}
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._parse_declarations()
        self.calls: List[WireCall] = []
        self.handlers: List[Handler] = []
        self.emits: List[EventEmit] = []
        self._collect()

    # -- declarations -------------------------------------------------------

    def _parse_declarations(self) -> None:
        for node in self.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "GRAFTWIRE"):
                continue
            try:
                value = ast.literal_eval(node.value)
            except (ValueError, TypeError, SyntaxError):
                self.errors.append(Finding(
                    self.path, node.lineno, node.col_offset, "E2",
                    "bad-declaration",
                    "GRAFTWIRE must be a literal dict of tuples/lists "
                    "of strings"))
                return
            if not isinstance(value, dict):
                self.errors.append(Finding(
                    self.path, node.lineno, node.col_offset, "E2",
                    "bad-declaration", "GRAFTWIRE must be a dict"))
                return
            for key, val in value.items():
                if key not in DECL_DEFAULTS:
                    self.errors.append(Finding(
                        self.path, node.lineno, node.col_offset, "E2",
                        "bad-declaration",
                        f"unknown GRAFTWIRE key {key!r} (known: "
                        f"{', '.join(sorted(DECL_DEFAULTS))})"))
                    continue
                if (not isinstance(val, (list, tuple)) or
                        not all(isinstance(x, str) for x in val)):
                    self.errors.append(Finding(
                        self.path, node.lineno, node.col_offset, "E2",
                        "bad-declaration",
                        f"GRAFTWIRE[{key!r}] must be a tuple of "
                        "strings"))
                    continue
                self.decl[key] = tuple(val)

    # -- scope helpers (graftthread's walk model) ---------------------------

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    def walk_same_scope(self, node: ast.AST):
        """Yield descendants of `node` without crossing into nested
        function/class definitions (their bodies run later, under their
        own locks)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            child = stack.pop()
            yield child
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(child))

    # -- lock scopes --------------------------------------------------------

    def is_lockish(self, name: str) -> bool:
        segs = segments(name)
        if any(LOCKISH.search(s) for s in segs):
            return True
        return any(s in self.decl["locks"] or s in
                   self.decl["wire_locks"] for s in segs)

    def is_wire_lock(self, name: str) -> bool:
        return any(s in self.decl["wire_locks"] for s in segments(name))

    def held_lock_scopes(self):
        """Yield (lock_name, with_node) for every `with <lockish>:`
        context in the module."""
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                expr = item.context_expr
                # `with lock:` or `with self._lock:` — also unwrap
                # `lock.acquire_timeout(...)`-style calls
                target = expr.func if isinstance(expr, ast.Call) else expr
                name = dotted(target)
                if name and self.is_lockish(name):
                    yield name, node

    # -- fact extraction ----------------------------------------------------

    def _collect(self) -> None:
        emitter_names = {"record_event"} | set(self.decl["event_emitters"])
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._collect_handlers(node)
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # client wire call: <recv>.call("m", ...) / <recv>._call("m", ...)
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in ("call", "_call")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                func = self.enclosing_function(node)
                self.calls.append(WireCall(
                    method=node.args[0].value, line=node.lineno,
                    col=node.col_offset,
                    has_request_id=self._carries_request_id(node),
                    func=self.qualname(func) if func else ""))
            # event emission: record_event("kind", ...) or a declared
            # wrapper like self._emit("kind", ...)
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name in emitter_names and node.args:
                match = self._event_match(node.args[0])
                if match is not None:
                    self.emits.append(EventEmit(
                        match=match, line=node.lineno,
                        col=node.col_offset))

    def _collect_handlers(self, cls: ast.ClassDef) -> None:
        """A worker handler table is a class that defines `handle` and
        dispatches to `_m_<method>` methods (the PR-18 HostWorker
        shape)."""
        names = {n.name for n in cls.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))}
        if "handle" not in names:
            return
        for n in cls.body:
            if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n.name.startswith("_m_")):
                self.handlers.append(Handler(
                    method=n.name[len("_m_"):], line=n.lineno,
                    col=n.col_offset, cls=cls.name))

    @staticmethod
    def _carries_request_id(call: ast.Call) -> bool:
        """True when the call's payload visibly carries a request id —
        a `request_id=` keyword anywhere, or a dict argument with a
        "request_id" key."""
        for kw in call.keywords:
            if kw.arg == "request_id":
                return True
        for arg in list(call.args[1:]) + [kw.value for kw in
                                          call.keywords]:
            if isinstance(arg, ast.Dict):
                for k in arg.keys:
                    if (isinstance(k, ast.Constant)
                            and k.value == "request_id"):
                        return True
            if (isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Name)
                    and arg.func.id == "dict"):
                if any(kw.arg == "request_id" for kw in arg.keywords):
                    return True
        return False

    @staticmethod
    def _event_match(arg: ast.AST) -> Optional[Tuple[str, str]]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return ("exact", arg.value)
        # `"breaker_" + new` — a constant prefix is still checkable
        if (isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add)
                and isinstance(arg.left, ast.Constant)
                and isinstance(arg.left.value, str)):
            return ("prefix", arg.left.value)
        return None          # fully dynamic: the runtime drill owns it

    # -- cacheable summary --------------------------------------------------

    def facts(self) -> WireFacts:
        return WireFacts(
            calls=[{"method": c.method, "line": c.line, "col": c.col,
                    "request_id": c.has_request_id, "func": c.func}
                   for c in self.calls],
            handlers=[{"method": h.method, "line": h.line,
                       "col": h.col, "cls": h.cls}
                      for h in self.handlers],
            idempotent=list(self.decl["idempotent"]))
