"""graftwire — wire-protocol static analysis for the multi-host fleet.

The sixth analysis tier: where graftthread's T-rules stop at the
thread seam, the W-rules follow the serving stack across the process
boundary (serving/transport.py, serving/hosts.py) and mechanize the
bug classes PRs 6-18 caught by hand, re-appearing on the wire:

- W1 method-table-drift        — client `call("m")` strings vs worker
                                 `_m_m` handler tables, cross-file
- W2 unretryable-call          — retried remote calls neither declared
                                 idempotent nor carrying a request_id
- W3 wire-call-under-lock      — transport/socket/subprocess waits
                                 inside `with <lock>` (T1 over the
                                 seam; GRAFTWIRE['wire_locks'] exempts
                                 the transport's own serialization)
- W4 settle-before-consequence — host-verdict fns settling futures
                                 before quarantine/failover land (T6
                                 across host verdicts)
- W5 unbounded-retry-loop      — reconnect loops not paced by
                                 utils/retry.backoff_delays
- W6 wire-schema-drift         — events/methods absent from the
                                 serving/schema.py registry; raw
                                 socket I/O outside framed helpers
- W7 undrilled-fault-site      — armed fault_point sites no chaos
                                 plan ever draws (KNOWN_SITES is the
                                 single source of truth)

Run ``python -m tools.graftwire --help`` from the repo root; the
tier-1 gate is ``tests/test_graftwire.py``.
"""

from .core import (DEFAULT_PATHS, apply_baseline, lint_file, lint_paths,
                   load_baseline, main, write_baseline)
from .finding import Finding

__all__ = ["Finding", "DEFAULT_PATHS", "apply_baseline", "lint_file",
           "lint_paths", "load_baseline", "main", "write_baseline"]
