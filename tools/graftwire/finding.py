"""The one record type every graftwire rule emits."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    path: str      # as given on the command line (relative in CI)
    line: int      # 1-based, node start line
    col: int       # 0-based
    rule: str      # "W1".."W7"
    name: str      # kebab-case rule name, e.g. "method-table-drift"
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}[{self.name}] {self.message}")

    def key(self, code_line: str) -> tuple:
        """Baseline identity: line NUMBERS drift across edits, the
        (path, rule, source text) triple mostly doesn't."""
        return (self.path.replace("\\", "/"), self.rule, code_line.strip())
