"""W1: method-table drift.

Every method string a transport client `call()`s must be registered in
a worker handler table (`_m_<method>` on a class with `handle`), and
every registered handler must have at least one client caller — in the
UNION of scanned files, because client and worker are different
modules (scheduler/aot call, hosts.py handles).

Single-file semantics are deliberately conservative: a client-only
module has no handler table to check against (and vice versa), so the
rule only fires when the union actually contains the other side. That
is what makes drift a cross-file-pass-only finding, like graftthread's
T3 cycles.
"""

from __future__ import annotations

from typing import Dict, List

from tools.graftwire.declarations import WireFacts
from tools.graftwire.finding import Finding

RULE = "W1"
NAME = "method-table-drift"


def check_union(facts_by_path: Dict[str, WireFacts]) -> List[Finding]:
    calls = [(path, c) for path, facts in facts_by_path.items()
             for c in facts.calls]
    handlers = [(path, h) for path, facts in facts_by_path.items()
                for h in facts.handlers]
    findings: List[Finding] = []
    if handlers:
        handled = {h["method"] for _, h in handlers}
        for path, c in calls:
            if c["method"] not in handled:
                findings.append(Finding(
                    path, c["line"], c["col"], RULE, NAME,
                    f"client calls wire method {c['method']!r} but no "
                    f"worker handler table registers "
                    f"_m_{c['method']} — the call can only raise "
                    "'unknown method' at runtime"))
    if calls:
        called = {c["method"] for _, c in calls}
        for path, h in handlers:
            if h["method"] not in called:
                findings.append(Finding(
                    path, h["line"], h["col"], RULE, NAME,
                    f"worker handler _m_{h['method']} "
                    f"({h['cls']}) is registered but no transport "
                    f"client calls {h['method']!r} — dead protocol "
                    "surface (or the caller's method string drifted)"))
    return findings
