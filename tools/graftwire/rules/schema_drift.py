"""W6: wire-schema drift.

Everything that crosses the wire or lands in metrics.jsonl must
correspond to a `serving/schema.py` registry entry:

- `record_event("kind", ...)` (and declared `event_emitters` wrappers)
  with a kind absent from `EVENT_FIELDS` — the runtime validator would
  reject the line, but only when a drill happens to emit it; W6 makes
  it a parse-time finding.
- wire method strings (client calls AND `_m_` handler entries) absent
  from the `WIRE_METHODS` registry — the payload contract exists only
  in two function bodies otherwise.
- raw `sock.send`/`recv` outside the blessed length-framed helpers
  (`GRAFTWIRE["framed_helpers"]`) — unframed bytes are how protocol
  drift becomes a hang instead of an error.

Constant-prefix emissions (`"breaker_" + state`) pass when any
registered event carries the prefix; fully dynamic kinds are left to
the runtime drill (`tests/test_serving_schema.py`), W6's dynamic twin.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.graftwire.declarations import (SOCKET_VERBS, SOCKETISH,
                                          WireAnalysis, dotted,
                                          segments)
from tools.graftwire.finding import Finding

RULE = "W6"
NAME = "wire-schema-drift"


def _socketish(name: Optional[str]) -> bool:
    return name is not None and any(SOCKETISH.search(s)
                                    for s in segments(name))


def check(analysis: WireAnalysis, registry=None) -> List[Finding]:
    findings: List[Finding] = []
    if registry is not None:
        for e in analysis.emits:
            if not registry.event_declared(e.match):
                kind, value = e.match
                what = (f"event {value!r}" if kind == "exact"
                        else f"event prefix {value!r}")
                findings.append(Finding(
                    analysis.path, e.line, e.col, RULE, NAME,
                    f"{what} has no serving/schema.py EVENT_FIELDS "
                    "entry — the metrics validator rejects the line "
                    "at the first drill that emits it"))
        if registry.methods:
            for c in analysis.calls:
                if c.method not in registry.methods:
                    findings.append(Finding(
                        analysis.path, c.line, c.col, RULE, NAME,
                        f"wire method {c.method!r} has no "
                        "serving/schema.py WIRE_METHODS entry — the "
                        "payload contract lives only in the two "
                        "function bodies"))
            for h in analysis.handlers:
                if h.method not in registry.methods:
                    findings.append(Finding(
                        analysis.path, h.line, h.col, RULE, NAME,
                        f"handler _m_{h.method} has no "
                        "serving/schema.py WIRE_METHODS entry — "
                        "register the method's payload keys"))
    framed = set(analysis.decl["framed_helpers"])
    for node in ast.walk(analysis.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in SOCKET_VERBS
                and _socketish(dotted(node.func.value))):
            continue
        fn = analysis.enclosing_function(node)
        if fn is not None and fn.name in framed:
            continue
        findings.append(Finding(
            analysis.path, node.lineno, node.col_offset, RULE, NAME,
            f"raw socket .{node.func.attr}() outside a blessed "
            "length-framed helper (GRAFTWIRE['framed_helpers']) — "
            "unframed bytes turn protocol drift into a hang, not an "
            "error"))
    return findings
