"""W5: reconnect/retry loops not routed through
`utils/retry.backoff_delays`.

A loop that catches transport-transient errors (or re-issues wire
calls/connects) and paces itself with a hand-rolled `time.sleep(k)` is
the unbounded-hammer class: constant-rate retries against a dead peer,
no jitter, thundering herd on recovery. The blessed pacing is
`backoff_delays(...)` (bounded, factor-grown, jittered) — a sleep
whose delay visibly comes from `next(<delays>)` or a name assigned
from `backoff_delays(...)` passes; anything else in such a loop is a
finding.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.graftwire.declarations import WireAnalysis
from tools.graftwire.finding import Finding

RULE = "W5"
NAME = "unbounded-retry-loop"

#: exception names whose catch marks a loop as a transport-retry loop
TRANSIENT = {"TransportError", "OSError", "IOError", "ConnectionError",
             "ConnectionResetError", "ConnectionRefusedError",
             "BrokenPipeError", "TimeoutError", "timeout"}

#: call attrs that mark the loop body as wire-touching
WIRE_TOUCH = {"connect", "create_connection", "reopen"}


def _exc_names(node: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            names.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.add(sub.attr)
    return names


def _is_sleep(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "sleep"
    return isinstance(fn, ast.Name) and fn.id == "sleep"


def _mentions_backoff(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "backoff_delays":
            return True
        if isinstance(sub, ast.Attribute) and \
                sub.attr == "backoff_delays":
            return True
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Name) and sub.func.id == "next":
            return True
    return False


def _blessed_names(scope: ast.AST, analysis: WireAnalysis) -> Set[str]:
    """Names assigned (anywhere in the enclosing scope) from a
    backoff-derived expression — `delays = backoff_delays(...)`,
    `delay = next(delays)`."""
    names: Set[str] = set()
    for sub in analysis.walk_same_scope(scope):
        if isinstance(sub, ast.Assign) and _mentions_backoff(sub.value):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def check(analysis: WireAnalysis, registry=None) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(analysis.tree):
        if not isinstance(node, (ast.While, ast.For)):
            continue
        body = list(analysis.walk_same_scope(node))
        sleeps = [c for c in body
                  if isinstance(c, ast.Call) and _is_sleep(c)]
        if not sleeps:
            continue
        retryish = False
        for sub in body:
            if isinstance(sub, ast.ExceptHandler) and sub.type and \
                    _exc_names(sub.type) & TRANSIENT:
                retryish = True
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute):
                if sub.func.attr in WIRE_TOUCH:
                    retryish = True
                if sub.func.attr in ("call", "_call") and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str):
                    retryish = True
        if not retryish:
            continue
        scope = analysis.enclosing_function(node) or analysis.tree
        blessed = _blessed_names(scope, analysis)
        for sleep in sleeps:
            arg = sleep.args[0] if sleep.args else None
            if arg is not None:
                if _mentions_backoff(arg):
                    continue
                if isinstance(arg, ast.Name) and arg.id in blessed:
                    continue
            findings.append(Finding(
                analysis.path, sleep.lineno, sleep.col_offset, RULE,
                NAME,
                "retry/reconnect loop paced by a hand-rolled sleep — "
                "route the delay through "
                "raft_tpu.utils.retry.backoff_delays(...) (bounded, "
                "jittered) instead of a constant-rate hammer"))
    return findings
