"""graftwire rule registry.

Per-file rules see one module's :class:`WireAnalysis`; the cross-file
rules (W1/W2) see the UNION of every scanned file's wire facts — a
client and its worker live in different modules, so method-table and
idempotency drift only close in the global pass (graftthread's T3
union-graph move). W7 is repo-level: it cross-references the armed
fault sites against the chaos drills.
"""

from . import (blocking_wire, fault_coverage, idempotency, method_table,
               retry_loop, schema_drift, verdicts)

#: rules that run over one file's analysis in scan_file
PER_FILE_RULES = [blocking_wire, verdicts, retry_loop, schema_drift]

#: rules that run over the union of per-file facts in lint_paths
GLOBAL_RULES = [method_table, idempotency]

ALL_RULES = [method_table, idempotency, blocking_wire, verdicts,
             retry_loop, schema_drift, fault_coverage]
