"""W2: idempotency discipline.

The PR-18 transport contract makes EVERY remote call retryable
(`TransportError` is always-retryable; failover re-queues in-flight
work), so every wire method is reachable from a retry path. A method
must therefore either be declared idempotent in some module's
`GRAFTWIRE["idempotent"]` (the worker module owns that contract) or
visibly carry a `request_id` in its payload so the worker can dedup
the zombie re-send.

Declarations union across scanned files: hosts.py declaring
`"put_artifact"` idempotent covers aot.py's call site — which is why
the gate's verdict is the GLOBAL pass, and a client module linted
alone may fire where the fleet-wide union is clean.
"""

from __future__ import annotations

from typing import Dict, List

from tools.graftwire.declarations import WireFacts
from tools.graftwire.finding import Finding

RULE = "W2"
NAME = "unretryable-call"


def check_union(facts_by_path: Dict[str, WireFacts]) -> List[Finding]:
    idempotent = {m for facts in facts_by_path.values()
                  for m in facts.idempotent}
    findings: List[Finding] = []
    for path, facts in facts_by_path.items():
        for c in facts.calls:
            if c["method"] in idempotent or c["request_id"]:
                continue
            findings.append(Finding(
                path, c["line"], c["col"], RULE, NAME,
                f"remote call {c['method']!r} is retried on "
                "TransportError but is neither declared in "
                "GRAFTWIRE['idempotent'] nor carries a request_id — "
                "a zombie re-send double-applies it"))
    return findings
