"""W7: fault-site coverage — every armed fault site must be drilled.

Three-way cross-reference, with `KNOWN_SITES` in
`raft_tpu/testing/faults.py` as the single source of truth:

1. a site armed in code (`fault_point`/`fault_file`/`fault_data` with
   a string-constant name) but missing from `KNOWN_SITES` — the
   registry drifted;
2. a `KNOWN_SITES` entry no code ever arms — a stale registry row that
   makes the drill matrix claim more than it covers;
3. a known, armed site that no chaos plan or `CHAOS_SITES` list under
   `tests/` / `raft_tpu/cli/` ever draws — an undrilled failure mode
   (today that is convention; this makes it a finding).

"Drawn" means the site name appears as a non-docstring string constant
somewhere under the drill roots — chaos plans pass sites as literals
(`CHAOS_SITES` tuples, `faults.arm([{"site": ...}])`, env-plan
strings), so constant-scanning is the honest static proxy for "some
drill can select this site".
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Sequence, Set, Tuple

from tools import lintcache
from tools.graftwire.finding import Finding

RULE = "W7"
NAME = "undrilled-fault-site"

ARMING_FNS = {"fault_point", "fault_file", "fault_data"}

FAULTS_REL = os.path.join("raft_tpu", "testing", "faults.py")
CODE_ROOTS = ("raft_tpu",)
DRILL_ROOTS = ("tests", os.path.join("raft_tpu", "cli"))


def _parse(path: str):
    try:
        with open(path, encoding="utf-8") as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def known_sites(faults_path: str) -> Tuple[Set[str], int]:
    """KNOWN_SITES keys + the assignment's line (finding anchor for
    never-armed entries)."""
    tree = _parse(faults_path)
    if tree is None:
        return set(), 0
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "KNOWN_SITES"
                and isinstance(node.value, ast.Dict)):
            keys = {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            return keys, node.lineno
    return set(), 0


def armed_sites(roots: Sequence[str]) -> Dict[str, List[Tuple]]:
    """site -> [(path, line, col), ...] for every string-constant
    arming call under `roots`."""
    armed: Dict[str, List[Tuple]] = {}
    for path in lintcache.collect_files(list(roots)):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name in ARMING_FNS and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                armed.setdefault(node.args[0].value, []).append(
                    (path, node.lineno, node.col_offset))
    return armed


def _docstring_constants(tree: ast.Module) -> Set[int]:
    ids: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                ids.add(id(body[0].value))
    return ids


def drawn_sites(roots: Sequence[str],
                candidates: Set[str]) -> Set[str]:
    """Site names appearing as non-docstring string constants under
    the drill roots."""
    drawn: Set[str] = set()
    for path in lintcache.collect_files(list(roots)):
        tree = _parse(path)
        if tree is None:
            continue
        doc_ids = _docstring_constants(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in candidates \
                    and id(node) not in doc_ids:
                drawn.add(node.value)
        if drawn >= candidates:
            break
    return drawn


def check_repo(repo_root: str, faults_rel: str = FAULTS_REL,
               code_roots: Sequence[str] = CODE_ROOTS,
               drill_roots: Sequence[str] = DRILL_ROOTS,
               ) -> List[Finding]:
    def under(rel: str) -> str:
        return os.path.normpath(os.path.join(repo_root, rel))

    faults_path = under(faults_rel)
    known, known_line = known_sites(faults_path)
    armed = armed_sites([under(r) for r in code_roots
                         if os.path.exists(under(r))])
    # the arming call inside faults.py's own machinery/doctests is the
    # mechanism, not a site
    armed = {s: [site for site in sites
                 if os.path.normpath(site[0]) != faults_path]
             for s, sites in armed.items()}
    armed = {s: sites for s, sites in armed.items() if sites}
    drawn = drawn_sites([under(r) for r in drill_roots
                         if os.path.exists(under(r))],
                        known | set(armed))

    findings: List[Finding] = []
    for site in sorted(armed):
        path, line, col = min(armed[site])
        if site not in known:
            findings.append(Finding(
                path, line, col, RULE, NAME,
                f"fault site {site!r} is armed here but missing from "
                f"KNOWN_SITES in {faults_rel} — register it so plans "
                "validate and W7 can audit its drills"))
        elif site not in drawn:
            findings.append(Finding(
                path, line, col, RULE, NAME,
                f"armed fault site {site!r} is drawn by no chaos plan "
                f"or CHAOS_SITES list under {' / '.join(drill_roots)} "
                "— an undrilled failure mode"))
    for site in sorted(known - set(armed)):
        findings.append(Finding(
            faults_path, known_line, 0, RULE, NAME,
            f"KNOWN_SITES entry {site!r} is never armed by any "
            "fault_point/fault_file/fault_data call — stale registry "
            "row"))
    return findings
