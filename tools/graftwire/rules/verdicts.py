"""W4: consequences-before-futures across host verdicts —
graftthread's T6 line-order dominance, applied to the fleet seam.

A declared host-verdict function (`GRAFTWIRE["verdicts"]`) decides a
host is gone. If it settles caller-visible futures (`settle_future` /
`set_result` / `set_exception` / declared extras) BEFORE the declared
consequences (quarantine, placement mark, transport poison, breaker
record), a woken caller can re-submit into the dead lane — the
zombie-host window PR 18's `_wedge_host` closes by ordering
consequences first.
"""

from __future__ import annotations

import ast
from typing import List

from tools.graftwire.declarations import SETTLE_NAMES, WireAnalysis
from tools.graftwire.finding import Finding

RULE = "W4"
NAME = "settle-before-consequence"


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def check(analysis: WireAnalysis, registry=None) -> List[Finding]:
    verdicts = set(analysis.decl["verdicts"])
    if not verdicts:
        return []
    consequences = set(analysis.decl["consequences"])
    settles = SETTLE_NAMES | set(analysis.decl["settles"])
    findings: List[Finding] = []
    for node in ast.walk(analysis.tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if node.name not in verdicts:
            continue
        settle_sites = []
        consequence_lines = []
        for child in analysis.walk_same_scope(node):
            if not isinstance(child, ast.Call):
                continue
            name = _call_name(child)
            if name in settles:
                settle_sites.append(child)
            elif name in consequences:
                consequence_lines.append(child.lineno)
        if not settle_sites:
            continue
        first = min(settle_sites, key=lambda c: (c.lineno,
                                                 c.col_offset))
        if not any(line < first.lineno for line in consequence_lines):
            findings.append(Finding(
                analysis.path, first.lineno, first.col_offset, RULE,
                NAME,
                f"host-verdict fn {node.name!r} settles futures "
                f"({_call_name(first)}) before any declared "
                "consequence "
                f"({', '.join(sorted(consequences)) or 'none declared'}"
                ") — a woken caller can re-submit into the dead lane; "
                "quarantine/failover must land first"))
    return findings
