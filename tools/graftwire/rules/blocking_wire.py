"""W3: blocking wire call under a held lock — graftthread's T1
extended across the process seam.

A `transport.call(...)`, framed-socket helper, raw socket send/recv,
connect, or subprocess wait lexically inside `with <lockish>:` wedges
every thread contending that lock for a full network round-trip (or
forever, against a dead peer).

Exemption: a lock declared in `GRAFTWIRE["wire_locks"]` IS the
transport's serialization contract (one request per connection, the
PR-18 SocketTransport design) — holding it across the I/O is the
point, not the bug. Scheduler/registry/fleet locks get no such pass.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.graftwire.declarations import (PROCESSISH, SOCKET_VERBS,
                                          SOCKETISH, SUBPROCESS_WAITS,
                                          WireAnalysis, dotted,
                                          segments)
from tools.graftwire.finding import Finding

RULE = "W3"
NAME = "wire-call-under-lock"

FRAMED_IO = {"_send_msg", "_recv_msg", "_recv_exact"}


def _socketish(name: Optional[str]) -> bool:
    return name is not None and any(SOCKETISH.search(s)
                                    for s in segments(name))


def _processish(name: Optional[str]) -> bool:
    if name is None:
        return False
    segs = segments(name)
    return "subprocess" in segs or any(PROCESSISH.search(s)
                                       for s in segs)


def blocking_desc(node: ast.AST) -> Optional[str]:
    """A human description of the wire-blocking operation `node`
    performs, or None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        recv = dotted(fn.value)
        if (fn.attr in ("call", "_call") and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            return f"transport call {node.args[0].value!r}"
        if fn.attr in SOCKET_VERBS and _socketish(recv):
            return f"raw socket .{fn.attr}()"
        if fn.attr == "connect" and _socketish(recv):
            return "socket connect"
        if fn.attr == "create_connection" and recv is not None \
                and "socket" in segments(recv):
            return "socket.create_connection()"
        if fn.attr in FRAMED_IO:
            return f"framed socket I/O {fn.attr}()"
        if fn.attr in SUBPROCESS_WAITS and _processish(recv):
            return f"subprocess wait .{fn.attr}()"
        return None
    if isinstance(fn, ast.Name) and fn.id in FRAMED_IO:
        return f"framed socket I/O {fn.id}()"
    return None


def check(analysis: WireAnalysis, registry=None) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for lock_name, with_node in analysis.held_lock_scopes():
        if analysis.is_wire_lock(lock_name):
            continue
        for child in analysis.walk_same_scope(with_node):
            desc = blocking_desc(child)
            if desc is None:
                continue
            key = (child.lineno, child.col_offset)
            if key in seen:
                continue          # nested lock scopes: report once
            seen.add(key)
            findings.append(Finding(
                analysis.path, child.lineno, child.col_offset, RULE,
                NAME,
                f"{desc} while holding {lock_name!r} — a wire "
                "round-trip (or a dead peer) wedges every thread "
                "behind this lock; move the I/O outside the critical "
                "section or declare the lock in "
                "GRAFTWIRE['wire_locks'] if serialization is the "
                "contract"))
    return findings
