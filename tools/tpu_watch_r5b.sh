#!/bin/bash
# Round-5 late-window watchdog: poll the axon tunnel every ~6 min; on
# each window run the marker-guarded late-window runbook
# (tools/onchip_round5b.sh: bare HEAD bench, 3k-step sustained train,
# --resume restart). Appends the availability trace to OUTAGE_r05.log.
# Exits when all round-5b terminal markers exist.
set -u
cd /root/repo
LOG=/root/repo/OUTAGE_r05.log
MARK=${RAFT_R5B_MARK:-/root/.cache/raft_tpu/r5b_markers}
while true; do
    if [ -e "$MARK/bare_final_head" ] && [ -e "$MARK/sustained_train" ] \
            && [ -e "$MARK/resume_check" ] && [ -e "$MARK/recorded" ]; then
        echo "$(date -u +%H:%M:%S) r5b runbook fully done" >> "$LOG"
        exit 0
    fi
    # Real 1-op execute probe (tools/chip_probe.sh): a half-up tunnel
    # (devices() OK, compile/execute hung — seen 08:47 UTC) must read as
    # down, or the loop burns 900 s runbook passes against a wedged
    # backend.
    if bash tools/chip_probe.sh 180; then
        echo "$(date -u +%H:%M:%S) chip up — running round-5b runbook" \
            >> "$LOG"
        bash tools/onchip_round5b.sh /tmp/onchip_round5b.out
        echo "$(date -u +%H:%M:%S) r5b runbook pass ended" >> "$LOG"
    else
        echo "$(date -u +%H:%M:%S) chip unavailable" >> "$LOG"
    fi
    sleep 180
done
