#!/bin/bash
# Round-3e: the rows the worker crash swallowed, with recovery waits.
# Twice now a `corr_bench --grad` run was followed by "TPU worker process
# crashed or restarted" on the NEXT process's first call; the worker
# recovers in ~1-2 min. So: probe the backend before each step and retry
# once after a crash.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round3e.out}
MARK=/root/.cache/raft_tpu/r3_markers
LADDER=/root/.cache/raft_tpu/r3_ladder
mkdir -p "$MARK" "$LADDER"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
wait_chip() {  # block (max ~5 min) until the backend answers
    for _ in 1 2 3 4 5; do
        if timeout -k 10 120 python -c \
            "import jax; assert jax.devices()[0].platform != 'cpu'" \
            >/dev/null 2>&1; then return 0; fi
        log "chip not answering; waiting 60s"
        sleep 60
    done
    return 1
}
step() {
    local name=$1 tmo=$2; shift 2
    if [ -e "$MARK/$name" ]; then log "skip $name (done)"; return 0; fi
    wait_chip || { log "SKIP $name (chip unavailable)"; return 1; }
    log "begin $name"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        touch "$MARK/$name"; log "done $name"
    else
        log "retry $name after 90s (rc=$?)"
        sleep 90
        if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
            touch "$MARK/$name"; log "done $name (retry)"
        else
            log "FAILED rc=$? $name"
        fi
    fi
    cp "$OUT" /root/repo/ONCHIP_r03e.log 2>/dev/null || true
}
bench_cfg() {
    local tag=$1 tmo=$2; shift 2
    if [ -e "$MARK/bench_$tag" ]; then log "skip bench_$tag"; return 0; fi
    wait_chip || { log "SKIP bench_$tag (chip unavailable)"; return 1; }
    log "begin bench_$tag: $*"
    if timeout "$tmo" python bench.py --steps 10 "$@" \
            > "$LADDER/$tag.json" 2>> "$OUT"; then
        cat "$LADDER/$tag.json" >> "$OUT"
        touch "$MARK/bench_$tag"; log "done bench_$tag"
    else
        log "FAILED bench_$tag rc=$?"; cat "$LADDER/$tag.json" >> "$OUT"
    fi
    cp "$OUT" /root/repo/ONCHIP_r03e.log 2>/dev/null || true
}

# whole-step bench with the transposed-volume lookup (isolated rows lost;
# the in-model picture can differ — decide the default on THIS number)
bench_cfg h_onehot_t_b8 1800 --batches 8 --corr-dtype bfloat16 --no-remat \
    --corr-impl onehot_t
# softsel lookup (bilinear lerp folded into the selection GEMMs — kills
# the ~60 ms/step post-GEMM lerp chain): isolated + whole-step decision
step s_grad 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot softsel --grad
step s_bf16 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot softsel --grad --corr-dtype bfloat16
bench_cfg i_softsel_b8 1800 --batches 8 --corr-dtype bfloat16 --no-remat \
    --corr-impl softsel
# fused subpixel-domain loss: frees the 560 MB prediction stack + its
# cotangent — try batch 10 FIRST (the stack was part of why b10 OOM'd)
bench_cfg j_fused 2400 --batches 10 8 --corr-dtype bfloat16 --no-remat \
    --fused-loss
step pick_defaults_s 120 python tools/pick_bench_defaults.py "$LADDER"

# the bf16 shootout row LAST among benches: twice its neighborhood saw the
# worker crash; keep it from eating the window before the decision rows
step t_bf16 1800 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls gather onehot onehot_t --grad --corr-dtype bfloat16
step pick_defaults_e 120 python tools/pick_bench_defaults.py "$LADDER"

# clean trainer steps/s with the fixed logger accounting (the previous
# resume-leg "5.01 steps/s" line was a resume-window artifact)
step train_rate 1800 python -m raft_tpu.cli.train --name r3rate \
    --stage chairs --mixed_precision --synthetic 64 --num_steps 220 \
    --val_freq 1000 --batch_size 8 --num_workers 4 \
    --checkpoint_dir /root/.cache/raft_tpu/r3_rate --log_dir runs

# serving re-measure: the session-C rows predate the test-mode rework
# (mask rides the scan carry; only the final iteration upsamples)
step infer_bf16_v2 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 \
    --corr_dtype bfloat16
step infer_fp32_v2 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024

log "round3e complete"
cp "$OUT" /root/repo/ONCHIP_r03e.log 2>/dev/null || true
for f in ONCHIP_r03e.log BENCH_DEFAULTS.json; do
    git add "$f" 2>/dev/null || true
done
git diff --cached --quiet || git commit -q -m \
    "On-chip round-3e artifacts: onehot_t step bench, bf16 shootout row" \
    -m "No-Verification-Needed: measurement logs and recorded defaults only"
