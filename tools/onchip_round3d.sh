#!/bin/bash
# Round-3d: trained-weights parity at exact fp32 matmul precision.
# XLA's default fp32 conv on TPU runs multi-pass bf16; through 20
# recurrent refinement iterations that costs ~0.13 px max vs the torch
# CPU reference. --matmul-precision highest (now the tool default)
# removes it; the torch-side flows come from the new on-disk cache, so
# only the TPU forwards rerun.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round3d.out}
MARK=/root/.cache/raft_tpu/r3_markers
mkdir -p "$MARK"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
step() {
    local name=$1 tmo=$2; shift 2
    if [ -e "$MARK/$name" ]; then log "skip $name (done)"; return 0; fi
    log "begin $name"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        touch "$MARK/$name"; log "done $name"
    else
        log "FAILED rc=$? $name"
    fi
    cp "$OUT" /root/repo/ONCHIP_r03d.log 2>/dev/null || true
}

step trained_parity_exact 2400 python tools/trained_parity.py

log "round3d complete"
cp /root/.cache/raft_tpu/ref_ckpt/trained_parity.json \
    /root/repo/TRAINED_PARITY_onchip.json 2>/dev/null || true
for f in ONCHIP_r03d.log TRAINED_PARITY_onchip.json; do
    git add "$f" 2>/dev/null || true
done
git diff --cached --quiet || git commit -q -m \
    "On-chip round-3d artifacts: exact-precision trained-weights parity" \
    -m "No-Verification-Needed: measurement logs and records only"
