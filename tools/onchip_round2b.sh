#!/bin/bash
# Follow-up on-chip runbook (round 2, session B) — run after
# tools/onchip_runbook.sh. Ordered by value-per-minute: chip windows have
# been ~100 min, so the headline-affecting measurements (batch ladder,
# bf16 volume, trace) come before the informational kernel shootouts.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round2b.out}
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }

log "1 bench.py batch ladder, onehot default (b8 first, b6 fallback)"
timeout 2400 python bench.py --steps 10 --batches 8 6 >> "$OUT" 2>&1

log "2 bench.py corr_dtype=bfloat16 (halved volume traffic)"
timeout 2400 python bench.py --steps 10 --batches 8 6 \
    --corr-dtype bfloat16 >> "$OUT" 2>&1

log "3 profile_step trace with the onehot default"
timeout 2400 python -m raft_tpu.cli.profile_step --batch 6 --steps 10 \
    --trace-dir /tmp/raft_trace_onehot >> "$OUT" 2>&1
timeout 1200 python -m raft_tpu.cli.trace_summary /tmp/raft_trace_onehot \
    --top 30 >> "$OUT" 2>&1

log "4 bench.py remat variants (memory headroom for bigger batches)"
timeout 2400 python bench.py --steps 10 --batches 10 8 --remat \
    --remat-policy dots >> "$OUT" 2>&1
timeout 2400 python bench.py --steps 10 --batches 10 8 --remat >> "$OUT" 2>&1

log "5 corr_bench chairs fwd+grad, pallas vs onehot (post scoped-VMEM fix)"
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot pallas >> "$OUT" 2>&1
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot pallas --grad >> "$OUT" 2>&1

log "6 corr_bench alt_pallas (post alignment fix), chairs + 128x128"
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls alt alt_pallas >> "$OUT" 2>&1
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 1 --hw 128 128 \
    --iters 10 --impls alt alt_pallas >> "$OUT" 2>&1

log "7 inference throughput (serving forward, test_trt.py timing analog)"
timeout 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 >> "$OUT" 2>&1
timeout 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 \
    --corr_dtype bfloat16 >> "$OUT" 2>&1

log "done"
