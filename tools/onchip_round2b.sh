#!/bin/bash
# Follow-up on-chip runbook (round 2, session B) — run after
# tools/onchip_runbook.sh. Validates the two kernel fixes that came out of
# the first session's failures (scoped-VMEM tiling, 8-aligned alt DMA) and
# finishes the measurement program with the onehot default.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round2b.out}
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }

log "1 corr_bench chairs fwd+grad, pallas vs onehot (post scoped-VMEM fix)"
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot pallas >> "$OUT" 2>&1
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot pallas --grad >> "$OUT" 2>&1

log "2 corr_bench alt_pallas (post alignment fix), chairs + 128x128"
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls alt alt_pallas >> "$OUT" 2>&1
timeout 2400 python -m raft_tpu.cli.corr_bench --batch 1 --hw 128 128 \
    --iters 10 --impls alt alt_pallas >> "$OUT" 2>&1

log "3 bench.py batch ladder with the onehot default (b8 first)"
timeout 2400 python bench.py --steps 10 --batches 8 6 >> "$OUT" 2>&1
timeout 2400 python bench.py --steps 10 --batches 8 6 --remat >> "$OUT" 2>&1

log "4 bench.py corr_dtype=bfloat16 (halved volume traffic)"
timeout 2400 python bench.py --steps 10 --batches 6 \
    --corr-dtype bfloat16 >> "$OUT" 2>&1

log "5 profile_step trace with the onehot default"
timeout 2400 python -m raft_tpu.cli.profile_step --batch 6 --steps 10 \
    --trace-dir /tmp/raft_trace_onehot >> "$OUT" 2>&1
timeout 1200 python -m raft_tpu.cli.trace_summary /tmp/raft_trace_onehot \
    --top 30 >> "$OUT" 2>&1

log "6 inference throughput (serving forward, test_trt.py timing analog)"
timeout 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 >> "$OUT" 2>&1
timeout 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 \
    --corr_dtype bfloat16 >> "$OUT" 2>&1

log "done"
