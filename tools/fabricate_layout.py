"""Fabricate a minimal staged-data layout for real_data_accept.sh --selftest.

Writes exactly the directory shapes the acceptance script expects the
operator to stage (Sintel per ``datasets.py`` MpiSintel's scene globs,
FlyingChairs per its ``*.ppm``/``*.flo`` + split-file contract, reference
``evaluate.py:75,96`` context) so the acceptance pipeline is provable
TODAY, end to end, without the real data: staging day becomes execution,
not development.
"""

from __future__ import annotations

import os
import os.path as osp
import sys

import numpy as np

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

from PIL import Image  # noqa: E402

from raft_tpu.data.frame_utils import write_flow  # noqa: E402


def _img(rng, h, w):
    return rng.randint(0, 255, (h, w, 3), dtype=np.uint8)


def fabricate(root: str, h: int = 128, w: int = 256) -> None:
    """Sintel frames at (h, w) — eval pads, so small+fast is fine; Chairs
    frames at the dataset's real 384x512, which the chairs-stage 368x496
    training crop (train_standard.sh) must fit inside."""
    rng = np.random.RandomState(0)

    # --- Sintel: training/{clean,final,flow}/<scene>/frame_%04d ---------
    for scene in ("alley_1", "temple_2"):
        for dstype in ("clean", "final"):
            d = osp.join(root, "Sintel", "training", dstype, scene)
            os.makedirs(d, exist_ok=True)
            for i in range(3):
                Image.fromarray(_img(rng, h, w)).save(
                    osp.join(d, f"frame_{i + 1:04d}.png"))
        d = osp.join(root, "Sintel", "training", "flow", scene)
        os.makedirs(d, exist_ok=True)
        for i in range(2):  # one flow per consecutive pair
            write_flow(osp.join(d, f"frame_{i + 1:04d}.flo"),
                       rng.randn(h, w, 2).astype(np.float32))

    # --- FlyingChairs: data/%05d_img{1,2}.ppm + %05d_flow.flo -----------
    d = osp.join(root, "FlyingChairs_release", "data")
    os.makedirs(d, exist_ok=True)
    n = 8
    ch, cw = 384, 512  # real FlyingChairs frame size
    for i in range(1, n + 1):
        Image.fromarray(_img(rng, ch, cw)).save(
            osp.join(d, f"{i:05d}_img1.ppm"))
        Image.fromarray(_img(rng, ch, cw)).save(
            osp.join(d, f"{i:05d}_img2.ppm"))
        write_flow(osp.join(d, f"{i:05d}_flow.flo"),
                   rng.randn(ch, cw, 2).astype(np.float32))
    # split file: mark most pairs train(1), last two validation(2)
    with open(osp.join(root, "FlyingChairs_release",
                       "chairs_split.txt"), "w") as f:
        for i in range(1, n + 1):
            f.write(f"{1 if i <= n - 2 else 2}\n")
    print(f"fabricated selftest layout under {root}")


if __name__ == "__main__":
    fabricate(sys.argv[1] if len(sys.argv) > 1 else "/tmp/raft_accept_data")
