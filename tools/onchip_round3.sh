#!/bin/bash
# Round-3 on-chip runbook. Ordered by value-per-minute for ~100-min chip
# windows; every step is marker-guarded so a dropped tunnel mid-run
# resumes where it left off on the next window (the persistent compile
# cache makes re-entry cheap).
#
# Produces, inside the repo (for the round-end snapshot):
#   ONCHIP_r03.log           — raw session log (VERDICT r2 missing #2)
#   BENCH_DEFAULTS.json      — best MEASURED bench config (bench.py reads it)
#   runs/r3synth/metrics.jsonl — 500-step training loss series (missing #3)
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round3.out}
MARK=/root/.cache/raft_tpu/r3_markers
LADDER=/root/.cache/raft_tpu/r3_ladder
mkdir -p "$MARK" "$LADDER"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
step() {  # step <name> <timeout-s> <cmd...>
    local name=$1 tmo=$2; shift 2
    if [ -e "$MARK/$name" ]; then log "skip $name (done)"; return 0; fi
    log "begin $name"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        touch "$MARK/$name"; log "done $name"
    else
        log "FAILED rc=$? $name"
    fi
    cp "$OUT" /root/repo/ONCHIP_r03.log 2>/dev/null || true
}
bench_cfg() {  # bench_cfg <tag> <timeout> <flags...>
    local tag=$1 tmo=$2; shift 2
    if [ -e "$MARK/bench_$tag" ]; then log "skip bench_$tag"; return 0; fi
    log "begin bench_$tag: $*"
    if timeout "$tmo" python bench.py --steps 10 "$@" \
            > "$LADDER/$tag.json" 2>> "$OUT"; then
        cat "$LADDER/$tag.json" >> "$OUT"
        touch "$MARK/bench_$tag"; log "done bench_$tag"
    else
        log "FAILED bench_$tag rc=$?"; cat "$LADDER/$tag.json" >> "$OUT"
    fi
    cp "$OUT" /root/repo/ONCHIP_r03.log 2>/dev/null || true
}

# ---- 1. headline config ladder (VERDICT r2 next-round #1) --------------
# most-likely winner first: if the window is short, the headline shot
# (bf16 volumes cleared by the trained-weights EPE gate, batch 8) still
# lands. fp32 next for the apples-to-apples delta, then remat variants.
# every ladder row pins corr-dtype and remat EXPLICITLY so the
# BENCH_DEFAULTS.json written mid-ladder can't bleed into later rows
bench_cfg b_bf16_b8      1800 --batches 8 6 --corr-dtype bfloat16 --no-remat
# write defaults immediately after the first result: if the tunnel dies
# mid-ladder, the driver's bare bench.py still reruns a measured config
step pick_defaults_early 120 python tools/pick_bench_defaults.py "$LADDER"
bench_cfg a_fp32_b8      1800 --batches 8 6 --corr-dtype float32 --no-remat
bench_cfg c_bf16_dots    1800 --batches 12 10 8 --corr-dtype bfloat16 \
                              --remat --remat-policy dots
bench_cfg d_fp32_dots    1800 --batches 12 10 8 --corr-dtype float32 \
                              --remat --remat-policy dots

step pick_defaults 120 python tools/pick_bench_defaults.py "$LADDER"

# ---- 2. 500-step training w/ real pipeline + save/resume (#5) ----------
step train450 2400 python -m raft_tpu.cli.train --name r3synth \
    --stage chairs --mixed_precision --synthetic 64 --num_steps 450 \
    --val_freq 200 --batch_size 6 --num_workers 4 \
    --checkpoint_dir /root/.cache/raft_tpu/r3_ck --log_dir runs
step train500_resume 1800 python -m raft_tpu.cli.train --name r3synth \
    --stage chairs --mixed_precision --synthetic 64 --num_steps 500 \
    --val_freq 200 --batch_size 6 --num_workers 4 --resume \
    --checkpoint_dir /root/.cache/raft_tpu/r3_ck --log_dir runs

# ---- 3. trace: attribute the unexplained ~300 ms (PROFILE.md) ----------
# mirror the ladder's winning config so the trace explains the headline
step trace 2400 python - <<'PYEOF'
import json, os, sys
from raft_tpu.cli import profile_step
argv = ["--steps", "10", "--trace-dir", "/tmp/raft_trace_r3"]
try:
    with open("/root/repo/BENCH_DEFAULTS.json") as f:
        d = json.load(f)
    argv += ["--batch", str(d.get("batches", [6])[0])]
    if d.get("corr_dtype"):
        argv += ["--corr_dtype", d["corr_dtype"]]
    if d.get("remat"):
        argv += ["--remat"]
except OSError:
    argv += ["--batch", "6"]
print("profile_step", argv, flush=True)
profile_step.main(argv)  # returns avg step seconds — not an exit code
sys.exit(0)
PYEOF
step trace_summary 1200 python -m raft_tpu.cli.trace_summary \
    /tmp/raft_trace_r3 --top 30

# ---- 4. kernel shootout completion (VERDICT #3: pallas + alt_pallas) ---
step corr_fwd 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot pallas
step corr_grad 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls onehot pallas --grad
step corr_bf16 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls gather onehot pallas --grad --corr-dtype bfloat16
step corr_alt 2400 python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 \
    --iters 20 --impls alt alt_pallas
step corr_alt_128 2400 python -m raft_tpu.cli.corr_bench --batch 1 \
    --hw 128 128 --iters 10 --impls alt alt_pallas

# ---- 5. serving at the envelope + export cycle (VERDICT #7) ------------
step infer_fp32 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024
step infer_bf16 2400 python -m raft_tpu.cli.infer_bench --hw 440 1024 \
    --corr_dtype bfloat16
step export_cycle 2400 python tools/export_cycle_check.py

# ---- 5b. things-stage geometry (optional breadth: 400x720 crop) --------
bench_cfg e_things_bf16  1800 --hw 400 720 --batches 6 4 \
                              --corr-dtype bfloat16 --no-remat

# ---- 6. trained-weights parity + bf16-volume delta (VERDICT #2/#4) -----
# cheap (two forwards per model); runs only once the CPU-trained genuine
# .pth exists (tools/train_reference_ckpt.py)
if [ -f /root/.cache/raft_tpu/ref_ckpt/raft-basic-cputrained.pth ]; then
    step trained_parity 2400 python tools/trained_parity.py
fi

log "runbook complete"
cp "$OUT" /root/repo/ONCHIP_r03.log 2>/dev/null || true
# artifacts-only commit so a round-end snapshot can't lose the evidence
cp /root/.cache/raft_tpu/ref_ckpt/trained_parity.json \
    /root/repo/TRAINED_PARITY_onchip.json 2>/dev/null || true
# add each artifact separately: one missing pathspec must not abort the
# whole staging (it silently killed the pass-1 artifact commit)
cd /root/repo
for f in ONCHIP_r03.log BENCH_DEFAULTS.json runs/r3synth/metrics.jsonl \
         TRAINED_PARITY_onchip.json; do
    git add "$f" 2>/dev/null || true
done
git diff --cached --quiet || git commit -q -m \
    "On-chip round-3 artifacts: bench ladder, training run, kernel shootout" \
    -m "No-Verification-Needed: measurement logs and recorded defaults only"
