"""The one record type every graftshard rule emits.

Identical shape to graftaudit's (``tools/graftaudit/finding.py``): a
sharding finding anchors to a *target* (a partitioned program compiled
on the forced multi-device CPU mesh) plus a stable ``detail`` string
(op_name, flat-arg path, geometry name) — the detail IS the baseline
identity, since compiled artifacts have no line numbers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class ShardFinding:
    target: str    # shard target name, e.g. "train_step_dp"
    rule: str      # "S1".."S6"
    name: str      # kebab-case rule name, e.g. "comm-in-loop"
    detail: str    # stable identity inside the artifact (op_name, arg
                   # path, geometry name)
    message: str

    def render(self) -> str:
        return (f"{self.target}: {self.rule}[{self.name}] "
                f"{self.message}")

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: details derive from op_names, arg paths
        and declared geometry, which survive recompiles of the same
        program."""
        return (self.target, self.rule, self.detail)
