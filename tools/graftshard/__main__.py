import sys

from tools.graftshard.core import main

if __name__ == "__main__":
    sys.exit(main())
