"""Shard-audit target declarations: what compiles, and what is waived.

A ``ShardTarget`` names one real partitioned program (the data-parallel
train step, the pjit-sharded serve trace) plus the *declared* sharding
discipline the audit holds it to: which args it donates (S6), which
boundary specs it promises (S4), which derived extents must divide the
mesh (S5), how many replicated bytes a boundary value may carry (S2).

``Waiver`` is graftaudit's pragma analog, verbatim: rule id + a
substring of the finding's ``detail`` + a REQUIRED justification,
reviewed where the target is declared. Waivers are for
intentional-by-design sharding (weights replicated under data
parallelism; the backward scan's gradient all-reduces the TPU pass
pipeline sinks), never "fix later" — that is the shrink-only baseline's
job, and this tier ships with it EMPTY.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

#: 64 KiB: the default ceiling for a fully-replicated value a mesh
#: axis could shard (S2). Sized between the biggest legitimately-tiny
#: boundary values at audit shapes (scalars, rng, 1/8-res flow rows —
#: well under 16 KiB) and the smallest replication accident the first
#: scan caught (the ~96 KiB image-concat all-reduce at 32x32 audit
#: shapes — real traffic multiplies it by the request geometry);
#: re-anchored against real sharded TPU HLO by the ``shard_audit_r6``
#: rung.
DEFAULT_REPLICATED_BYTES_MAX = 64 << 10

#: de-minimis floor for S4's unconstrained-boundary check: scalars and
#: tiny host knobs below this ride replicated for free; anything bigger
#: must DECLARE its sharding (with_sharding_constraint discipline).
DEFAULT_BOUNDARY_BYTES_MIN = 4096


@dataclass(frozen=True)
class Waiver:
    rule: str      # "S2"
    match: str     # substring of the finding's detail
    reason: str    # justification — empty reasons are rejected

    def __post_init__(self):
        if not self.reason.strip():
            raise ValueError(
                f"waiver for {self.rule} ({self.match!r}) has no "
                "justification — waivers document intent or they are "
                "just silent baselining")


@dataclass(frozen=True)
class ShardTarget:
    """One audited partitioned program.

    ``kind="trace"``: ``build()`` returns ``(fn, args, mesh)`` —
    positional example args (``jax.ShapeDtypeStruct``s carrying
    ``NamedSharding``s, or real arrays). The driver traces the jaxpr,
    lowers with ``donate_argnums``, compiles on the mesh, and records
    per-flat-arg sharding info for the boundary rules.

    ``kind="decl"``: ``build()`` returns ``mesh`` only — a
    declaration-level target (specs + geometry, no program). S4/S5
    audit these without compiling anything; jax itself would reject
    e.g. an uneven boundary sharding with an opaque error long after
    the mistake was made, so the decl tier is where geometry fixtures
    and pre-flight checks live.
    """

    name: str
    build: Callable
    kind: str = "trace"
    donate_argnums: Tuple[int, ...] = ()
    #: (value kind, per-dim axis names) pairs the program promises —
    #: normally ``Partitioner.declared_specs()`` so the audit checks
    #: the very table the runtime shards with (S4)
    declared_specs: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = ()
    #: derived extents that must divide their mesh axis (S5): dicts of
    #: ``{name, extent, axis, row_bytes}`` — normally
    #: ``Partitioner.shard_geometry(bucket)``
    shard_geometry: Tuple[Dict, ...] = ()
    replicated_bytes_max: int = DEFAULT_REPLICATED_BYTES_MAX
    boundary_bytes_min: int = DEFAULT_BOUNDARY_BYTES_MIN
    compiled: bool = True            # False: jaxpr/lowered tier only
    waivers: Tuple[Waiver, ...] = ()
    notes: str = ""

    def waived(self, rule: str, detail: str) -> bool:
        return any(w.rule == rule and w.match in detail
                   for w in self.waivers)


@dataclass
class ArgInfo:
    """One flat boundary value (entry parameter or output) of a
    compiled mesh program. ``spec`` is the resolved PartitionSpec as a
    tuple of per-dim entries (None / axis name / tuple of axis names);
    ``annotated`` records whether the LOWERED module carried an explicit
    ``mhlo.sharding`` for it (inputs only — XLA resolves unannotated
    params to replicated, silently: the S4 hazard)."""

    index: int
    path: str
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int
    spec: Optional[Tuple] = None
    replicated: bool = False
    annotated: bool = True


@dataclass
class Artifacts:
    """Everything the rules see for one target. ``mesh_axes`` maps axis
    name -> size for the mesh the target built; the texts are jax's
    lowered StableHLO and XLA's optimized (SPMD-partitioned) HLO;
    ``in_info``/``out_info`` are per-flat-boundary-value records."""

    jaxpr: object = None
    lowered_text: str = ""
    hlo_text: str = ""
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    in_info: List[ArgInfo] = field(default_factory=list)
    out_info: List[ArgInfo] = field(default_factory=list)
    seconds: float = 0.0
