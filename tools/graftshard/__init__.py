"""graftshard: the sharding & collectives static-analysis tier.

Fourth tier of the gate family — graftlint reads source, graftaudit
reads single-device compiled artifacts, graftthread reads
thread-safety declarations, graftshard reads PARTITIONED programs: the
real mesh programs (the data-parallel train step, the pjit-sharded
serve trace) compiled on a forced multi-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=4`` — no TPU
needed), audited at jaxpr + StableHLO + optimized-HLO level against
rules S1–S6, each a concrete sharding bug class:

- S1 ``comm-in-loop``: collectives inside the scan/while body —
  per-iteration communication;
- S2 ``replicated-large-value``: big values resolved to full
  replication a mesh axis could shard;
- S3 ``host-transfer-in-mesh-program``: callbacks / in-program
  ``device_put`` inside the compiled hot path;
- S4 ``spec-inconsistent``: specs naming absent axes; unconstrained
  boundary values XLA silently replicates;
- S5 ``uneven-shard-padding``: extents that don't divide their mesh
  axis (waste bytes reported);
- S6 ``donation-dropped-by-resharding``: declared donations whose
  ``input_output_alias`` vanished under partitioning.

Same surface as the siblings: ``python -m tools.graftshard --json``,
shrink-only (and EMPTY) ``baseline.json``, per-finding ``Waiver`` with
required justification, lintcache-backed warm repeats. The meta-gate
``python -m tools.graft --json`` runs all four tiers.
"""

from .core import (apply_baseline, audit_targets,  # noqa: F401
                   load_baseline, load_fixture_targets, main,
                   write_baseline)
from .finding import ShardFinding  # noqa: F401
from .spec import Artifacts, ShardTarget, Waiver  # noqa: F401
