"""S1: collectives inside a loop body — per-iteration communication.

The refinement GRU runs as a ``lax.scan``; a collective GSPMD places
INSIDE the compiled ``while`` body executes once per iteration — at
``iters=20`` a single stray all-gather is twenty all-gathers per
request, and the latency multiplies exactly where the serving stack
can least afford it (arXiv 2604.15464's lesson: per-iteration comm
and padding discipline decide TPU serving throughput). Ground truth
is the optimized (SPMD-partitioned) HLO: every collective whose
computation is reachable from a ``while`` op's ``body=``/``condition=``
region (transitively through called sub-computations) fires here.

The jaxpr tier catches the EXPLICIT form too: ``psum``-family
primitives traced into a scan/while body (a shard_map'd reduction
inside the loop) — visible before XLA ever runs.
"""

from __future__ import annotations

from typing import List

from ..finding import ShardFinding
from ..spec import Artifacts, ShardTarget

RULE = "S1"
NAME = "comm-in-loop"

#: explicit collective primitives at the jaxpr tier
_COLLECTIVE_PRIMS = ("psum", "pmax", "pmin", "pmean", "all_gather",
                     "all_to_all", "ppermute", "pshuffle",
                     "reduce_scatter")

#: jaxpr loop primitives whose body params hold per-iteration code
_LOOP_PRIMS = ("scan", "while")


def _jaxpr_findings(target: ShardTarget, art: Artifacts,
                    out: List[ShardFinding], seen: set) -> None:
    def walk_loops(jaxpr, in_loop: bool):
        for eqn in jaxpr.eqns:
            pname = eqn.primitive.name
            if in_loop and any(pname == p or pname.startswith(p + "_")
                               for p in _COLLECTIVE_PRIMS):
                detail = f"{pname} @ {eqn.source_info.name_stack}"
                if detail not in seen:
                    seen.add(detail)
                    out.append(ShardFinding(
                        target.name, RULE, NAME, detail,
                        f"'{pname}' traced inside a scan/while body at "
                        f"{eqn.source_info.name_stack} — this "
                        "collective runs once per iteration"))
            inner_loop = in_loop or pname in _LOOP_PRIMS
            for v in eqn.params.values():
                for j in (v if isinstance(v, (list, tuple)) else [v]):
                    inner = None
                    if hasattr(j, "eqns"):
                        inner = j
                    elif hasattr(j, "jaxpr") and hasattr(j.jaxpr, "eqns"):
                        inner = j.jaxpr
                    if inner is not None:
                        walk_loops(inner, inner_loop)

    walk_loops(art.jaxpr.jaxpr, False)


def check(target: ShardTarget, art: Artifacts) -> List[ShardFinding]:
    out: List[ShardFinding] = []
    seen: set = set()
    if art.jaxpr is not None:
        _jaxpr_findings(target, art, out, seen)
    if art.hlo_text:
        from tools import hlo_lib

        bodies = hlo_lib.while_body_computations(art.hlo_text)
        for rec in hlo_lib.find_collectives(art.hlo_text, within=bodies):
            detail = (f"{rec['opcode']} {rec['shape']} @ "
                      f"{rec['op_name'] or '(no op_name)'}")
            if detail in seen:
                continue
            seen.add(detail)
            out.append(ShardFinding(
                target.name, RULE, NAME, detail,
                f"'{rec['opcode']}' of {rec['shape']} "
                f"({rec['bytes']:,} bytes) inside loop body "
                f"'{rec['comp']}' at "
                f"{rec['op_name'] or '(no op_name)'} — executes once "
                "per scan iteration; hoist it out of the loop or "
                "reshard outside the scan"))
    return out
