"""S5: uneven shards — axis extents that don't divide their mesh axis.

The ragged-tail lesson at the shard level: when an extent doesn't
divide the axis that shards it, GSPMD pads the trailing shard and
every device computes the padded extent — pure waste, billed per
dispatch/step (arXiv 2604.15464's padding-discipline argument one
level down). jax rejects the BOUNDARY form with an opaque error at
dispatch time; the DERIVED form (the 1/8-res feature grid under
'spatial': H divisible by the axis does not make H/8 divisible)
compiles fine and silently pads. Targets declare their derived
extents (``Partitioner.shard_geometry``); the rule reports each
violation with the wasted bytes per shard so geometry fixes can be
prioritized by cost.
"""

from __future__ import annotations

from typing import List

from ..finding import ShardFinding
from ..spec import Artifacts, ShardTarget

RULE = "S5"
NAME = "uneven-shard-padding"


def check(target: ShardTarget, art: Artifacts) -> List[ShardFinding]:
    out: List[ShardFinding] = []
    for geo in target.shard_geometry:
        axis = geo["axis"]
        size = art.mesh_axes.get(axis, 1)
        if size <= 1:
            continue
        extent = int(geo["extent"])
        if extent % size == 0:
            continue
        per = -(-extent // size)            # padded per-shard extent
        waste_rows = per * size - extent
        waste_bytes = waste_rows * int(geo.get("row_bytes", 1))
        detail = f"geometry {geo['name']} over {axis}"
        out.append(ShardFinding(
            target.name, RULE, NAME, detail,
            f"extent {extent} ({geo['name']}) does not divide mesh "
            f"axis '{axis}'={size}: GSPMD pads the trailing shard to "
            f"{per} — {waste_rows} dead rows, ~{waste_bytes:,} wasted "
            "bytes per dispatch; round the geometry to the shard "
            "grain or resize the axis"))
    # boundary form: declared (aval, spec) pairs that would shard
    # unevenly — jax refuses these at dispatch with an opaque error,
    # so catching them here turns a runtime failure into a review
    for side, infos in (("arg", art.in_info), ("out", art.out_info)):
        for inf in infos:
            if not inf.spec:
                continue
            for dim, entry in enumerate(inf.spec):
                axes = (entry if isinstance(entry, (tuple, list))
                        else [entry]) if entry is not None else []
                k = 1
                for a in axes:
                    k *= art.mesh_axes.get(a, 1)
                if k > 1 and dim < len(inf.shape) \
                        and inf.shape[dim] % k:
                    detail = (f"{side} {inf.index} {inf.path} "
                              f"dim {dim}")
                    out.append(ShardFinding(
                        target.name, RULE, NAME, detail,
                        f"{side} {inf.index} ({inf.path}) dim {dim} "
                        f"extent {inf.shape[dim]} does not divide its "
                        f"sharding axes {axes} (total {k}) — jax "
                        "rejects this at dispatch; fix the bucket "
                        "geometry"))
    return out
