"""S6: donation dropped under resharding — the H4 analog that only
exists on a mesh.

graftaudit H4 verifies XLA honors single-device donations; on a mesh a
new way to lose one appears: when a donated input's sharding differs
from its matching output's, XLA cannot alias the buffers (the value
physically moves between devices) and silently DEGRADES the donation —
the optimized module carries the arg as a mere ``buffer_donor`` (or
nothing) instead of an ``input_output_alias`` entry, and the program
pays an input-sized copy every call. The serve seam's whole zero-copy
story (donated flow_init → flow_low, three donated cache rows) rides
on these aliases surviving partitioning; this rule is the proof.

Detection is graftaudit's, re-grounded: flat args the LOWERED module
marks donatable (``tf.aliasing_output``/``jax.buffer_donor``) must
appear in the optimized module's ``input_output_alias`` map. The mesh
twist is the attribution — on a miss, the rule compares the input's
resolved sharding against same-shaped outputs' and names the spec
mismatch that killed the alias.
"""

from __future__ import annotations

from typing import List

from ..finding import ShardFinding
from ..spec import Artifacts, ShardTarget

RULE = "S6"
NAME = "donation-dropped-by-resharding"


def _cause(inf, art: Artifacts) -> str:
    """Why the alias died: the same-shaped output whose sharding
    differs, if one exists."""
    for o in art.out_info:
        if o.shape == inf.shape and o.dtype == inf.dtype:
            if o.spec != inf.spec:
                return (f"input sharded {inf.spec} but its same-shaped "
                        f"output {o.path} resolved {o.spec} — the "
                        "reshard copy breaks the alias; constrain the "
                        "output to the input's spec (or stop donating)")
            return (f"a same-sharded output ({o.path}) exists — XLA "
                    "still declined; check layout/tuple-position "
                    "mismatches")
    return ("no output matches the donated buffer's shape/dtype — "
            "nothing to alias onto")


def check(target: ShardTarget, art: Artifacts) -> List[ShardFinding]:
    if not (target.donate_argnums and art.lowered_text and art.hlo_text):
        return []
    from tools import hlo_lib

    from ..artifacts import declared_donations

    declared = declared_donations(art.lowered_text)
    out: List[ShardFinding] = []
    if not declared:
        out.append(ShardFinding(
            target.name, RULE, NAME,
            "no donatable args survived lowering",
            f"donate_argnums={target.donate_argnums} declared but the "
            "lowered mesh module carries no tf.aliasing_output/"
            "jax.buffer_donor attribute — jax found no output to "
            "reuse any donated buffer for"))
        return out
    aliased = hlo_lib.parse_aliased_params(art.hlo_text)
    by_index = {i.index: i for i in art.in_info}
    for ix in declared:
        if ix in aliased:
            continue
        inf = by_index.get(ix)
        shape = (f"{inf.dtype}{list(inf.shape)}" if inf else "?")
        path = inf.path if inf else f"arg{ix}"
        detail = f"param {ix} {path}"
        cause = _cause(inf, art) if inf else "no boundary info"
        out.append(ShardFinding(
            target.name, RULE, NAME, detail,
            f"arg {ix} ({path}, {shape}) was donated but the "
            "partitioned module's input_output_alias map does not "
            f"cover it — the donation silently degraded and this "
            f"buffer is copied every call. {cause}"))
    return out
