"""S2: large values resolved to full replication a mesh axis could
shard.

GSPMD's default for anything unconstrained is "replicate it" — correct,
silent, and N× the HBM. Three surfaces, checked coarsest-first (the H5
byte-band idea applied to replication):

- **boundary values**: entry params / outputs whose resolved sharding
  is fully replicated, at ``>= target.replicated_bytes_max`` bytes,
  with at least one dim a >1 mesh axis divides — the axis was RIGHT
  THERE;
- **constrained intermediates**: ``with_sharding_constraint(x, P())``
  sites in the lowered StableHLO (``custom_call @Sharding`` with a
  ``"{replicated}"`` annotation) at threshold size — an explicit
  replicate of something big enough to matter gets reviewed, not
  assumed;
- **materialized replication**: non-gradient ``all-reduce``s at
  threshold size in the optimized HLO — the signature of XLA
  rebuilding a full array on every device (the first real scan caught
  the two-frame image-concat doing exactly this; see
  ``RAFTConfig.split_encode``). Gradient reductions are data
  parallelism's PURPOSE, not a finding: instructions whose ``op_name``
  marks the backward transpose are skipped.
"""

from __future__ import annotations

import re
from typing import List

from ..finding import ShardFinding
from ..spec import Artifacts, ShardTarget

RULE = "S2"
NAME = "replicated-large-value"

#: op_name marker of reverse-mode transpose computations — their
#: all-reduces ARE the data-parallel gradient reduction
_GRAD_MARK = "transpose("

_SHARDING_CC_RE = re.compile(
    r"stablehlo\.custom_call @Sharding\((%[\w#]+)\)\s*"
    r"\{[^\n]*mhlo\.sharding = \"\{replicated\}\"[^\n]*\}\s*:\s*"
    r"\(tensor<([^>]+)>\)")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "i64": 8,
                "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
                "i8": 1, "ui8": 1, "i1": 1}


def _tensor_bytes(ty: str) -> int:
    """bytes of a stablehlo ``tensor<...>`` body, e.g. '8x32x3xf32'."""
    parts = ty.split("x")
    n = _DTYPE_BYTES.get(parts[-1], 4)
    for p in parts[:-1]:
        if p.isdigit():
            n *= int(p)
    return n


def _shardable(shape, mesh_axes) -> bool:
    sizes = [s for s in mesh_axes.values() if s > 1]
    return any(d % s == 0 and d >= s for d in shape for s in sizes)


def check(target: ShardTarget, art: Artifacts) -> List[ShardFinding]:
    out: List[ShardFinding] = []
    limit = target.replicated_bytes_max
    for side, infos in (("arg", art.in_info), ("out", art.out_info)):
        for inf in infos:
            if not inf.replicated or inf.nbytes < limit:
                continue
            if not _shardable(inf.shape, art.mesh_axes):
                continue
            detail = f"{side} {inf.index} {inf.path}"
            out.append(ShardFinding(
                target.name, RULE, NAME, detail,
                f"{side} {inf.index} ({inf.path}, {inf.dtype}"
                f"{list(inf.shape)}, {inf.nbytes:,} bytes) resolved "
                "fully replicated though a mesh axis divides it — "
                "every device holds the whole array; declare a "
                "PartitionSpec or waive with the reason it must "
                "replicate"))
    if art.lowered_text:
        for m in _SHARDING_CC_RE.finditer(art.lowered_text):
            nbytes = _tensor_bytes(m.group(2))
            if nbytes < limit:
                continue
            detail = f"constrained-replicated tensor<{m.group(2)}>"
            out.append(ShardFinding(
                target.name, RULE, NAME, detail,
                f"with_sharding_constraint pins tensor<{m.group(2)}> "
                f"({nbytes:,} bytes) to full replication — if the "
                "constraint is load-bearing, waive it with the reason; "
                "otherwise name the axis that should shard it"))
    if art.hlo_text:
        from tools import hlo_lib

        seen = set()
        for rec in hlo_lib.find_collectives(art.hlo_text):
            if rec["opcode"] != "all-reduce":
                continue
            if _GRAD_MARK in rec["op_name"]:
                continue
            if rec["bytes"] < limit:
                continue
            detail = (f"all-reduce {rec['shape']} @ "
                      f"{rec['op_name'] or '(no op_name)'}")
            if detail in seen:
                continue
            seen.add(detail)
            out.append(ShardFinding(
                target.name, RULE, NAME, detail,
                f"all-reduce materializes {rec['shape']} "
                f"({rec['bytes']:,} bytes) identically on every device "
                f"at {rec['op_name'] or '(no op_name)'} — a "
                "non-gradient reduction this large is a value being "
                "rebuilt replicated (resharding fallout, e.g. a "
                "concat/reshape across the sharded dim); restructure "
                "or waive with the reason"))
    return out
