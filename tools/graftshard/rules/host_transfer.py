"""S3: host boundary crossings inside a compiled mesh program.

graftaudit H1 already hunts callbacks in single-device programs; on a
mesh the stakes are higher — a host round-trip serializes EVERY device
in the partition against one host thread — and a new hazard appears:
``jax.device_put`` traced INSIDE the program. In eager code device_put
is placement; inside jit it becomes a resharding op whose cost
(cross-device copies, or a full gather to host semantics) is invisible
at the call site. Placement belongs OUTSIDE the compiled hot path —
the engine's dispatch layer device_puts against the Partitioner's
specs before calling the executable; in-program resharding should be
``with_sharding_constraint``, which is declarative and free when
already satisfied.
"""

from __future__ import annotations

from typing import List

from ..finding import ShardFinding
from ..spec import Artifacts, ShardTarget

RULE = "S3"
NAME = "host-transfer-in-mesh-program"

_HOST_PRIMS = ("pure_callback", "io_callback", "debug_callback",
               "callback", "infeed", "outfeed", "host_callback")
_PLACEMENT_PRIMS = ("device_put",)


def check(target: ShardTarget, art: Artifacts) -> List[ShardFinding]:
    from tools.graftaudit.artifacts import iter_subjaxprs

    out: List[ShardFinding] = []
    seen = set()
    if art.jaxpr is not None:
        for eqn in iter_subjaxprs(art.jaxpr.jaxpr):
            pname = eqn.primitive.name
            host = any(pname == p or pname.startswith(p + "_")
                       for p in _HOST_PRIMS)
            placement = any(pname == p for p in _PLACEMENT_PRIMS)
            if not (host or placement):
                continue
            detail = f"{pname} @ {eqn.source_info.name_stack}"
            if detail in seen:
                continue
            seen.add(detail)
            if host:
                msg = (f"'{pname}' traced into the mesh program at "
                       f"{eqn.source_info.name_stack} — every "
                       "execution serializes the whole partition "
                       "against the host")
            else:
                msg = (f"'{pname}' traced into the mesh program at "
                       f"{eqn.source_info.name_stack} — in-program "
                       "placement is a hidden reshard; move it to the "
                       "dispatch layer or use with_sharding_constraint")
            out.append(ShardFinding(target.name, RULE, NAME, detail,
                                    msg))
    if art.hlo_text:
        from tools import hlo_lib

        for rec in hlo_lib.find_host_ops(art.hlo_text):
            detail = f"hlo:{rec['detail']} @ {rec['op_name']}"
            if detail in seen:
                continue
            seen.add(detail)
            out.append(ShardFinding(
                target.name, RULE, NAME, detail,
                f"compiled mesh module contains host-boundary op "
                f"'{rec['opcode']}' ({rec['detail']}) at "
                f"{rec['op_name'] or '(no metadata)'}"))
    return out
