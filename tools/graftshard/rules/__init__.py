"""graftshard rules S1–S6, one module per sharding bug class.

Every module exports ``RULE`` (the id), ``NAME`` (kebab-case), and
``check(target, art) -> List[ShardFinding]``. Waivers are applied by
the driver, not here.
"""

from . import comm_in_loop            # noqa: F401  (S1)
from . import replication             # noqa: F401  (S2)
from . import host_transfer           # noqa: F401  (S3)
from . import spec_consistency        # noqa: F401  (S4)
from . import uneven_shard            # noqa: F401  (S5)
from . import donation_reshard        # noqa: F401  (S6)

ALL_RULES = [comm_in_loop, replication, host_transfer,
             spec_consistency, uneven_shard, donation_reshard]
