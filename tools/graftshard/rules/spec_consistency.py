"""S4: partition-spec consistency — specs must name real axes, and
boundary values must be CONSTRAINED.

Two failure shapes, both silent at runtime:

- a declared spec naming an axis the mesh doesn't have. jax rejects a
  ``NamedSharding`` built against the wrong mesh loudly, but the
  DECLARATION layer (the Partitioner's rule table, a config file, a
  fixture) drifts independently of whichever mesh a deployment builds
  — the audit holds the two together;
- an entry parameter with no explicit sharding at all. With parameter
  propagation off (jax's default for lowered-with-avals programs) XLA
  resolves it to REPLICATED without a word — the
  ``with_sharding_constraint`` discipline as a gate: every boundary
  value above the de-minimis floor either declares its spec or gets
  reviewed. (The first real scan caught the train step's rng key
  riding unconstrained; trainer.py now device_puts it replicated on
  purpose, where a reviewer can see the decision.)
"""

from __future__ import annotations

from typing import List

from ..finding import ShardFinding
from ..spec import Artifacts, ShardTarget

RULE = "S4"
NAME = "spec-inconsistent"


def _axes_of(spec_axes) -> List[str]:
    out = []
    for entry in spec_axes:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.extend(entry)
        else:
            out.append(entry)
    return out


def check(target: ShardTarget, art: Artifacts) -> List[ShardFinding]:
    out: List[ShardFinding] = []
    for kind, axes in target.declared_specs:
        missing = [a for a in _axes_of(axes)
                   if a not in art.mesh_axes]
        if missing:
            detail = f"spec {kind} names {missing}"
            out.append(ShardFinding(
                target.name, RULE, NAME, detail,
                f"declared spec for '{kind}' ({tuple(axes)}) names "
                f"mesh axes {missing} absent from the target's mesh "
                f"{sorted(art.mesh_axes)} — the declaration drifted "
                "from the deployment mesh; values under this spec "
                "will not shard the way the code promises"))
    for inf in art.in_info:
        if inf.annotated or inf.nbytes < target.boundary_bytes_min:
            continue
        detail = f"unconstrained arg {inf.index} {inf.path}"
        out.append(ShardFinding(
            target.name, RULE, NAME, detail,
            f"arg {inf.index} ({inf.path}, {inf.dtype}"
            f"{list(inf.shape)}, {inf.nbytes:,} bytes) enters the "
            "mesh program with no declared sharding — XLA silently "
            "replicates it; declare the spec (or device_put it "
            "replicated on purpose, where the decision is visible)"))
    return out
