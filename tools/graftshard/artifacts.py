"""Build shard-audit artifacts: trace, lower, and compile on a forced
multi-device CPU mesh.

Sharding structure — which values replicate, where GSPMD inserts
collectives, whether a donation survives resharding — is decided at
trace/lower/partition time, not by the execution platform, so a CPU
host forced to ``--xla_force_host_platform_device_count=4`` exercises
the same SPMD partitioner a TPU pod runs (the byte THRESHOLDS are the
one platform-sensitive knob; ``shard_audit_r6`` re-anchors them from
real sharded TPU HLO).
"""

from __future__ import annotations

import time

from .spec import ArgInfo, Artifacts, ShardTarget

#: devices the audit mesh needs; the driver forces the CPU host to at
#: least this many when it owns the interpreter
MESH_DEVICES = 4



def prepare_env(min_devices: int = MESH_DEVICES) -> None:
    """Env-only half of :func:`ensure_mesh_cpu`: set the CPU backend +
    device-count flags if jax is not yet imported, WITHOUT importing
    jax. The driver calls this before loading fixture modules (which,
    like the sibling tiers' fixtures, import jax at module scope)."""
    import os
    import sys

    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{min_devices}").strip()


def ensure_mesh_cpu(min_devices: int = MESH_DEVICES):
    """Force the CPU backend with >= ``min_devices`` virtual devices.

    Same discipline as graftaudit's ``ensure_cpu`` (the image's
    sitecustomize registers the 'axon' remote-TPU plugin everywhere —
    an audit must never dial the tunnel), plus the host-platform
    device-count flag, which only works BEFORE jax initializes. Inside
    pytest the conftest already forced 8 devices; a bare
    ``python -m tools.graftshard`` sets its own flag here. An
    interpreter that already initialized jax with too few devices
    cannot grow them — that is a usage error, reported actionably.
    """
    prepare_env(min_devices)
    import jax

    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")
    n = len(jax.devices())
    if n < min_devices:
        raise RuntimeError(
            f"graftshard needs a {min_devices}-device mesh but this "
            f"interpreter already initialized jax with {n} device(s) — "
            "run `python -m tools.graftshard` in a fresh process, or "
            "set XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{min_devices} before anything imports jax")
    return jax


def _entry_arg_chunks(lowered_text: str):
    """``(index, chunk text)`` per entry parameter of the lowered
    module's ``@main`` signature. Split on ``%arg`` instead of regexing
    attribute dicts: a mesh program's attrs NEST braces
    (``mhlo.sharding = "{devices=[4]<=[4]}"``), which brace-matching
    regexes silently fail on — graftaudit's single-device ``_ARG_RE``
    is exactly such a regex and must not be reused here."""
    try:
        sig = lowered_text[lowered_text.index("@main("):]
        sig = sig[:sig.index(") -> ")]
    except ValueError:
        return
    for chunk in sig.split("%arg")[1:]:
        ix = chunk.split(":", 1)[0]
        if ix.isdigit():
            yield int(ix), chunk


def annotated_args(lowered_text: str) -> set:
    """Flat arg indices whose LOWERED entry signature carries an
    explicit ``mhlo.sharding`` attribute. XLA resolves the rest to
    replicated without a word — the S4 'unconstrained boundary'
    surface."""
    return {ix for ix, chunk in _entry_arg_chunks(lowered_text)
            if "mhlo.sharding" in chunk}


def declared_donations(lowered_text: str) -> list:
    """Flat arg indices the lowered mesh module marks donatable
    (``tf.aliasing_output`` when jax matched an output itself,
    ``jax.buffer_donor`` when it deferred to XLA) — the S6 input set."""
    return sorted(ix for ix, chunk in _entry_arg_chunks(lowered_text)
                  if "tf.aliasing_output" in chunk
                  or "jax.buffer_donor" in chunk)


def _spec_tuple(sharding):
    """NamedSharding -> per-dim spec tuple, or None when the sharding
    carries no spec (GSPMD/other backends)."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return tuple(spec)


def _info(index, path, aval, sharding, annotated=True) -> ArgInfo:
    import numpy as np

    nbytes = int(np.prod(aval.shape)) * aval.dtype.itemsize \
        if aval.shape else aval.dtype.itemsize
    # UNKNOWN sharding (compiled=False target, or a jax version whose
    # input_shardings read failed) must not read as replicated — S2
    # would then report false replicated-large-value findings for
    # every properly-sharded boundary value
    replicated = bool(getattr(sharding, "is_fully_replicated", False)) \
        if sharding is not None else False
    return ArgInfo(index=index, path=path, shape=tuple(aval.shape),
                   dtype=str(aval.dtype), nbytes=nbytes,
                   spec=_spec_tuple(sharding) if sharding is not None
                   else None,
                   replicated=replicated, annotated=annotated)


def build_artifacts(target: ShardTarget) -> Artifacts:
    """Trace/lower/compile one target on its mesh and bundle what the
    rules need."""
    jax = ensure_mesh_cpu()
    t0 = time.perf_counter()
    art = Artifacts()
    if target.kind == "decl":
        mesh = target.build()
        art.mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        art.seconds = time.perf_counter() - t0
        return art
    if target.kind != "trace":
        raise ValueError(f"target {target.name}: unknown kind "
                         f"{target.kind!r} (trace|decl)")
    fn, args, mesh = target.build()
    art.mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))

    flat, _ = jax.tree_util.tree_flatten_with_path(tuple(args))
    paths = [jax.tree_util.keystr(p) for p, _ in flat]

    art.jaxpr = jax.make_jaxpr(fn)(*args)
    jitted = jax.jit(fn, donate_argnums=target.donate_argnums)
    lowered = jitted.lower(*args)
    art.lowered_text = lowered.as_text()
    ann = annotated_args(art.lowered_text)

    in_avals = list(art.jaxpr.in_avals)
    in_shardings = None
    out_shardings = None
    out_paths = None
    if target.compiled:
        compiled = lowered.compile()
        art.hlo_text = compiled.as_text()
        try:
            args_sh, _kwargs_sh = compiled.input_shardings
            in_shardings = jax.tree_util.tree_leaves(
                args_sh, is_leaf=lambda x: x is None)
            out_flat, _ = jax.tree_util.tree_flatten_with_path(
                compiled.output_shardings,
                is_leaf=lambda x: x is None)
            out_paths = [jax.tree_util.keystr(p) for p, _ in out_flat]
            out_shardings = [s for _, s in out_flat]
        except Exception:
            pass

    for i, aval in enumerate(in_avals):
        sh = (in_shardings[i] if in_shardings is not None
              and i < len(in_shardings) else None)
        art.in_info.append(_info(
            i, paths[i] if i < len(paths) else f"arg{i}", aval, sh,
            annotated=(i in ann)))
    for i, aval in enumerate(art.jaxpr.out_avals):
        sh = (out_shardings[i] if out_shardings is not None
              and i < len(out_shardings) else None)
        # output paths come from the output tree (so a waiver can
        # scope to e.g. the returned train state, " [0][", without
        # swallowing every output); outputs have no annotation story —
        # propagation to outputs is allowed by design
        path = (out_paths[i] if out_paths is not None
                and i < len(out_paths) else f"out[{i}]")
        art.out_info.append(_info(i, path, aval, sh))
    art.seconds = time.perf_counter() - t0
    return art
