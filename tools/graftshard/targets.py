"""The repo's real shard-audit targets: the data-parallel train step
and the pjit-sharded serve trace.

Shapes are tiny (32x32, batch 4 over a data=4 mesh, 2 refinement
iterations): sharding structure — where collectives land, what
replicates, whether donations survive partitioning — is decided by
program structure and the spec table, not by scale. The one
scale-sensitive artifact is the S2 byte threshold, pinned per target
here and re-anchored against real sharded TPU HLO by the
``shard_audit_r6`` rung.

Both targets pull their specs from ``parallel.partitioner.Partitioner``
— the audit checks the SAME table the runtime shards with, which is
the point: drift between what the code promises and what the mesh can
do fails this gate, not a 3 a.m. page.

First-scan findings, FIXED at the site rather than baselined (the
graftlint/graftaudit/graftthread arc, one tier up):

- S2: the two-frame batch-concat encode redistributed every image row
  per step (XLA materialized the concat replicated via
  dynamic-update-slice + all-reduce, then collective-permuted the
  fmap halves back) → ``RAFTConfig.split_encode``, turned on by
  ``mesh_model_config`` wherever the 'data' axis is >1;
- S4: the train step's rng key entered the program unconstrained
  (silently replicated) → trainer.py now device_puts it replicated
  where a reviewer can see the decision.

What remains waived below is intentional-by-design, with the reason
at the declaration.
"""

from __future__ import annotations

from typing import List

from .artifacts import ensure_mesh_cpu
from .spec import ShardTarget, Waiver

_IMAGE_HW = (32, 32)
_ITERS = 2
_BATCH = 4     # one whole example per 'data' shard at the audit mesh

#: weights/optimizer state replicated by design: every device runs the
#: whole net over its batch rows — plain data parallelism. Sharded
#: (FSDP-style) state is the ROADMAP's next axis, not a default.
#: The match is the STATE TREE's path prefix, hitting exactly values
#: inside the first positional arg's (and, for the train step, the
#: first output's) pytree — the train state is a flax struct so its
#: leaves render attr-style (``arg 4 [0].params['cnet']...``,
#: ``out 12 [0].params...``); the serve weights are a plain dict
#: (``arg 33 [0]['params']...``). It must NOT be a bare "arg"/"out":
#: that would waive EVERY S2 boundary finding (a dropped frames
#: sharding, a new unsharded input) and kill the rule's surface on
#: these targets.
_W_STATE = Waiver(
    "S2", " [0].",
    "the train state tree (params + opt state, arg 0 in and out) is "
    "replicated by design under data parallelism; FSDP-style sharded "
    "state is a ROADMAP item — this waiver is the marker to drop "
    "when it lands")
_W_WEIGHTS = Waiver(
    "S2", " [0][",
    "the serving weight tree (arg 0) is replicated by design: every "
    "device runs the whole net over its batch rows; weight-sharded "
    "serving is the 4K-frame spatial regime, not this seam")

#: the backward scan's per-iteration gradient all-reduces: XLA's CPU
#: pipeline leaves the scan-carried weight-grad reductions inside the
#: transpose loop body; the TPU pipeline sinks loop-accumulated
#: all-reduces out of the while (WhileLoopAllReduceCodeMotion), which
#: is the deployment this audits for. Scoped to the transpose op_names
#: so FORWARD-loop comm — the serving hazard — still gates. The
#: ``shard_audit_r6`` rung captures real sharded TPU HLO to verify the
#: sink and retire or tighten this waiver.
_W_BWD_SCAN = Waiver(
    "S1", "transpose(",
    "per-iteration weight-grad all-reduces in the backward scan are a "
    "forced-CPU-mesh artifact: the TPU pass pipeline sinks "
    "loop-accumulated reductions (WhileLoopAllReduceCodeMotion); "
    "re-anchored on real sharded TPU HLO by shard_audit_r6")


def _get_jax(n_devices: int, force_cpu: bool):
    """The gate builds on the forced CPU mesh; the ``shard_audit_r6``
    on-chip rung passes ``force_cpu=False`` to compile the SAME
    recipes on the real backend's devices (one builder, two
    platforms — the re-anchoring evidence must come from the exact
    program the gate audits)."""
    if force_cpu:
        return ensure_mesh_cpu(n_devices)
    import jax
    return jax


def _build_train_step_dp(image_hw=_IMAGE_HW, batch=_BATCH, iters=_ITERS,
                         n_devices=4, force_cpu=True):
    def build():
        jax = _get_jax(n_devices, force_cpu)
        import jax.numpy as jnp

        from raft_tpu.config import RAFTConfig, TrainConfig
        from raft_tpu.parallel.mesh import make_mesh
        from raft_tpu.parallel.partitioner import (Partitioner,
                                                   mesh_model_config)
        from raft_tpu.training.train_step import (create_train_state,
                                                  make_train_step)

        mesh = make_mesh(n_devices, spatial=1)
        part = Partitioner(mesh)
        cfg = mesh_model_config(RAFTConfig(), mesh)
        h, w = image_hw
        tc = TrainConfig(iters=iters, batch_size=batch,
                         image_size=(h, w))
        rng = jax.random.PRNGKey(0)
        # avals only — the audit lowers/compiles against shapes +
        # shardings, it never runs the step
        state = jax.eval_shape(
            lambda: create_train_state(cfg, tc, rng,
                                       image_hw=(h, w)))
        state = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=part.replicated),
            state)
        frames = part.sharding("frames")
        b = {
            "image1": jax.ShapeDtypeStruct((batch, h, w, 3), jnp.uint8,
                                           sharding=frames),
            "image2": jax.ShapeDtypeStruct((batch, h, w, 3), jnp.uint8,
                                           sharding=frames),
            "flow": jax.ShapeDtypeStruct((batch, h, w, 2), jnp.float32,
                                         sharding=part.sharding("flow")),
            "valid": jax.ShapeDtypeStruct((batch, h, w), jnp.uint8,
                                          sharding=part.sharding("valid")),
        }
        # the rng boundary is DECLARED replicated (trainer.py does the
        # same device_put) — the first-scan S4 fix, kept fixed
        rngspec = jax.ShapeDtypeStruct(rng.shape, rng.dtype,
                                       sharding=part.replicated)
        return (make_train_step(cfg, tc), (state, b, rngspec), mesh)
    return build


def _build_serve_shard(image_hw=_IMAGE_HW, batch=_BATCH, iters=_ITERS,
                       n_devices=4, force_cpu=True):
    def build():
        jax = _get_jax(n_devices, force_cpu)
        import jax.numpy as jnp

        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT
        from raft_tpu.parallel.mesh import make_mesh
        from raft_tpu.parallel.partitioner import (Partitioner,
                                                   mesh_model_config)

        mesh = make_mesh(n_devices, spatial=1)
        part = Partitioner(mesh)
        cfg = mesh_model_config(RAFTConfig(), mesh)
        model = RAFT(cfg)
        h, w = image_hw
        # the deployed fan-out recipe this PR opens
        # (RAFTEngine(mesh=..., warm_start=True, wire="u8")): uint8
        # frames batch-sharded over 'data', on-device normalize, the
        # 1/8-res flow_init donated to its same-shaped (and
        # same-SHARDED) flow_low output — S6 verifies the alias
        # survives partitioning
        img = jax.ShapeDtypeStruct((batch, h, w, 3), jnp.uint8,
                                   sharding=part.sharding("frames"))
        finit = jax.ShapeDtypeStruct((batch, h // 8, w // 8, 2),
                                     jnp.float32,
                                     sharding=part.sharding("flow_init"))
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3)),
                               jnp.zeros((1, h, w, 3)), iters=1))
        variables = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=part.replicated),
            variables)

        def serve(variables, image1, image2, flow_init):
            flow_low, flow_up = model.apply(
                variables, image1, image2, iters=iters,
                flow_init=flow_init, test_mode=True)
            return flow_low, flow_up

        return serve, (variables, img, img, finit), mesh
    return build


def _audit_partitioner():
    """The committed spec table + audit-bucket geometry, for the
    declaration-tier rules. A LITERAL MIRROR of
    ``Partitioner.declared_specs()`` / ``Partitioner.shard_geometry
    ((4, 32, 32))`` on purpose: building the real Partitioner needs a
    mesh and therefore jax, and this module must stay importable
    jax-free (the warm cache path answers with no jax import at all).
    Drift between the mirror and the live methods is itself a gate
    failure — ``tests/test_graftshard.py::
    test_targets_declare_the_partitioner_table`` pins both halves."""
    specs = (
        ("frames", ("data", "spatial", None, None)),
        ("flow_init", ("data", "spatial", None, None)),
        ("flow", ("data", "spatial", None, None)),
        ("valid", ("data", "spatial", None)),
        ("weights", ()),
    )
    h, w = _IMAGE_HW
    geometry = (
        {"name": f"batch {_BATCH}", "extent": _BATCH, "axis": "data",
         "row_bytes": h * w * 3 * 4},
        {"name": f"image-height {h}", "extent": h, "axis": "spatial",
         "row_bytes": _BATCH * w * 3 * 4},
        # feature rows carry the basic fnet's 256 channels — the
        # dominant per-row tensor a padded shard wastes whole
        {"name": f"feature-height {h}//8", "extent": h // 8,
         "axis": "spatial", "row_bytes": _BATCH * (w // 8) * 256 * 4},
    )
    return specs, geometry


def build_targets() -> List[ShardTarget]:
    specs, geometry = _audit_partitioner()
    return [
        ShardTarget(
            name="train_step_dp",
            build=_build_train_step_dp(),
            donate_argnums=(0,),   # trainer.py jits with donate (0,);
            #                        state in AND out replicated — the
            #                        alias must survive partitioning
            declared_specs=specs,
            shard_geometry=geometry,
            waivers=(_W_STATE, _W_BWD_SCAN),
            notes="data-parallel train step on the (data=4, spatial=1) "
                  "forced CPU mesh: the raft_tpu/parallel recipe "
                  "(replicated state, shard_batch layouts, declared "
                  "rng) exactly as trainer.py builds it"),
        ShardTarget(
            name="serve_shard",
            build=_build_serve_shard(),
            donate_argnums=(3,),   # flow_init -> flow_low: the u8 warm
            #                        engine's zero-copy recurrence,
            #                        sharded — S6 proves the alias
            #                        survives partitioning
            declared_specs=specs,
            shard_geometry=geometry,
            waivers=(_W_WEIGHTS,),
            notes="pjit-sharded serve trace (the "
                  "RAFTEngine(mesh=..., warm_start=True, wire='u8') "
                  "program): batch over 'data', weights replicated, "
                  "donated flow_init — the fan-out seam's first brick, "
                  "audited before any multi-device config ships"),
    ]
