"""StableHLO export -> reload -> run, on the real chip (VERDICT r2 #7).

The reference proves its export path by running the TRT engine against
torch on the same frames (test_trt.py:74-97); the analog here is: export
the serving fn at the Linux-envelope shape, deserialize the blob as a
fresh consumer would, execute it on the TPU, and diff against the live
jit path. Timing uses a host value-fetch fence (block_until_ready lies on
the axon backend — BENCH_NOTES methodology).
"""

import os.path as osp
import sys
import time

import numpy as np

# runnable as `python tools/export_cycle_check.py` — put the repo root on
# the path so raft_tpu imports without an install step
sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

from raft_tpu.utils.platform import setup_cli

setup_cli()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from raft_tpu.config import RAFTConfig  # noqa: E402
from raft_tpu.models import RAFT  # noqa: E402
from raft_tpu.serving.export import (export_stablehlo,  # noqa: E402
                                     load_stablehlo, make_serving_fn)


def main():
    hw = (440, 1024)
    cfg = RAFTConfig()
    model = RAFT(cfg)
    rng = np.random.RandomState(0)
    img = rng.rand(1, *hw, 3).astype(np.float32) * 255
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(img),
                           jnp.asarray(img), iters=1)

    t0 = time.perf_counter()
    blob = export_stablehlo(variables, cfg, iters=20, image_hw=hw,
                            dynamic_batch=False)
    print(f"export: {len(blob) / 1e6:.1f} MB in "
          f"{time.perf_counter() - t0:.1f}s", flush=True)

    runner = load_stablehlo(blob)
    i1 = jnp.asarray(img)
    i2 = jnp.asarray(rng.rand(1, *hw, 3).astype(np.float32) * 255)

    t0 = time.perf_counter()
    out = runner(i1, i2)
    first = float(jnp.abs(out).mean())  # value fetch = honest fence
    print(f"reloaded-run first call (compile+run): "
          f"{time.perf_counter() - t0:.1f}s, mean|flow|={first:.3f}",
          flush=True)

    t0 = time.perf_counter()
    n = 5
    for _ in range(n):
        # same-stream in-order execution: fetching the LAST result fences
        # the whole sequence (per-call block_until_ready lies on axon)
        out = runner(i1, i2)
    fenced = float(jnp.abs(out).mean())
    dt = (time.perf_counter() - t0) / n
    print(f"reloaded-run steady: {dt * 1e3:.1f} ms/pair "
          f"({1 / dt:.2f} pairs/s) at {hw}, mean|flow|={fenced:.3f}",
          flush=True)

    want = jax.jit(make_serving_fn(variables, cfg, 20))(i1, i2)
    diff = float(jnp.abs(out - want).max())
    print(f"export-vs-jit max diff: {diff:.2e} px", flush=True)
    ok = np.isfinite(fenced) and diff < 1e-2
    print("EXPORT_CYCLE", "OK" if ok else "MISMATCH", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
