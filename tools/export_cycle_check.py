"""Serialized-executable cache cycle, on the real chip (VERDICT r2 #7).

The reference proves its export path by running the TRT engine against
torch on the same frames (test_trt.py:74-97); the analog here rides
the PRODUCTION artifact seam (``raft_tpu/serving/aot.py``): compile
the serving fn at the Linux-envelope shape, STORE it through
``AOTCache`` (serialize + manifest), reload it through the verified
load path as a restarting replica would, execute the loaded
executable on the TPU, and diff against the live jit path. The
StableHLO text export (``serving/export.py``) is kept as the
portability artifact — its size line still prints — but the
round-trip under test is the one ``RAFTEngine(aot_cache=...)``
actually serves from. Timing uses a host value-fetch fence
(block_until_ready lies on the axon backend — BENCH_NOTES
methodology).
"""

import os.path as osp
import sys
import tempfile
import time

import numpy as np

# runnable as `python tools/export_cycle_check.py` — put the repo root on
# the path so raft_tpu imports without an install step
sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

from raft_tpu.utils.platform import setup_cli

setup_cli()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from raft_tpu.config import RAFTConfig  # noqa: E402
from raft_tpu.models import RAFT  # noqa: E402
from raft_tpu.serving import aot  # noqa: E402
from raft_tpu.serving.export import (export_stablehlo,  # noqa: E402
                                     make_serving_fn)


def main():
    hw = (440, 1024)
    iters = 20
    cfg = RAFTConfig()
    model = RAFT(cfg)
    rng = np.random.RandomState(0)
    img = rng.rand(1, *hw, 3).astype(np.float32) * 255
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(img),
                           jnp.asarray(img), iters=1)

    # the portability artifact (text MLIR): size only — the executable
    # round trip below is the path replicas actually load from
    t0 = time.perf_counter()
    blob = export_stablehlo(variables, cfg, iters=iters, image_hw=hw,
                            dynamic_batch=False)
    print(f"export: {len(blob) / 1e6:.1f} MB in "
          f"{time.perf_counter() - t0:.1f}s", flush=True)

    fn = jax.jit(make_serving_fn(variables, cfg, iters))
    i1 = jnp.asarray(img)
    i2 = jnp.asarray(rng.rand(1, *hw, 3).astype(np.float32) * 255)

    t0 = time.perf_counter()
    # fresh_compile: a jax-persistent-cache-deserialized executable
    # serializes to a payload that can never load back — the compile
    # feeding the store must come from the backend
    with aot.fresh_compile():
        lowered = fn.lower(i1, i2)
        compiled = lowered.compile()
    print(f"live compile: {time.perf_counter() - t0:.1f}s", flush=True)

    cache = aot.AOTCache(tempfile.mkdtemp(prefix="export-cycle-"))
    key = {
        "format": aot.AOT_FORMAT,
        "program": "export_cycle",
        "weights": aot.weights_fingerprint(variables),
        "geometry": [1, *hw],
        "wire": "f32",
        "iters": iters,
        "config": aot.config_fingerprint(cfg, iters),
        "donations": [],
        "partition": "single",
        "jax": jax.__version__,
        "jaxlib": __import__("jaxlib").__version__,
        "platform": jax.default_backend(),
    }
    t0 = time.perf_counter()
    edir = cache.store(key, compiled, lowered=lowered, args=(i1, i2))
    if edir is None:
        print("EXPORT_CYCLE MISMATCH (store failed)", flush=True)
        return 1
    print(f"aot store: {time.perf_counter() - t0:.1f}s -> {edir}",
          flush=True)

    t0 = time.perf_counter()
    runner = cache.load(key)   # the verified path a replica takes
    if runner is None:
        print(f"EXPORT_CYCLE MISMATCH (load missed: {cache.last_miss})",
              flush=True)
        return 1
    out = runner(i1, i2)
    first = float(jnp.abs(out).mean())  # value fetch = honest fence
    print(f"reloaded-run first call (load+run, NO compile): "
          f"{time.perf_counter() - t0:.1f}s, mean|flow|={first:.3f}",
          flush=True)

    t0 = time.perf_counter()
    n = 5
    for _ in range(n):
        # same-stream in-order execution: fetching the LAST result fences
        # the whole sequence (per-call block_until_ready lies on axon)
        out = runner(i1, i2)
    fenced = float(jnp.abs(out).mean())
    dt = (time.perf_counter() - t0) / n
    print(f"reloaded-run steady: {dt * 1e3:.1f} ms/pair "
          f"({1 / dt:.2f} pairs/s) at {hw}, mean|flow|={fenced:.3f}",
          flush=True)

    want = fn(i1, i2)
    diff = float(jnp.abs(out - want).max())
    print(f"aot-load-vs-jit max diff: {diff:.2e} px", flush=True)
    ok = np.isfinite(fenced) and diff == 0.0
    print("EXPORT_CYCLE", "OK" if ok else "MISMATCH", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
