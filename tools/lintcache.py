"""Shared static-analysis machinery: pragmas, baselines, parse cache.

graftlint (source tier) and graftthread (thread-safety tier) are the
same *kind* of tool — walk files, run AST rules, apply per-line pragmas
and a shrink-only baseline, accelerate repeats with a content-hash
cache — differing only in their rule packages. This module is the one
copy of everything below the rules:

- ``parse_pragmas(source, tool)``: tokenizer-backed per-line
  ``# <tool>: disable=...`` suppression (a string literal that merely
  CONTAINS the pragma text must not suppress);
- ``package_signature(*roots)``: content hash over the tool's own
  ``.py`` files (this module included by the callers) — a cache must
  never outlive the code that produced it;
- ``load_cache``/``save_cache``: the content-hash parse cache, atomic
  last-writer-wins writes (concurrent gate runs may each write; any
  complete file is a valid cache);
- ``evict_dead_entries``: superseded-digest and deleted-file eviction,
  so the shared user-level cache file cannot grow forever;
- ``map_jobs``: serial or process-pool execution over cache misses;
- ``load_baseline``/``write_baseline``/``apply_baseline``/``code_line``:
  the shrink-only grandfather file keyed on (path, rule, source text)
  — line numbers drift across edits, the triple mostly doesn't.

Pure stdlib; importable by tools that must not import jax.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import tokenize
from collections import Counter
from typing import (Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

#: directory basenames never entered when walking a directory argument
#: (the *_fixtures dirs hold intentionally-violating code for the
#: other tiers' tests — each tool must skip them all, or one tier's
#: fixtures fail another tier's gate)
EXCLUDED_DIRS = {"__pycache__", ".git", "graftlint_fixtures",
                 "graftaudit_fixtures", "graftthread_fixtures",
                 "graftshard_fixtures", "graftexport_fixtures",
                 "graftwire_fixtures", "node_modules", ".venv"}


def collect_files(paths: Sequence[str],
                  excluded_dirs: Optional[set] = None) -> List[str]:
    """Expand dir args to ``**/*.py`` (minus excluded dirs); keep
    explicit file args verbatim (even non-.py: caller's choice)."""
    excluded = EXCLUDED_DIRS if excluded_dirs is None else excluded_dirs
    out: List[str] = []
    seen = set()

    def add(path: str) -> None:
        key = os.path.normpath(path)
        if key not in seen:   # a file named explicitly AND reached by a
            seen.add(key)     # dir walk must lint once, not twice
            out.append(path)

    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in excluded)
                for f in sorted(files):
                    if f.endswith(".py"):
                        add(os.path.join(root, f))
        else:
            add(p)
    return out


# -- pragmas --------------------------------------------------------------

def _pragma_re(tool: str) -> re.Pattern:
    # rule list only — a trailing bare-word justification ("disable=T5
    # poll-loop daemon by design") must not be swallowed into the id
    return re.compile(
        rf"#\s*{re.escape(tool)}:\s*disable="
        r"(all|[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


def parse_pragmas(source: str, tool: str) -> Dict[int, Optional[set]]:
    """line number -> set of disabled rule ids (None = all rules).

    Tokenized, not regexed over raw lines: the pragma must live in an
    actual COMMENT token."""
    pragma_re = _pragma_re(tool)
    pragmas: Dict[int, Optional[set]] = {}
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return pragmas   # unparsable files already yield E1 findings
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = pragma_re.search(tok.string)
        if not m:
            continue
        spec = m.group(1).strip()
        line = tok.start[0]
        if spec.lower() == "all":
            pragmas[line] = None
        else:
            pragmas[line] = {r.strip().upper() for r in spec.split(",")
                             if r.strip()}
    return pragmas


# -- package signature + cache file ---------------------------------------

_SIG_CACHE: Dict[Tuple[str, ...], str] = {}


def package_signature(*roots: str) -> str:
    """Content hash over every ``.py`` under ``roots`` (dirs or files):
    editing any rule, driver, or this shared module invalidates every
    cache entry keyed under the old signature."""
    key = tuple(os.path.abspath(r) for r in roots)
    cached = _SIG_CACHE.get(key)
    if cached is not None:
        return cached
    h = hashlib.sha256()

    def feed(path: str) -> None:
        with open(path, "rb") as fh:
            h.update(os.path.basename(path).encode() + b"\0" + fh.read())

    for root in key:
        if os.path.isdir(root):
            for r, dirs, files in os.walk(root):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        feed(os.path.join(r, f))
        else:
            feed(root)
    sig = h.hexdigest()[:16]
    _SIG_CACHE[key] = sig
    return sig


def default_cache_path(env_var: str, filename: str) -> str:
    root = os.environ.get(env_var)
    if root:
        return root
    home = os.path.expanduser("~")
    base = (os.path.join(home, ".cache") if home != "~"
            else os.path.join(os.sep, "tmp"))
    return os.path.join(base, "raft_tpu", filename)


def load_cache(path: str, signature: str) -> Dict:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("sig") == signature:
            return data
    except (OSError, ValueError):
        pass
    return {"sig": signature, "files": {}}


def save_cache(path: str, cache: Dict) -> None:
    """Atomic, last-writer-wins: concurrent gate runs (pytest spawns
    several) may each write; any complete file is a valid cache."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cache, f)
        os.replace(tmp, path)
    except OSError:
        pass     # a cache is an accelerator, never a correctness gate


def file_digest(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def cache_key(path: str, digest: str, rule_key: str) -> str:
    """ABSOLUTE key paths: the default cache is user-global, so
    cwd-relative keys from two working directories would collide and
    evict each other."""
    return f"{os.path.abspath(path)}|{digest}|{rule_key}"


def evict_dead_entries(cache: Dict, hashes: Dict[str, str]) -> None:
    """Evict dead weight — without this the shared user-level file
    grows forever: entries for a file seen this run under a superseded
    digest (any rule filter), and entries whose file no longer exists
    at all (deleted/renamed paths; keys are absolute, so the exists()
    check is cwd-independent)."""
    current = {os.path.abspath(p): d for p, d in hashes.items()}
    alive: Dict[str, bool] = {}
    for key in list(cache["files"]):
        path, digest = key.split("|", 2)[:2]
        if path in current:
            if digest != current[path]:
                del cache["files"][key]
        else:
            if path not in alive:
                alive[path] = os.path.exists(path)
            if not alive[path]:
                del cache["files"][key]


def map_jobs(worker: Callable, items: List, jobs: int) -> List:
    """Run ``worker`` over ``items``, serially or on a process pool.
    ``worker`` must be a module-level (picklable) function."""
    if jobs > 1 and len(items) > 1:
        import multiprocessing

        with multiprocessing.Pool(min(jobs, len(items))) as pool:
            return pool.map(worker, items)
    return [worker(i) for i in items]


# -- baselines ------------------------------------------------------------

# keyed on (mtime, size) so library users that lint across edits (a
# pytest process, an editor integration) never key a baseline entry
# off stale content
_LINES_CACHE: Dict[str, Tuple[Tuple[float, int], List[str]]] = {}


def code_line(path: str, line: int) -> str:
    try:
        st = os.stat(path)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        return ""
    cached = _LINES_CACHE.get(path)
    if cached is None or cached[0] != stamp:
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            lines = []
        _LINES_CACHE[path] = (stamp, lines)
    else:
        lines = cached[1]
    if 1 <= line <= len(lines):
        return lines[line - 1].strip()
    return ""


def load_baseline(path: str) -> Counter:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Counter(
        (e["path"].replace("\\", "/"), e["rule"], e["code"])
        for e in data.get("findings", []))


def write_baseline(path: str, keys: Iterable[Tuple[str, str, str]],
                   tool: str) -> None:
    entries = [{"path": k[0], "rule": k[1], "code": k[2]}
               for k in sorted(keys)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "comment": f"{tool} grandfathered findings — burn down, "
                       "never grow; regenerate with --write-baseline "
                       "after fixing one",
            "findings": entries,
        }, f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: List, baseline: Counter,
                   finding_key: Callable,
                   linted_paths: Optional[Iterable[str]] = None,
                   ) -> Tuple[List, List[Tuple[str, str, str]]]:
    """Returns (new findings, stale baseline keys).

    Stale entries are NOT a free pass: an unconsumed entry would
    silently grandfather the next reintroduction of that exact line,
    so the CLIs fail on them and demand a regenerate (the baseline
    must only ever shrink, and shrink EXPLICITLY). An entry whose file
    was not in ``linted_paths`` at all (a partial run) is merely
    unchecked, not stale; ``linted_paths=None`` treats every
    unconsumed entry as stale."""
    remaining = Counter(baseline)
    new: List = []
    for f in findings:
        k = finding_key(f)
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    if linted_paths is not None:
        linted = {os.path.normpath(p).replace("\\", "/")
                  for p in linted_paths}
        checked = (lambda k: os.path.normpath(k[0]).replace("\\", "/")
                   in linted)
    else:
        checked = (lambda k: True)
    stale = sorted(k for k, n in remaining.items() if checked(k)
                   for _ in range(n))
    return new, stale
