"""graft: the one-command static-analysis meta-gate.

Runs all six tiers — graftlint (source), graftaudit (single-device
compiled artifacts), graftthread (thread-safety declarations),
graftshard (partitioned programs on the forced multi-device CPU mesh),
graftexport (serialized executables round-tripped through the AOT
artifact cache), graftwire (wire-protocol invariants across the
multi-host seam) — and merges their machine-readable output into one
JSON summary with one exit code. This is the pre-commit check::

    python -m tools.graft --json

Exit codes: 0 every tier clean, 1 any tier found something (its
findings are in the summary), 2 usage error or a tier that failed to
run at all. Each tier runs in its own subprocess: the tiers disagree
about interpreter state on purpose (graftlint/graftthread/graftwire
must never import jax; graftshard must configure the virtual mesh
BEFORE jax initializes; graftexport pins the single-device CPU
backend), and isolation keeps each tier's contract intact.

``--tiers a,b`` runs a subset (the test gate uses the stdlib tiers to
stay fast; CI and pre-commit run all six). Each tier's summary block
carries its wall time (``seconds``) and finding count (``count``) so a
slow or noisy tier is visible from the merged output alone.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Optional, Sequence

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: tier name -> extra CLI args before --json. graftlint takes the
#: linted tree as positional paths AND its committed baseline (its
#: --baseline has no default — without it a legitimately grandfathered
#: entry would fail the meta-gate that the tier's own gate passes, and
#: stale-entry detection would never run through this command); the
#: exact invocation its own gate test pins. The artifact tiers own
#: their target registries and default to their committed baselines.
TIER_ARGS = {
    "graftlint": ["raft_tpu", "bench.py", "tools", "tests",
                  "--baseline",
                  os.path.join("tools", "graftlint", "baseline.json")],
    "graftaudit": [],
    "graftthread": [],
    "graftshard": [],
    "graftexport": [],
    "graftwire": [],
}
TIERS = tuple(TIER_ARGS)


def run_tier(name: str) -> dict:
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", f"tools.{name}", *TIER_ARGS[name],
         "--json"],
        cwd=_REPO, capture_output=True, text=True)
    dt = time.perf_counter() - t0
    try:
        findings = json.loads(proc.stdout) if proc.stdout.strip() else []
        parse_error = None
    except ValueError as exc:
        findings = []
        parse_error = f"unparsable tier output: {exc}"
    rec = {
        "exit": proc.returncode,
        "findings": findings,
        "count": len(findings),
        "seconds": round(dt, 1),
    }
    if parse_error or proc.returncode not in (0, 1):
        # a tier that crashed (not "found something") must surface its
        # stderr — a silent [] would read as clean
        rec["error"] = parse_error or "tier did not run"
        rec["stderr_tail"] = proc.stderr[-2000:]
    return rec


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graft",
        description="Run all six static-analysis tiers (graftlint, "
                    "graftaudit, graftthread, graftshard, graftexport, "
                    "graftwire) with one merged JSON summary and one "
                    "exit code — the pre-commit gate.")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable merged summary")
    p.add_argument("--tiers", metavar="T1,T2",
                   help=f"run only these tiers (default: all of "
                        f"{','.join(TIERS)})")
    args = p.parse_args(argv)

    tiers = list(TIERS)
    if args.tiers:
        want = [t.strip() for t in args.tiers.split(",") if t.strip()]
        unknown = [t for t in want if t not in TIERS]
        if unknown:
            print(f"graft: unknown tier(s): {unknown} "
                  f"(choose from {list(TIERS)})", file=sys.stderr)
            return 2
        tiers = want

    results = {name: run_tier(name) for name in tiers}
    total = sum(len(r["findings"]) for r in results.values())
    crashed = [n for n, r in results.items()
               if r["exit"] not in (0, 1) or "error" in r]
    dirty = [n for n, r in results.items() if r["exit"] == 1]
    ok = not crashed and not dirty

    summary = {
        "ok": ok,
        "tiers": results,
        "findings_total": total,
        "crashed": crashed,
        "dirty": dirty,
    }
    if args.as_json:
        print(json.dumps(summary, indent=2))
    else:
        for name, r in results.items():
            state = ("clean" if r["exit"] == 0 else
                     f"{len(r['findings'])} finding(s)"
                     if r["exit"] == 1 else "FAILED TO RUN")
            print(f"graft: {name}: {state} ({r['seconds']}s)")
            for f in r["findings"]:
                where = f.get("target") or f.get("path", "?")
                print(f"  {where}: {f.get('rule', '?')} "
                      f"{f.get('message', '')[:140]}")
            if "stderr_tail" in r:
                print(f"  stderr: ...{r['stderr_tail'][-400:]}",
                      file=sys.stderr)
    if crashed:
        return 2
    return 1 if dirty else 0


if __name__ == "__main__":
    sys.exit(main())
