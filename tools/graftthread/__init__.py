"""graftthread — thread-safety static analysis for the serving stack.

The third analysis tier beside graftlint (source invariants) and
graftaudit (compiled artifacts): pure-stdlib ``ast`` over the
multi-threaded serving modules plus a lightweight declaration
convention (``LOCK_ORDER`` / ``GRAFTTHREAD`` module constants — see
tools/graftthread/declarations.py). Six rules, each the mechanized
form of a concurrency bug PRs 6-10 caught by hand:

- T1 blocking-call-under-lock     — XLA compiles, Future waits, sleeps
                                    inside a ``with <lock>`` body
- T2 unguarded-future-settle      — raw set_result/set_exception
                                    instead of serving.futures.
                                    settle_future
- T3 lock-order-cycle             — cycles in the declared + inferred
                                    lock acquisition graph
- T4 callback-under-lock          — declared listeners fired while a
                                    lock is held
- T5 thread-lifecycle             — threads not daemon-flagged, or
                                    never joined/quarantine-accounted
- T6 consequences-before-futures  — verdict fns settling futures
                                    before their consequences land

Run ``python -m tools.graftthread --help`` from the repo root; the
tier-1 gate is ``tests/test_graftthread.py``.
"""

from .core import (DEFAULT_PATHS, apply_baseline, lint_file, lint_paths,
                   load_baseline, main, write_baseline)
from .finding import Finding

__all__ = ["Finding", "DEFAULT_PATHS", "apply_baseline", "lint_file",
           "lint_paths", "load_baseline", "main", "write_baseline"]
