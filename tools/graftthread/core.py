"""graftthread driver: walk files, run rules, global lock graph, CLI.

Usage (from the repo root; the argument-less form is the tier-1
gate)::

    python -m tools.graftthread --json
    python -m tools.graftthread raft_tpu/serving some_file.py \
        --baseline tools/graftthread/baseline.json

With no paths the scan covers :data:`DEFAULT_PATHS` — the
multi-threaded serving stack, the training supervisor, and the shared
utils — the tree whose concurrency invariants T1-T6 encode. Exit
codes: 0 clean (modulo baseline), 1 new findings, 2 usage/parse error.
``--json`` prints a machine-readable findings list; ``--write-baseline``
regenerates the grandfather file (shrink-only discipline, as in
graftlint/graftaudit — the shipped baseline is EMPTY and must stay
that way: findings are fixed or pragma-waived with justification,
never silently baselined).

Suppression: ``# graftthread: disable=T1,T5   (justification)`` on the
finding's anchor line. T3 cycle findings anchor at the cycle's
lexicographically-first edge site (a ``LOCK_ORDER`` chain line or an
inferred nested-``with`` line).

Two passes per run: the per-file rules (T1/T2/T4/T5/T6, plus T3 over a
*single* file's edges in ``lint_file``), then — in ``lint_paths`` —
the GLOBAL T3 pass over the union of every file's declared + inferred
acquisition edges, where cross-module cycles (scheduler→breaker→
metrics, registry→scheduler) actually close. The content-hash parse
cache (tools/lintcache, shared with graftlint) stores each file's
findings, edges, and pragma lines; the global graph pass re-runs every
time (it is a dict walk, not a parse) so a cache hit can never hide a
cross-file cycle.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

try:
    from tools import lintcache
except ImportError:          # invoked as a top-level package (tests
    import lintcache         # insert the repo root on sys.path)

from .declarations import ThreadAnalysis
from .finding import Finding

#: the argument-less scan: the multi-threaded serving stack, the
#: process supervisor, and the shared utils (watchdog's poll thread,
#: retry, timing) — relative to the repo root the gate runs from
DEFAULT_PATHS = ("raft_tpu/serving",
                 os.path.join("raft_tpu", "training", "supervisor.py"),
                 "raft_tpu/utils")


def collect_files(paths: Sequence[str]) -> List[str]:
    return lintcache.collect_files(paths)


def parse_pragmas(source: str) -> Dict[int, Optional[set]]:
    return lintcache.parse_pragmas(source, "graftthread")


def _apply_pragmas(findings: List[Finding],
                   pragmas: Dict[int, Optional[set]]) -> List[Finding]:
    kept = []
    for f in findings:
        disabled = pragmas.get(f.line)
        if f.line in pragmas and (disabled is None or f.rule in disabled):
            continue
        kept.append(f)
    return sorted(kept, key=lambda f: (f.line, f.col, f.rule))


def scan_file(path: str, rules=None) -> Dict:
    """One file's full scan: ``{"findings": [per-file findings, pragma-
    filtered], "edges": [lock-graph edges], "pragmas": {line: rules}}``.
    T3 runs over the file's own edges ONLY in :func:`lint_file`; here
    the edges are returned raw for the driver's global pass."""
    from .rules import ALL_RULES, lock_order
    rules = ALL_RULES if rules is None else rules
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as exc:
        return {"findings": [Finding(path, 0, 0, "E0", "unreadable",
                                     str(exc))],
                "edges": [], "pragmas": {}}
    try:
        analysis = ThreadAnalysis(ast.parse(source, filename=path),
                                  source, path)
    except SyntaxError as exc:
        return {"findings": [Finding(path, exc.lineno or 0,
                                     exc.offset or 0, "E1",
                                     "syntax-error",
                                     exc.msg or "syntax error")],
                "edges": [], "pragmas": {}}
    pragmas = parse_pragmas(source)
    findings: List[Finding] = [
        Finding(path, line, col, "E2", "bad-declaration", msg)
        for line, col, msg in analysis.decl_errors]
    for mod in rules:
        if mod is lock_order:
            continue          # global pass; lint_file adds it per-file
        findings.extend(mod.check(analysis))
    active_edges = (lock_order.edges(analysis)
                    if lock_order in rules else [])
    return {"findings": _apply_pragmas(findings, pragmas),
            "edges": active_edges, "pragmas": pragmas}


def lint_file(path: str, rules=None) -> List[Finding]:
    """All findings for ONE file — per-file rules plus T3 over the
    file's own edge set (the fixture/unit mode; the repo gate's T3 is
    global, via :func:`lint_paths`)."""
    from .rules import ALL_RULES, lock_order
    rules = ALL_RULES if rules is None else rules
    entry = scan_file(path, rules)
    findings = list(entry["findings"])
    if lock_order in rules and entry["edges"]:
        cyc = [f for f, _ in lock_order.cycle_findings(entry["edges"])]
        findings.extend(_apply_pragmas(cyc, entry["pragmas"]))
    return sorted(findings, key=lambda f: (f.line, f.col, f.rule))


# -- parse cache + parallel walk (tools/lintcache machinery) --------------

def _rules_signature() -> str:
    """Content hash of the graftthread package PLUS the shared
    lintcache module — a cache must never outlive the code that
    produced it."""
    return lintcache.package_signature(
        os.path.dirname(os.path.abspath(__file__)),
        lintcache.__file__)


def default_cache_path() -> str:
    return lintcache.default_cache_path("RAFT_GRAFTTHREAD_CACHE",
                                        "graftthread_cache.json")


def _rule_ids(rules) -> Optional[List[str]]:
    return None if rules is None else sorted(m.RULE for m in rules)


def _rules_from_ids(ids: Optional[List[str]]):
    if ids is None:
        return None
    from .rules import ALL_RULES
    return [m for m in ALL_RULES if m.RULE in set(ids)]


def _entry_to_json(entry: Dict) -> Dict:
    return {"findings": [f.__dict__ for f in entry["findings"]],
            "edges": entry["edges"],
            "pragmas": {str(k): (sorted(v) if v is not None else None)
                        for k, v in entry["pragmas"].items()}}


def _entry_from_json(data: Dict) -> Dict:
    return {"findings": [Finding(**d) for d in data["findings"]],
            "edges": data["edges"],
            "pragmas": {int(k): (set(v) if v is not None else None)
                        for k, v in data["pragmas"].items()}}


def _scan_one(job: Tuple[str, Optional[List[str]]]) -> Dict:
    """Pool worker: rule MODULES don't pickle, ids do."""
    path, ids = job
    return scan_file(path, rules=_rules_from_ids(ids))


def lint_paths(paths: Sequence[str], rules=None,
               cache_path: Optional[str] = None,
               jobs: int = 1) -> List[Finding]:
    """Scan, optionally with the shared content-hash parse cache and a
    process pool over cache misses (cache entries key on file hash +
    active rule ids under the package signature — identical discipline
    to graftlint's). Per-file findings come first in path order, then
    the global T3 cycle findings."""
    from .rules import lock_order
    files = collect_files(paths)
    entries: Dict[str, Dict] = {}
    misses: List[str] = []
    cache = hashes = None
    ids = _rule_ids(rules)
    rkey = ",".join(ids) if ids is not None else "*"
    if cache_path:
        cache = lintcache.load_cache(cache_path, _rules_signature())
        hashes = {}
        for path in files:
            digest = lintcache.file_digest(path)
            if digest is None:
                misses.append(path)   # unreadable: E0 via scan_file
                continue
            hashes[path] = digest
            stored = cache["files"].get(
                lintcache.cache_key(path, digest, rkey))
            if stored is None:
                misses.append(path)
            else:
                entries[path] = _entry_from_json(stored)
    else:
        misses = list(files)

    if jobs > 1 and len(misses) > 1:
        scanned = lintcache.map_jobs(_scan_one,
                                     [(p, ids) for p in misses], jobs)
    else:
        # serial path uses the caller's actual rule MODULES — a custom
        # rule object outside ALL_RULES must run, not silently resolve
        # to nothing through the id round-trip the pool needs
        scanned = [scan_file(p, rules=rules) for p in misses]
    for path, entry in zip(misses, scanned):
        entries[path] = entry

    if cache is not None:
        for path, entry in zip(misses, scanned):
            digest = hashes.get(path)
            if digest is not None:
                cache["files"][lintcache.cache_key(path, digest, rkey)] \
                    = _entry_to_json(entry)
        lintcache.evict_dead_entries(cache, hashes)
        lintcache.save_cache(cache_path, cache)

    out: List[Finding] = []
    for path in files:
        out.extend(entries.get(path, {}).get("findings", []))

    # the global T3 pass: union every file's edges, re-run the cycle
    # check (cheap — no parsing), pragma-filter each cycle finding
    # against its ANCHOR file's pragma lines
    if rules is None or any(m is lock_order for m in rules):
        all_edges = [e for path in files
                     for e in entries.get(path, {}).get("edges", [])]
        for finding, _anchor in lock_order.cycle_findings(all_edges):
            pragmas = entries.get(finding.path, {}).get("pragmas", {})
            if _apply_pragmas([finding], pragmas):
                out.append(finding)
    return out


# -- baseline (tools/lintcache machinery) ---------------------------------

def finding_key(finding: Finding) -> Tuple[str, str, str]:
    return finding.key(lintcache.code_line(finding.path, finding.line))


def load_baseline(path: str) -> Counter:
    return lintcache.load_baseline(path)


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    lintcache.write_baseline(path, (finding_key(f) for f in findings),
                             "graftthread")


def apply_baseline(findings: List[Finding], baseline: Counter,
                   linted_paths: Optional[Iterable[str]] = None,
                   ) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """Returns (new findings, stale baseline keys) — the shrink-only
    discipline of :func:`tools.lintcache.apply_baseline`."""
    return lintcache.apply_baseline(findings, baseline, finding_key,
                                    linted_paths=linted_paths)


# -- CLI ------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftthread",
        description="Thread-safety static analysis for the serving "
                    "stack (rules T1-T6; see tools/graftthread/"
                    "rules/). With no paths, scans the serving stack "
                    "+ supervisor + utils against the shipped "
                    "baseline.")
    p.add_argument("paths", nargs="*",
                   help="files and/or directories to check (default: "
                        f"{' '.join(DEFAULT_PATHS)}, with the shipped "
                        "baseline applied)")
    p.add_argument("--baseline", metavar="JSON",
                   help="grandfather file: matching findings don't "
                        "fail the run (burn-down workflow)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (list of findings)")
    p.add_argument("--write-baseline", metavar="JSON",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--rules", metavar="T1,T3,...",
                   help="run only these rule ids")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="scan cache misses across N processes "
                        "(default 1: in-process)")
    p.add_argument("--cache", metavar="JSON", default=None,
                   help="parse-cache file (default: "
                        "$RAFT_GRAFTTHREAD_CACHE or "
                        "~/.cache/raft_tpu/graftthread_cache.json); "
                        "same content-hash + package-signature "
                        "discipline as graftlint's cache")
    p.add_argument("--no-cache", action="store_true",
                   help="scan every file from scratch")
    args = p.parse_args(argv)

    if args.jobs < 1:
        print("graftthread: --jobs must be >= 1", file=sys.stderr)
        return 2
    cache_path = None if args.no_cache \
        else (args.cache or default_cache_path())

    paths = list(args.paths)
    baseline_path = args.baseline
    if not paths:
        paths = list(DEFAULT_PATHS)
        if baseline_path is None and not args.write_baseline:
            # the argument-less gate applies the shipped baseline, so
            # `python -m tools.graftthread --json` IS the tier-1 gate
            baseline_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "baseline.json")

    rules = None
    if args.rules:
        from .rules import ALL_RULES
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [m for m in ALL_RULES if m.RULE in want]
        unknown = want - {m.RULE for m in rules}
        if unknown:
            print(f"graftthread: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    if args.write_baseline and args.rules:
        # a rule-filtered regenerate would silently drop every other
        # rule's grandfathered entries and fail the next full gate run
        print("graftthread: refusing --write-baseline with --rules — "
              "regenerate from a full-rule run over the gate's paths",
              file=sys.stderr)
        return 2

    findings = lint_paths(paths, rules=rules,
                          cache_path=cache_path, jobs=args.jobs)
    hard_errors = [f for f in findings if f.rule.startswith("E")]

    if args.write_baseline:
        write_baseline(args.write_baseline,
                       [f for f in findings
                        if not f.rule.startswith("E")])
        print(f"graftthread: wrote {len(findings) - len(hard_errors)} "
              f"finding(s) to {args.write_baseline} — remember the "
              "discipline: the SHIPPED baseline stays EMPTY (fix or "
              "pragma-with-justification instead)", file=sys.stderr)
        return 0

    stale: List[Tuple[str, str, str]] = []
    if baseline_path:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as exc:
            print(f"graftthread: unreadable baseline "
                  f"{baseline_path}: {exc}", file=sys.stderr)
            return 2
        if rules is not None:
            active = {m.RULE for m in rules}
            baseline = Counter({k: v for k, v in baseline.items()
                                if k[1] in active})
        findings, stale = apply_baseline(
            findings, baseline, linted_paths=collect_files(paths))

    if args.as_json:
        # stale entries ride in the same list (rule B0) so a machine
        # consumer sees WHY the run failed, not `[]` with rc=1
        print(json.dumps([{
            "path": f.path, "line": f.line, "col": f.col,
            "rule": f.rule, "name": f.name, "message": f.message,
        } for f in findings] + [{
            "path": k[0], "line": 0, "col": 0, "rule": "B0",
            "name": "stale-baseline",
            "message": f"stale baseline entry for {k[1]}: {k[2]!r} — "
                       "regenerate with --write-baseline",
        } for k in stale], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftthread: {len(findings)} new finding(s)",
                  file=sys.stderr)
    if stale:
        for k in stale:
            print(f"graftthread: stale baseline entry {k[0]} [{k[1]}] "
                  f"{k[2]!r}", file=sys.stderr)
        print(f"graftthread: {len(stale)} stale baseline entr(y/ies) — "
              "regenerate with --write-baseline so it cannot "
              "grandfather a future reintroduction", file=sys.stderr)
    return 1 if (findings or stale) else 0
