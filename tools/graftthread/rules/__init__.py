"""Rule registry: each module exposes RULE, NAME, and check(analysis).

``lock_order`` (T3) additionally exposes ``edges``/``cycle_findings``
— the driver unions edges across every scanned file and runs the
cycle check globally (cross-module cycles only close there)."""

from __future__ import annotations

from . import (blocking, callback, lifecycle, lock_order, ordering,
               settle)

ALL_RULES = (blocking, settle, lock_order, callback, lifecycle,
             ordering)

RULE_IDS = {mod.RULE for mod in ALL_RULES}
