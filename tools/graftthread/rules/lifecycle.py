"""T5 thread-lifecycle.

The PR-3 loader lesson, generalized: a thread the process cannot
account for is a leak that surfaces as a hung interpreter exit, a
stolen mailbox, or a watchdog firing into a torn-down stack. Every
``threading.Thread`` this stack arms must be:

- **daemon-flagged** (``daemon=True`` in the constructor) — a
  non-daemon thread blocks interpreter exit forever if its shutdown
  path is ever missed; and
- **joined or quarantine-accounted** on the owning class's shutdown
  path: the class must define a stop-ish method (``close``/``stop``/
  ``shutdown``/``__exit__``) and either ``join`` a thread somewhere or
  append to a quarantine roster (an attribute named ``quarantined*``,
  the DispatchExecutor discipline: Python can't kill a wedged thread,
  so it is abandoned, replaced, and *accounted* instead of leaked
  silently).

A thread armed in a plain function must be joined in that function
(graftlint R5 separately enforces the try/finally shape). Module-level
arming is process-lifetime by intent and exempt, as in R5.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..declarations import ThreadAnalysis, dotted, walk_same_scope
from ..finding import Finding

RULE = "T5"
NAME = "thread-lifecycle"

_STOPPISH = {"close", "stop", "shutdown", "__exit__"}


def _is_thread_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted(node.func) in ("threading.Thread", "Thread"))


def _daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if (kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True):
            return True
    return False


def _has_join(nodes) -> bool:
    for node in ast.walk(nodes) if isinstance(nodes, ast.AST) else nodes:
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            return True
    return False


def _has_quarantine_append(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"):
            recv = dotted(node.func.value) or ""
            if "quarantin" in recv.rsplit(".", 1)[-1].lower():
                return True
    return False


def _stoppish_methods(cls: ast.ClassDef) -> List[ast.AST]:
    return [n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in _STOPPISH]


def check(a: ThreadAnalysis) -> List[Finding]:
    out: List[Finding] = []
    flagged_classes = set()
    for node in ast.walk(a.tree):
        if not _is_thread_ctor(node):
            continue
        if not _daemon_true(node):
            out.append(Finding(
                a.path, node.lineno, node.col_offset, RULE, NAME,
                "threading.Thread without daemon=True — if the "
                "shutdown path is ever missed (an exception, a wedge) "
                "this thread blocks interpreter exit forever; arm it "
                "daemon and own its lifecycle explicitly"))
        cls = a.enclosing_class(node)
        fn = a.enclosing_function(node)
        if cls is not None:
            if cls.name in flagged_classes:
                continue
            stoppers = _stoppish_methods(cls)
            ok = (bool(stoppers)
                  and (_has_join(cls) or _has_quarantine_append(cls)))
            if not ok:
                flagged_classes.add(cls.name)
                out.append(Finding(
                    a.path, node.lineno, node.col_offset, RULE, NAME,
                    f"class {cls.name} arms a thread but "
                    + ("has no close/stop/shutdown/__exit__ path"
                       if not stoppers else
                       "never joins it (and keeps no quarantine "
                       "roster)")
                    + " — a thread nobody joins or accounts for is a "
                      "leak (the PR-3 loader lesson); join it on the "
                      "stop path or quarantine-account it (the "
                      "DispatchExecutor discipline)"))
        elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # plain-function arming: the join must live in the same
            # function (R5 covers the try/finally shape)
            if not _has_join(walk_same_scope(list(fn.body))):
                out.append(Finding(
                    a.path, node.lineno, node.col_offset, RULE, NAME,
                    "thread armed in a function that never joins it — "
                    "the caller cannot know when (or whether) it "
                    "exited; join it here or own it in a class with a "
                    "stop path"))
        # module-level arming: process-lifetime by intent (R5 parity)
    return out
