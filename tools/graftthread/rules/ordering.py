"""T6 consequences-before-futures.

The PR-7 wedge-verdict invariant, machine-checked: when a verdict
fails a batch's futures, every *consequence* of the verdict — the
suspect executable dropped, the breaker recorded, the stuck thread
quarantined — must land BEFORE any future settles, so a caller woken
by its ``DispatchWedged`` observes consistent state (breaker open,
bucket gone, health degraded) instead of racing the cleanup. The
chaos harness asserts this dynamically; this rule pins it in the
source: in every declared verdict function, the first settle call must
be lexically preceded by at least one declared consequence call.

Modules opt in by declaring the verdict set and its consequences::

    GRAFTTHREAD = {
        "verdicts": ("_wedge_verdict", "_wedge_completion"),
        "consequences": ("drop_bucket", "record_failure",
                         "quarantine_and_replace"),
        "settles": ("_fail_requests",),   # extends settle_future
    }

Lexical (line-order) domination is an approximation of true
dominator analysis — good enough for straight-line verdict bodies,
and a verdict gnarly enough to defeat it should be simplified, not
waved through.
"""

from __future__ import annotations

import ast
from typing import List

from ..declarations import ThreadAnalysis, dotted, walk_same_scope
from ..finding import Finding

RULE = "T6"
NAME = "consequences-before-futures"

_RAW_SETTLES = {"set_result", "set_exception"}


def check(a: ThreadAnalysis) -> List[Finding]:
    verdicts = set(a.decl["verdicts"])
    if not verdicts:
        return []
    consequences = set(a.decl["consequences"])
    out: List[Finding] = []
    for node in ast.walk(a.tree):
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in verdicts):
            continue
        settle_calls: List[ast.Call] = []
        first_consequence = None
        for sub in walk_same_scope(list(node.body)):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted(sub.func)
            if name is None:
                continue
            last = name.rsplit(".", 1)[-1]
            if last in a.settles or last in _RAW_SETTLES:
                settle_calls.append(sub)
            elif last in consequences:
                if (first_consequence is None
                        or sub.lineno < first_consequence):
                    first_consequence = sub.lineno
        for call in settle_calls:
            if first_consequence is None:
                out.append(Finding(
                    a.path, call.lineno, call.col_offset, RULE, NAME,
                    f"verdict {node.name}() settles futures but calls "
                    "no declared consequence (drop/quarantine/breaker-"
                    "record) at all — a woken caller would observe a "
                    "verdict with none of its consequences applied"))
            elif call.lineno < first_consequence:
                out.append(Finding(
                    a.path, call.lineno, call.col_offset, RULE, NAME,
                    f"verdict {node.name}() settles futures at line "
                    f"{call.lineno}, before its first consequence at "
                    f"line {first_consequence} — consequences must "
                    "land BEFORE the futures fail, or a woken caller "
                    "races the cleanup (the PR-7 wedge-verdict "
                    "ordering invariant)"))
    return out
