"""T1 blocking-call-under-lock.

The PR-6 bug this rule mechanizes: XLA bucket compiles originally ran
INSIDE the engine lock — a minutes-long compile stalled every weight
swap and every already-compiled dispatch behind it (fixed by moving
``lower()/compile()`` outside; engine.py documents the discipline).
The general form: any call that can block for unbounded time while a
lock is held turns that lock into a convoy for every other thread —
and under a Condition it can deadlock outright.

Flagged lexically inside a ``with <lock>:`` body (nested functions
excluded — a closure runs later, without the lock):

- ``.lower()`` / ``.compile()``  (XLA compile; ``re.compile`` exempt)
- ``.result()`` / ``.exception()``  (Future waits)
- ``.join()``  (thread waits)
- ``sleep()`` / ``time.sleep()``
- ``.wait()``  on anything OTHER than the held lock itself (waiting on
  the held Condition releases it — the one legal blocking wait)
- ``.block_until_ready()`` / ``.fetch()``  (device syncs)
- ``.get()``  on queue-ish receivers (``*queue*``/``*mailbox*``)
- bare ``open()``  (filesystem I/O under a lock)
"""

from __future__ import annotations

import ast
from typing import List

from ..declarations import ThreadAnalysis, dotted, walk_same_scope
from ..finding import Finding

RULE = "T1"
NAME = "blocking-call-under-lock"

_BLOCKING_ATTRS = {"result", "exception", "join", "sleep", "wait",
                   "fetch", "compile", "lower", "block_until_ready"}
_QUEUEISH = ("queue", "mailbox", "inbox")


def _receiver(call: ast.Call):
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return None


def _canonical(a: ThreadAnalysis, expr_dotted: str) -> str:
    """Alias-resolve a lock expression's last segment so a Condition
    and the lock it wraps (``aliases={'_decided': '_lock'}``) compare
    equal — ``self._decided`` and ``self._lock`` are the SAME lock."""
    prefix, _, seg = expr_dotted.rpartition(".")
    seg = a.decl["aliases"].get(seg, seg)
    return f"{prefix}.{seg}" if prefix else seg


def check(a: ThreadAnalysis) -> List[Finding]:
    out: List[Finding] = []
    seen = set()
    for lw in a.lock_withs:
        for node in walk_same_scope(list(lw.node.body)):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            name = dotted(node.func)
            if name is None:
                continue
            last = name.rsplit(".", 1)[-1]
            what = None
            if last in _BLOCKING_ATTRS:
                if name == "re.compile":
                    continue        # regex compile, not XLA
                if last == "wait":
                    recv = _receiver(node)
                    if recv is not None and any(
                            _canonical(a, h.expr_dotted)
                            == _canonical(a, recv)
                            for h in a.held_locks(node)):
                        continue    # Condition.wait on the HELD lock
                        #             (alias-resolved: `with _lock:
                        #             _decided.wait()` is the same
                        #             lock) releases it — the legal
                        #             idiom
                what = f"{name}()"
            elif last == "get":
                recv = _receiver(node) or ""
                seg = recv.rsplit(".", 1)[-1].lower()
                if any(q in seg for q in _QUEUEISH):
                    what = f"{name}() (blocking queue read)"
            elif name == "open":
                what = "open() (filesystem I/O)"
            if what is None:
                continue
            seen.add(id(node))
            out.append(Finding(
                a.path, node.lineno, node.col_offset, RULE, NAME,
                f"{what} can block while holding {lw.expr_dotted} — "
                "every other thread convoys behind the lock (the PR-6 "
                "compile-under-engine-lock bug class); move the "
                "blocking call outside the with body"))
    return out
