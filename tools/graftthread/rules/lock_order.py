"""T3 lock-order-cycle.

Deadlock by lock-order inversion is the classic multi-thread failure
the serving stack's comment-only discipline ("lock order: _state_lock
-> _cv -> breaker lock", scheduler.py) cannot machine-check — and the
next replica/ragged rewrite multiplies the thread graph. This rule
builds one global acquisition graph over every scanned file:

- **declared edges**: consecutive pairs in each module's
  ``LOCK_ORDER`` chain (qualified ``module.Class.attr`` names —
  cross-module edges like ``registry.ModelRegistry._lock ->
  scheduler.MicroBatchScheduler._cv`` are declared by the module that
  owns the outer lock);
- **inferred edges**: lexically nested ``with <lock>:`` statements —
  holding A while acquiring B is an A->B edge whether or not anyone
  declared it.

Any cycle in the union graph is a finding: two threads walking the
cycle from different entry points deadlock. The declaration is the
contract; the inference catches code drifting from it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..declarations import ThreadAnalysis, walk_same_scope
from ..finding import Finding

RULE = "T3"
NAME = "lock-order-cycle"


def edges(a: ThreadAnalysis) -> List[Dict]:
    """This file's contribution to the global acquisition graph."""
    out: List[Dict] = []
    for chain, lineno in a.lock_order:
        for src, dst in zip(chain, chain[1:]):
            out.append({"src": src, "dst": dst, "path": a.path,
                        "line": lineno, "origin": "declared"})
    # inferred: a lock-with nested lexically inside another lock-with's
    # body (same function scope — walk_same_scope stops at closures)
    for outer in a.lock_withs:
        for node in walk_same_scope(list(outer.node.body)):
            if not isinstance(node, ast.With):
                continue
            for inner in a.lock_withs:
                if inner.node is node \
                        and inner.qualified != outer.qualified:
                    out.append({"src": outer.qualified,
                                "dst": inner.qualified,
                                "path": a.path,
                                "line": inner.node.lineno,
                                "origin": "inferred"})
    return out


def find_cycles(edge_list: List[Dict]) -> List[List[str]]:
    """Elementary cycles in the acquisition graph, via strongly
    connected components (each SCC with more than one node — or a
    self-loop — holds at least one cycle; one representative cycle per
    SCC is reported, deterministically). Exposed for the synthetic-
    graph unit tests."""
    graph: Dict[str, set] = {}
    for e in edge_list:
        graph.setdefault(e["src"], set()).add(e["dst"])
        graph.setdefault(e["dst"], set())

    # Tarjan, iterative (rule code must not recurse past recursion
    # limits on adversarial graphs)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack[root] = True
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack[w] = True
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif on_stack.get(w):
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)

    cycles: List[List[str]] = []
    for scc in sccs:
        if len(scc) > 1:
            members = set(scc)
            # one representative cycle: walk the SCC's edges from its
            # smallest node until it closes
            start = min(scc)
            path = [start]
            seen = {start}
            cur = start
            while True:
                nxt = min((w for w in graph[cur] if w in members),
                          default=None)
                if nxt is None or nxt == start:
                    break
                if nxt in seen:
                    path = path[path.index(nxt):]
                    break
                path.append(nxt)
                seen.add(nxt)
                cur = nxt
            cycles.append(path)
        elif scc[0] in graph[scc[0]]:
            cycles.append(scc)          # self-loop
    return sorted(cycles)


def _edge_site(edge_list: List[Dict], src: str, dst: str
               ) -> Optional[Dict]:
    best = None
    for e in edge_list:
        if e["src"] == src and e["dst"] == dst:
            if best is None or (e["path"], e["line"]) \
                    < (best["path"], best["line"]):
                best = e
    return best


def cycle_findings(edge_list: List[Dict]) -> List[Tuple[Finding, Dict]]:
    """(finding, anchor edge) per cycle in ``edge_list``. The anchor is
    the cycle's lexicographically-first edge site, so the finding (and
    any pragma suppressing it) lands deterministically."""
    out: List[Tuple[Finding, Dict]] = []
    for cycle in find_cycles(edge_list):
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        sites = [(pair, _edge_site(edge_list, *pair)) for pair in pairs]
        sites = [(p, s) for p, s in sites if s is not None]
        if not sites:
            continue
        anchor = min(sites, key=lambda ps: (ps[1]["path"],
                                            ps[1]["line"]))[1]
        detail = "; ".join(
            f"{p[0]} -> {p[1]} ({s['origin']} at {s['path']}:"
            f"{s['line']})" for p, s in sites)
        loop = " -> ".join(cycle + cycle[:1])
        out.append((Finding(
            anchor["path"], anchor["line"], 0, RULE, NAME,
            f"lock-order cycle {loop}: two threads entering this loop "
            f"at different locks deadlock — {detail}; fix the "
            "acquisition order (or the LOCK_ORDER declaration that "
            "misstates it)"), anchor))
    return out


def check(a: ThreadAnalysis) -> List[Finding]:
    """Single-file mode (``lint_file``): cycles visible from this
    file's own edges. The repo gate runs the GLOBAL graph in the
    driver instead — cross-module cycles only close there."""
    return [f for f, _ in cycle_findings(edges(a))]
