"""T2 unguarded-future-settle.

``Future.set_result``/``set_exception`` raise ``InvalidStateError``
when the future is already done — and in a serving stack every settle
races something: a caller's ``cancel()``, a wedge verdict, a
supervision-loop expiry sweep. PR 7's hardening list is a museum of
this class (the ``_expire``-vs-cancel race would have killed the
dispatcher thread from the sweep). The blessed idiom is ONE shared
helper, ``raft_tpu.serving.futures.settle_future``, which guards the
race and reports whether the settle won — so per-future accounting
(submitted == completed + failed + deadline_missed + cancelled) stays
exact at every site by construction.

This rule flags every raw ``.set_result(``/``.set_exception(`` call.
The helper module itself declares ``GRAFTTHREAD = {"settle_helper":
True}`` and is exempt inside its ``settle_future`` function — the one
place the raw calls belong.
"""

from __future__ import annotations

import ast
from typing import List

from ..declarations import ThreadAnalysis
from ..finding import Finding

RULE = "T2"
NAME = "unguarded-future-settle"

_SETTLE_ATTRS = {"set_result", "set_exception"}


def check(a: ThreadAnalysis) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(a.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SETTLE_ATTRS):
            continue
        if a.decl["settle_helper"]:
            fn = a.enclosing_function(node)
            if (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name == "settle_future"):
                continue   # the one blessed raw-settle site
        out.append(Finding(
            a.path, node.lineno, node.col_offset, RULE, NAME,
            f"raw .{node.func.attr}() — a concurrent cancel/verdict "
            "makes this raise InvalidStateError and kill the calling "
            "thread; settle through raft_tpu.serving.futures."
            "settle_future (guards the race, returns whether the "
            "settle won so accounting stays exact)"))
    return out
