"""T4 callback-under-lock.

A caller-supplied callback is arbitrary code: fired while a lock is
held, it re-enters whatever the listener touches WITH that lock — the
breaker-listener convention exists because a transition listener that
recomputes scheduler health reads *other* breakers, and firing it
inside the breaker lock would deadlock the health recompute
(resilience.py's ``_set``/``_notify`` split is the blessed shape:
record the transition under the lock, fire the listener after
releasing).

Modules declare their listener attributes::

    GRAFTTHREAD = {"callbacks": ("on_transition", "_on_transition")}

Any call to a declared callback name lexically inside a ``with
<lock>:`` body is a finding. No declaration, no findings — the rule is
opt-in per module, like the convention it enforces.
"""

from __future__ import annotations

import ast
from typing import List

from ..declarations import ThreadAnalysis, dotted, walk_same_scope
from ..finding import Finding

RULE = "T4"
NAME = "callback-under-lock"


def check(a: ThreadAnalysis) -> List[Finding]:
    callbacks = set(a.decl["callbacks"])
    if not callbacks:
        return []
    out: List[Finding] = []
    seen = set()
    for lw in a.lock_withs:
        for node in walk_same_scope(list(lw.node.body)):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            name = dotted(node.func)
            if name is None or name.rsplit(".", 1)[-1] not in callbacks:
                continue
            seen.add(id(node))
            out.append(Finding(
                a.path, node.lineno, node.col_offset, RULE, NAME,
                f"listener {name}() fired while holding "
                f"{lw.expr_dotted} — a callback that reads other "
                "locked state (the breaker-board health recompute) "
                "deadlocks; record the transition under the lock, "
                "fire the listener after releasing (resilience.py's "
                "_set/_notify split)"))
    return out
