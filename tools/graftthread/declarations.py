"""Shared per-file analysis for graftthread rules: locks + declarations.

Thread-safety facts a static checker cannot infer reliably — which
attributes are locks when their names don't say so, the intended
cross-module lock acquisition order, which attributes hold
caller-supplied callbacks, which functions are wedge/rollback verdicts
and which calls are their "consequences" — ride a **lightweight
declaration convention** in the checked modules themselves. Two
module-level constants, both plain literals (parsed with
``ast.literal_eval``, zero runtime cost, greppable):

``LOCK_ORDER``
    A tuple of acquisition *chains* — each chain a tuple of qualified
    lock names (``"module.Class.attr"``; a bare name is qualified with
    the declaring module). Consecutive names form allowed
    before→after edges; T3 unions these with the *inferred* edges from
    lexically nested ``with <lock>:`` statements across every scanned
    file and fails on any cycle. A single-name chain just registers a
    leaf lock (nothing may be declared or inferred to nest under it in
    the reverse direction).

``GRAFTTHREAD``
    A dict of rule inputs (all keys optional)::

        GRAFTTHREAD = {
            "locks": ("_decided",),       # attrs that ARE locks despite
                                          #   the name (Condition etc.)
            "aliases": {"_decided": "_lock"},  # same underlying lock
            "callbacks": ("on_transition",),   # T4: caller-supplied
                                          #   listeners — never call
                                          #   them under a lock
            "verdicts": ("_wedge_verdict",),   # T6: verdict functions
            "consequences": ("drop_bucket",),  # T6: must precede settles
            "settles": ("_fail_requests",),    # T6 ONLY: extra calls
                                          #   that COUNT as settles for
                                          #   verdict ordering (T2
                                          #   stays strict: raw settles
                                          #   belong in settle_future
                                          #   alone)
            "settle_helper": True,        # T2: this module DEFINES the
                                          #   one blessed settle idiom
        }

Everything else here is the per-file AST plumbing the rule modules
share (parents map, scope walk, lock-``with`` discovery). Pure stdlib
``ast`` — graftthread must check files that import jax without
importing jax itself.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

#: attr-name heuristic: these read as locks without a declaration
_LOCKISH_RE = re.compile(r"lock|mutex|_cv$|^cv$|cond|semaphore", re.I)

#: declaration keys and their defaults (unknown keys are an E2 finding
#: — a typo'd key would silently disable the rule it feeds)
DECL_DEFAULTS = {
    "locks": (),
    "aliases": {},
    "callbacks": (),
    "verdicts": (),
    "consequences": (),
    "settles": (),
    "settle_helper": False,
}

#: settle wrappers blessed everywhere (the raft_tpu.serving.futures
#: helper); module declarations extend per file
BASE_SETTLES = ("settle_future",)

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> "a.b.c"; None for anything not a plain name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_same_scope(nodes) -> Iterator[ast.AST]:
    """Walk ``nodes`` (a list of statements or one node) without
    descending into nested function/lambda bodies — a lock held at the
    ``with`` is NOT held when a closure defined inside it runs later."""
    todo = list(nodes) if isinstance(nodes, list) else [nodes]
    while todo:
        node = todo.pop()
        yield node
        if isinstance(node, _SCOPES):
            continue        # never descend INTO a nested scope body
        todo.extend(ast.iter_child_nodes(node))


class LockWith:
    """One ``with <lock>:`` acquisition site."""

    __slots__ = ("node", "expr", "expr_dotted", "segment", "qualified")

    def __init__(self, node: ast.With, expr: ast.AST, expr_dotted: str,
                 segment: str, qualified: str):
        self.node = node                  # the With statement
        self.expr = expr                  # the lock expression node
        self.expr_dotted = expr_dotted    # e.g. "self._cv"
        self.segment = segment            # e.g. "_cv" (alias-resolved)
        self.qualified = qualified        # e.g. "scheduler.MicroBatchScheduler._cv"


class ThreadAnalysis:
    """One-pass per-file analysis shared by all graftthread rules."""

    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.source = source
        self.path = path
        self.modname = os.path.splitext(os.path.basename(path))[0]
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.decl_errors: List[Tuple[int, int, str]] = []
        self.decl = dict(DECL_DEFAULTS)
        #: list of (chain names, lineno) from LOCK_ORDER
        self.lock_order: List[Tuple[List[str], int]] = []
        self._parse_declarations()
        self.settles = set(BASE_SETTLES) | set(self.decl["settles"])
        #: every ``with <lock>:`` site in the file
        self.lock_withs: List[LockWith] = []
        self._collect_lock_withs()

    # -- declarations -----------------------------------------------------

    def _parse_declarations(self) -> None:
        for node in self.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "GRAFTTHREAD":
                self._parse_decl_dict(node)
            elif tgt.id == "LOCK_ORDER":
                self._parse_lock_order(node)

    def _err(self, node: ast.AST, msg: str) -> None:
        self.decl_errors.append((node.lineno, node.col_offset, msg))

    def _parse_decl_dict(self, node: ast.Assign) -> None:
        try:
            val = ast.literal_eval(node.value)
        except ValueError:
            self._err(node, "GRAFTTHREAD must be a literal dict "
                            "(strings/tuples only)")
            return
        if not isinstance(val, dict):
            self._err(node, "GRAFTTHREAD must be a dict")
            return
        for key, v in val.items():
            if key not in DECL_DEFAULTS:
                self._err(node, f"unknown GRAFTTHREAD key {key!r} "
                                f"(valid: {sorted(DECL_DEFAULTS)})")
                continue
            self.decl[key] = v

    def _parse_lock_order(self, node: ast.Assign) -> None:
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            self._err(node, "LOCK_ORDER must be a literal tuple of "
                            "chains")
            return
        for chain_node in value.elts:
            if isinstance(chain_node, (ast.Tuple, ast.List)):
                try:
                    names = [str(x) for x in
                             ast.literal_eval(chain_node)]
                except ValueError:
                    self._err(chain_node, "LOCK_ORDER chain must hold "
                                          "string lock names")
                    continue
            elif (isinstance(chain_node, ast.Constant)
                    and isinstance(chain_node.value, str)):
                names = [chain_node.value]
            else:
                self._err(chain_node, "LOCK_ORDER chain must be a "
                                      "tuple of string lock names")
                continue
            self.lock_order.append(
                ([self.qualify_name(n) for n in names],
                 chain_node.lineno))

    def qualify_name(self, name: str) -> str:
        """A declared lock name with no module prefix belongs to the
        declaring module."""
        return name if "." in name else f"{self.modname}.{name}"

    # -- locks ------------------------------------------------------------

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST
                           ) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, _SCOPES):
            cur = self.parents.get(cur)
        return cur

    def _is_lockish(self, segment: str) -> bool:
        return (segment in self.decl["locks"]
                or bool(_LOCKISH_RE.search(segment)))

    def _lock_with(self, node: ast.With, expr: ast.AST
                   ) -> Optional[LockWith]:
        name = dotted(expr)
        if name is None:
            return None
        segment = name.rsplit(".", 1)[-1]
        if not self._is_lockish(segment):
            return None
        segment = self.decl["aliases"].get(segment, segment)
        cls = self.enclosing_class(node)
        if name.startswith("self.") and cls is not None:
            qualified = f"{self.modname}.{cls.name}.{segment}"
        else:
            qualified = f"{self.modname}.{segment}"
        return LockWith(node, expr, name, segment, qualified)

    def _collect_lock_withs(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                lw = self._lock_with(node, item.context_expr)
                if lw is not None:
                    self.lock_withs.append(lw)

    def held_locks(self, node: ast.AST) -> List[LockWith]:
        """The lock-``with`` statements lexically enclosing ``node``
        within the same function (innermost first) — what is HELD when
        ``node`` executes, as far as lexical analysis can say."""
        by_with = {}
        for lw in self.lock_withs:
            by_with.setdefault(lw.node, []).append(lw)
        held: List[LockWith] = []
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, _SCOPES):
            if isinstance(cur, ast.With) and cur in by_with:
                held.extend(by_with[cur])
            cur = self.parents.get(cur)
        return held


def analyze(source: str, path: str) -> ThreadAnalysis:
    return ThreadAnalysis(ast.parse(source, filename=path), source, path)
