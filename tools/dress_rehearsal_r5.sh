#!/bin/bash
# CPU dress rehearsal of every command class the round-5 on-chip runbook
# (tools/onchip_round5.sh) will execute — tiny shapes, CPU backend, exit
# codes asserted. The point: when a chip window opens, no window minute
# may be lost to an argparse typo, an import error, or a broken code
# path in a command that never ran on today's code. The NUMBERS here are
# meaningless (CPU); only "the command executes end to end" counts.
#
# Mapping to runbook steps:
#   bare/ladder bench rows  -> bench.py tiny (incl. fused/softsel/unroll
#                              combos and the defaults fold-in path)
#   trained parity rows     -> tools/trained_parity.py tiny crop, both
#                              impls (torch flows come from / populate
#                              the on-disk cache)
#   train_rate              -> cli/train on the --synthetic loader path
#                              (the exact path train_rate uses), plus
#                              real_data_accept.sh --selftest for the
#                              --data_root train + evaluate CLI path
#   pick_defaults_r5        -> tools/pick_bench_defaults.py against a
#                              scratch ladder dir
#   infer rows              -> cli/infer_bench tiny, fp32/bf16/unroll2
#   corr_bench rows         -> cli/corr_bench tiny, the exact impl sets
#   trace + summary         -> cli/profile_step --trace-dir + trace_summary
#   crash bisect            -> chip-only by nature (its cells are the
#                              corr_bench commands above)
set -u
cd /root/repo
export PYTHONPATH= JAX_PLATFORMS=cpu
OUT=${1:-/tmp/dress_r5.out}
: > "$OUT"
FAILED=0
rehearse() {
    local name=$1 tmo=$2; shift 2
    echo "=== $(date -u +%H:%M:%S) $name: $*" >> "$OUT"
    if timeout "$tmo" "$@" >> "$OUT" 2>&1; then
        echo "=== PASS $name" >> "$OUT"
    else
        echo "=== FAIL rc=$? $name" >> "$OUT"
        FAILED=1
    fi
}

# bench.py: bare-style (defaults fold-in + probe path) and the ladder's
# flag combos, single tiny step each
rehearse bench_bare 600 python bench.py --hw 64 64 --batches 2 \
    --steps 1 --warmup 1
rehearse bench_fused 600 python bench.py --hw 64 64 --batches 2 \
    --steps 1 --warmup 1 --corr-dtype bfloat16 --no-remat --fused-loss
rehearse bench_softsel 600 python bench.py --hw 64 64 --batches 2 \
    --steps 1 --warmup 1 --corr-dtype bfloat16 --no-remat \
    --corr-impl softsel
rehearse bench_unroll2 600 python bench.py --hw 64 64 --batches 2 \
    --steps 1 --warmup 1 --corr-dtype bfloat16 --no-remat --scan-unroll 2
rehearse bench_fused_softsel 600 python bench.py --hw 64 64 --batches 2 \
    --steps 1 --warmup 1 --corr-dtype bfloat16 --no-remat --fused-loss \
    --corr-impl softsel
rehearse bench_fused_unroll2 600 python bench.py --hw 64 64 --batches 2 \
    --steps 1 --warmup 1 --corr-dtype bfloat16 --no-remat --fused-loss \
    --scan-unroll 2

# trained parity, tiny crop, both backends the runbook measures — in a
# COPY of the checkpoint dir: the tool writes its result JSONs (and
# torch-flow cache) into --ckpt-dir, and the runbook copies those JSONs
# out as *_onchip records; rehearsal CPU numbers must never be able to
# masquerade as them (that corruption happened once — see the guarded
# cp in onchip_round5.sh)
DRESS_CKPT=/tmp/dress_ref_ckpt_r5
rm -rf "$DRESS_CKPT"
cp -r /root/.cache/raft_tpu/ref_ckpt "$DRESS_CKPT"
rehearse parity_default 1200 python tools/trained_parity.py \
    --hw 128 256 --iters 4 --ckpt-dir "$DRESS_CKPT"
rehearse parity_softsel 1200 python tools/trained_parity.py \
    --hw 128 256 --iters 4 --corr_impl softsel --ckpt-dir "$DRESS_CKPT"

# serving rows
rehearse infer_fp32 600 python -m raft_tpu.cli.infer_bench \
    --hw 64 64 --iters 2 --reps 1
rehearse infer_bf16 600 python -m raft_tpu.cli.infer_bench \
    --hw 64 64 --iters 2 --reps 1 --corr_dtype bfloat16
rehearse infer_unroll2 600 python -m raft_tpu.cli.infer_bench \
    --hw 64 64 --iters 2 --reps 1 --corr_dtype bfloat16 --scan_unroll 2

# corr_bench rows: the exact impl sets the runbook runs
rehearse corr_softsel 900 python -m raft_tpu.cli.corr_bench --batch 2 \
    --hw 24 32 --iters 4 --impls onehot softsel --grad \
    --corr-dtype bfloat16
rehearse corr_pallas 900 python -m raft_tpu.cli.corr_bench --batch 1 \
    --hw 24 32 --iters 4 --impls onehot pallas

# the --synthetic train path the runbook's train_rate step uses
# (selftest below exercises the --data_root path instead)
rehearse train_synthetic 1200 python -m raft_tpu.cli.train \
    --name dressrate --stage chairs --small --image_size 64 64 \
    --mixed_precision --synthetic 8 --num_steps 2 --val_freq 100 \
    --batch_size 2 --num_workers 1 \
    --checkpoint_dir /tmp/dress_ckpt_r5 --log_dir /tmp/dress_runs_r5

# the defaults pick that gates the tier-B BENCH_DEFAULTS.json decision —
# run a throwaway COPY of the tool (it writes BENCH_DEFAULTS.json one
# dir above itself, so the copy writes under /tmp, leaving the repo's
# real BENCH_DEFAULTS.json untouched) against a scratch ladder dir so a
# pick bug surfaces here, not on chip
DRESS_PICK=/tmp/dress_pick_r5
rm -rf "$DRESS_PICK" && mkdir -p "$DRESS_PICK/tools" "$DRESS_PICK/ladder"
cp tools/pick_bench_defaults.py "$DRESS_PICK/tools/"
printf '%s\n' \
    '{"metric": "raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip_corrbfloat16", "value": 21.0, "unit": "img_pairs_per_sec"}' \
    > "$DRESS_PICK/ladder/a.json"
rehearse pick_defaults 120 python "$DRESS_PICK/tools/pick_bench_defaults.py" \
    "$DRESS_PICK/ladder"

# trace capture + headless summary — at the SAME flag set the runbook's
# trace_r5 will derive from BENCH_DEFAULTS.json (batch forced tiny),
# via the shared tools/bench_default_flags.py mapping
rm -rf /tmp/dress_trace_r5
TRACE_FLAGS=$(python tools/bench_default_flags.py) || {
    echo "=== FAIL bench_default_flags" >> "$OUT"; FAILED=1; TRACE_FLAGS=""; }
rehearse profile_step 900 python -m raft_tpu.cli.profile_step --batch 1 \
    --hw 64 64 --steps 1 --trace-dir /tmp/dress_trace_r5 $TRACE_FLAGS
rehearse trace_summary 300 python -m raft_tpu.cli.trace_summary \
    /tmp/dress_trace_r5

# train + evaluate CLI end to end (tiny fabricated layout + trained
# fixture; asserts exit codes only)
rehearse accept_selftest 1800 bash tools/real_data_accept.sh --selftest

echo "=== $(date -u +%H:%M:%S) dress rehearsal done FAILED=$FAILED" >> "$OUT"
# commit only the marker lines — the raw stdout is ~19 MB of CPU noise
grep -E "^=== " "$OUT" > /root/repo/DRESS_r05.log 2>/dev/null || true
exit $FAILED
