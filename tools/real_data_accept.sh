#!/bin/bash
# Acceptance pipeline for the day real weights/datasets get staged
# (VERDICT r3 item 5). The moment the operator provides:
#
#   models/raft-sintel.pth            (from models.zip, download_models.sh:2)
#   datasets/Sintel/training/{clean,final,flow}/<scene>/...
#   datasets/FlyingChairs_release/data/*.ppm + *.flo
#
# this script turns staging into execution:
#   1. convert raft-sintel.pth -> flax msgpack (tools/convert)
#   2. validate_sintel at the BASELINE config (milestone config 2;
#      eval iters 32 per reference evaluate.py:96) -> EPE printed, the
#      <0.01-parity north star measured at last
#   3. a 1k-step real-FlyingChairs training leg at the measured bench
#      defaults (milestone config 4)
#
# --selftest: prove the same pipeline end to end TODAY on a fabricated
# layout (tools/fabricate_layout.py) + the committed genuinely-trained
# small checkpoint fixture — tiny shapes, CPU-safe, asserts exit codes
# only (the numbers are meaningless on random data).
set -eu
cd /root/repo

if [ "${1:-}" = "--selftest" ] || [ "${1:-}" = "--selftest-tpu" ]; then
    # --selftest runs CPU-safe (works while the tunnel is down);
    # --selftest-tpu runs the identical pipeline on the live chip
    # (proven 2026-08-01: convert -> validate_sintel -> 3-step train leg
    # all green on the v5e-1, BENCH_NOTES round 5)
    if [ "${1:-}" = "--selftest" ]; then
        export PYTHONPATH= JAX_PLATFORMS=cpu
    fi
    DATA=/tmp/raft_accept_data
    MODELS=/tmp/raft_accept_models
    rm -rf "$DATA" "$MODELS"; mkdir -p "$MODELS"
    python tools/fabricate_layout.py "$DATA"
    cp tests/fixtures/raft-small-cputrained.pth "$MODELS/raft-sintel.pth"
    SMALL="--small"; STEPS=3; BATCH=2; VALB=2; ITERS="--iters 4"
else
    DATA=${1:-datasets}
    MODELS=${2:-models}
    SMALL=""; STEPS=1000; BATCH=8; VALB=4; ITERS=""
fi

PTH="$MODELS/raft-sintel.pth"
for path in "$PTH" "$DATA/Sintel/training/clean" \
        "$DATA/FlyingChairs_release/data"; do
    if [ ! -e "$path" ]; then
        echo "MISSING: $path" >&2
        echo "Stage the layout documented at the top of this script" \
             "(README 'Data & weights staging')." >&2
        exit 2
    fi
done

echo "== 1/3 convert $PTH =="
MSGPACK="${PTH%.pth}.msgpack"
python -m raft_tpu.tools.convert $SMALL "$PTH" "$MSGPACK"

echo "== 2/3 validate_sintel (BASELINE milestone config 2; eval iters" \
     "are pinned per-dataset inside the validator, sintel=32) =="
python -m raft_tpu.cli.evaluate --model "$MSGPACK" $SMALL \
    --dataset sintel --data_root "$DATA" --eval_batch "$VALB"

echo "== 3/3 real-FlyingChairs training leg ($STEPS steps) =="
python -m raft_tpu.cli.train --name accept-chairs --stage chairs $SMALL \
    $ITERS --mixed_precision --num_steps "$STEPS" --batch_size "$BATCH" \
    --data_root "$DATA" --validation chairs --val_freq "$STEPS" \
    --num_workers 2 \
    --checkpoint_dir /tmp/raft_accept_ckpt --log_dir /tmp/raft_accept_runs

echo "ACCEPTANCE PIPELINE GREEN"
