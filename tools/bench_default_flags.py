"""Print the measured-default CLI flags from ``BENCH_DEFAULTS.json``.

One definition of the BENCH_DEFAULTS -> underscore-style CLI-flag mapping
(profile_step / infer-style entry points), shared by the shell runbooks —
``tools/onchip_round5.sh`` derives the trace config from it and
``tools/dress_rehearsal_r5.sh`` rehearses profile_step at the same flags —
so the two scripts cannot drift. ``--with-batch`` adds ``--batch N`` from
the winning rung (the rehearsal forces its own tiny batch instead).
"""

import json
import os
import sys


def flags(with_batch: bool) -> list:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        with open(os.path.join(repo, "BENCH_DEFAULTS.json")) as f:
            d = json.load(f)
    except (OSError, ValueError):
        d = {}
    out = []
    if with_batch:
        out += ["--batch", str(d.get("batches", [8])[0])]
    if d.get("corr_dtype"):
        out += ["--corr_dtype", d["corr_dtype"]]
    if d.get("corr_impl"):
        out += ["--corr_impl", d["corr_impl"]]
    if d.get("fused_loss"):
        out.append("--fused_loss")
    if d.get("scan_unroll", 1) != 1:
        out += ["--scan_unroll", str(d["scan_unroll"])]
    if d.get("gru_impl"):
        out += ["--gru_impl", d["gru_impl"]]
    if d.get("remat"):
        out.append("--remat")
        if d.get("remat_policy"):
            out += ["--remat_policy", d["remat_policy"]]
    return out


if __name__ == "__main__":
    print(" ".join(flags("--with-batch" in sys.argv[1:])))
