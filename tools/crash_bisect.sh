#!/bin/bash
# Bisect the round-3 "bf16 shootout" TPU-worker crash (VERDICT r3 weak #3).
#
# Evidence re-read (ONCHIP_r03c.log:14-21): t_grad's process exited at
# 05:23:17 and t_bf16 STARTED the same second; inside t_bf16 ALL THREE
# impls — including onehot, which had just run clean at fp32 40 s
# earlier — failed with the identical "TPU worker process crashed or
# restarted" message. corr_bench runs impls back-to-back in one process,
# so a worker that was already dead when the process started fails all
# three without any impl ever executing on chip.
#
# Hypothesis A (primary): the crash is the known CRASH-ON-EXIT mode (the
#   worker dies right after the previous client exits; memory:
#   axon-tunnel-ops) — nothing bf16-specific ever ran.
# Hypothesis B: a genuine bf16-input kernel fault in one impl's grad.
#
# Protocol: for each cell, WAIT for a healthy probe, run the cell in a
# fresh process, record rc, wait 20 s, then probe again to see whether
# the worker survived the cell's exit. healthy->pass->healthy for every
# cell confirms A (fence: probe-before-run, already in the runbooks);
# a reproducible in-cell failure after a healthy pre-probe pins B to
# that exact impl x dtype x grad cell.
set -u
cd /root/repo
OUT=${1:-/tmp/crash_bisect.out}
MARK=${RAFT_R5_MARK:-/root/.cache/raft_tpu/r5_markers}
mkdir -p "$MARK"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
probe() {
    # Shared execute probe — enumeration-only reads a half-up tunnel
    # (devices() OK, execute hung; OUTAGE_r05.log 08:47 UTC) as up.
    bash tools/chip_probe.sh 120
}
wait_chip() {
    for _ in 1 2 3 4 5 6 7 8; do
        probe && return 0
        log "chip not answering; waiting 60s"
        sleep 60
    done
    return 1
}
cell() {
    local name=$1; shift
    if [ -e "$MARK/bisect_$name" ]; then log "skip $name (done)"; return 0; fi
    wait_chip || { log "SKIP $name (chip unavailable)"; return 1; }
    log "begin $name: $*"
    if timeout 900 "$@" >> "$OUT" 2>&1; then
        log "cell $name rc=0"
    else
        log "cell $name rc=$?"
    fi
    sleep 20  # crash-on-exit takes a moment to manifest on the next call
    if probe; then
        log "post-$name probe: worker ALIVE"
    else
        log "post-$name probe: worker DEAD (crash-on-exit reproduced)"
    fi
    touch "$MARK/bisect_$name"
    cp "$OUT" /root/repo/CRASH_BISECT_r05.log 2>/dev/null || true
}

CB="python -m raft_tpu.cli.corr_bench --batch 6 --hw 46 62 --iters 20"
# the failing row's cells, one impl per fresh process
cell gather_bf16_grad   $CB --impls gather   --grad --corr-dtype bfloat16
cell onehot_bf16_grad   $CB --impls onehot   --grad --corr-dtype bfloat16
cell onehot_t_bf16_grad $CB --impls onehot_t --grad --corr-dtype bfloat16
cell softsel_bf16_grad  $CB --impls softsel  --grad --corr-dtype bfloat16
# controls: fp32 grad passed in r3; bf16 fwd-only isolates grad-ness
cell onehot_fp32_grad_ctl $CB --impls onehot --grad
cell gather_bf16_fwd      $CB --impls gather --corr-dtype bfloat16
# the original three-impl single-process row, now AFTER a guaranteed
# healthy probe — if it passes here, hypothesis A is confirmed
cell original_row $CB --impls gather onehot onehot_t --grad \
    --corr-dtype bfloat16

log "bisect complete"
cp "$OUT" /root/repo/CRASH_BISECT_r05.log 2>/dev/null || true
