"""shard_audit_r6: capture REAL sharded TPU HLO for graftshard.

The graftshard gate runs on a forced multi-device CPU mesh; structure
transfers, byte thresholds and pass-pipeline behavior do not. This
rung compiles the same two mesh programs (tools/graftshard/targets.py)
on the REAL backend's devices, dumps the partitioned HLO next to the
round-6 artifacts, and answers the two questions the audit's waivers
defer to hardware:

- does the TPU pipeline SINK the backward scan's per-iteration
  gradient all-reduces out of the while body
  (WhileLoopAllReduceCodeMotion)? If yes, the S1 'transpose(' waiver
  on train_step_dp is confirmed CPU-only (keep, with this evidence);
  if no, the waiver is hiding real per-iteration comm — tighten it;
- what are the real collective sizes (S2) and shard extents (S5) at
  deployment shapes, so the CPU-anchored thresholds can be re-anchored.

Single-chip windows can't shard: with fewer than 2 devices this
script reports and exits 0 (the rung is a no-op until a slice
window). Usage::

    python tools/shard_audit_onchip.py [--out DIR] [--image-hw H,W]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# runnable as `python tools/shard_audit_onchip.py` from the repo root
# (the onchip runbook's invocation): sys.path[0] is tools/, not the root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="shard_audit_onchip")
    p.add_argument("--out", default="/tmp/raft_shard_audit_r6")
    p.add_argument("--image-hw", default="64,64",
                   help="audit shapes (bigger than the CPU gate's — "
                        "thresholds re-anchor at deployment-ish sizes)")
    args = p.parse_args(argv)

    import jax

    devs = jax.devices()
    if len(devs) < 2:
        print(f"shard_audit_r6: {len(devs)} {devs[0].platform} "
              "device(s) — sharded HLO needs a slice; skipping "
              "(rerun in a multi-chip window)")
        return 0

    from tools import hlo_lib
    from tools.graftshard.targets import (_build_serve_shard,
                                          _build_train_step_dp,
                                          build_targets)

    os.makedirs(args.out, exist_ok=True)
    h, w = (int(v) for v in args.image_hw.split(","))
    n = len(devs)
    batch = n                       # one example per device
    #: the gate's own declarations: donation args come from the SAME
    #: registry the audit uses, so the evidence can't drift from it
    decl = {t.name: t for t in build_targets()}
    summary = {"devices": n, "platform": devs[0].platform,
               "image_hw": [h, w], "batch": batch, "programs": {}}

    def report(name, lowered):
        hlo = lowered.compile().as_text()
        path = os.path.join(args.out, f"{name}.hlo.txt")
        with open(path, "w", encoding="utf-8") as f:
            f.write(hlo)
        bodies = hlo_lib.while_body_computations(hlo)
        in_loop = hlo_lib.find_collectives(hlo, within=bodies)
        all_coll = hlo_lib.find_collectives(hlo)
        rec = {
            "hlo": path,
            "collectives": len(all_coll),
            "collectives_in_loop": len(in_loop),
            "in_loop_grad": sum(1 for r in in_loop
                                if "transpose(" in r["op_name"]),
            "max_all_reduce_bytes": max(
                (r["bytes"] for r in all_coll
                 if r["opcode"] == "all-reduce"), default=0),
        }
        summary["programs"][name] = rec
        print(f"shard_audit_r6: {name}: {rec['collectives']} "
              f"collectives, {rec['collectives_in_loop']} in-loop "
              f"({rec['in_loop_grad']} gradient) — "
              f"{'SINK CONFIRMED, S1 waiver holds' if name == 'train_step_dp' and rec['in_loop_grad'] == 0 else 'see ' + path}")

    # THE gate's target recipes (tools/graftshard/targets.py builders,
    # parameterized — not copies), on the real backend's devices.
    # Train keeps the gate's iters=2 (loop structure is what matters);
    # serve runs deployment iters=20 so per-iteration comm evidence is
    # at the served loop length.
    fn, fargs, _ = _build_train_step_dp(
        image_hw=(h, w), batch=batch, iters=2, n_devices=n,
        force_cpu=False)()
    report("train_step_dp",
           jax.jit(fn, donate_argnums=decl["train_step_dp"]
                   .donate_argnums).lower(*fargs))

    fn, fargs, _ = _build_serve_shard(
        image_hw=(h, w), batch=batch, iters=20, n_devices=n,
        force_cpu=False)()
    report("serve_shard",
           jax.jit(fn, donate_argnums=decl["serve_shard"]
                   .donate_argnums).lower(*fargs))

    spath = os.path.join(args.out, "summary.json")
    with open(spath, "w", encoding="utf-8") as f:
        json.dump(summary, f, indent=2)
    print(f"shard_audit_r6: summary -> {spath}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
