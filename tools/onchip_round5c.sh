#!/bin/bash
# Round-5c runbook: attribute the remaining scan-body band.
#
# The post-rework trace (PROFILE.md tail) leaves ~44 ms/step in six
# conv fusions at 20-80 GB/s effective that an XProf trace alone cannot
# attribute. This pass captures a fresh trace AND the matching XLA
# after-optimizations dump from the SAME process, then maps the top
# fusion names to source ops with tools/hlo_attr.py. Marker-guarded,
# cheap (~3-6 min), safe to fire on any window after the 5b musts.
#
#   trace_attr    profile_step (bench defaults) + trace_summary +
#                 hlo_attr -> PROFILE_r05c.log committed
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round5c.out}
MARK=${RAFT_R5B_MARK:-/root/.cache/raft_tpu/r5b_markers}
mkdir -p "$MARK"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }

if [ ! -e "$MARK/trace_attr" ]; then
    # a probe-failed pass must leave a trace in $OUT (mirrors the
    # FAILED trace_attr path) — a silent exit 1 reads as "never ran"
    bash tools/chip_probe.sh 120 \
        || { log "FAILED chip_probe (probe failed, skipping trace_attr)"; \
             exit 1; }
    log "begin trace_attr (profile_step + XLA dump at bench defaults)"
    rm -rf /tmp/trace_r5c /tmp/hlo_r5c
    if timeout 900 env \
            XLA_FLAGS="--xla_dump_to=/tmp/hlo_r5c --xla_dump_hlo_as_text" \
            python -m raft_tpu.cli.profile_step --batch 8 --hw 368 496 \
            --corr_impl softsel --corr_dtype bfloat16 --fused-loss \
            --steps 2 --trace-dir /tmp/trace_r5c >> "$OUT" 2>&1 \
            && timeout 300 python -m raft_tpu.cli.trace_summary \
            /tmp/trace_r5c --top 30 > /tmp/r5c_summary.txt 2>&1; then
        # op names are the LAST field of each top-op row; the bare token
        # "fusion" from category columns ("loop fusion") must not leak
        # into hlo_attr's substring match
        NAMES=$(awk '{print $NF}' /tmp/r5c_summary.txt | grep fusion \
            | grep -vx 'fusion' | sort -u | head -40)
        {
            echo "# Round-5c trace attribution ($(date -u +%F\ %H:%M) UTC)"
            echo "# profile_step --batch 8 --hw 368 496 --corr_impl softsel"
            echo "#   --corr_dtype bfloat16 --fused-loss (bench defaults)"
            cat /tmp/r5c_summary.txt
            echo
            echo "# hlo_attr: top-trace fusion names -> source ops"
            if [ -n "$NAMES" ]; then
                # shellcheck disable=SC2086
                python tools/hlo_attr.py /tmp/hlo_r5c $NAMES 2>&1
            else
                echo "(no fusion names found in the trace summary)"
            fi
            echo
            echo "# hlo_attr --top 25 (largest fused computations)"
            python tools/hlo_attr.py /tmp/hlo_r5c --top 25 2>&1
        } > PROFILE_r05c.log
        touch "$MARK/trace_attr"
        git add PROFILE_r05c.log 2>/dev/null || true
        git diff --cached --quiet || git commit -q \
            -m "Round-5c: trace + HLO-dump attribution of the scan-body band" \
            -m "No-Verification-Needed: measurement logs and records only"
        log "done trace_attr"
    else
        log "FAILED trace_attr"
    fi
fi
log "round5c pass complete"
