#!/bin/bash
# Round-5 late-window runbook: everything mandated already landed in the
# 00:04-04:50 UTC window (see BENCH_NOTES round-5 scoreboard); these are
# the nice-to-haves cut short when the tunnel dropped at ~04:50 —
# marker-guarded and cheap, safe to fire on any remaining window.
#
#   bare_final_head   bare driver-style bench at final HEAD -> refresh
#                     BENCH_r05_local.json (cap 900 s)
#   sustained_train   3,000-step synthetic-chairs training at the bench
#                     defaults, val/ckpt every 1,000 (cap 3600 s)
#   resume_check      restart the same run with --resume for +200 steps
#                     (full-state restore on silicon; cap 1200 s)
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round5b.out}
MARK=${RAFT_R5B_MARK:-/root/.cache/raft_tpu/r5b_markers}
mkdir -p "$MARK"
log() { echo "=== $(date -u +%H:%M:%S) $* ===" >> "$OUT"; }
chip_up() {
    # Real 1-op execute probe (shared helper): a half-up tunnel —
    # devices() enumerates, compile/execute hangs (OUTAGE_r05.log
    # 08:47 UTC) — must read as down.
    bash tools/chip_probe.sh 120
}
commit_msmt() {
    local msg=$1; shift
    for f in "$@"; do git add "$f" 2>/dev/null || true; done
    git diff --cached --quiet || git commit -q -m "$msg" -m \
        "No-Verification-Needed: measurement logs and records only"
}

if [ ! -e "$MARK/bare_final_head" ]; then
    chip_up || exit 1
    log "begin bare_final_head"
    if timeout 900 python bench.py > /tmp/r5b_bare.json 2>> "$OUT" \
            && python -c "import json,sys; sys.exit(0 if json.load(open('/tmp/r5b_bare.json')).get('value',0) > 0 else 1)"; then
        cat /tmp/r5b_bare.json >> "$OUT"
        cp /tmp/r5b_bare.json BENCH_r05_local.json
        touch "$MARK/bare_final_head"
        commit_msmt "Refresh BENCH_r05_local.json with a bare run at final HEAD" \
            BENCH_r05_local.json
        log "done bare_final_head"
    else
        log "FAILED bare_final_head"
    fi
fi

if [ ! -e "$MARK/sustained_train" ]; then
    chip_up || exit 1
    log "begin sustained_train (3000 steps)"
    if timeout 3600 python -m raft_tpu.cli.train --name r5long \
            --stage chairs --mixed_precision --synthetic 64 \
            --num_steps 3000 --val_freq 1000 --batch_size 8 \
            --num_workers 4 --corr_dtype bfloat16 --corr_impl softsel \
            --checkpoint_dir /root/.cache/raft_tpu/r5_long \
            --log_dir runs >> "$OUT" 2>&1; then
        touch "$MARK/sustained_train"
        log "done sustained_train"
    else
        log "FAILED sustained_train rc=$?"
    fi
fi

if [ -e "$MARK/sustained_train" ] && [ ! -e "$MARK/resume_check" ]; then
    chip_up || exit 1
    log "begin resume_check (+200 steps from full state)"
    if timeout 1200 python -m raft_tpu.cli.train --name r5long \
            --stage chairs --mixed_precision --synthetic 64 \
            --num_steps 3200 --val_freq 1000 --batch_size 8 \
            --num_workers 4 --corr_dtype bfloat16 --corr_impl softsel \
            --checkpoint_dir /root/.cache/raft_tpu/r5_long \
            --log_dir runs --resume >> "$OUT" 2>&1; then
        touch "$MARK/resume_check"
        log "done resume_check"
    else
        log "FAILED resume_check rc=$?"
    fi
fi

if [ -e "$MARK/sustained_train" ] && [ ! -e "$MARK/recorded" ]; then
    RATE=$(grep -oE '\([0-9.]+ steps/s\)' "$OUT" | tail -1)
    {
        echo
        echo "### Sustained on-chip training (round-5 late window)"
        echo
        echo '`cli/train` 3,000 synthetic-chairs steps at the bench defaults'
        echo "(softsel, bf16 volumes, fused loss, uint8 wire, b8) with"
        echo "val/checkpoint every 1,000 steps, then a --resume restart for"
        echo "+200 more from the full Orbax state — both green on the v5e-1."
        echo "Last printed rate: ${RATE:-see /tmp/onchip_round5b.out}."
    } >> BENCH_NOTES.md
    touch "$MARK/recorded"
    commit_msmt "Record the sustained-training + resume proof" BENCH_NOTES.md
fi
log "round5b pass complete"
