#!/bin/bash
# Round-3 watchdog: poll the axon tunnel every 5 minutes; each time the
# chip answers, (re-)run the marker-guarded round-3 runbook. Loops until
# the runbook's final marker exists, so a window that drops mid-run is
# resumed on the next one. Raw log lands in the repo after every step
# (onchip_round3.sh handles the copy + artifact commit).
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round3.out}
LOG=/tmp/tpu_watch.log
MARK=/root/.cache/raft_tpu/r3_markers
while true; do
    if [ -e "$MARK/export_cycle" ] && [ -e "$MARK/train500_resume" ]; then
        echo "$(date -u +%H:%M:%S) r3 runbook fully done" >> "$LOG"
        exit 0
    fi
    if timeout 180 python -c "import jax; d = jax.devices(); assert d[0].platform != 'cpu', d" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) chip up — running round3 runbook" >> "$LOG"
        bash /root/repo/tools/onchip_round3.sh "$OUT"
        echo "$(date -u +%H:%M:%S) runbook pass ended" >> "$LOG"
    else
        echo "$(date -u +%H:%M:%S) chip unavailable" >> "$LOG"
    fi
    sleep 300
done
