"""HLO-text parsing library: fusions, op traffic, aliases, constants.

Grew out of ``tools/hlo_attr.py`` (which keeps its CLI and re-exports
the parsing entry points from here): the fusion -> ``metadata.op_name``
attribution it built for trace work is exactly what a compiled-artifact
audit needs as a *library* — ``tools/graftaudit`` consumes this module
for its H4 (donation honored), H5 (per-op-name traffic budgets), and
H6 (constant-folding traps) rules, over HLO text obtained either from
``jax.stages.Compiled.as_text()`` or an ``--xla_dump_to`` directory.

Everything here is pure text parsing over XLA's HLO dump format — no
jax import, so it loads in pure-stdlib contexts (pytest collection,
the graftlint process) for free.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

# Two dialects of the same format must parse: ``Compiled.as_text()``
# prefixes every name with ``%`` and computation headers carry a typed
# signature (``%comp (a: f32[]) -> f32[] {``); ``--xla_dump_to`` files
# drop both (``comp {``, ``dot.4 = ...``). ``%`` is optional everywhere
# and the header signature is optional in _COMP_RE.
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|\S+)\s+fusion\(")
_META_RE = re.compile(r'op_name="(?P<op>[^"]*)"')
_CALLS_RE = re.compile(r"calls=%?(?P<comp>[\w.\-]+)")
_KIND_RE = re.compile(r"kind=(?P<kind>k\w+)")
# any instruction def: `%name = <shape> <opcode>(`; shape is either a
# tuple `(f32[2]{0}, ...)` or a single token
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|\S+)\s+(?P<opcode>[\w\-]+)\(")
# a computation header is a top-of-line (never indented — instructions
# are) name followed by an optional typed signature (which may carry
# layout braces, `f32[8,64]{1,0}`), ending in the opening brace
_COMP_RE = re.compile(
    r"^(?:ENTRY\s+)?%?(?P<comp>[\w.\-]+)\s*(?:\(.*)?\{\s*$")
# computations referenced as fusion/call/reduce bodies — their inner ops
# are accounted for at the call site, not individually. while/conditional
# regions (body=/condition=/branch_computations=) are deliberately NOT
# here: control flow executes those ops directly, each line carrying its
# own op_name, and the scan-body band graftaudit budgets lives there.
_SUBCOMP_RE = re.compile(r"(?:calls|to_apply)=%?([\w.\-]+)")
_CCTARGET_RE = re.compile(r'custom_call_target="(?P<t>[^"]+)"')

#: bytes per element for the HLO dtype prefixes this repo's programs use
DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}
_SHAPE_TOKEN_RE = re.compile(
    r"\b(?P<dt>" + "|".join(sorted(DTYPE_BYTES, key=len, reverse=True))
    + r")\[(?P<dims>[\d,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Total bytes of every ``dtype[dims]`` token in ``shape_str`` —
    handles single shapes, tuple shapes, and whole instruction lines
    (result + inline operand shapes)."""
    total = 0
    for m in _SHAPE_TOKEN_RE.finditer(shape_str):
        n = DTYPE_BYTES[m.group("dt")]
        dims = m.group("dims")
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n
    return total


def pick_module(dump_dir: str) -> Optional[str]:
    """Largest after-optimizations HLO text in the dump (the main jit)."""
    cands: List[Tuple[int, str]] = []
    if not os.path.isdir(dump_dir):
        return None
    for fn in os.listdir(dump_dir):
        if fn.endswith("after_optimizations.txt"):
            p = os.path.join(dump_dir, fn)
            cands.append((os.path.getsize(p), p))
    return max(cands)[1] if cands else None


def parse_fusions_text(text) -> Dict[str, dict]:
    """name -> {shape, kind, op_name, calls, body_lines} for every
    fusion. ``text`` is a string or any iterable of lines — real
    after-optimizations dumps run to hundreds of MB, so the file path
    (:func:`parse_fusions`) streams instead of slurping."""
    fusions: Dict[str, dict] = {}
    comp_sizes: Dict[str, int] = {}
    comp_ops: Dict[str, List[str]] = {}
    cur_comp = None
    lines = text.splitlines(keepends=True) if isinstance(text, str) \
        else text
    for line in lines:
        m = _COMP_RE.match(line)
        if m:
            # ENTRY opens the top-level computation: stop attributing
            # lines to the previous fused computation
            cur_comp = None if line.startswith("ENTRY") \
                else m.group("comp")
            if cur_comp is not None:
                comp_sizes[cur_comp] = 0
                comp_ops[cur_comp] = []
            continue
        if line.strip() == "}":
            cur_comp = None
        elif cur_comp is not None and line.strip():
            comp_sizes[cur_comp] += 1
            bm = _META_RE.search(line)
            if bm:
                comp_ops[cur_comp].append(bm.group("op"))
        d = _DEF_RE.match(line)
        if d:
            meta = _META_RE.search(line)
            calls = _CALLS_RE.search(line)
            kind = _KIND_RE.search(line)
            fusions[d.group("name")] = {
                "shape": d.group("shape"),
                "kind": kind.group("kind") if kind else "?",
                "op_name": meta.group("op") if meta else "(no metadata)",
                "calls": calls.group("comp") if calls else None,
            }
    for info in fusions.values():
        info["body_lines"] = comp_sizes.get(info["calls"] or "", 0)
        if info["op_name"] == "(no metadata)":
            # fall back to the fused computation's own ops: report the
            # most frequent op_name in the body
            ops = comp_ops.get(info["calls"] or "", [])
            if ops:
                # max over the list: first-seen wins ties (deterministic)
                best = max(ops, key=ops.count)
                info["op_name"] = f"(body) {best}"
    return fusions


def parse_fusions(path: str) -> Dict[str, dict]:
    with open(path) as f:
        return parse_fusions_text(f)   # streamed, not slurped


# -- audit-tier parsers (graftaudit consumers) ----------------------------

#: opcodes that move no bytes of their own (aliases, plumbing), whose
#: bytes are accounted inside their region (while/conditional carry the
#: whole loop state tuple on their def line — the region's ops already
#: bill those bytes), or that materialize nothing (iota, constants —
#: constants are H6's concern, not traffic)
_FREE_OPCODES = {"parameter", "constant", "tuple", "get-tuple-element",
                 "bitcast", "after-all", "iota", "while", "conditional"}


def _operand_names(line: str, opcode: str) -> List[str]:
    """Bare operand names from an instruction's call parens (the dump
    dialect: ``fusion(dot.4, Arg_0.1)`` — no inline shapes)."""
    i = line.find(opcode + "(")
    if i < 0:
        return []
    seg = line[i + len(opcode) + 1:]
    depth = 0
    for j, ch in enumerate(seg):
        if ch == "(":
            depth += 1
        elif ch == ")":
            if depth == 0:
                seg = seg[:j]
                break
            depth -= 1
    return [t for t in (p.strip().lstrip("%") for p in seg.split(","))
            if re.fullmatch(r"[\w.\-]+", t)]


def iter_op_traffic(text: str) -> Iterable[dict]:
    """One record per byte-moving instruction OUTSIDE called
    sub-computations: ``{name, opcode, op_name, bytes, custom_target}``.

    ``bytes`` sums the result plus every operand shape — a
    deterministic traffic *estimate* in the spirit of XLA's cost
    analysis, attributable per-op via ``metadata.op_name`` (which the
    aggregate ``Compiled.cost_analysis()`` number is not). In the
    ``Compiled.as_text()`` dialect operand shapes are inline on the
    line; ``--xla_dump_to`` files print bare operand names, so those
    are resolved against the module's defs — both dialects price the
    same instruction the same. Instructions inside fusion/reduce bodies
    are skipped: the fusion def line already carries the fused region's
    operand/result shapes, so counting body lines would double-bill
    every fused byte. While/conditional bodies are *not* skipped —
    control-flow regions execute their ops directly and each line
    carries its own op_name (the scan-body band lives there)."""
    sub: Set[str] = set(m for m in _SUBCOMP_RE.findall(text))
    lines = text.splitlines()
    # def map for the bare-operand dialect: name -> result-shape bytes
    def_bytes: Dict[str, int] = {}
    for line in lines:
        d = _OP_RE.match(line)
        if d:
            def_bytes[d.group("name")] = shape_bytes(d.group("shape"))
    cur_comp = None
    for line in lines:
        m = _COMP_RE.match(line)
        if m:
            cur_comp = None if line.startswith("ENTRY") else m.group("comp")
            continue
        if line.strip() == "}":
            cur_comp = None
            continue
        if cur_comp in sub:
            continue
        d = _OP_RE.match(line)
        if not d or d.group("opcode") in _FREE_OPCODES:
            continue
        total = shape_bytes(line)
        result = shape_bytes(d.group("shape"))
        if total == result:
            # no inline operand shapes (dump dialect): resolve names
            total += sum(def_bytes.get(n, 0) for n in
                         _operand_names(line, d.group("opcode")))
        meta = _META_RE.search(line)
        cct = _CCTARGET_RE.search(line)
        yield {
            "name": d.group("name"),
            "opcode": d.group("opcode"),
            "op_name": meta.group("op") if meta else "",
            "bytes": total,
            "custom_target": cct.group("t") if cct else "",
        }


def band_traffic(text: str, match: str) -> Tuple[int, int]:
    """(total bytes, op count) over instructions whose ``op_name``
    contains ``match`` (empty string matches every instruction)."""
    total = ops = 0
    for rec in iter_op_traffic(text):
        if match in rec["op_name"]:
            total += rec["bytes"]
            ops += 1
    return total, ops


def parse_aliased_params(text: str) -> Set[int]:
    """Param indices the optimized module's ``input_output_alias`` map
    covers — XLA's ground truth for which donations were HONORED."""
    hdr = text.split("\n", 1)[0]
    i = hdr.find("input_output_alias={")
    if i < 0:
        return set()
    seg = hdr[i + len("input_output_alias={"):]
    # entries look like `{out_idx}: (param, {path}, may-alias)`; the
    # segment ends at the first `}` that closes the map — but entries
    # nest one brace level, so cut at the next header key instead
    end = seg.find("}, ")
    while end >= 0 and seg[:end].count("{") != seg[:end].count("}"):
        end = seg.find("}, ", end + 1)
    seg = seg if end < 0 else seg[:end]
    return {int(p) for p in re.findall(r"\}:\s*\((\d+)\s*,", seg)}


def parse_entry_param_shapes(text: str) -> List[str]:
    """Entry parameter shapes, by param index, from the module header's
    ``entry_computation_layout={(...)->...}``. Split on top-level commas
    only — dims and layouts carry commas of their own
    (``f32[4,4]{1,0}``), and tuple params nest parens."""
    hdr = text.split("\n", 1)[0]
    anchor = "entry_computation_layout={("
    start = hdr.find(anchor)
    if start < 0:
        return []
    out: List[str] = []
    depth = 0
    cur: List[str] = []

    def flush():
        s = "".join(cur).strip()
        if s:
            out.append(s)
        cur.clear()

    for ch in hdr[start + len(anchor):]:
        if ch == ")" and depth == 0:       # closes the params list
            break
        if ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        if ch == "," and depth == 0:
            flush()
        else:
            cur.append(ch)
    flush()
    return out


def find_large_constants(text: str, min_bytes: int) -> List[dict]:
    """Materialized literals at least ``min_bytes`` big, anywhere in the
    module: ``{name, shape, bytes, op_name}``. Byte size comes from the
    declared result shape, so elided literals (``constant({...})``) are
    still sized correctly."""
    out: List[dict] = []
    for line in text.splitlines():
        d = _OP_RE.match(line)
        if not d or d.group("opcode") != "constant":
            continue
        size = shape_bytes(d.group("shape"))
        if size >= min_bytes:
            meta = _META_RE.search(line)
            out.append({
                "name": d.group("name"),
                "shape": d.group("shape"),
                "bytes": size,
                "op_name": meta.group("op") if meta else "",
            })
    return out


# -- sharding-tier parsers (graftshard consumers) --------------------------

#: cross-device communication opcodes GSPMD inserts when partitioning;
#: async forms (``all-reduce-start``/``-done``) normalize onto these
COLLECTIVE_OPCODES = {"all-reduce", "all-gather", "all-to-all",
                      "collective-permute", "reduce-scatter",
                      "collective-broadcast", "ragged-all-to-all"}


def _norm_collective(opcode: str) -> Optional[str]:
    base = re.sub(r"-(start|done)$", "", opcode)
    return base if base in COLLECTIVE_OPCODES else None


def computation_lines(text: str) -> Dict[str, List[str]]:
    """computation name -> its instruction lines (both HLO dialects —
    see the module-header note on ``%``/signature differences)."""
    out: Dict[str, List[str]] = {}
    cur = None
    for line in text.splitlines():
        m = _COMP_RE.match(line)
        if m:
            cur = m.group("comp")
            out[cur] = []
            continue
        if line.strip() == "}":
            cur = None
        elif cur is not None and line.strip():
            out[cur].append(line)
    return out


def while_body_computations(text: str) -> Set[str]:
    """Names of computations executed PER LOOP ITERATION: every
    ``body=``/``condition=`` region of a ``while``, expanded through
    the computations those regions call (``calls=``/``to_apply=``) —
    a collective buried in a called sub-computation of a loop body is
    still per-iteration comm."""
    comps = computation_lines(text)
    roots = set(re.findall(r"(?:body|condition)=%?([\w.\-]+)", text))
    seen: Set[str] = set()
    stack = [r for r in roots if r in comps]
    while stack:
        c = stack.pop()
        if c in seen:
            continue
        seen.add(c)
        for line in comps.get(c, ()):
            for ref in re.findall(r"(?:calls|to_apply|body|condition)"
                                  r"=%?([\w.\-]+)", line):
                if ref in comps and ref not in seen:
                    stack.append(ref)
    return seen


def find_collectives(text: str, within: Optional[Set[str]] = None
                     ) -> List[dict]:
    """Collective instruction defs, each ``{name, opcode, shape, bytes,
    op_name, comp}`` — optionally restricted to the ``within``
    computations (e.g. :func:`while_body_computations` for the
    comm-in-loop question)."""
    out: List[dict] = []
    for comp, lines in computation_lines(text).items():
        if within is not None and comp not in within:
            continue
        for line in lines:
            d = _OP_RE.match(line)
            if not d:
                continue
            opcode = _norm_collective(d.group("opcode"))
            if opcode is None:
                continue
            meta = _META_RE.search(line)
            out.append({
                "name": d.group("name"),
                "opcode": opcode,
                "shape": d.group("shape"),
                "bytes": shape_bytes(d.group("shape")),
                "op_name": meta.group("op") if meta else "",
                "comp": comp,
            })
    return out


def find_host_ops(text: str) -> List[dict]:
    """Instructions that cross the host boundary inside the module:
    infeed/outfeed/send/recv and custom-calls whose target names a host
    callback. Returns ``{name, opcode, detail, op_name}``."""
    out: List[dict] = []
    for rec in iter_op_traffic(text):
        op = rec["opcode"]
        if op in ("infeed", "outfeed", "send", "recv",
                  "send-done", "recv-done"):
            out.append({"name": rec["name"], "opcode": op,
                        "detail": op, "op_name": rec["op_name"]})
        elif op == "custom-call":
            tgt = rec["custom_target"]
            if re.search(r"callback|CallbackTo|host", tgt, re.IGNORECASE):
                out.append({"name": rec["name"], "opcode": op,
                            "detail": tgt, "op_name": rec["op_name"]})
    return out
