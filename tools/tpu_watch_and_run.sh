#!/bin/bash
# Poll the axon TPU tunnel every 5 minutes; when it answers, run the
# follow-up on-chip runbook once and exit. Survives tunnel claim-wait
# hangs via a per-probe timeout.
set -u
cd /root/repo
OUT=${1:-/tmp/onchip_round2b.out}
LOG=/tmp/tpu_watch.log
while true; do
    if timeout 180 python -c "import jax; d = jax.devices(); assert d[0].platform != 'cpu', d" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) chip up — launching round2b" >> "$LOG"
        bash /root/repo/tools/onchip_round2b.sh "$OUT"
        # land the results in the repo so the round-end snapshot commit
        # preserves them even if the session is over by then
        cp "$OUT" /root/repo/ONCHIP_r02.log 2>/dev/null || true
        echo "$(date -u +%H:%M:%S) round2b done" >> "$LOG"
        exit 0
    fi
    echo "$(date -u +%H:%M:%S) chip unavailable" >> "$LOG"
    sleep 300
done
