#!/bin/bash
# Round-5 watchdog: poll the axon tunnel every 5 min; on each window run
# the marker-guarded round-5 runbook (bare driver bench FIRST, then
# parity, ladder tiers, trace, crash bisect). Appends an outage trace to
# OUTAGE_r05.log (committed at round end as the availability record).
# Exits when the runbook's terminal markers all exist.
set -u
cd /root/repo
LOG=/root/repo/OUTAGE_r05.log
MARK=${RAFT_R5_MARK:-/root/.cache/raft_tpu/r5_markers}
while true; do
    if [ -e "$MARK/bare_bench" ] && [ -e "$MARK/trained_parity_exact" ] \
            && [ -e "$MARK/bench_j_fused" ] \
            && [ -e "$MARK/bench_i_softsel_b8" ] \
            && [ -e "$MARK/train_rate" ] \
            && [ -e "$MARK/infer_bf16_v2" ] \
            && [ -e "$MARK/trace_summary_r5" ] \
            && [ -e "$MARK/crash_bisect" ]; then
        echo "$(date -u +%H:%M:%S) r5 runbook fully done" >> "$LOG"
        exit 0
    fi
    if timeout -k 10 180 python -c \
        "import jax; assert jax.devices()[0].platform != 'cpu'" \
        >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) chip up — running round-5 runbook" \
            >> "$LOG"
        bash tools/onchip_round5.sh /tmp/onchip_round5.out
        echo "$(date -u +%H:%M:%S) runbook pass ended" >> "$LOG"
    else
        echo "$(date -u +%H:%M:%S) chip unavailable" >> "$LOG"
    fi
    # 180s sleep + up-to-180s hung probe = ~6 min poll period while the
    # tunnel is down; a fresh ~100-min window loses at most that
    sleep 180
done
