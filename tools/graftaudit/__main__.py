import os
import sys

# Force the CPU backend BEFORE jax initializes: the audit traces on CPU
# by contract (tiny shapes; artifact structure is platform-independent)
# and must never dial the image's remote-TPU tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"

from tools.graftaudit.artifacts import ensure_cpu  # noqa: E402

ensure_cpu()
try:
    # persistent compile cache: the audit's compiles are identical run
    # to run, so everything after the first invocation is cache hits
    from raft_tpu.utils.platform import enable_persistent_cache

    enable_persistent_cache("graftaudit")
except Exception:
    pass

from tools.graftaudit.core import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
