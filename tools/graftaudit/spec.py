"""Audit target declarations: what gets traced, and what is waived.

A ``Target`` names one real program (train step, serving function,
engine canary) plus the *declared* discipline the audit holds it to:
which args it donates (H4), what dtype its hot path is supposed to run
in (H2), how many executables its canary is documented to compile (H3).

``Waiver`` is the pragma analog for compiled artifacts. graftlint
suppresses a finding with a per-line ``# graftlint: disable=RN``
comment; an audit finding has no source line, so the suppression lives
on the target declaration instead — rule id, a substring of the
finding's ``detail``, and a REQUIRED justification, reviewed in the
same place the target is defined. Like pragmas, waivers are for
intentional-by-design behavior (the fp32 correlation island), never
for "we'll fix it later" — that is what the baseline's shrink-only
burn-down is for.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple


@dataclass(frozen=True)
class Waiver:
    rule: str      # "H2"
    match: str     # substring of the finding's detail
    reason: str    # justification — empty reasons are rejected

    def __post_init__(self):
        if not self.reason.strip():
            raise ValueError(
                f"waiver for {self.rule} ({self.match!r}) has no "
                "justification — waivers document intent or they are "
                "just silent baselining")


@dataclass(frozen=True)
class CanaryResult:
    """What a canary target observed when it exercised its program."""

    observed_compiles: int
    detail: str                      # what was swept, for the finding
    hlo_texts: Tuple[str, ...] = ()  # executables' optimized HLO, so
                                     # the artifact rules audit them too


@dataclass(frozen=True)
class Target:
    """One audited program.

    ``kind="trace"``: ``build()`` returns ``(fn, args)`` — positional
    example args, real arrays or ``jax.ShapeDtypeStruct``s. The driver
    traces the jaxpr, lowers with ``donate_argnums``, and (when
    ``compiled``) compiles for the HLO-tier rules.

    ``kind="canary"``: ``build()`` returns a :class:`CanaryResult`; the
    target runs its own shape/batch sweep (H3) and hands back any
    executables' HLO for the artifact rules.
    """

    name: str
    build: Callable
    kind: str = "trace"
    donate_argnums: Tuple[int, ...] = ()
    compute_dtype: str = "float32"   # "bfloat16" arms H2
    compiled: bool = True            # False: jaxpr/lowered tier only
    expect_compiles: Optional[int] = None   # canary: documented count
    waivers: Tuple[Waiver, ...] = ()
    notes: str = ""

    def waived(self, rule: str, detail: str) -> bool:
        return any(w.rule == rule and w.match in detail
                   for w in self.waivers)


@dataclass
class Artifacts:
    """Everything the rules see for one target. ``jaxpr`` is the traced
    ``ClosedJaxpr``; the texts are jax's lowered StableHLO and XLA's
    optimized HLO; ``cost`` is ``Compiled.cost_analysis()``'s aggregate
    dict; ``canary`` is set for canary targets (whose ``hlo_texts``
    also land in ``hlo_text``, concatenated — the line-scanning rules
    don't care about module boundaries)."""

    jaxpr: object = None
    lowered_text: str = ""
    hlo_text: str = ""
    cost: Dict[str, float] = field(default_factory=dict)
    canary: Optional[CanaryResult] = None
    seconds: float = 0.0             # build wall time, for --json timing
    traffic_obs: Optional[Dict[str, int]] = None   # H5 observe() memo:
                                     # the rule and the driver's
                                     # --budget-update share one scan
