"""The one record type every graftaudit rule emits.

The graftlint analog (``tools/graftlint/finding.py``) anchors findings
to source positions; an audit finding anchors to a *target* (a traced
program) plus a stable ``detail`` string (op path, param index, band
name) — compiled artifacts have no line numbers, so the detail IS the
baseline identity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class AuditFinding:
    target: str    # audit target name, e.g. "train_step"
    rule: str      # "H1".."H6"
    name: str      # kebab-case rule name, e.g. "host-transfer-in-step"
    detail: str    # stable identity inside the artifact (op path, band,
                   # param index) — line numbers don't exist here
    message: str

    def render(self) -> str:
        return (f"{self.target}: {self.rule}[{self.name}] "
                f"{self.message}")

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: details are derived from op paths and
        param shapes, which survive recompiles of the same program."""
        return (self.target, self.rule, self.detail)
