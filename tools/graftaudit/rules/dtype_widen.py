"""H2: unintended dtype widening in a bf16-configured step.

Armed only for targets declaring ``compute_dtype="bfloat16"``: every
``dot_general``/``conv_general_dilated`` whose result is f32 is flagged
— on TPU those run at a fraction of the bf16 MXU rate and double the
operand traffic of the step's heaviest ops. Weak-type promotion (a bare
python scalar touching a bf16 array) is the classic silent source.

Intentional fp32 islands (the all-pairs correlation GEMM, reference
parity — core/raft.py:102-103 analog) are waived on the target
declaration with a justification, mirroring graftlint pragmas. The
detail key is the eqn's source ``name_stack``, which names the model
path and survives recompiles.
"""

from __future__ import annotations

from typing import List

from ..finding import AuditFinding
from ..spec import Artifacts, Target

RULE = "H2"
NAME = "fp32-widening-in-bf16-step"

_WIDE_PRIMS = ("dot_general", "conv_general_dilated")


def check(target: Target, art: Artifacts, budgets=None
          ) -> List[AuditFinding]:
    if target.compute_dtype != "bfloat16" or art.jaxpr is None:
        return []
    from ..artifacts import iter_subjaxprs

    out: List[AuditFinding] = []
    seen = set()
    for eqn in iter_subjaxprs(art.jaxpr.jaxpr):
        if eqn.primitive.name not in _WIDE_PRIMS:
            continue
        res = eqn.outvars[0].aval
        if str(getattr(res, "dtype", "")) != "float32":
            continue
        ins = ",".join(str(v.aval.dtype) for v in eqn.invars
                       if hasattr(v, "aval"))
        detail = f"{eqn.primitive.name} f32 @ {eqn.source_info.name_stack}"
        if detail in seen:
            continue
        seen.add(detail)
        out.append(AuditFinding(
            target.name, RULE, NAME, detail,
            f"f32 {eqn.primitive.name} (operands {ins}) in a "
            f"bf16-configured step at {eqn.source_info.name_stack} — "
            "intentional fp32 islands get a waiver on the target, "
            "promotion escapes get fixed at the site"))
    return out
