"""H1: host transfers/callbacks inside a compiled step.

A callback or infeed/outfeed inside the jitted step serializes the
device against the host every step — the compiled-artifact form of
graftlint's R1 (which can only see host syncs written in source; a
`jax.debug.print` buried three layers into a library helper is
invisible to the AST but shows up here as a `debug_callback` eqn and a
host custom-call in the optimized HLO).
"""

from __future__ import annotations

from typing import List

from ..finding import AuditFinding
from ..spec import Artifacts, Target

RULE = "H1"
NAME = "host-transfer-in-step"

#: jaxpr primitives that cross the host boundary
_HOST_PRIMS = ("pure_callback", "io_callback", "debug_callback",
               "callback", "infeed", "outfeed", "host_callback")


def check(target: Target, art: Artifacts, budgets=None
          ) -> List[AuditFinding]:
    from ..artifacts import iter_subjaxprs

    out: List[AuditFinding] = []
    seen = set()
    if art.jaxpr is not None:
        for eqn in iter_subjaxprs(art.jaxpr.jaxpr):
            pname = eqn.primitive.name
            if not any(pname == p or pname.startswith(p + "_")
                       for p in _HOST_PRIMS):
                continue
            detail = f"{pname} @ {eqn.source_info.name_stack}"
            if detail in seen:
                continue
            seen.add(detail)
            out.append(AuditFinding(
                target.name, RULE, NAME, detail,
                f"'{pname}' primitive traced into the step at "
                f"{eqn.source_info.name_stack} — every execution "
                "round-trips the host"))
    if art.hlo_text:
        from tools import hlo_lib

        for rec in hlo_lib.find_host_ops(art.hlo_text):
            detail = f"hlo:{rec['detail']} @ {rec['op_name']}"
            if detail in seen:
                continue
            seen.add(detail)
            out.append(AuditFinding(
                target.name, RULE, NAME, detail,
                f"compiled module contains host-boundary op "
                f"'{rec['opcode']}' ({rec['detail']}) at "
                f"{rec['op_name'] or '(no metadata)'}"))
    return out
