"""H5: per-op-name memory-traffic budgets over the optimized HLO.

The round-5 lesson (PROFILE.md): the step is memory-bound and its cost
concentrates in a few ``metadata.op_name`` bands — above all the
refinement scan body, whose per-iteration leak any regression
multiplies by the iteration count. This rule pins each documented band
to a byte budget: band traffic is summed with
``tools/hlo_lib.iter_op_traffic`` (result + operand shapes of every
instruction whose op_name contains the band's ``match``), and the
whole-step number comes from XLA's own ``Compiled.cost_analysis()``
"bytes accessed". Budgets live in ``tools/graftaudit/budgets.json``
and are SHRINK-ONLY: ``--budget-update`` only ever lowers them toward
the observed value; raising one is a hand edit that a reviewer sees.
"""

from __future__ import annotations

import math
from typing import List

from ..finding import AuditFinding
from ..spec import Artifacts, Target

RULE = "H5"
NAME = "traffic-budget-exceeded"

#: headroom --budget-update leaves above the observed value, absorbing
#: minor XLA version drift without letting a real regression hide
HEADROOM = 1.10


def observe(target: Target, art: Artifacts, budgets: dict) -> dict:
    """band name -> observed bytes for every MEASURABLE budget entry of
    ``target`` (the whole-step entry under the reserved band name
    'whole-step'; absent from the result when ``cost_analysis`` did not
    report bytes — ``check`` flags that, it must not read as 0).
    Memoized on the artifact: ``check`` and the driver's
    --budget-update sweep share one HLO scan."""
    from tools import hlo_lib

    if art.traffic_obs is not None:
        return art.traffic_obs
    entries = (budgets or {}).get("targets", {}).get(target.name, [])
    obs: dict = {}
    if entries and art.hlo_text:
        for e in entries:
            if e["band"] == "whole-step":
                if "bytes accessed" in art.cost:
                    obs[e["band"]] = int(art.cost["bytes accessed"])
            else:
                total, ops = hlo_lib.band_traffic(art.hlo_text,
                                                  e["match"])
                # a band whose match string hits NO instruction is not
                # "0 bytes, under budget" — the op_name scheme drifted
                # and the band measures nothing
                if ops:
                    obs[e["band"]] = total
    art.traffic_obs = obs
    return obs


def check(target: Target, art: Artifacts, budgets=None
          ) -> List[AuditFinding]:
    out: List[AuditFinding] = []
    observed = observe(target, art, budgets or {})
    for e in (budgets or {}).get("targets", {}).get(target.name, []):
        got = observed.get(e["band"])
        if got is None:
            # a budget that cannot be measured must fail loudly — a
            # silent 0 would pass the gate forever (and a later
            # --budget-update would shrink the ceiling toward 0)
            out.append(AuditFinding(
                target.name, RULE, "traffic-unmeasurable",
                f"band {e['band']} unmeasurable",
                f"band '{e['band']}' has a committed budget but no "
                "measurement — the target produced no optimized HLO, "
                "cost_analysis stopped reporting 'bytes accessed', or "
                f"the op_name match {e['match']!r} no longer hits any "
                "instruction (metadata drift): re-point the band or "
                "move the budget entry"))
            continue
        if got <= e["max_bytes"]:
            continue
        pct = 100.0 * got / e["max_bytes"] - 100.0
        out.append(AuditFinding(
            target.name, RULE, NAME, f"band {e['band']}",
            f"band '{e['band']}' (op_name ~ {e['match']!r}) moves "
            f"{got:,} bytes, {pct:.1f}% over its {e['max_bytes']:,}-"
            "byte budget — shrink the traffic or raise the budget by "
            "hand with a PROFILE.md-grade justification"))
    return out


def shrink(entry: dict, observed: int) -> int:
    """New max_bytes after --budget-update: never above the current
    budget, never below the observed traffic."""
    return min(entry["max_bytes"],
               max(observed, math.ceil(observed * HEADROOM)))
