"""H6: constant-folding traps — multi-MB literals embedded in HLO.

Weights captured by closure (instead of passed as arguments) get baked
into the executable as literals: every recompile re-uploads them, the
compile cache keys on their VALUES (a checkpoint swap recompiles the
world — the exact failure the serving engine's weights-as-args design
note documents), and XLA may constant-fold through them at compile
time. Any literal at or above the threshold is flagged; the detail is
the constant's shape plus its op_name attribution, so a baseline entry
survives recompiles.
"""

from __future__ import annotations

from typing import List

from ..finding import AuditFinding
from ..spec import Artifacts, Target

RULE = "H6"
NAME = "constant-folded-weights"

#: 1 MiB: an order of magnitude above any legitimate lookup table in
#: this codebase, an order of magnitude below the smallest checkpoint
DEFAULT_LIMIT = 1 << 20


def check(target: Target, art: Artifacts, budgets=None
          ) -> List[AuditFinding]:
    if not art.hlo_text:
        return []
    from tools import hlo_lib

    limit = int((budgets or {}).get("const_limit_bytes", DEFAULT_LIMIT))
    out: List[AuditFinding] = []
    seen = set()
    for rec in hlo_lib.find_large_constants(art.hlo_text, limit):
        detail = f"{rec['shape']} @ {rec['op_name'] or '(no metadata)'}"
        if detail in seen:   # same literal re-materialized per module
            continue
        seen.add(detail)
        out.append(AuditFinding(
            target.name, RULE, NAME, detail,
            f"{rec['bytes']:,}-byte literal {rec['shape']} baked into "
            "the executable — a closure-captured array that should be "
            "an argument (weights-as-args keeps executables KB-sized "
            "and checkpoint swaps recompile-free)"))
    return out
