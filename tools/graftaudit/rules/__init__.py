"""Rule registry: each module exposes RULE, NAME, and
check(target, artifacts, budgets)."""

from __future__ import annotations

from . import (const_fold, donation, dtype_widen, host_transfer,
               recompile, traffic)

ALL_RULES = (host_transfer, dtype_widen, recompile, donation, traffic,
             const_fold)

RULE_IDS = {mod.RULE for mod in ALL_RULES}
