"""H3: recompile audit — compile-cache count over a canary sweep.

graftlint's R3 guesses retrace hazards from source shape (`jax.jit` in
a loop, unhashable statics); this rule *measures*: a canary target runs
the documented shape/batch sweep against the real routing code (the
serving engine's bucket router, a jitted step fed the loader's wire
dtypes) and asserts the executable count lands exactly on the
documented bucket count. Catches both directions — a ragged tail
compiling per distinct batch (the PR-2 serving regression this
mechanizes) and a doc that promises more buckets than the router
builds.
"""

from __future__ import annotations

from typing import List

from ..finding import AuditFinding
from ..spec import Artifacts, Target

RULE = "H3"
NAME = "compile-cache-budget"


def check(target: Target, art: Artifacts, budgets=None
          ) -> List[AuditFinding]:
    if target.kind != "canary" or art.canary is None:
        return []
    observed = art.canary.observed_compiles
    documented = target.expect_compiles
    if documented is None or observed == documented:
        return []
    return [AuditFinding(
        target.name, RULE, NAME,
        f"compiles {observed} != documented {documented}",
        f"canary sweep ({art.canary.detail}) produced {observed} "
        f"executable(s); the documented bucket count is {documented} — "
        + ("a shape leak is compiling per request"
           if observed > documented else
           "the documented bucketing overstates the router"))]
