"""H4: donation honored end-to-end.

graftlint's R4 checks that state-threading jits *declare*
``donate_argnums``; XLA is still free to decline — a donated buffer
whose dtype/shape matches no output, or one the compiler copies anyway,
silently doubles peak HBM for that arg with no warning at the call
site. Ground truth is the optimized module's ``input_output_alias``
map: every flat argument jax marked donatable in the lowered StableHLO
(``tf.aliasing_output`` when jax found the match itself,
``jax.buffer_donor`` when it deferred to XLA) must appear as an aliased
parameter, or the donation was declined.
"""

from __future__ import annotations

import re
from typing import List

from ..finding import AuditFinding
from ..spec import Artifacts, Target

RULE = "H4"
NAME = "donation-declined"

_ARG_RE = re.compile(r"%arg(\d+): tensor<[^>]*>\s*(\{[^{}]*\})?")


def declared_donations(lowered_text: str) -> List[int]:
    """Flat arg indices the lowered module marks as donated."""
    try:
        sig = lowered_text[lowered_text.index("@main("):]
        sig = sig[:sig.index(" -> ")]
    except ValueError:
        return []
    return [int(i) for i, attrs in _ARG_RE.findall(sig)
            if attrs and ("tf.aliasing_output" in attrs
                          or "jax.buffer_donor" in attrs)]


def check(target: Target, art: Artifacts, budgets=None
          ) -> List[AuditFinding]:
    if not (target.donate_argnums and art.lowered_text and art.hlo_text):
        return []
    from tools import hlo_lib

    declared = declared_donations(art.lowered_text)
    out: List[AuditFinding] = []
    if not declared:
        # the jit declares donate_argnums but jax dropped every leaf at
        # lowering (nothing matched) — donation is silently OFF
        out.append(AuditFinding(
            target.name, RULE, NAME, "no donatable args survived lowering",
            f"donate_argnums={target.donate_argnums} declared but the "
            "lowered module carries no tf.aliasing_output/"
            "jax.buffer_donor attribute — jax found no output to reuse "
            "any donated buffer for"))
        return out
    aliased = hlo_lib.parse_aliased_params(art.hlo_text)
    shapes = hlo_lib.parse_entry_param_shapes(art.hlo_text)
    for ix in declared:
        if ix in aliased:
            continue
        shape = shapes[ix] if ix < len(shapes) else "?"
        out.append(AuditFinding(
            target.name, RULE, NAME, f"param {ix} ({shape})",
            f"arg {ix} ({shape}) was donated but the optimized module's "
            "input_output_alias map does not cover it — XLA declined "
            "the donation and this buffer is copied every step"))
    return out
