"""graftaudit driver: build targets, run rules, baseline + budgets.

Usage (from the repo root; this exact bare invocation is the tier-1
gate, ``tests/test_graftaudit.py``)::

    python -m tools.graftaudit --json

Exit codes mirror graftlint: 0 clean (modulo baseline), 1 new findings
or stale baseline entries, 2 usage error. The baseline
(``tools/graftaudit/baseline.json``) and the H5 budgets
(``tools/graftaudit/budgets.json``) are both SHRINK-ONLY:
``--write-baseline`` regenerates the grandfather file after a fix (a
stale entry fails the gate exactly like graftlint's), and
``--budget-update`` only ever lowers a band's byte ceiling toward the
observed traffic — raising either is a hand edit a reviewer sees.

Suppression: findings with no source line can't carry pragmas, so the
pragma analog is a :class:`~tools.graftaudit.spec.Waiver` on the target
declaration — rule id + detail substring + REQUIRED justification
(``tools/graftaudit/targets.py``).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .finding import AuditFinding
from .spec import Target

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(_HERE, "baseline.json")
DEFAULT_BUDGETS = os.path.join(_HERE, "budgets.json")


# -- audit ----------------------------------------------------------------

def audit_targets(targets: Sequence[Target], rules=None,
                  budgets: Optional[dict] = None,
                  ) -> Tuple[List[AuditFinding], Dict[str, Dict[str, int]],
                             Dict[str, float]]:
    """Run ``rules`` over ``targets``.

    Returns ``(findings, observed, seconds)`` where ``observed`` maps
    target -> band -> measured bytes (for --budget-update) and
    ``seconds`` maps target -> artifact build wall time. Waivers are
    applied here — a waived finding never reaches the baseline logic,
    same as a pragma'd graftlint finding.
    """
    from .artifacts import build_artifacts
    from .rules import ALL_RULES
    from .rules import traffic as traffic_rule

    rules = ALL_RULES if rules is None else rules
    budgets = budgets or {}
    findings: List[AuditFinding] = []
    observed: Dict[str, Dict[str, int]] = {}
    seconds: Dict[str, float] = {}
    for target in targets:
        art = build_artifacts(target)
        seconds[target.name] = art.seconds
        for mod in rules:
            for f in mod.check(target, art, budgets):
                if not target.waived(f.rule, f.detail):
                    findings.append(f)
        obs = traffic_rule.observe(target, art, budgets)
        if obs:
            observed[target.name] = obs
    return findings, observed, seconds


def load_fixture_targets(path: str
                         ) -> Tuple[List[Target], Optional[dict]]:
    """(TARGETS, BUDGETS-or-None) from a fixture module file
    (tests/graftaudit_fixtures) — fixtures planting H5 violations ship
    their own tiny budgets dict."""
    name = "graftaudit_fixture_" + \
        os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise OSError(f"cannot import fixture module {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return list(mod.TARGETS), getattr(mod, "BUDGETS", None)


# -- baseline (same shrink-only semantics as graftlint's) -----------------

def load_baseline(path: str) -> Counter:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Counter((e["target"], e["rule"], e["detail"])
                   for e in data.get("findings", []))


def write_baseline(path: str, findings: Iterable[AuditFinding]) -> None:
    entries = [{"target": k[0], "rule": k[1], "detail": k[2]}
               for k in sorted(f.key() for f in findings)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "comment": "graftaudit grandfathered findings — burn down, "
                       "never grow; regenerate with --write-baseline "
                       "after fixing one",
            "findings": entries,
        }, f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: List[AuditFinding], baseline: Counter,
                   audited_targets: Optional[Iterable[str]] = None,
                   ) -> Tuple[List[AuditFinding],
                              List[Tuple[str, str, str]]]:
    """(new findings, stale keys). An unconsumed entry whose target WAS
    audited is stale and fails the run — it would silently grandfather
    the next reintroduction; an entry for a target outside this run
    (--targets subset) is merely unchecked."""
    remaining = Counter(baseline)
    new: List[AuditFinding] = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    if audited_targets is not None:
        audited = set(audited_targets)
        checked = (lambda k: k[0] in audited)
    else:
        checked = (lambda k: True)
    stale = sorted(k for k, n in remaining.items() if checked(k)
                   for _ in range(n))
    return new, stale


# -- budgets --------------------------------------------------------------

def load_budgets(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def shrink_budgets(budgets: dict,
                   observed: Dict[str, Dict[str, int]]) -> dict:
    """New budgets dict with every measured band lowered toward its
    observed traffic (never raised — shrink-only by construction)."""
    from .rules import traffic as traffic_rule

    out = json.loads(json.dumps(budgets))   # deep copy
    for tname, entries in out.get("targets", {}).items():
        for e in entries:
            got = observed.get(tname, {}).get(e["band"])
            if got is not None:
                e["max_bytes"] = traffic_rule.shrink(e, got)
                e["observed_bytes"] = got
    return out


def write_budgets(path: str, budgets: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budgets, f, indent=2, sort_keys=True)
        f.write("\n")


# -- CLI ------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftaudit",
        description="Compiled-artifact invariant checker (rules H1-H6 "
                    "over the traced jaxpr + optimized HLO of the real "
                    "train/serving programs; see "
                    "tools/graftaudit/rules/).")
    p.add_argument("--baseline", metavar="JSON", default=DEFAULT_BASELINE,
                   help="grandfather file (default: the committed "
                        "tools/graftaudit/baseline.json)")
    p.add_argument("--budgets", metavar="JSON", default=DEFAULT_BUDGETS,
                   help="H5 traffic budgets (default: the committed "
                        "tools/graftaudit/budgets.json)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (list of findings)")
    p.add_argument("--write-baseline", metavar="JSON",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--budget-update", action="store_true",
                   help="rewrite --budgets in place with every "
                        "measured band lowered toward its observed "
                        "traffic (shrink-only; never raises)")
    p.add_argument("--targets", metavar="T1,T2",
                   help="audit only these targets")
    p.add_argument("--rules", metavar="H1,H2,...",
                   help="run only these rule ids")
    p.add_argument("--fixture", metavar="PY",
                   help="audit the TARGETS of this fixture module "
                        "instead of the repo registry (no default "
                        "baseline/budgets)")
    args = p.parse_args(argv)

    rules = None
    if args.rules:
        from .rules import ALL_RULES
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [m for m in ALL_RULES if m.RULE in want]
        unknown = want - {m.RULE for m in rules}
        if unknown:
            print(f"graftaudit: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    if args.write_baseline and (args.rules or args.targets):
        # a filtered regenerate would drop every other rule's/target's
        # grandfathered entries and fail the next full gate run
        print("graftaudit: refusing --write-baseline with --rules/"
              "--targets — regenerate from a full run",
              file=sys.stderr)
        return 2

    fixture_budgets = None
    if args.fixture:
        try:
            targets, fixture_budgets = load_fixture_targets(args.fixture)
        # exec_module can raise anything (ImportError, NameError, a jax
        # error at module scope) — all of it is "unloadable fixture",
        # exit 2, never a raw traceback (graftlint R6 discipline)
        except Exception as exc:  # noqa: BLE001
            print(f"graftaudit: unloadable fixture {args.fixture}: "
                  f"{exc}", file=sys.stderr)
            return 2
        # fixtures run bare: the committed baseline/budgets describe
        # the REPO's targets, not a fixture's
        if args.baseline == DEFAULT_BASELINE:
            args.baseline = None
        if args.budgets == DEFAULT_BUDGETS:
            args.budgets = None
    else:
        from .targets import build_targets
        targets = build_targets()
    if args.targets:
        want_t = {t.strip() for t in args.targets.split(",")}
        unknown_t = want_t - {t.name for t in targets}
        if unknown_t:
            print(f"graftaudit: unknown target(s): {sorted(unknown_t)}",
                  file=sys.stderr)
            return 2
        targets = [t for t in targets if t.name in want_t]

    budgets: dict = fixture_budgets or {}
    if args.budgets:
        try:
            budgets = load_budgets(args.budgets)
        except (OSError, ValueError) as exc:
            print(f"graftaudit: unreadable budgets {args.budgets}: "
                  f"{exc}", file=sys.stderr)
            return 2

    findings, observed, seconds = audit_targets(targets, rules=rules,
                                                budgets=budgets)
    for tname, dt in seconds.items():
        print(f"graftaudit: {tname} audited in {dt:.1f}s",
              file=sys.stderr)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"graftaudit: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}", file=sys.stderr)
        return 0

    if args.budget_update:
        if not args.budgets:
            print("graftaudit: --budget-update needs --budgets",
                  file=sys.stderr)
            return 2
        write_budgets(args.budgets, shrink_budgets(budgets, observed))
        print(f"graftaudit: budgets re-anchored (shrink-only) in "
              f"{args.budgets}", file=sys.stderr)
        # findings still gate below: --budget-update cannot bless a
        # regression, it only tightens ceilings after an improvement

    stale: List[Tuple[str, str, str]] = []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as exc:
            print(f"graftaudit: unreadable baseline "
                  f"{args.baseline}: {exc}", file=sys.stderr)
            return 2
        if rules is not None:
            active = {m.RULE for m in rules}
            baseline = Counter({k: v for k, v in baseline.items()
                                if k[1] in active})
        findings, stale = apply_baseline(
            findings, baseline,
            audited_targets=[t.name for t in targets])

    if args.as_json:
        print(json.dumps([{
            "target": f.target, "rule": f.rule, "name": f.name,
            "detail": f.detail, "message": f.message,
        } for f in findings] + [{
            "target": k[0], "rule": "B0", "name": "stale-baseline",
            "detail": k[2],
            "message": f"stale baseline entry for {k[1]}: {k[2]!r} — "
                       "regenerate with --write-baseline",
        } for k in stale], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"graftaudit: {len(findings)} new finding(s)",
                  file=sys.stderr)
    if stale:
        for k in stale:
            print(f"graftaudit: stale baseline entry {k[0]} [{k[1]}] "
                  f"{k[2]!r}", file=sys.stderr)
        print(f"graftaudit: {len(stale)} stale baseline entr(y/ies) — "
              "the finding was fixed (good!) but the entry must go: "
              "regenerate with --write-baseline so it cannot "
              "grandfather a future reintroduction", file=sys.stderr)
    return 1 if (findings or stale) else 0
