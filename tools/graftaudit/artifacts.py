"""Build audit artifacts: trace, lower, and compile targets on CPU.

The audit runs the REAL programs (the train step, the serving path) at
tiny shapes on the CPU backend — jaxpr and optimized-HLO structure is
what the rules check, and that structure (callbacks, dtype of dots,
donation aliasing, constants) is decided at trace/lower time, not by
the execution platform. The one platform-dependent artifact is the H5
traffic estimate; its budgets file records which platform anchored it.
"""

from __future__ import annotations

import time

from .spec import Artifacts, CanaryResult, Target


def ensure_cpu():
    """Force the CPU backend exactly the way tests/conftest.py does:
    the image's sitecustomize registers the 'axon' remote-TPU plugin in
    every interpreter and jax would initialize it even under
    JAX_PLATFORMS=cpu — an audit must never dial (or block on) the
    tunnel. Safe to call when jax is already imported/configured."""
    import os
    import sys

    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")
    return jax


def build_artifacts(target: Target) -> Artifacts:
    """Trace/lower/compile one target and bundle what the rules need."""
    jax = ensure_cpu()
    t0 = time.perf_counter()
    art = Artifacts()
    if target.kind == "canary":
        result = target.build()
        if not isinstance(result, CanaryResult):
            raise TypeError(
                f"canary target {target.name}: build() must return a "
                f"CanaryResult, got {type(result).__name__}")
        art.canary = result
        art.hlo_text = "\n".join(result.hlo_texts)
    elif target.kind == "trace":
        fn, args = target.build()
        art.jaxpr = jax.make_jaxpr(fn)(*args)
        jitted = jax.jit(fn, donate_argnums=target.donate_argnums)
        lowered = jitted.lower(*args)
        art.lowered_text = lowered.as_text()
        if target.compiled:
            compiled = lowered.compile()
            art.hlo_text = compiled.as_text()
            cost = compiled.cost_analysis()
            # jaxlib has returned both a bare dict and a 1-elem list of
            # dicts across versions
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            art.cost = dict(cost or {})
    else:
        raise ValueError(f"target {target.name}: unknown kind "
                         f"{target.kind!r} (trace|canary)")
    art.seconds = time.perf_counter() - t0
    return art


def iter_subjaxprs(jaxpr):
    """Yield every eqn of ``jaxpr`` and, recursively, of every jaxpr
    buried in eqn params (pjit bodies, scan/while bodies, custom_vjp
    branches, remat) — duck-typed so rule modules stay jax-agnostic."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for j in (v if isinstance(v, (list, tuple)) else [v]):
                inner = None
                if hasattr(j, "eqns"):
                    inner = j
                elif hasattr(j, "jaxpr") and hasattr(j.jaxpr, "eqns"):
                    inner = j.jaxpr
                if inner is not None:
                    yield from iter_subjaxprs(inner)
