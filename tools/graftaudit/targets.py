"""The repo's real audit targets: train step, serving path, engine.

Shapes are deliberately tiny (32x32, batch 1, 2 refinement iterations)
— every invariant the rules check (callbacks traced in, dtype of dots,
donation aliasing, constants, op_name band structure) is decided by
program STRUCTURE, which is shape-independent; tiny shapes just make
the CPU trace/compile fit the tier-1 budget. The one scale-sensitive
artifact, H5's byte numbers, is pinned at exactly these shapes by
``budgets.json`` (platform/shape recorded there).
"""

from __future__ import annotations

from typing import List

from .artifacts import ensure_cpu
from .spec import CanaryResult, Target, Waiver

_IMAGE_HW = (32, 32)
_ITERS = 2

#: the fp32 correlation island: the all-pairs GEMM runs fp32 by design
#: (reference parity, core/raft.py:102-103 analog — see the
#: RAFTConfig.corr_dtype comment), and its jvp/transpose replicas ride
#: the same einsum path. Everything else in a bf16 step must be bf16.
_CORR_ISLAND = Waiver(
    "H2", "bxc,byc->bxy",
    "all-pairs correlation GEMM is an intentional fp32 island "
    "(reference parity; RAFTConfig.corr_dtype docs) — the volume "
    "STORAGE dtype is corr_dtype's knob, the GEMM itself stays fp32")


def _train_batch_avals(jax, batch_size=1):
    import jax.numpy as jnp

    h, w = _IMAGE_HW
    return {
        # uint8 images/valid: the loader's documented low-bandwidth wire
        # format (data/loader._collate), the dtype the real loop feeds
        "image1": jax.ShapeDtypeStruct((batch_size, h, w, 3), jnp.uint8),
        "image2": jax.ShapeDtypeStruct((batch_size, h, w, 3), jnp.uint8),
        "flow": jax.ShapeDtypeStruct((batch_size, h, w, 2), jnp.float32),
        "valid": jax.ShapeDtypeStruct((batch_size, h, w), jnp.uint8),
    }


def _build_train_step(model_kwargs):
    def build():
        jax = ensure_cpu()
        from raft_tpu.config import RAFTConfig, TrainConfig
        from raft_tpu.training.train_step import (create_train_state,
                                                  make_train_step)

        cfg = RAFTConfig(**model_kwargs)
        tc = TrainConfig(iters=_ITERS, batch_size=1,
                         image_size=_IMAGE_HW)
        rng = jax.random.PRNGKey(0)
        # avals only — the audit lowers/compiles against shapes, it
        # never runs the step, so the real (slow) init is skipped
        state = jax.eval_shape(
            lambda: create_train_state(cfg, tc, rng,
                                       image_hw=_IMAGE_HW))
        return (make_train_step(cfg, tc),
                (state, _train_batch_avals(jax), rng))
    return build


def _build_serve():
    def build():
        jax = ensure_cpu()
        import jax.numpy as jnp
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        cfg = RAFTConfig()
        model = RAFT(cfg)
        h, w = _IMAGE_HW
        img = jax.ShapeDtypeStruct((1, h, w, 3), jnp.float32)
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3)),
                               jnp.zeros((1, h, w, 3)), iters=1))

        def serve(variables, image1, image2):
            # the RAFTEngine serving fn shape: weights as an ARGUMENT
            # (serving/engine.py design note) — H6 holds it to that
            _, flow_up = model.apply(variables, image1, image2,
                                     iters=_ITERS, test_mode=True)
            return flow_up

        return serve, (variables, img, img)
    return build


def _build_serve_u8():
    def build():
        jax = ensure_cpu()
        import jax.numpy as jnp
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        cfg = RAFTConfig()
        model = RAFT(cfg)
        h, w = _IMAGE_HW
        # the u8-wire recipe (RAFTEngine(wire="u8", warm_start=True)):
        # uint8 frame params — the 2*(x/255)-1 normalize's
        # astype(float32) is then IN the program, so the wire stays
        # uint8 until the on-device widen (the H2-ish discipline the
        # dedicated test pins on the param dtypes) — plus the 1/8-res
        # flow_init warm start, donated to its same-shaped flow_low
        # output (H4 verifies XLA honors the alias)
        img = jax.ShapeDtypeStruct((1, h, w, 3), jnp.uint8)
        finit = jax.ShapeDtypeStruct((1, h // 8, w // 8, 2),
                                     jnp.float32)
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3)),
                               jnp.zeros((1, h, w, 3)), iters=1))

        def serve(variables, image1, image2, flow_init):
            flow_low, flow_up = model.apply(
                variables, image1, image2, iters=_ITERS,
                flow_init=flow_init, test_mode=True)
            return flow_low, flow_up

        return serve, (variables, img, img, finit)
    return build


def _build_serve_cached():
    def build():
        jax = ensure_cpu()
        import jax.numpy as jnp
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        cfg = RAFTConfig()
        model = RAFT(cfg)
        h, w = _IMAGE_HW
        lh, lw = h // 8, w // 8
        # the cross-frame cached serving recipe
        # (RAFTEngine(feature_cache=True)): ONE frame of pixels plus
        # the previous dispatch's device-resident features; all three
        # cache inputs donated to their same-shaped cache outputs
        # (fmap1->fmap2, cnet1->cnet2, flow_init->flow_low) — H4
        # verifies XLA honors all three aliases
        img = jax.ShapeDtypeStruct((1, h, w, 3), jnp.float32)
        fmap = jax.ShapeDtypeStruct((1, lh, lw, cfg.fnet_dim),
                                    jnp.float32)
        ctx = jax.ShapeDtypeStruct((1, lh, lw, cfg.cnet_dim),
                                   jnp.float32)
        finit = jax.ShapeDtypeStruct((1, lh, lw, 2), jnp.float32)
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3)),
                               jnp.zeros((1, h, w, 3)), iters=1))

        def serve_cached(variables, image2, fmap1, cnet1, flow_init):
            return model.apply(variables, image2, fmap1, cnet1,
                               flow_init, iters=_ITERS,
                               method="forward_cached")

        return serve_cached, (variables, img, fmap, ctx, finit)
    return build


def _build_serve_ragged():
    def build():
        jax = ensure_cpu()
        import jax.numpy as jnp
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        cfg = RAFTConfig()
        model = RAFT(cfg)
        h, w = _IMAGE_HW
        # the ragged serving recipe (RAFTEngine(ragged=True,
        # warm_start=True, wire="u8")): uint8 frames at the capacity
        # box, the per-row validity descriptor as TRACED (B,) i32
        # arguments (any shape mix is data, never a new program), and
        # the warm-start flow_init donated to its same-shaped flow_low
        # output exactly like the plain u8 warm engine — H4 verifies
        # XLA honors the alias through the masked graph
        img = jax.ShapeDtypeStruct((1, h, w, 3), jnp.uint8)
        vspec = jax.ShapeDtypeStruct((1,), jnp.int32)
        finit = jax.ShapeDtypeStruct((1, h // 8, w // 8, 2),
                                     jnp.float32)
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3)),
                               jnp.zeros((1, h, w, 3)), iters=1))

        def serve_ragged(variables, image1, image2, valid_h8, valid_w8,
                         flow_init):
            return model.apply(variables, image1, image2, valid_h8,
                               valid_w8, flow_init, iters=_ITERS,
                               method="forward_ragged")

        return serve_ragged, (variables, img, img, vspec, vspec, finit)
    return build


# -- engine canaries ------------------------------------------------------

_ENGINE_WEIGHTS = []   # [(variables, cfg)] — one real init, both canaries


def _engine_weights():
    jax = ensure_cpu()
    import jax.numpy as jnp
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT

    if not _ENGINE_WEIGHTS:
        # the small model: canaries exercise the ENGINE's routing, not
        # the model, and the small net's real init/compile is ~4x
        # cheaper on CPU
        cfg = RAFTConfig(small=True)
        model = RAFT(cfg)
        h, w = _IMAGE_HW
        img = jnp.zeros((1, h, w, 3))
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
        _ENGINE_WEIGHTS.append((variables, cfg))
    return _ENGINE_WEIGHTS[0]


def _build_engine_exact_ragged():
    def build():
        ensure_cpu()
        import numpy as np
        from raft_tpu.serving.engine import RAFTEngine

        variables, cfg = _engine_weights()
        eng = RAFTEngine(variables, cfg, iters=_ITERS,
                         exact_shapes=True)
        h, w = _IMAGE_HW
        # 6 frames / batch_size 2 -> full chunks of 2 plus a ragged
        # tail of 1: exact-shapes mode must batch-fill the tail into
        # the already-compiled bucket (the PR-2 serving fix), not
        # compile per distinct tail batch
        frames = [np.zeros((h, w, 3), np.float32) for _ in range(6)]
        eng.infer(frames, batch_size=2)
        texts = tuple(exe.as_text()
                      for exe in eng._compiled.values() if exe)
        return CanaryResult(
            observed_compiles=len(eng._compiled),
            detail=f"exact_shapes engine, 5 pairs at {h}x{w} in "
                   "batches of 2 (ragged tail 1)",
            hlo_texts=texts)
    return build


def _build_engine_bucketed():
    def build():
        ensure_cpu()
        import numpy as np
        from raft_tpu.serving.engine import RAFTEngine

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        eng = RAFTEngine(variables, cfg, iters=_ITERS,
                         envelope=[(2, h, w)], precompile=True)
        # in-envelope requests (smaller batch AND smaller spatial) must
        # route into the precompiled bucket, padding up — never compile
        eng.infer_batch(np.zeros((1, h - 8, w - 8, 3), np.float32),
                        np.zeros((1, h - 8, w - 8, 3), np.float32))
        eng.infer_batch(np.zeros((2, h, w, 3), np.float32),
                        np.zeros((2, h, w, 3), np.float32))
        texts = tuple(exe.as_text()
                      for exe in eng._compiled.values() if exe)
        return CanaryResult(
            observed_compiles=len(eng._compiled),
            detail=f"bucketed engine, envelope [(2,{h},{w})], "
                   "in-envelope requests at two geometries",
            hlo_texts=texts)
    return build


def _build_engine_u8_wire():
    def build():
        ensure_cpu()
        import numpy as np
        from raft_tpu.serving.engine import RAFTEngine

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        eng = RAFTEngine(variables, cfg, iters=_ITERS,
                         envelope=[(2, h, w)], precompile=True,
                         warm_start=True, wire="u8")
        rng = np.random.RandomState(0)
        frames = rng.randint(0, 256, (2, h, w, 3)).astype(np.uint8)
        frames2 = rng.randint(0, 256, (2, h, w, 3)).astype(np.uint8)
        flow, low = eng.infer_batch(frames, frames2, return_low=True)
        warm = eng.infer_batch(frames, frames2, flow_init=low)
        assert len(eng._compiled) == 1, "u8 wire leaked a bucket"
        texts = tuple(exe.as_text()
                      for exe in eng._compiled.values() if exe)
        # the wire-stays-uint8 invariant, at the artifact: the
        # executable's entry layout must take u8 frame params — a
        # host-side widening would surface here as f32[...,3] params
        # (and 4x the H2D bytes the budgets pin)
        assert "u8[2,32,32,3]" in texts[0], \
            "u8-wire executable does not take uint8 frame params"
        # bitwise parity vs the fp32 wire at integer-valued inputs:
        # uint8->f32 conversion is exact, so the on-device normalize
        # sees identical values
        ref = RAFTEngine(variables, cfg, iters=_ITERS,
                         envelope=[(2, h, w)], precompile=True,
                         warm_start=True)
        rflow, rlow = ref.infer_batch(frames.astype(np.float32),
                                      frames2.astype(np.float32),
                                      return_low=True)
        assert np.array_equal(flow, rflow) and np.array_equal(low, rlow), \
            "u8 wire is not bitwise the f32 path at integer inputs"
        rwarm = ref.infer_batch(frames.astype(np.float32),
                                frames2.astype(np.float32),
                                flow_init=rlow)
        assert np.array_equal(warm, rwarm), \
            "u8 warm start diverged from the f32 path"
        return CanaryResult(
            observed_compiles=len(eng._compiled),
            detail=f"u8-wire warm-start engine at {h}x{w}: uint8 "
                   "params pinned in the executable, bitwise parity "
                   "vs the f32 wire, warm round-trip",
            hlo_texts=texts)
    return build


def _build_engine_feature_cache():
    def build():
        ensure_cpu()
        import numpy as np
        from raft_tpu.serving.engine import RAFTEngine
        from raft_tpu.serving.scheduler import MicroBatchScheduler
        from raft_tpu.serving.session import VideoSession

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        # video-only deployment: the engine compiles NOTHING up front
        # (no envelope) — the canary pins that a stream's whole
        # lifecycle (cold prime -> warm pairs -> LRU-evicted ->
        # re-primed -> warm again) runs through ONE cached executable
        # per spatial shape, with no per-state compile forks and no
        # stray plain-signature compiles
        eng = RAFTEngine(variables, cfg, iters=_ITERS, warm_start=True,
                         feature_cache=True)
        rng = np.random.RandomState(0)
        with MicroBatchScheduler(eng, max_batch=2, gather_window_s=0.0,
                                 feature_cache=True,
                                 feature_cache_capacity=1) as sched:
            sess = VideoSession(sched, feature_cache=True)

            def frame():
                return rng.randint(0, 256, (h, w, 3)).astype(np.float32)

            futs = [sess.submit_frame(frame()) for _ in range(4)]
            for f in futs:
                if f is not None:
                    f.result(timeout=600)
            # force an eviction: a second stream takes the capacity-1
            # pool slot, then the first stream's next pair misses and
            # cold-restarts (re-prime + pair) — same executable
            other = VideoSession(sched, feature_cache=True)
            for _ in range(3):
                f = other.submit_frame(frame())
                if f is not None:
                    f.result(timeout=600)
            evicted_before = sched._fcache.snapshot()["evictions"]
            assert evicted_before > 0, \
                "capacity-1 pool with two streams did not evict"
            f = sess.submit_frame(frame())     # miss -> re-prime -> pair
            assert f is not None and f.result(timeout=600).flow is not None
            assert len(eng._compiled) == 0, \
                "video-only traffic compiled a plain-signature bucket"
            assert len(eng._compiled_cached) == 1, \
                "cold->warm->evicted->warm forked cached executables"
        texts = tuple(exe.as_text()
                      for exe in eng._compiled_cached.values() if exe)
        return CanaryResult(
            observed_compiles=eng.executable_count(),
            detail=f"feature-cache pool at {h}x{w}, capacity 1, two "
                   "streams: cold->warm->evicted->re-primed->warm all "
                   "through ONE cached executable (no per-state "
                   "compile forks, no plain-signature strays)",
            hlo_texts=texts)
    return build


def _build_engine_ragged():
    def build():
        ensure_cpu()
        import numpy as np
        from raft_tpu.serving.engine import RAFTEngine
        from raft_tpu.serving.scheduler import MicroBatchScheduler

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        # the ROADMAP's stated gate: a >=3-distinct-shape canary sweep
        # through ONE ragged executable (the bucketed path compiles
        # one per shape — pinned by the exact-shapes oracle below)
        # class batch 1: the bitwise pin below runs the feature net at
        # total batch 2 on BOTH sides (XLA CPU conv bits move with
        # total batch past the vectorization width — the established
        # bucket-batch-1 parity geometry); cross-shape batch
        # coalescing is pinned at batch 2+ in tests/test_ragged.py
        shapes = [(h, w), (h - 8, w), (h, w - 8)]
        eng = RAFTEngine(variables, cfg, iters=_ITERS, ragged=True,
                         capacity_classes=[(1, h, w)], precompile=True,
                         warm_start=True)
        rng = np.random.RandomState(0)

        def pair(hh, ww):
            return (rng.randint(0, 256, (hh, ww, 3)).astype(np.float32),
                    rng.randint(0, 256, (hh, ww, 3)).astype(np.float32))

        with MicroBatchScheduler(eng, max_batch=1,
                                 gather_window_s=0.0,
                                 ragged=True) as sched:
            sweep = shapes + shapes[:1]
            futs = [sched.submit(*pair(hh, ww)) for hh, ww in sweep]
            flows = [f.result(timeout=600).flow for f in futs]
            rec = sched.metrics.snapshot(
                executables=eng.executable_count())
        assert eng.executable_count() == 1, \
            f"mixed-shape sweep forked ragged executables: " \
            f"{eng.ragged_classes()}"
        assert rec["ragged"]["dispatches"] > 0, \
            "no dispatch rode the ragged path"
        # the bucketed oracle: the SAME sweep on the per-shape path
        # compiles one executable per shape (what the ragged table
        # collapses to 1), and at bucket-batch-1 integer inputs every
        # swept shape's flow is oracle-pinned against it — the
        # full-extent shape BITWISE against the shared class here
        # (identity mask); every shape bitwise at its own per-shape
        # class in tests/test_ragged.py (same-geometry oracle).
        oracle = RAFTEngine(variables, cfg, iters=_ITERS,
                            exact_shapes=True, warm_start=True)
        rng = np.random.RandomState(0)   # replay the sweep's pairs
        for (hh, ww), flow in zip(sweep, flows):
            i1, i2 = pair(hh, ww)
            ref = oracle.infer_batch(i1[None], i2[None])[0]
            if (hh, ww) == (h, w):
                assert np.array_equal(flow, ref), \
                    "full-extent ragged row is not bitwise the " \
                    "bucketed path"
        assert len(oracle._compiled) == len(shapes), \
            "oracle did not compile one bucket per shape"
        texts = tuple(exe.as_text()
                      for exe in eng._compiled_ragged.values() if exe)
        return CanaryResult(
            observed_compiles=eng.executable_count(),
            detail=f"ragged engine at capacity (1,{h},{w}): "
                   f"{len(shapes)}-distinct-shape sweep through ONE "
                   "executable (bucketed oracle: one per shape), "
                   "full-extent row bitwise vs the oracle",
            hlo_texts=texts)
    return build


def _build_scheduler_coalesce():
    def build():
        ensure_cpu()
        import random
        import threading
        import time

        import numpy as np

        from raft_tpu.serving.engine import RAFTEngine
        from raft_tpu.serving.resilience import (CircuitOpen,
                                                 DispatchWedged)
        from raft_tpu.serving.scheduler import MicroBatchScheduler
        from raft_tpu.testing import faults

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        # warm-start engine (flow_init input, flow_low output): the
        # serving front-end's deployed configuration — its bucket
        # executable is a DIFFERENT program than the plain canaries'
        eng = RAFTEngine(variables, cfg, iters=_ITERS,
                         envelope=[(2, h, w)], precompile=True,
                         warm_start=True)
        results = []
        # resilience knobs armed: the second leg below wedges a
        # dispatch and the H3 count must hold THROUGH drop + recompile
        # backoff is sized ABOVE the recompile (~10s CPU): the probe
        # that recompiles will itself wedge on the 0.5s watchdog, but
        # its quarantined thread's compile still lands (first-insert-
        # wins) — a long backoff means the next probe finds it ready
        # instead of churning a compile storm
        with MicroBatchScheduler(eng, max_batch=2,
                                 gather_window_s=0.05,
                                 dispatch_timeout_s=0.5,
                                 breaker_failures=1,
                                 breaker_backoff_s=8.0,
                                 breaker_backoff_max_s=12.0,
                                 breaker_rng=random.Random(0)) as sched:
            def caller(seed):
                rng = np.random.RandomState(seed)
                futs = [sched.submit(
                    rng.rand(h, w, 3).astype(np.float32) * 255,
                    rng.rand(h, w, 3).astype(np.float32) * 255)
                    for _ in range(3)]
                results.extend(f.result(timeout=600) for f in futs)

            threads = [threading.Thread(target=caller, args=(s,))
                       for s in (0, 1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == 6, "scheduler dropped requests"
            # resilience leg: wedge one dispatch — the verdict drops
            # the suspect bucket executable; the breaker's half-open
            # probe must lazily RECOMPILE it, landing back at the
            # documented count (no leaked duplicate buckets after
            # recovery — the H3 invariant through the recovery path)
            faults.arm([{"site": "serve.request", "kind": "hang",
                         "hang_s": 2.0}])
            try:
                rng = np.random.RandomState(99)
                doomed = sched.submit(
                    rng.rand(h, w, 3).astype(np.float32) * 255,
                    rng.rand(h, w, 3).astype(np.float32) * 255)
                try:
                    doomed.result(timeout=60)
                    raise AssertionError("hung dispatch did not wedge")
                except DispatchWedged:
                    pass
                assert (2, h, w) not in eng._compiled, \
                    "wedge verdict did not drop the suspect bucket"
                recovered = None
                t_end = time.monotonic() + 120
                while recovered is None and time.monotonic() < t_end:
                    try:
                        recovered = sched.submit(
                            rng.rand(h, w, 3).astype(np.float32) * 255,
                            rng.rand(h, w, 3).astype(np.float32) * 255
                        ).result(timeout=120)
                    except (CircuitOpen, DispatchWedged):
                        time.sleep(0.05)
                assert recovered is not None, "no recovery after wedge"
            finally:
                faults.disarm()
        texts = tuple(exe.as_text()
                      for exe in eng._compiled.values() if exe)
        return CanaryResult(
            observed_compiles=len(eng._compiled),
            detail=f"micro-batch scheduler, 2 submitters x 3 requests "
                   f"at {h}x{w} (ragged vs the (2,{h},{w}) bucket), "
                   "warm-start engine; then a wedge verdict drops the "
                   "bucket and the half-open probe recompiles it",
            hlo_texts=texts)
    return build


def _build_engine_fleet():
    def build():
        ensure_cpu()
        import shutil
        import tempfile

        import numpy as np

        from raft_tpu.serving.engine import RAFTEngine
        from raft_tpu.serving.scheduler import MicroBatchScheduler

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        store = tempfile.mkdtemp(prefix="graftaudit_fleet_aot_")
        try:
            # the replica-fleet recipe: the PRIMARY compiles its one
            # bucket fresh and serializes it into the artifact store;
            # replicas 2..4 spawn from it and must warm ENTIRELY from
            # AOT loads — zero extra XLA compiles per added replica is
            # the fleet's headline contract, pinned here on the
            # engines' own counters (never timing)
            primary = RAFTEngine(variables, cfg, iters=_ITERS,
                                 envelope=[(1, h, w)], precompile=True,
                                 aot_cache=store)
            rng = np.random.RandomState(0)

            def pair():
                return (rng.rand(h, w, 3).astype(np.float32) * 255,
                        rng.rand(h, w, 3).astype(np.float32) * 255)

            with MicroBatchScheduler(primary, replicas=4, max_batch=1,
                                     gather_window_s=0.0) as sched:
                futs = [sched.submit(*pair()) for _ in range(12)]
                for f in futs:
                    assert f.result(timeout=600).flow is not None
                lanes = sched.health()["fleet"]["lanes"]
                assert len(lanes) == 4, f"fleet size: {sorted(lanes)}"
                assert all(v["dispatches"] >= 1 for v in lanes.values()), \
                    f"idle lane in a least-loaded fleet: {lanes}"
                engines = [lane.engine for lane in sched._lanes]
            stats = [e.aot_stats() for e in engines]
            assert stats[0]["compiles"] == 1, \
                f"primary compiled != 1 bucket: {stats[0]}"
            for k, s in enumerate(stats[1:], start=1):
                assert s["compiles"] == 0, \
                    f"replica {k} compiled instead of AOT-loading: {s}"
                assert s["aot_hits"] >= 1, \
                    f"replica {k} never hit the artifact store: {s}"
            per_lane = [len(e._compiled) for e in engines]
            assert per_lane == [1, 1, 1, 1], \
                f"per-replica executable counts drifted: {per_lane}"
            # zero cross-replica leakage: each replica owns its table —
            # dropping replica 1's bucket must not reach its siblings
            bucket = next(iter(engines[1]._compiled))
            engines[1].drop_bucket(bucket)
            survivors = [len(e._compiled) for e in engines]
            assert survivors == [1, 0, 1, 1], \
                f"drop_bucket leaked across replicas: {survivors}"
            texts = tuple(exe.as_text()
                          for exe in engines[0]._compiled.values() if exe)
            return CanaryResult(
                observed_compiles=sum(per_lane),
                detail=f"4-replica fleet at {h}x{w}: one bucket per "
                       "replica (4 total), ONE fresh XLA compile — "
                       "replicas 2..4 warmed from the AOT artifact "
                       "store (compiles=0, aot_hits>=1 each); every "
                       "lane dispatched; per-replica tables isolated "
                       "(drop on one leaves siblings intact)",
                hlo_texts=texts)
        finally:
            shutil.rmtree(store, ignore_errors=True)
    return build


def _build_engine_hosts():
    def build():
        ensure_cpu()
        import shutil
        import tempfile

        import numpy as np

        from raft_tpu.serving.aot import AOTCache
        from raft_tpu.serving.engine import RAFTEngine
        from raft_tpu.serving.hosts import HostFleet, HostWorker
        from raft_tpu.serving.transport import LoopbackTransport

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        store = tempfile.mkdtemp(prefix="graftaudit_hosts_aot_")
        remote = tempfile.mkdtemp(prefix="graftaudit_hosts_remote_")
        try:
            # the multi-host join recipe: the PRIMARY compiles its one
            # bucket fresh and serializes it; the joining host's
            # engine is built at PREWARM time against its OWN (empty)
            # artifact root — it can only warm from what the fleet's
            # admit PUSHED over the transport, sha256-verified. Zero
            # XLA compiles on the joining host is the headline
            # contract, pinned on the prewarm reply's own counters.
            primary = RAFTEngine(variables, cfg, iters=_ITERS,
                                 envelope=[(1, h, w)], precompile=True,
                                 aot_cache=store)

            def factory():
                return RAFTEngine(variables, cfg, iters=_ITERS,
                                  envelope=[(1, h, w)],
                                  precompile=True, aot_cache=remote)

            worker = HostWorker(engine_factory=factory,
                                aot_root=remote)
            fleet = HostFleet(
                {"h0": LoopbackTransport(worker, name="h0")},
                aot_cache=AOTCache(store), heartbeat_s=30.0,
                reconnect_backoff_s=600.0)
            try:
                stats = fleet.admit_all()["h0"]
                assert stats["compiles"] == 0, \
                    f"joining host compiled instead of loading: {stats}"
                assert stats["aot_hits"] >= 1, \
                    f"joining host never hit pushed artifacts: {stats}"
                assert stats["executables"] == 1, \
                    f"host executable count drifted: {stats}"
                host = fleet.health()["hosts"]["h0"]
                assert host["push_entries"] >= 1 \
                    and host["push_bytes"] > 0, \
                    f"artifact push never shipped: {host}"
                rng = np.random.RandomState(0)
                i1 = rng.rand(1, h, w, 3).astype(np.float32) * 255
                i2 = rng.rand(1, h, w, 3).astype(np.float32) * 255
                want = np.asarray(primary.infer_batch(i1, i2))
                got = np.asarray(
                    fleet.hosts["h0"].engine.infer_batch(i1, i2))
                assert np.array_equal(want, got), (
                    "remote infer diverged from the primary (same "
                    "weights, same pushed executable)")
            finally:
                fleet.close()
            texts = tuple(exe.as_text()
                          for exe in primary._compiled.values() if exe)
            return CanaryResult(
                observed_compiles=(len(primary._compiled)
                                   + stats["executables"]),
                detail=f"multi-host join at {h}x{w}: one bucket on "
                       "the primary (the only fresh XLA compile) + "
                       "one on the joining host, prewarmed entirely "
                       "from artifacts pushed sha256-verified over "
                       "the loopback transport (compiles=0, "
                       "aot_hits>=1 on the prewarm reply); remote "
                       "infer bitwise vs the primary",
                hlo_texts=texts)
        finally:
            shutil.rmtree(store, ignore_errors=True)
            shutil.rmtree(remote, ignore_errors=True)
    return build


def _build_registry_two_models():
    def build():
        ensure_cpu()
        import numpy as np
        from raft_tpu.serving.registry import ModelRegistry

        variables, cfg = _engine_weights()
        h, w = _IMAGE_HW
        rng = np.random.RandomState(0)

        def pair():
            return (rng.rand(h, w, 3).astype(np.float32) * 255,
                    rng.rand(h, w, 3).astype(np.float32) * 255)

        def counts(reg):
            return {name: len(reg._models[name].live.engine._compiled)
                    for name in reg.models()}

        # two model families over one weight tree (the canary audits
        # the REGISTRY's engine hygiene — per-model executable
        # ownership — not the models): each gets its own engine with
        # one documented bucket
        with ModelRegistry(max_batch=2, gather_window_s=0.0) as reg:
            reg.add_model("accurate", variables, cfg, iters=_ITERS,
                          envelope=[(2, h, w)])
            reg.add_model("fast", variables, cfg, iters=_ITERS,
                          envelope=[(2, h, w)])
            for name in ("accurate", "fast"):
                for i in range(2):
                    i1, i2 = pair()
                    reg.submit(i1, i2, model=name).result(timeout=600)
            assert counts(reg) == {"accurate": 1, "fast": 1}, \
                f"pre-deploy executable leakage: {counts(reg)}"
            # deploy -> canary -> promote on "accurate" (same arch):
            # the canary compiles ITS one bucket; the other model's
            # engine must not grow (no cross-model leakage)
            reg.deploy("accurate", variables, canary_fraction=0.5)
            canary_eng = reg._models["accurate"].canary.engine
            for i in range(4):
                i1, i2 = pair()
                reg.submit(i1, i2, model="accurate",
                           route_key=f"c{i}").result(timeout=600)
            assert len(canary_eng._compiled) == 1, \
                "canary engine leaked buckets"
            assert counts(reg) == {"accurate": 1, "fast": 1}, \
                f"canary deploy leaked into live engines: {counts(reg)}"
            live_eng = reg._models["accurate"].live.engine
            reg.promote("accurate")
            # same-arch promote is a weight swap INTO the live engine:
            # same engine object, same single executable — no compile
            # storm, no swap to the canary's duplicate engine
            assert reg._models["accurate"].live.engine is live_eng, \
                "same-arch promote replaced the live engine"
            for i in range(2):
                i1, i2 = pair()
                reg.submit(i1, i2, model="accurate").result(timeout=600)
            assert counts(reg) == {"accurate": 1, "fast": 1}, \
                f"post-promote compile storm: {counts(reg)}"
            engines = {name: reg._models[name].live.engine
                       for name in reg.models()}
        texts = tuple(exe.as_text()
                      for eng in engines.values()
                      for exe in eng._compiled.values() if exe)
        return CanaryResult(
            observed_compiles=sum(len(eng._compiled)
                                  for eng in engines.values()),
            detail="two-model registry at "
                   f"{h}x{w}: per-model engines pinned at 1 bucket "
                   "each through a deploy -> canary -> promote cycle "
                   "(same-arch promote reuses the live executable)",
            hlo_texts=texts)
    return build


def build_targets() -> List[Target]:
    return [
        Target(
            name="train_step",
            build=_build_train_step({}),
            donate_argnums=(0,),   # trainer.py jits with donate (0,)
            notes="basic model, library-default corr backend, fp32"),
        Target(
            name="train_step_bf16",
            # the deployed mixed recipe (BENCH_DEFAULTS winner config):
            # softsel lookup, bf16 corr volume, bf16 compute
            build=_build_train_step(dict(mixed_precision=True,
                                         corr_dtype="bfloat16",
                                         corr_impl="softsel")),
            donate_argnums=(0,),
            compute_dtype="bfloat16",
            compiled=False,        # H2/H1 are jaxpr-tier; the fp32
                                   # twin above covers the HLO tier
            waivers=(_CORR_ISLAND,),
            notes="mixed-precision step at the r5 winner config"),
        Target(
            name="serve",
            build=_build_serve(),
            notes="RAFTEngine serving fn shape (weights as argument)"),
        Target(
            name="serve_u8",
            build=_build_serve_u8(),
            donate_argnums=(3,),   # flow_init -> flow_low alias: the
            #                        u8-wire warm engine donates it and
            #                        H4 verifies XLA honors the alias
            notes="u8-wire warm-start serving recipe "
                  "(RAFTEngine(wire='u8', warm_start=True)): uint8 "
                  "frames, on-device normalize, donated flow_init"),
        Target(
            name="serve_ragged",
            build=_build_serve_ragged(),
            donate_argnums=(5,),   # flow_init -> flow_low alias: the
            #                        u8-wire warm RAGGED engine donates
            #                        it (arg 5 — after the two
            #                        descriptor arrays) and H4 verifies
            #                        XLA honors the alias through the
            #                        masked graph
            notes="ragged capacity-class serving recipe "
                  "(RAFTEngine(ragged=True, warm_start=True, "
                  "wire='u8')): uint8 frames, traced per-row validity "
                  "descriptor, masked-tail correlation, donated "
                  "flow_init"),
        Target(
            name="serve_cached",
            build=_build_serve_cached(),
            donate_argnums=(2, 3, 4),   # fmap1 -> fmap2, cnet1 ->
            #                             cnet2, flow_init -> flow_low:
            #                             the per-stream cache recycles
            #                             its own HBM every dispatch —
            #                             H4 verifies XLA honors all
            #                             three aliases
            notes="cross-frame cached serving recipe "
                  "(RAFTEngine(feature_cache=True)): one frame of "
                  "pixels + donated device-resident cache rows"),
        Target(
            name="engine_feature_cache",
            kind="canary",
            build=_build_engine_feature_cache(),
            expect_compiles=1,     # ONE cached executable per spatial
            #                        shape across cold -> warm ->
            #                        evicted -> warm (pool transitions
            #                        are data, never new programs)
            notes="feature-cache pool canary: stream lifecycle with a "
                  "forced LRU eviction stays on one cached executable; "
                  "no plain-signature strays in a video-only serve"),
        Target(
            name="engine_exact_ragged",
            kind="canary",
            build=_build_engine_exact_ragged(),
            expect_compiles=1,     # pinned in tests/test_serving.py
            notes="ragged-tail batch fill, exact_shapes mode"),
        Target(
            name="engine_ragged",
            kind="canary",
            build=_build_engine_ragged(),
            expect_compiles=1,     # ONE executable for the whole
            #                        mixed-shape sweep — the capacity
            #                        class IS the compile unit; the
            #                        bucketed oracle in the same build
            #                        compiles one per shape
            notes="ragged single-executable serving: 3-distinct-shape "
                  "sweep through one capacity-class executable, "
                  "full-extent row bitwise vs the bucketed oracle"),
        Target(
            name="engine_bucketed",
            kind="canary",
            build=_build_engine_bucketed(),
            expect_compiles=1,
            notes="envelope routing pads up instead of recompiling"),
        Target(
            name="engine_u8_wire",
            kind="canary",
            build=_build_engine_u8_wire(),
            expect_compiles=1,
            notes="u8 wire: uint8 executable params (no host-side "
                  "widening), bitwise parity vs f32 at integer "
                  "inputs, warm-start round-trip"),
        Target(
            name="engine_fleet",
            kind="canary",
            build=_build_engine_fleet(),
            expect_compiles=4,     # one bucket per replica, 4 lanes —
            #                        but only ONE of them is a fresh
            #                        XLA compile; replicas 2..4 warm
            #                        from the AOT artifact store
            #                        (compiles=0, aot_hits>=1, asserted
            #                        in the build on engine counters)
            notes="replica-fleet fan-out: 4 lanes behind one "
                  "scheduler, one executable per replica with zero "
                  "XLA compiles past the primary (AOT-loaded) and no "
                  "cross-replica table leakage"),
        Target(
            name="engine_hosts",
            kind="canary",
            build=_build_engine_hosts(),
            expect_compiles=2,     # one bucket on the primary + one on
            #                        the joining host — but only the
            #                        primary's is a fresh XLA compile;
            #                        the host prewarms from artifacts
            #                        PUSHED over the transport
            #                        (compiles=0, aot_hits>=1, asserted
            #                        in the build on the prewarm reply)
            notes="multi-host join: sha256-verified artifact push "
                  "over the loopback transport, prewarm "
                  "loads-not-compiles (zero XLA compiles on the "
                  "joining host), remote infer bitwise vs the "
                  "primary"),
        Target(
            name="registry_two_models",
            kind="canary",
            build=_build_registry_two_models(),
            expect_compiles=2,     # one bucket per live model engine —
            #                        pinned through deploy -> canary ->
            #                        promote (the canary's own single
            #                        bucket retires with it; same-arch
            #                        promote swaps weights, not
            #                        executables)
            notes="multi-model registry: per-engine executable counts "
                  "through a canary rollout — no cross-model leakage, "
                  "no compile storm on promote"),
        Target(
            name="scheduler_coalesce",
            kind="canary",
            build=_build_scheduler_coalesce(),
            expect_compiles=1,     # one bucket, cross-caller filled —
                                   # pinned in tests/test_scheduler.py;
                                   # this mechanizes it for the artifact
                                   # tier (the PR-2 ragged-tail lesson,
                                   # one layer up)
            notes="async micro-batching front-end coalesces two "
                  "callers' ragged traffic into the documented bucket"),
    ]
