"""graftaudit: compiled-artifact invariant checker (jaxpr/HLO tier).

graftlint (tools/graftlint) enforces TPU invariants at the AST level;
this package audits what the compiler actually PRODUCED — the traced
jaxpr and XLA's optimized HLO for the real train step, serving
function, and engine routing — against rules H1-H6 (host transfers,
fp32 widening, recompile count, donation honored, traffic budgets,
constant-folding traps). Same shrink-only baseline discipline, plus
shrink-only per-op-name byte budgets. See tools/graftaudit/core.py.
"""

from .core import (apply_baseline, audit_targets, load_baseline,
                   load_budgets, load_fixture_targets, main,
                   shrink_budgets, write_baseline, write_budgets)
from .finding import AuditFinding
from .spec import Artifacts, CanaryResult, Target, Waiver

__all__ = [
    "AuditFinding", "Artifacts", "CanaryResult", "Target", "Waiver",
    "apply_baseline", "audit_targets", "load_baseline", "load_budgets",
    "load_fixture_targets", "main", "shrink_budgets", "write_baseline",
    "write_budgets",
]
