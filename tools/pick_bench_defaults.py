"""Pick the fastest measured bench config and pin it as the default.

Reads the per-config JSON lines the round-3 ladder wrote (one file per
config under the dir given as argv[1]), takes the argmax by value, and
writes ``BENCH_DEFAULTS.json`` at the repo root — which ``bench.py`` folds
into its defaults so the driver's bare ``python bench.py`` reruns the
proven-best configuration instead of a guess.

The flag reconstruction parses the metric NAME (bench.py's ``emit`` tags
encode batch/remat/corr choices), so this stays correct if the ladder adds
configs.
"""

import json
import os
import re
import sys


def flags_from_metric(metric: str):
    m = re.search(r"_b(\d+)_iters", metric)
    if not m:
        return None
    flags = {"batches": [int(m.group(1))]}
    if "_remat" in metric:
        flags["remat"] = True
        if "_dots" in metric:
            flags["remat_policy"] = "dots"
    mc = re.search(r"_corr(bfloat16|float32)", metric)
    if mc:
        flags["corr_dtype"] = mc.group(1)
    if "_fusedloss" in metric:
        flags["fused_loss"] = True
    mu = re.search(r"_unroll(\d+)", metric)
    if mu:
        flags["scan_unroll"] = int(mu.group(1))
    mg = re.search(r"_gru(xla|fused)", metric)
    if mg:
        flags["gru_impl"] = mg.group(1)
    mi = re.search(r"_(gather|onehot_t|onehot|softsel|pallas)$", re.sub(
        r"_(?:unroll\d+|gruxla|grufused)", "", metric.replace(
            "_corrbfloat16", "").replace("_corrfloat32", "").replace(
            "_fusedloss", "")))
    if mi:
        flags["corr_impl"] = mi.group(1)
    return flags


def with_fallbacks(batches):
    """Measured batch first, then smaller rungs: a driver-time OOM at the
    winner (e.g. HBM fragmentation) must degrade bench.py to a slower
    number, not to 0.0. The rung list includes every batch the ladder
    measures (12/10/8/...), so a winner of 12 falls back through 10
    rather than skipping straight to 8 (ADVICE r4)."""
    return batches + [b for b in (10, 8, 6, 4, 2) if b < batches[0]]


def main():
    ladder_dir = sys.argv[1]
    best = None
    for name in sorted(os.listdir(ladder_dir)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(ladder_dir, name)
        try:
            with open(path) as f:
                lines = [ln for ln in f if ln.strip().startswith("{")]
            rec = json.loads(lines[-1])
        except (OSError, ValueError, IndexError):
            continue
        if rec.get("value", 0) <= 0:
            continue
        if "chairs_" not in rec.get("metric", ""):
            continue  # informational geometries (e.g. things 400x720)
            # must not set the chairs-crop headline defaults
        if best is None or rec["value"] > best[0]["value"]:
            best = (rec, name)
    if best is None:
        print("no successful ladder run; BENCH_DEFAULTS.json not written")
        return 1
    rec, name = best
    flags = flags_from_metric(rec["metric"])
    if flags is None:
        print(f"could not parse flags from metric {rec['metric']!r}")
        return 1
    out = dict(flags)
    out["batches"] = with_fallbacks(out["batches"])
    out["_measured"] = {"metric": rec["metric"], "value": rec["value"],
                        "ladder_file": name}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "BENCH_DEFAULTS.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(f"BENCH_DEFAULTS.json <- {name}: {rec['value']} pairs/s {flags}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
