#!/bin/bash
# Shared TPU liveness probe: exit 0 iff the tunnel backend can actually
# EXECUTE a jitted op, not merely enumerate devices. The tunnel has a
# documented half-up failure mode (OUTAGE_r05.log 08:47 UTC: devices()
# returns the chip but any compile/execute hangs forever), so callers
# must treat enumeration-only success as down.
#
#   bash tools/chip_probe.sh [timeout_s]    # default 120
set -u
T=${1:-120}
exec timeout -k 10 "$T" python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != 'cpu'
jax.jit(lambda a: (a * 2).sum())(jnp.ones((8, 128))).block_until_ready()
" >/dev/null 2>&1
