"""graftshard: the sharding & collectives audit gate (tools/graftshard/).

Three layers, mirroring test_graftaudit:

- per-rule fixture tests: each rule S1-S6 has a fixture program under
  ``tests/graftshard_fixtures/`` with a PLANTED violation (an in-loop
  all-reduce, a replicated 256 KiB value, an in-program device_put, a
  spec naming a ghost axis + an unconstrained boundary, an uneven
  extent, a donation killed by resharding) — detection must fire, and
  both suppression channels (a Waiver on the target; a baseline entry)
  must round-trip;
- mechanism tests: waiver-justification enforcement, the lintcache-
  backed warm cache, stale-baseline failure, CLI usage errors;
- the repo gate: ``python -m tools.graftshard --json`` over the REAL
  mesh programs (the data-parallel train step + the pjit-sharded serve
  trace) on a forced 4-device CPU mesh must exit 0 with no findings,
  the committed baseline must stay EMPTY (first-scan findings were
  fixed at the site — split_encode, the declared rng — never
  grandfathered), and the warm gate must answer in under 45 s.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "graftshard_fixtures")
BASELINE = os.path.join(REPO, "tools", "graftshard", "baseline.json")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tests.conftest import mesh_subprocess_env  # noqa: E402
from tools.graftshard import (ShardTarget, Waiver,  # noqa: E402
                              apply_baseline, audit_targets,
                              load_baseline, load_fixture_targets,
                              write_baseline)
from tools.graftshard.core import cached_audit, main  # noqa: E402

RULES = ("S1", "S2", "S3", "S4", "S5", "S6")

_AUDIT_CACHE = {}


def fixture(name):
    return os.path.join(FIXTURES, name)


def audit_fixture(name):
    """(targets, findings) for one fixture module, audited once per
    test session — detection, waiver, and baseline tests all read the
    same run."""
    if name not in _AUDIT_CACHE:
        targets = load_fixture_targets(fixture(name))
        findings, _ = audit_targets(targets)
        _AUDIT_CACHE[name] = (targets, findings)
    return _AUDIT_CACHE[name]


class TestRuleFixtures:
    @pytest.mark.parametrize("rule", RULES)
    def test_planted_violation_detected(self, rule):
        _, findings = audit_fixture(f"{rule.lower()}_pos.py")
        assert any(f.rule == rule for f in findings), \
            f"{rule} fixture produced no {rule} finding: {findings}"

    @pytest.mark.parametrize("rule", RULES)
    def test_waiver_suppresses_with_justification(self, rule):
        """The pragma analog: a Waiver(rule, detail-substring, reason)
        on the target declaration silences exactly that finding."""
        targets, findings = audit_fixture(f"{rule.lower()}_pos.py")
        details = [f.detail for f in findings if f.rule == rule]
        assert details
        waived_targets = [
            dataclasses.replace(
                t, waivers=t.waivers + tuple(
                    Waiver(rule, d, "fixture round-trip")
                    for d in details))
            for t in targets]
        refindings, _ = audit_targets(waived_targets)
        assert not any(f.rule == rule for f in refindings), \
            f"waiver did not suppress: {refindings}"
        # a waiver naming a DIFFERENT rule must not suppress
        wrong = "S1" if rule != "S1" else "S2"
        wrong_targets = [
            dataclasses.replace(
                t, waivers=tuple(Waiver(wrong, d, "wrong rule")
                                 for d in details))
            for t in targets]
        refindings, _ = audit_targets(wrong_targets)
        assert any(f.rule == rule for f in refindings)

    @pytest.mark.parametrize("rule", RULES)
    def test_baseline_roundtrip_then_stale(self, rule, tmp_path):
        """Grandfathering consumes the entry; a fixed finding leaves a
        STALE entry that must fail (it would otherwise silently
        grandfather the next reintroduction)."""
        targets, findings = audit_fixture(f"{rule.lower()}_pos.py")
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), findings)
        new, stale = apply_baseline(findings, load_baseline(str(bl)))
        assert new == [] and stale == []
        # "fixed": nothing found, every entry unconsumed -> stale
        new, stale = apply_baseline(
            [], load_baseline(str(bl)),
            audited_targets=[t.name for t in targets])
        assert new == [] and len(stale) == len(findings)
        # an entry for a target OUTSIDE this run is merely unchecked
        new, stale = apply_baseline(
            [], load_baseline(str(bl)),
            audited_targets=["some_other_target"])
        assert new == [] and stale == []

    def test_clean_fixture_is_silent(self):
        """The negative: declared specs over real axes, even extents,
        same-sharded donation, out-of-loop reduction — all rules
        silent."""
        _, findings = audit_fixture("clean.py")
        assert findings == [], \
            "; ".join(f.render() for f in findings)


class TestMechanisms:
    def test_waiver_requires_justification(self):
        with pytest.raises(ValueError, match="justification"):
            Waiver("S2", "anything", "   ")

    def test_cached_audit_hits_and_matches(self, tmp_path):
        """Second run through the lintcache file must serve from cache
        (no rebuild) and return identical findings."""
        targets = load_fixture_targets(fixture("s5_pos.py"))
        from tools.graftshard.rules import ALL_RULES
        path = str(tmp_path / "cache.json")
        f1, _, hits1 = cached_audit(targets, ALL_RULES, path)
        assert hits1 == {"s5_fixture": False}
        f2, _, hits2 = cached_audit(targets, ALL_RULES, path)
        assert hits2 == {"s5_fixture": True}
        assert [f.key() for f in f2] == [f.key() for f in f1]
        # a different rule set is a different key: no false hit
        f3, _, hits3 = cached_audit(targets, ALL_RULES[:1], path)
        assert hits3 == {"s5_fixture": False}
        assert f3 == []     # S1 alone can't see the S5 geometry

    def test_decl_target_needs_no_program(self):
        """kind='decl' audits declarations only — no trace, no HLO."""
        targets = load_fixture_targets(fixture("s5_pos.py"))
        assert targets[0].kind == "decl"
        findings, _ = audit_targets(targets)
        assert findings and all(f.rule == "S5" for f in findings)
        assert "wasted bytes" in findings[0].message

    def test_mesh_lowered_signature_parsers(self):
        """The chunk-based signature parsers must survive the nested
        braces a mesh program's attrs carry (brace-matching regexes
        silently fail on ``mhlo.sharding = "{devices=[4]<=[4]}"`` —
        the exact reason graftaudit's _ARG_RE is not reused here)."""
        from tools.graftshard.artifacts import (annotated_args,
                                                declared_donations)
        sig = ('func.func public @main('
               '%arg0: tensor<16xf32> {jax.buffer_donor = true, '
               'mhlo.sharding = "{devices=[4]<=[4]}"}, '
               '%arg1: tensor<8x16xf32> '
               '{mhlo.sharding = "{replicated}"}, '
               '%arg2: tensor<4xf32>) -> (tensor<16xf32>)')
        assert annotated_args(sig) == {0, 1}
        assert declared_donations(sig) == [0]

    def test_while_body_collectives_parser(self):
        """hlo_lib's loop-body analysis: collectives inside body=
        regions (transitively through called computations) are
        in-loop; the same opcode outside is not."""
        from tools import hlo_lib
        text = (
            "HloModule m\n"
            "%helper (p: f32[]) -> f32[] {\n"
            "  ROOT %ar2 = f32[] all-reduce(f32[] %p), "
            "to_apply=%add\n"
            "}\n"
            "%body (p: (s32[], f32[])) -> (s32[], f32[]) {\n"
            "  %c = f32[] call(f32[] %g), to_apply=%helper\n"
            "}\n"
            "%cond (p: (s32[], f32[])) -> pred[] {\n"
            "  ROOT %lt = pred[] compare(s32[] %i, s32[] %n)\n"
            "}\n"
            "ENTRY %main (a: f32[4]) -> f32[] {\n"
            "  %w = (s32[], f32[]) while((s32[], f32[]) %t), "
            "condition=%cond, body=%body\n"
            "  %ar = f32[4] all-reduce(f32[4] %a), to_apply=%add\n"
            "}\n")
        bodies = hlo_lib.while_body_computations(text)
        assert "body" in bodies and "helper" in bodies
        inloop = hlo_lib.find_collectives(text, within=bodies)
        assert [r["name"] for r in inloop] == ["ar2"]
        everywhere = hlo_lib.find_collectives(text)
        assert {r["name"] for r in everywhere} == {"ar", "ar2"}

    def test_cli_usage_errors(self, tmp_path):
        assert main(["--rules", "S9"]) == 2
        assert main(["--rules", "S1", "--write-baseline",
                     str(tmp_path / "b.json")]) == 2
        assert main(["--fixture",
                     str(tmp_path / "missing.py")]) == 2
        broken = tmp_path / "broken_fixture.py"
        broken.write_text("import no_such_module_xyz\n")
        assert main(["--fixture", str(broken)]) == 2

    def test_cli_fixture_json_and_baseline_flow(self, tmp_path, capsys):
        """CLI end-to-end on the cheapest fixture: findings as JSON,
        then grandfathered via --write-baseline, then stale once the
        'violation' would be fixed."""
        rc = main(["--fixture", fixture("s5_pos.py"), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert any(f["rule"] == "S5" for f in out)
        assert all({"target", "rule", "name", "detail", "message"}
                   <= set(f) for f in out)
        bl = tmp_path / "bl.json"
        rc = main(["--fixture", fixture("s5_pos.py"),
                   "--write-baseline", str(bl)])
        assert rc == 0 and bl.exists()
        capsys.readouterr()
        rc = main(["--fixture", fixture("s5_pos.py"),
                   "--baseline", str(bl)])
        assert rc == 0        # grandfathered
        rc = main(["--fixture", fixture("clean.py"),
                   "--baseline", str(bl)])
        capsys.readouterr()
        assert rc == 0        # different targets: unchecked, not stale


class TestRepoGate:
    """The actual gate: the real mesh programs must audit clean."""

    def _run_gate(self, cache_dir):
        env = mesh_subprocess_env(
            local_devices=4,
            extra_env={"RAFT_GRAFTSHARD_CACHE":
                       os.path.join(cache_dir, "cache.json")})
        return subprocess.run(
            [sys.executable, "-m", "tools.graftshard", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=600,
            env=env)

    def test_repo_audit_clean_and_warm_under_45s(self, tmp_path):
        """Cold run builds the partitioned artifacts and must gate
        clean; the SECOND run answers from the lintcache entry keyed
        on the artifact hash + rule set — pinned under the 45 s warm
        budget (measured ~0.4 s: no jax import at all)."""
        r = self._run_gate(str(tmp_path))
        assert r.returncode == 0, \
            f"graftshard findings:\n{r.stdout}\n{r.stderr}"
        assert json.loads(r.stdout) == []
        t0 = time.monotonic()
        r2 = self._run_gate(str(tmp_path))
        warm_s = time.monotonic() - t0
        assert r2.returncode == 0 and json.loads(r2.stdout) == []
        assert "cache" in r2.stderr, r2.stderr
        assert warm_s < 45, f"warm gate took {warm_s:.1f}s"

    def test_baseline_stays_empty(self):
        """The first scan's findings were FIXED at the site — the
        image-concat replication by RAFTConfig.split_encode (via
        mesh_model_config), the unconstrained rng by trainer.py's
        declared device_put; what remains intentional is a justified
        Waiver on the target declaration. The baseline ships EMPTY
        and stays that way: new findings are fixed or waived with
        justification, never grandfathered."""
        with open(BASELINE) as f:
            entries = json.load(f)["findings"]
        assert entries == [], (
            "graftshard baseline regrew — fix or waive the finding "
            f"instead of grandfathering it: {entries}")

    def test_s2_waivers_scope_to_the_state_trees_only(self):
        """The committed S2 waivers cover exactly the arg-0 state/
        weight trees (replicated by design). They must NOT swallow a
        NEW replication accident on any other boundary value — a
        dropped frames sharding, a fresh unsharded input, a concat
        all-reduce — which is the bug class S2 exists to catch."""
        from tools.graftshard.targets import build_targets
        targets = {t.name: t for t in build_targets()}
        train, serve = (targets["train_step_dp"],
                        targets["serve_shard"])
        # covered: the state/weight trees, in their actual renderings
        assert train.waived("S2", "arg 4 [0].params['cnet']['k']")
        assert train.waived("S2", "out 12 [0].opt_state[0].mu")
        assert serve.waived("S2", "arg 33 [0]['params']['fnet']")
        # NOT covered: every other boundary value or HLO surface
        for t in (train, serve):
            assert not t.waived("S2", "arg 154 [1]")          # frames
            assert not t.waived("S2", "arg 156 [3]")          # f_init
            assert not t.waived("S2", "out 0 [0]")            # flow
            assert not t.waived(
                "S2", "all-reduce f32[8,32,32,3] @ jit(serve)/"
                      "jit(main)/RAFT/concatenate")
            assert not t.waived("S2",
                                "constrained-replicated tensor<x>")

    def test_meta_gate_merges_tiers(self):
        """``python -m tools.graft --json``: one merged summary, one
        exit code. Pinned over the stdlib tiers (fast — the full
        four-tier run is the pre-commit command; graftaudit/graftshard
        have their own gate tests above/alongside)."""
        r = subprocess.run(
            [sys.executable, "-m", "tools.graft", "--json",
             "--tiers", "graftlint,graftthread"],
            cwd=REPO, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        merged = json.loads(r.stdout)
        assert merged["ok"] is True
        assert set(merged["tiers"]) == {"graftlint", "graftthread"}
        for rec in merged["tiers"].values():
            assert rec["exit"] == 0 and rec["findings"] == []
        assert merged["findings_total"] == 0
        # usage errors stay usage errors
        r2 = subprocess.run(
            [sys.executable, "-m", "tools.graft", "--tiers", "nope"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert r2.returncode == 2

    def test_targets_declare_the_partitioner_table(self):
        """The audit must check the SAME spec table and geometry the
        runtime shards with. targets.py carries a jax-free literal
        MIRROR of the Partitioner's audit surface (the warm cache path
        must not import jax); this pin is what makes the mirror safe —
        drift between the literals and the live
        ``Partitioner.declared_specs()``/``shard_geometry()`` fails
        here before it can desynchronize the gate from the runtime."""
        from raft_tpu.parallel.mesh import make_mesh
        from raft_tpu.parallel.partitioner import Partitioner
        from tools.graftshard.targets import build_targets
        part = Partitioner(make_mesh(4, spatial=1))
        live_specs = dict(part.declared_specs())
        live_geo = part.shard_geometry((4, 32, 32))
        for t in build_targets():
            assert dict(t.declared_specs) == live_specs, t.name
            assert tuple(t.shard_geometry) == live_geo, t.name
