"""Head-to-head numeric parity against the ACTUAL reference implementation.

Imports the PyTorch reference model from ``/root/reference/core`` (the same
``sys.path`` trick its own entry points use, train.py:3), random-inits it
with a fixed torch seed, converts the live ``state_dict`` through
``tools/convert.py``, and compares ``flow_up`` on a real Sintel pair from
``demo-frames/`` — the end-to-end check that every layer convention
(padding, norms, sampling, upsampling, iteration structure) matches, not
just the per-module oracles in test_convert.py.

Bound: max per-pixel flow diff < 5e-4 px in fp32 (measured ~2e-5 for basic
and ~6e-5 for small at |flow| up to ~80 px — see assertions), for both
models and both materialized-corr lookup impls at the reference's own
iteration counts (train 12 / demo 20, train.py:232, demo.py:62).
"""

import os.path as osp
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REF = "/root/reference"

torch = pytest.importorskip("torch")

if not osp.isdir(osp.join(REF, "core")):  # pragma: no cover
    pytest.skip("reference checkout not available", allow_module_level=True)


@pytest.fixture(scope="module")
def torch_raft():
    sys.path.insert(0, osp.join(REF, "core"))
    from raft import RAFT as TorchRAFT  # noqa: E402

    yield TorchRAFT
    sys.path.remove(osp.join(REF, "core"))


@pytest.fixture(scope="module")
def sintel_pair():
    from PIL import Image

    h, w = 192, 256  # crop keeps CPU runtime sane; divisible by 8
    f1 = np.asarray(Image.open(osp.join(REF, "demo-frames/frame_0016.png")))
    f2 = np.asarray(Image.open(osp.join(REF, "demo-frames/frame_0017.png")))
    return f1[:h, :w].astype(np.float32), f2[:h, :w].astype(np.float32)


@pytest.mark.parametrize("small,impl,iters", [
    (False, "gather", 12),
    (False, "onehot", 12),
    (True, "gather", 20),
    (True, "onehot", 12),
    # 'alt' = AlternateCorrBlock (the alt_cuda_corr analog): never
    # materializes the volume, but interpolate-then-dot is algebraically
    # the same lookup, so it must match the reference's materialized
    # CorrBlock output too (core/corr.py:63-91).
    (True, "alt", 12),
    # the Pallas kernels (interpret mode on CPU — same program semantics),
    # pinned DIRECTLY against the torch reference rather than transitively
    # through the gather oracle
    (True, "pallas", 12),
    (True, "alt_pallas", 12),
])
def test_full_model_flow_matches_reference(torch_raft, sintel_pair, small,
                                           impl, iters, monkeypatch):
    import argparse

    from raft_tpu.config import RAFTConfig
    from raft_tpu.kernels import corr_alt_pallas, corr_pallas
    from raft_tpu.models import RAFT
    from raft_tpu.tools.convert import convert_state_dict

    if impl in ("pallas", "alt_pallas"):
        monkeypatch.setattr(corr_pallas, "_INTERPRET", True)
        monkeypatch.setattr(corr_alt_pallas, "_INTERPRET", True)

    img1, img2 = sintel_pair
    h, w = img1.shape[:2]

    torch.manual_seed(1234)
    targs = argparse.Namespace(small=small, mixed_precision=False,
                               alternate_corr=False, dropout=0.0)
    tmodel = torch_raft(targs).eval()
    with torch.no_grad():
        t1 = torch.from_numpy(img1).permute(2, 0, 1)[None]
        t2 = torch.from_numpy(img2).permute(2, 0, 1)[None]
        # fork's test_mode returns ONLY flow_up (core/raft.py:141-143)
        flow_t = tmodel(t1, t2, iters=iters, test_mode=True)
    flow_t = flow_t[0].permute(1, 2, 0).numpy()

    if impl == "alt":
        cfg = RAFTConfig(small=small, alternate_corr=True)
    elif impl == "alt_pallas":
        cfg = RAFTConfig(small=small, alternate_corr=True,
                         corr_impl="pallas")
    else:
        cfg = RAFTConfig(small=small, corr_impl=impl)
    jmodel = RAFT(cfg)
    variables = jmodel.init(jax.random.PRNGKey(0), jnp.zeros((1, h, w, 3)),
                            jnp.zeros((1, h, w, 3)), iters=1)
    variables = convert_state_dict(tmodel.state_dict(), variables)
    _, flow_j = jmodel.apply(variables, jnp.asarray(img1[None]),
                             jnp.asarray(img2[None]), iters=iters,
                             test_mode=True)
    flow_j = np.asarray(flow_j)[0]

    diff = np.abs(flow_t - flow_j)
    assert np.abs(flow_t).max() > 1.0, "degenerate flow — test not probative"
    assert diff.max() < 5e-4, (
        f"max flow diff {diff.max():.2e} px (mean {diff.mean():.2e}) vs "
        f"reference, |flow|max {np.abs(flow_t).max():.1f}")
