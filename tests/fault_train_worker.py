"""Standalone tiny training run for fault-tolerance drills — the
subprocess target of tests/test_fault_tolerance.py, NOT a pytest module.

A deterministic single-batch loader drives the REAL ``train()`` so a
supervised run with an armed fault plan (wedge, checkpoint corruption)
can be compared bitwise against an uninterrupted control run: with one
fixed batch, identical seeds, and the step-counter-folded rng, the
final weights depend only on ``num_steps`` — resume from any intact
step reproduces the control run exactly.

Mirrors tests/conftest.py's backend setup (cpu, 8 virtual devices,
persistent 'cputest' compile cache, highest matmul precision) so the
control and supervised processes share one compiled program.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--log-dir", required=True)
    p.add_argument("--name", required=True)
    p.add_argument("--num-steps", type=int, default=4)
    p.add_argument("--hang-s", type=float, default=0.0)
    p.add_argument("--resume", action="store_true")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    from raft_tpu.utils.platform import (enable_persistent_cache,
                                         respect_cpu_request)
    respect_cpu_request()
    import jax
    jax.config.update("jax_default_matmul_precision", "highest")
    enable_persistent_cache("cputest")

    import numpy as np

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.training.trainer import train

    rng = np.random.RandomState(0)
    batch = {
        "image1": rng.rand(8, 64, 64, 3).astype(np.float32) * 255,
        "image2": rng.rand(8, 64, 64, 3).astype(np.float32) * 255,
        "flow": rng.randn(8, 64, 64, 2).astype(np.float32),
        "valid": np.ones((8, 64, 64), np.float32),
    }

    class OneBatch:
        def __iter__(self):
            return iter([batch])

    cfg = TrainConfig(
        name=args.name, stage="chairs", lr=1e-4, num_steps=args.num_steps,
        batch_size=8, image_size=(64, 64), iters=2, val_freq=2, sum_freq=2,
        hang_s=args.hang_s, checkpoint_dir=args.ckpt_dir,
        log_dir=args.log_dir, validation=())
    train(RAFTConfig(small=True), cfg, resume=args.resume,
          loader=OneBatch())


if __name__ == "__main__":
    main()
