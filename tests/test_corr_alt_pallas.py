"""Parity for the on-the-fly Pallas correlation (alt_cuda_corr analog).

The XLA formulation ``models.corr.alt_corr_lookup`` is itself pinned
against the materialized-volume path (test_corr_impls/test_corr), so it is
the oracle here. The kernel runs in interpret mode on CPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.kernels import corr_alt_pallas
from raft_tpu.models.corr import alt_corr_lookup
from raft_tpu.ops.pooling import avg_pool2x2

RADIUS = 2


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setattr(corr_alt_pallas, "_INTERPRET", True)


@pytest.fixture(scope="module")
def setup():
    rng = np.random.RandomState(11)
    B, H, W, C = 2, 8, 12, 16
    fmap1 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    fmap2 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    f2_pyr = [fmap2]
    for _ in range(2):
        f2_pyr.append(avg_pool2x2(f2_pyr[-1]))
    base = np.stack(np.meshgrid(np.arange(W), np.arange(H)), -1)
    coords = (base[None].astype(np.float32)
              + rng.randn(B, H, W, 2).astype(np.float32) * 2.5)
    coords[0, 0, 0] = [0.0, 0.0]
    coords[0, 0, 1] = [-50.0, 3.0]          # far OOB -> zeros
    coords[0, 1, 0] = [W + 40.0, H + 40.0]  # far OOB -> zeros
    coords[1, 0, 0] = [-0.5, H - 0.5]       # edge-straddling window
    return fmap1, tuple(f2_pyr), jnp.asarray(coords)


def test_matches_xla_alt(setup):
    fmap1, f2_pyr, coords = setup
    want = np.asarray(alt_corr_lookup(fmap1, f2_pyr, coords, RADIUS))
    got = np.asarray(corr_alt_pallas.alt_corr_lookup_pallas(
        fmap1, f2_pyr, coords, RADIUS))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_prepadded_matches(setup):
    fmap1, f2_pyr, coords = setup
    want = np.asarray(alt_corr_lookup(fmap1, f2_pyr, coords, RADIUS))
    f2_pp = corr_alt_pallas.pad_f2_pyramid(f2_pyr, RADIUS)
    got = np.asarray(corr_alt_pallas.alt_corr_lookup_pallas(
        fmap1, f2_pp, coords, RADIUS, prepadded=True))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_vjp_matches_xla_alt(setup):
    fmap1, f2_pyr, coords = setup

    def loss(fn):
        def f(args):
            f1, f2s = args
            return jnp.sum(fn(f1, f2s, coords, RADIUS) ** 2)
        return f

    g_want = jax.grad(loss(alt_corr_lookup))((fmap1, f2_pyr))
    g_got = jax.grad(
        loss(corr_alt_pallas.alt_corr_lookup_pallas))((fmap1, f2_pyr))
    for a, b in zip(jax.tree_util.tree_leaves(g_got),
                    jax.tree_util.tree_leaves(g_want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_coords_grad_matches_xla_alt(setup):
    """Unlike the pyramid-path kernel (whose model stop-gradients coords),
    the alt path advertises drop-in semantics — coords must carry real
    gradients, not silent zeros."""
    fmap1, f2_pyr, coords = setup

    def loss(fn):
        return lambda c: jnp.sum(fn(fmap1, f2_pyr, c, RADIUS) ** 2)

    g_want = np.asarray(jax.grad(loss(alt_corr_lookup))(coords))
    g_got = np.asarray(jax.grad(
        loss(corr_alt_pallas.alt_corr_lookup_pallas))(coords))
    assert np.abs(g_want).max() > 0  # the oracle really is nonzero
    np.testing.assert_allclose(g_got, g_want, atol=1e-4, rtol=1e-4)


def test_window_dma_in_bounds_at_extreme_coords():
    """The 8-aligned window DMA must stay inside the padded buffer for
    EVERY reachable coordinate. Interpret mode hides violations (XLA
    dynamic_slice clamps; Mosaic TPU DMAs do not), so pin the bound
    structurally: derive the clamp exactly as _level_alt_pallas does and
    check x0a + WSPAN <= Wp and y0 + P <= Hp for far-OOB queries.

    Regression: pad_f2_pyramid adds `extra` right-margin zeros beyond the
    2*PAD halo; deriving the level width as Wp - 2*PAD (without the
    -extra) inflates the x clamp and lets the DMA end up to extra columns
    past the buffer — an OOB HBM read on chip."""
    for radius in (2, 3, 4):
        P = 2 * radius + 2
        PAD = corr_alt_pallas._pad(radius)
        WSPAN = corr_alt_pallas._wspan(P)
        extra = WSPAN - P
        for Hl, Wl in [(8, 12), (46, 62), (5, 7)]:
            f2 = jnp.zeros((1, Hl, Wl, 8), jnp.float32)
            (f2_p,) = corr_alt_pallas.pad_f2_pyramid([f2], radius)
            _, Hp, Wp, _ = f2_p.shape
            # the exact width recovery _level_alt_pallas performs
            assert Wp - 2 * PAD - extra == Wl
            # worst-case coords: far past every edge
            x = jnp.asarray([[-1e4, 1e4, Wl + 30.0]])
            y = jnp.asarray([[-1e4, 1e4, Hl + 30.0]])
            base, _, _ = corr_alt_pallas._prep_coords(
                Hp - 2 * PAD, Wp - 2 * PAD - extra, x, y, radius)
            # base stores x0a/8 (the kernel multiplies back by 8 so Mosaic
            # can prove tile-aligned slicing); recover the DMA start
            x0a = np.asarray(base[..., 0]) * 8
            y0 = np.asarray(base[..., 1])
            off = np.asarray(base[..., 2])
            assert (x0a >= 0).all() and (y0 >= 0).all()
            assert (x0a % 8 == 0).all()
            assert (off >= 0).all() and (off < 8).all()
            assert (x0a + WSPAN <= Wp).all(), (x0a.max() + WSPAN, Wp)
            assert (y0 + P <= Hp).all(), (y0.max() + P, Hp)


def test_model_alternate_corr_pallas_matches_xla():
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT

    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
    img2 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)

    flows = {}
    for impl in ["gather", "pallas"]:
        model = RAFT(RAFTConfig(small=True, alternate_corr=True,
                                corr_impl=impl))
        variables = model.init(jax.random.PRNGKey(0), img1, img2, iters=1)
        flows[impl] = np.asarray(
            model.apply(variables, img1, img2, iters=3))
    np.testing.assert_allclose(flows["pallas"], flows["gather"],
                               atol=5e-3, rtol=1e-4)
