"""Test harness: force CPU backend with 8 virtual devices.

Multi-chip sharding is validated without TPU hardware via XLA's host-platform
device-count emulation, per the driver contract. Must run before jax imports.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/raft_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(1234)
