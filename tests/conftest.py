"""Test harness: force CPU backend with 8 virtual devices.

Multi-chip sharding is validated without TPU hardware via XLA's host-platform
device-count emulation, per the driver contract. Must run before jax imports.
"""

import os

# FORCE cpu: the environment pins JAX_PLATFORMS=axon (the remote TPU
# tunnel), which would serialize every test through the one real chip's
# remote compiler. Tests run on the virtual 8-device CPU mesh by contract.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The image's sitecustomize registers the 'axon' remote-TPU PJRT plugin in
# every interpreter, and jax initializes it even under JAX_PLATFORMS=cpu —
# each test process would then dial (and block on) the single TPU tunnel.
# Deregister the factory before any backend is initialized: tests are
# CPU-mesh only by contract.
try:  # noqa: SIM105
    from jax._src import xla_bridge as _xb

    for _reg in ("_backend_factories",):
        getattr(_xb, _reg, {}).pop("axon", None)
except Exception:
    pass

jax.config.update("jax_platforms", "cpu")
from raft_tpu.utils.platform import jax_cache_dir  # noqa: E402

jax.config.update("jax_compilation_cache_dir", jax_cache_dir("cputest"))
# Golden-parity tests compare against torch fp32 oracles; this XLA CPU build
# lowers conv/dot to a reduced-precision path by default (observed ~1e-1 abs
# drift vs torch on a 3x3 conv), so force true fp32 accumulation under test.
jax.config.update("jax_default_matmul_precision", "highest")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(1234)


def mesh_subprocess_env(local_devices: int = 1, extra_env=None) -> dict:
    """Environment for spawning a worker process with its OWN forced
    CPU device count.

    This test process runs on the conftest-forced 8-virtual-device
    mesh (the XLA_FLAGS above); a subprocess inherits that flag and
    with it a device count the test didn't choose. Strip it, then
    re-force exactly ``local_devices`` (>1 only — a 1-device worker
    needs no flag). One definition for every subprocess-mesh test —
    the 2-process ``jax.distributed`` suite and the graftshard gate
    both spawn through here, so the recipe can't drift between them.
    """
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    if local_devices > 1:
        env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{local_devices}")
    if extra_env:
        env.update(extra_env)
    return env


@pytest.fixture
def mesh_worker_env():
    """The subprocess-mesh env builder, as a fixture."""
    return mesh_subprocess_env
