"""Serving tests: AOT engine shape routing, StableHLO export round-trip,
video writer — the backend-parity discipline of test_trt.py:52-99 applied
to our export path. (The scheduler layer above the engine has its own
suite, tests/test_scheduler.py.)"""

import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.export import (export_stablehlo, load_stablehlo,
                                     make_serving_fn)
from raft_tpu.serving.video import optical_flow_visualize


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return cfg, variables


class TestEngine:
    def test_bucket_routing_and_parity(self, small_setup, rng):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=3,
                         envelope=[(1, 64, 64), (2, 96, 96)],
                         precompile=False)
        # smallest fitting bucket
        assert eng._select_bucket(1, 64, 64) == (1, 64, 64)
        assert eng._select_bucket(1, 72, 64) == (2, 96, 96)
        assert eng._select_bucket(4, 64, 64) is None

        img1 = rng.rand(1, 60, 62, 3).astype(np.float32) * 255
        img2 = rng.rand(1, 60, 62, 3).astype(np.float32) * 255
        flow = eng.infer_batch(img1, img2)
        assert flow.shape == (1, 60, 62, 2)

        # engine (padded to the 64x64 bucket) vs direct jit on the
        # stride-aligned shape: same computation modulo edge padding
        serve = jax.jit(make_serving_fn(variables, cfg, iters=3))
        from raft_tpu.ops.padding import InputPadder
        padder = InputPadder((1, 60, 62, 3))
        i1, i2 = padder.pad(jnp.asarray(img1), jnp.asarray(img2))
        want = np.asarray(padder.unpad(serve(i1, i2)))
        np.testing.assert_allclose(flow, want, atol=2e-2, rtol=1e-2)

    def test_compile_on_miss(self, small_setup, rng):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=2, envelope=[])
        img = rng.rand(1, 40, 40, 3).astype(np.float32) * 255
        flow = eng.infer_batch(img, img)
        assert flow.shape == (1, 40, 40, 2)
        assert (1, 40, 40) in eng._compiled

    def test_weight_hotswap_reuses_executables(self, small_setup, rng):
        """Weights are executable ARGUMENTS: a checkpoint swap must change
        the output without invalidating (or recompiling) any bucket."""
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=2, envelope=[(1, 64, 64)])
        exe_before = eng._compiled[(1, 64, 64)]
        img = rng.rand(1, 64, 64, 3).astype(np.float32) * 255
        img2 = rng.rand(1, 64, 64, 3).astype(np.float32) * 255
        flow_a = eng.infer_batch(img, img2)

        scaled = jax.tree_util.tree_map(lambda p: p * 1.5, variables)
        eng.update_weights(scaled)
        flow_b = eng.infer_batch(img, img2)
        assert eng._compiled[(1, 64, 64)] is exe_before, "recompiled"
        assert np.abs(flow_a - flow_b).max() > 1e-4, (
            "new weights did not change the output")

        with pytest.raises(ValueError, match="structure mismatch"):
            eng.update_weights({"params": {}})

        # container type is part of the contract: same key paths under a
        # FrozenDict would still fail at executable call time
        from flax.core import freeze

        with pytest.raises(ValueError, match="pytree definition"):
            eng.update_weights(freeze(variables))

    def test_threaded_swap_never_mixes_a_dispatch(self, small_setup, rng):
        """The live-swap race regression: update_weights hammering from
        another thread while infer_batch dispatches must yield outputs
        that match pure-old or pure-new weights EXACTLY — never a
        mixture (the engine snapshots its weight tree once per dispatch
        under its lock; a swap lands between dispatches)."""
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[(1, 64, 64)])
        scaled = jax.tree_util.tree_map(lambda p: p * 1.5, variables)
        img1 = rng.rand(1, 64, 64, 3).astype(np.float32) * 255
        img2 = rng.rand(1, 64, 64, 3).astype(np.float32) * 255
        ref_a = eng.infer_batch(img1, img2)
        eng.update_weights(scaled)
        ref_b = eng.infer_batch(img1, img2)
        eng.update_weights(variables)

        stop = threading.Event()

        def swapper():
            flip = False
            while not stop.is_set():
                eng.update_weights(scaled if flip else variables)
                flip = not flip

        th = threading.Thread(target=swapper, name="swap-churn")
        th.start()
        try:
            for _ in range(12):
                out = eng.infer_batch(img1, img2)
                da = np.abs(out - ref_a).max()
                db = np.abs(out - ref_b).max()
                # same executable + same weight tree is deterministic
                # on CPU: a mixed dispatch shows as BOTH distances
                # being large
                assert min(da, db) < 1e-5, (
                    f"dispatch mixed old/new weights (d_old={da}, "
                    f"d_new={db})")
        finally:
            stop.set()
            th.join()

    def test_exact_shapes_mode_matches_plain_jit_bitwise(self, small_setup,
                                                         rng):
        """exact_shapes=True must never pad beyond the ÷8 rule, so its
        output is BIT-identical to the plain jitted model — the accuracy
        knob for the measured bucket-fill instance-norm artifact (a
        bucketed engine only matches approximately)."""
        import jax

        from raft_tpu.models import RAFT

        cfg, variables = small_setup
        # 52x60: not a bucket shape; a bucketed engine would route it up
        # to the 64x64 envelope bucket and fill
        img1 = rng.rand(1, 52, 60, 3).astype(np.float32) * 255
        img2 = rng.rand(1, 52, 60, 3).astype(np.float32) * 255

        eng = RAFTEngine(variables, cfg, iters=2, envelope=[(1, 64, 64)],
                         exact_shapes=True)
        got = eng.infer_batch(img1, img2)
        assert (1, 56, 64) in eng._compiled  # ÷8 pad only, no bucket
        assert (1, 64, 64) in eng._compiled  # envelope still precompiled

        from raft_tpu.ops.padding import InputPadder

        model = RAFT(cfg)
        i1 = jnp.asarray(img1)
        i2 = jnp.asarray(img2)
        padder = InputPadder(i1.shape)
        p1, p2 = padder.pad(i1, i2)
        _, flow = jax.jit(lambda v, a, b: model.apply(
            v, a, b, iters=2, test_mode=True))(variables, p1, p2)
        want = np.asarray(padder.unpad(flow))
        np.testing.assert_array_equal(got, want)

    def test_sliding_window_sequence(self, small_setup, rng):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=2, envelope=[(2, 64, 64)])
        frames = [rng.rand(64, 64, 3).astype(np.float32) * 255
                  for _ in range(4)]
        flows = eng.infer(frames, batch_size=2)
        assert len(flows) == 3
        assert flows[0].shape == (64, 64, 2)

    def test_ragged_tail_reuses_compiled_bucket(self, small_setup, rng):
        """A 5-pair sequence at batch_size=2 ends in a 1-pair tail. The
        tail must batch-fill into the executable the full chunks already
        compiled — ONE executable serves the whole sequence — in both
        the bucketed and the exact-shapes engine (the latter used to
        compile a second executable per distinct tail batch)."""
        cfg, variables = small_setup
        frames = [rng.rand(32, 32, 3).astype(np.float32) * 255
                  for _ in range(6)]

        eng = RAFTEngine(variables, cfg, iters=1, envelope=[])
        flows = eng.infer(frames, batch_size=2)
        assert len(flows) == 5
        assert len(eng._compiled) == 1, sorted(eng._compiled)

        eng2 = RAFTEngine(variables, cfg, iters=1, envelope=[],
                          exact_shapes=True)
        flows2 = eng2.infer(frames, batch_size=2)
        assert len(flows2) == 5
        assert sorted(eng2._compiled) == [(2, 32, 32)]

        # batch fill is per-sample neutral: the batch-filled tail matches
        # the tail pair computed alone to fp32 vectorization noise
        # (measured ~3e-5 px; spatial fill — the real accuracy artifact
        # — is still exact in this mode)
        alone = RAFTEngine(variables, cfg, iters=1, envelope=[],
                           exact_shapes=True).infer_batch(
            frames[-2][None], frames[-1][None])[0]
        np.testing.assert_allclose(flows2[-1], alone, atol=1e-3, rtol=1e-4)


class TestU8Wire:
    """The zero-copy wire format: uint8 host→device, on-device
    normalize, bitwise parity at integer inputs, ~4x fewer H2D bytes,
    and the async dispatch split the pipelined scheduler rides."""

    def test_bitwise_parity_across_buckets_and_warm_cold(self,
                                                         small_setup,
                                                         rng):
        """uint8→fp32 conversion is exact, so at integer-valued [0,255]
        inputs the u8 wire must be BIT-identical to the fp32 wire —
        through bucket fill (batch + spatial), cold starts, and the
        warm-start flow_init round trip."""
        cfg, variables = small_setup
        # integer-valued frames, off-bucket shape (28x30 -> pads to
        # 32x32, batch-fills the (2,...) bucket)
        i1 = rng.randint(0, 256, (1, 28, 30, 3)).astype(np.float32)
        i2 = rng.randint(0, 256, (1, 28, 30, 3)).astype(np.float32)

        f32 = RAFTEngine(variables, cfg, iters=2, envelope=[(2, 32, 32)],
                         warm_start=True)
        u8 = RAFTEngine(variables, cfg, iters=2, envelope=[(2, 32, 32)],
                        warm_start=True, wire="u8")
        flow_a, low_a = f32.infer_batch(i1, i2, return_low=True)
        # the u8 engine accepts uint8 OR integer-valued float input
        flow_b, low_b = u8.infer_batch(i1.astype(np.uint8), i2,
                                       return_low=True)
        np.testing.assert_array_equal(flow_a, flow_b)
        np.testing.assert_array_equal(low_a, low_b)
        # warm start: same flow_init, same result, same executable
        warm_a = f32.infer_batch(i1, i2, flow_init=low_a)
        warm_b = u8.infer_batch(i1, i2, flow_init=low_b)
        np.testing.assert_array_equal(warm_a, warm_b)
        assert sorted(u8._compiled) == [(2, 32, 32)]

        with pytest.raises(ValueError, match="wire"):
            RAFTEngine(variables, cfg, wire="fp16")

    def test_h2d_bytes_quarter_of_f32(self, small_setup, rng):
        """The acceptance ratio: measured H2D bytes per request on the
        u8 wire ≤ 0.3x the fp32 baseline (0.25x frames + the fp32
        flow_init riding along)."""
        cfg, variables = small_setup
        i1 = rng.randint(0, 256, (2, 32, 32, 3)).astype(np.float32)
        i2 = rng.randint(0, 256, (2, 32, 32, 3)).astype(np.float32)
        f32 = RAFTEngine(variables, cfg, iters=1, envelope=[(2, 32, 32)],
                         warm_start=True)
        u8 = RAFTEngine(variables, cfg, iters=1, envelope=[(2, 32, 32)],
                        warm_start=True, wire="u8")
        pa = f32.infer_batch_async(i1, i2)
        pb = u8.infer_batch_async(i1, i2)
        ratio = pb.h2d_bytes / pa.h2d_bytes
        assert ratio <= 0.3, f"h2d ratio {ratio} above the 0.3 ceiling"
        pa.fetch(), pb.fetch()
        # wire="u8" pins uint8 PARAMS in the executable — the padding
        # path never widened on the host
        assert "u8[2,32,32,3]" in u8._compiled[(2, 32, 32)].as_text()

    def test_async_api_matches_sync_and_defers_blocking(self,
                                                        small_setup,
                                                        rng):
        """infer_batch IS infer_batch_async().fetch(): same numbers,
        and the async call must return before the result is readable
        (t_ready only set by fetch)."""
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=2, envelope=[(1, 64, 64)])
        i1 = rng.rand(1, 64, 64, 3).astype(np.float32) * 255
        i2 = rng.rand(1, 64, 64, 3).astype(np.float32) * 255
        want = eng.infer_batch(i1, i2)
        pending = eng.infer_batch_async(i1, i2)
        assert pending.t_ready is None
        assert pending.bucket == (1, 64, 64)
        assert pending.h2d_bytes == 2 * i1.size * 4
        got = pending.fetch()
        assert pending.t_ready is not None
        np.testing.assert_array_equal(got, want)

    def test_device_flow_init_round_trip(self, small_setup, rng):
        """A device-resident flow_init (low_device=True fetch) feeds
        straight back without touching the host and matches the host
        round trip bitwise."""
        import jax

        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[(1, 32, 32)],
                         warm_start=True, wire="u8")
        i1 = rng.randint(0, 256, (1, 32, 32, 3)).astype(np.uint8)
        i2 = rng.randint(0, 256, (1, 32, 32, 3)).astype(np.uint8)
        _, low_host = eng.infer_batch(i1, i2, return_low=True)
        p = eng.infer_batch_async(i1, i2, return_low=True,
                                  low_device=True)
        _, low_dev = p.fetch()
        assert isinstance(low_dev, jax.Array)
        warm_host = eng.infer_batch(i1, i2, flow_init=low_host)
        warm_dev = eng.infer_batch(i1, i2, flow_init=low_dev)
        np.testing.assert_array_equal(warm_host, warm_dev)

    def test_donating_fetch_returns_decoupled_flow_low(self, small_setup,
                                                       rng):
        """The order-dependent full-suite landmine (PR 8): on a
        donating engine (u8 warm) flow_low IS the donated flow_init
        buffer and a full-extent crop short-circuits to the same
        array, so fetch() used to hand callers views/handles of a
        donation-target buffer whose owning references it had just
        dropped. Pin the fix: the returned flow_low (host AND device)
        must be ready, independent storage — never the executable's
        aliased output buffer."""
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[(1, 32, 32)],
                         warm_start=True, wire="u8")
        i1 = rng.randint(0, 256, (1, 32, 32, 3)).astype(np.uint8)
        i2 = rng.randint(0, 256, (1, 32, 32, 3)).astype(np.uint8)
        p = eng.infer_batch_async(i1, i2, return_low=True,
                                  low_device=True)
        raw_low = p._flow_low       # the aliased executable output
        _, low_dev = p.fetch()
        assert isinstance(low_dev, jax.Array)
        assert low_dev is not raw_low
        assert (low_dev.unsafe_buffer_pointer()
                != raw_low.unsafe_buffer_pointer())
        # host path: the numpy flow_low must not be a zero-copy VIEW
        # of the executable's aliased output buffer (np.asarray of a
        # CPU jax array is zero-copy — a view of the copy is fine, a
        # view of the donation target is the landmine)
        p3 = eng.infer_batch_async(i1, i2, return_low=True)
        raw3 = p3._flow_low
        _, low_host = p3.fetch()
        assert isinstance(low_host, np.ndarray)
        assert low_host.ctypes.data != raw3.unsafe_buffer_pointer()
        # the f32 (non-donating) path keeps its zero-overhead contract:
        # no copy is forced on fetch
        f32 = RAFTEngine(variables, cfg, iters=1, envelope=[(1, 32, 32)],
                         warm_start=True)
        pf = f32.infer_batch_async(i1, i2, return_low=True,
                                   low_device=True)
        assert pf._donated is False
        pf.fetch()


class TestMeshServing:
    def test_sharded_engine_matches_single_device(self, small_setup, rng):
        """Multi-chip serving: an engine over the (data x spatial) mesh
        must produce the single-device engine's flow (the serving-side
        counterpart of the train-step sharding-equivalence check)."""
        from raft_tpu.parallel.mesh import make_mesh

        cfg, variables = small_setup
        img1 = rng.rand(2, 64, 64, 3).astype(np.float32) * 255
        img2 = rng.rand(2, 64, 64, 3).astype(np.float32) * 255

        ref = RAFTEngine(variables, cfg, iters=2,
                         envelope=[]).infer_batch(img1, img2)
        mesh = make_mesh(4, spatial=2)
        eng = RAFTEngine(variables, cfg, iters=2, envelope=[], mesh=mesh)
        got = eng.infer_batch(img1, img2)
        # compile-on-miss under the mesh keeps whole examples per device
        assert (2, 64, 64) in eng._compiled
        # tolerance: measured SPMD reduction-order noise at random-init
        # weights is ≤6e-3 abs on O(300) flows (data-only sharding alone
        # shows half of it); a real partitioning bug is O(10) flow units
        # (the r1 spatial miscompile was 43)
        np.testing.assert_allclose(got, ref, atol=0.05, rtol=1e-4)

    def test_sharded_miss_rounds_height_to_spatial_axis(self, small_setup,
                                                        rng):
        """A 72-px image on a spatial=2 mesh has 9 feature rows — not
        divisible by the axis — so the ad-hoc bucket must round height up
        (to 80) rather than refuse, and crop the output back."""
        from raft_tpu.parallel.mesh import make_mesh

        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[],
                         mesh=make_mesh(4, spatial=2))
        img = rng.rand(1, 72, 64, 3).astype(np.float32) * 255
        flow = eng.infer_batch(img, img)
        assert flow.shape == (1, 72, 64, 2)
        assert (2, 80, 64) in eng._compiled  # b->data axis, h->8*spatial

    def test_envelope_bucket_must_be_mesh_divisible(self, small_setup):
        """A user-supplied envelope bucket whose batch doesn't divide the
        'data' axis (or height the 8*spatial grain) would compile fine and
        only explode later at device_put with an uneven-sharding error —
        reject it at compile time with a readable message instead."""
        from raft_tpu.parallel.mesh import make_mesh

        cfg, variables = small_setup
        mesh = make_mesh(4, spatial=2)
        with pytest.raises(ValueError, match="not mesh-divisible"):
            RAFTEngine(variables, cfg, iters=1, envelope=[(1, 64, 64)],
                       mesh=mesh)
        # h=68 passes validate_spatial_extent (68//8=8 rows, even over
        # spatial=2) but is not a multiple of 8*spatial
        with pytest.raises(ValueError, match="not mesh-divisible"):
            RAFTEngine(variables, cfg, iters=1, envelope=[(2, 68, 64)],
                       mesh=mesh)
        # a divisible bucket still compiles
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[(2, 64, 64)],
                         mesh=mesh)
        assert (2, 64, 64) in eng._compiled

    def test_warm_start_mesh_engine_flow_low_roundtrip(self, small_setup,
                                                       rng):
        """warm_start under a mesh: the 1/8-res flow_init input shards
        with the same batch+spatial spec (bucket h % 8*spatial == 0
        makes h/8 divide the axis), the returned flow_low feeds back as
        the next call's warm start, and warm + cold calls share ONE
        executable (zero flow_init IS cold start)."""
        from raft_tpu.parallel.mesh import make_mesh

        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[],
                         mesh=make_mesh(4, spatial=2), warm_start=True)
        img1 = rng.rand(2, 64, 64, 3).astype(np.float32) * 255
        img2 = rng.rand(2, 64, 64, 3).astype(np.float32) * 255
        flow, low = eng.infer_batch(img1, img2, return_low=True)
        assert flow.shape == (2, 64, 64, 2) and low.shape == (2, 8, 8, 2)
        warm = eng.infer_batch(img1, img2, flow_init=low)
        assert sorted(eng._compiled) == [(2, 64, 64)]
        assert not np.array_equal(flow, warm)  # the start point moved

        # engine-direct contract: a cold engine rejects the warm args
        cold = RAFTEngine(variables, cfg, iters=1, envelope=[])
        with pytest.raises(ValueError, match="warm_start"):
            cold.infer_batch(img1, img2, return_low=True)

    def test_sharded_engine_bitwise_vs_bucket_batch1_oracle(self,
                                                            small_setup):
        """The graftshard-PR parity pin, same geometry discipline as
        the PR-12/PR-13 pins: the pjit-sharded engine with batch
        sharded over a data-only (1×N-style) CPU mesh, at bucket batch
        == the axis size (one request per device), is BITWISE the
        single-device bucket path at bucket-batch-1 integer inputs —
        SPMD partitioning itself adds zero numeric noise; each shard
        runs exactly the per-device program.

        The single-device oracle compiles ``split_encode=True``: that
        IS the mesh program's per-device form (mesh_model_config turns
        it on for data>1). Against the DEFAULT concat-encode path the
        fnet convs run at total batch 2 instead of 1, which moves
        XLA-CPU conv bits (the established batch-width caveat) — that
        leg is pinned approximately, not bitwise."""
        import dataclasses

        from raft_tpu.parallel.mesh import make_mesh

        cfg, variables = small_setup
        h = w = 32
        rng = np.random.RandomState(7)
        i1 = rng.randint(0, 256, (2, h, w, 3)).astype(np.float32)
        i2 = rng.randint(0, 256, (2, h, w, 3)).astype(np.float32)

        mesh = make_mesh(2, spatial=1)
        eng = RAFTEngine(variables, cfg, iters=2,
                         envelope=[(2, h, w)], precompile=True,
                         mesh=mesh)
        flows = eng.infer_batch(i1, i2)

        oracle = RAFTEngine(variables,
                            dataclasses.replace(cfg, split_encode=True),
                            iters=2, exact_shapes=True)
        for r in range(2):
            ref = oracle.infer_batch(i1[r:r + 1], i2[r:r + 1])[0]
            assert np.array_equal(flows[r], ref), \
                f"sharded row {r} is not bitwise the bucket-batch-1 " \
                f"oracle (max abs {np.abs(flows[r] - ref).max()})"
        # the concat-encode leg: same math, conv-batch-width bit noise
        # only (a partitioning bug is orders of magnitude larger)
        concat = RAFTEngine(variables, cfg, iters=2, exact_shapes=True)
        for r in range(2):
            ref = concat.infer_batch(i1[r:r + 1], i2[r:r + 1])[0]
            np.testing.assert_allclose(flows[r], ref, atol=1e-2)

    def test_sharded_engine_rejects_thin_spatial_shards(self, small_setup,
                                                       rng):
        from raft_tpu.parallel.mesh import make_mesh

        cfg, variables = small_setup
        mesh = make_mesh(8, spatial=4)
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[], mesh=mesh)
        img = rng.rand(1, 64, 64, 3).astype(np.float32) * 255
        with pytest.raises(ValueError, match="feature rows per shard"):
            eng.infer_batch(img, img)  # 64/8 rows / 4 shards = 2 <= halo


class TestStableHLOExport:
    def test_roundtrip_matches_jit(self, small_setup, rng):
        cfg, variables = small_setup
        blob = export_stablehlo(variables, cfg, iters=2, image_hw=(64, 64),
                                dynamic_batch=False)
        assert isinstance(blob, bytes) and len(blob) > 0
        restored = load_stablehlo(blob)

        img1 = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 64, 64, 3).astype(np.float32) * 255)
        got = np.asarray(restored(img1, img2))
        want = np.asarray(jax.jit(make_serving_fn(variables, cfg, 2))(
            img1, img2))
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


class TestVideo:
    def test_writes_avi(self, tmp_path, rng):
        flows = [rng.randn(32, 48, 2).astype(np.float32) for _ in range(3)]
        imgs = [rng.rand(32, 48, 3).astype(np.float32) * 255 for _ in range(3)]
        out = optical_flow_visualize(flows, str(tmp_path / "f.avi"),
                                     images=imgs)
        assert os.path.exists(out) and os.path.getsize(out) > 0
