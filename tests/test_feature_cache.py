"""Cross-frame device feature cache (PR 12): pool semantics, cached
engine/scheduler/session plumbing, bitwise cached-vs-uncached parity,
and the registry weight-swap flush drill.

Parity note (pinned in TestEncoderBatchBits): XLA CPU conv bits move
with the feature net's TOTAL batch size once it crosses the
vectorization width (batch 1 == batch 2, 2 != 4). The uncached serve
runs fnet at 2*bucket_batch, the cached serve at bucket_batch — so the
bitwise cached-vs-uncached pin is exact at the bucket-batch-1 serving
geometry (the steady-state single-stream case) on the BASIC model, and
allclose-tight elsewhere.
"""

import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.ops.interp import forward_interpolate_device
from raft_tpu.serving.engine import RAFTEngine, StaleFeatureError
from raft_tpu.serving.feature_cache import (FeatureCacheMiss,
                                            FeatureCachePool)
from raft_tpu.serving.scheduler import MicroBatchScheduler
from raft_tpu.serving.session import VideoSession


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return cfg, variables


@pytest.fixture(scope="module")
def small_cached_engine(small_setup):
    """One shared feature-cache engine (small, 32x32, iters=1): the
    scheduler/session tests reuse its compiles."""
    cfg, variables = small_setup
    return RAFTEngine(variables, cfg, iters=1, envelope=[(2, 32, 32)],
                      precompile=True, warm_start=True,
                      feature_cache=True)


def _frames(rng, n, h=32, w=32):
    return [rng.randint(0, 256, (h, w, 3)).astype(np.float32)
            for _ in range(n)]


class TestFeatureCachePool:
    def test_store_acquire_roundtrip_and_counters(self):
        pool = FeatureCachePool(capacity=4)
        pool.store("s1", (32, 32), seq=1, version=0, fmap="F", ctx="C",
                   flow_low="L")
        assert pool.valid("s1", (32, 32), 1)
        slot = pool.acquire("s1", (32, 32), 1, 0)
        assert (slot.fmap, slot.ctx, slot.flow_low) == ("F", "C", "L")
        snap = pool.snapshot()
        assert snap["hits"] == 1 and snap["misses"] == 0
        assert snap["stores"] == 1 and snap["occupancy"] == 1
        assert snap["hit_rate"] == 1.0

    @pytest.mark.parametrize("key,seq,version", [
        ((48, 32), 1, 0),    # geometry change
        ((32, 32), 2, 0),    # seq hole (missed store)
        ((32, 32), 1, 1),    # weight swap
    ])
    def test_mismatch_drops_slot_and_counts_stale(self, key, seq,
                                                  version):
        pool = FeatureCachePool(capacity=4)
        pool.store("s1", (32, 32), seq=1, version=0, fmap=1, ctx=2,
                   flow_low=3)
        assert pool.acquire("s1", key, seq, version) is None
        snap = pool.snapshot()
        assert snap["stale"] == 1 and snap["misses"] == 1
        # the slot can never become valid again: it is GONE
        assert snap["occupancy"] == 0
        assert pool.acquire("s1", (32, 32), 1, 0) is None

    def test_lru_eviction_order_and_capacity_bound(self):
        pool = FeatureCachePool(capacity=2)
        for i, s in enumerate(("a", "b", "c")):
            pool.store(s, (32, 32), seq=1, version=0, fmap=i, ctx=i,
                       flow_low=None)
        snap = pool.snapshot()
        assert snap["occupancy"] == 2 and snap["evictions"] == 1
        assert not pool.valid("a", (32, 32), 1)      # oldest evicted
        # touching "b" promotes it: "c" becomes LRU and dies next
        assert pool.acquire("b", (32, 32), 1, 0) is not None
        pool.store("d", (32, 32), seq=1, version=0, fmap=9, ctx=9,
                   flow_low=None)
        assert pool.valid("b", (32, 32), 1)
        assert not pool.valid("c", (32, 32), 1)

    def test_flush_and_invalidate(self):
        pool = FeatureCachePool(capacity=4)
        pool.store("a", (32, 32), 1, 0, 1, 1, None)
        pool.store("b", (32, 32), 1, 0, 2, 2, None)
        assert pool.invalidate("a") and not pool.invalidate("a")
        assert pool.flush() == 1
        snap = pool.snapshot()
        assert snap["flushes"] == 1 and snap["occupancy"] == 0
        assert len(pool) == 0

    def test_record_miss_and_capacity_validation(self):
        with pytest.raises(ValueError):
            FeatureCachePool(capacity=0)
        pool = FeatureCachePool(capacity=1)
        pool.record_miss()
        pool.record_miss(stale=True)
        snap = pool.snapshot()
        assert snap["misses"] == 2 and snap["stale"] == 1
        assert snap["hit_rate"] == 0.0

    def test_thread_safety_smoke(self):
        pool = FeatureCachePool(capacity=8)
        errs = []

        def worker(wid):
            try:
                for i in range(200):
                    pool.store(f"s{wid}", (32, 32), i, 0, i, i, None)
                    pool.acquire(f"s{wid}", (32, 32), i, 0)
            except Exception as exc:          # pragma: no cover
                errs.append(exc)

        ts = [threading.Thread(target=worker, args=(k,))
              for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        assert len(pool) <= 8


class TestEncoderBatchBits:
    def test_fnet_bits_move_with_total_batch(self, small_setup):
        """The parity pin's platform premise (see module docstring):
        per-row encoder bits are batch-size-invariant from 1 to 2 but
        not beyond — which is why the bitwise cached-vs-uncached pin
        lives at bucket_batch=1 (uncached fnet batch 2 vs cached 1)."""
        cfg = RAFTConfig()
        model = RAFT(cfg)
        rng = np.random.RandomState(0)
        imgs = jnp.asarray(
            rng.randint(0, 256, (4, 32, 32, 3)).astype(np.float32))
        variables = model.init(jax.random.PRNGKey(0), imgs[:1],
                               imgs[:1], iters=1)

        def enc(v, x):
            x = 2.0 * (x.astype(jnp.float32) / 255.0) - 1.0
            return model.apply(
                v, x, train=False, use_running_average=True,
                method=lambda m, x, train, use_running_average:
                m.fnet(x, train=train,
                       use_running_average=use_running_average))

        je = jax.jit(enc)
        o1 = je(variables, imgs[:1])
        o2 = je(variables, imgs[:2])
        o4 = je(variables, imgs)
        assert bool(jnp.all(o2[:1] == o1)), \
            "batch 1 vs 2 drifted — the bb=1 bitwise pin just broke"
        # informational premise: >2 is allowed to (and does) differ at
        # fp32 noise; if THIS ever becomes bitwise too, the parity pin
        # can extend to larger buckets
        assert float(jnp.max(jnp.abs(o4[:1] - o1))) < 1e-4


class TestCachedEngine:
    def test_feature_cache_requires_warm_start(self, small_setup):
        cfg, variables = small_setup
        with pytest.raises(ValueError, match="warm_start"):
            RAFTEngine(variables, cfg, feature_cache=True)

    def test_prime_then_pair_one_cached_executable(
            self, small_cached_engine, rng):
        eng = small_cached_engine
        f = _frames(rng if hasattr(rng, "randint")
                    else np.random.RandomState(0), 3)
        flow, low, fm, cn = eng.infer_cached(np.stack(f[:2]),
                                             [None, None])
        assert flow.shape == (2, 32, 32, 2)
        assert isinstance(fm, jax.Array) and isinstance(cn, jax.Array)
        lh, lw = 4, 4
        slot = (fm[0, :lh, :lw], cn[0, :lh, :lw], None)
        flow2, low2, _, _ = eng.infer_cached(np.stack(f[1:3]),
                                             [slot, None])
        assert np.isfinite(flow2).all()
        assert len(eng._compiled_cached) == 1
        assert eng.executable_count() == \
            len(eng._compiled) + len(eng._compiled_cached)

    def test_stale_version_guard(self, small_setup, rng):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1,
                         envelope=[(1, 32, 32)], precompile=True,
                         warm_start=True, feature_cache=True)
        f = _frames(np.random.RandomState(1), 2)
        _, _, fm, cn = eng.infer_cached(f[0][None], [None])
        eng.update_weights(variables)     # version 0 -> 1
        with pytest.raises(StaleFeatureError):
            eng.infer_cached(f[1][None],
                             [(fm[0, :4, :4], cn[0, :4, :4], None)],
                             expect_version=0)

    def test_cache_outputs_survive_input_release(self, small_cached_engine):
        """The PR-10 donated-alias regression, cached form: every
        device output of a cached fetch aliases a DONATED input
        buffer. What the caller must get are the call's OWNING result
        arrays — still valid (correct bits) after the PendingBatch
        released its input pins and fresh allocations churned the
        allocator. Per-row pool slices must be fresh buffers, not
        views of the full output."""
        eng = small_cached_engine
        rng = np.random.RandomState(2)
        f = _frames(rng, 2)
        _, _, fm_a, cn_a = eng.infer_cached(np.stack(f), [None, None])
        ref_fm = np.asarray(fm_a)         # reference bits, copied out
        row = fm_a[0, :4, :4]             # the pool's slice form
        assert (row.unsafe_buffer_pointer()
                != fm_a.unsafe_buffer_pointer())
        # allocation pressure + more donating dispatches over the same
        # executable: a use-after-donation would scribble these bits
        junk = [np.ones((256, 1024), np.float32) for _ in range(8)]
        for _ in range(3):
            eng.infer_cached(np.stack(f), [None, None])
        del junk
        np.testing.assert_array_equal(np.asarray(fm_a), ref_fm)
        np.testing.assert_array_equal(np.asarray(row), ref_fm[0, :4, :4])

    def test_u8_wire_cached_bitwise_vs_f32_cached(self, small_setup):
        """wire='u8' composes with the cached signature: uint8->f32 is
        exact, so at integer inputs the cached u8 program is bitwise
        the cached f32 program."""
        cfg, variables = small_setup
        rng = np.random.RandomState(3)
        f = [rng.randint(0, 256, (1, 32, 32, 3)) for _ in range(2)]
        outs = {}
        for wire in ("f32", "u8"):
            eng = RAFTEngine(variables, cfg, iters=1,
                             envelope=[(1, 32, 32)], precompile=True,
                             warm_start=True, wire=wire,
                             feature_cache=True)
            _, _, fm, cn = eng.infer_cached(
                f[0].astype(np.uint8 if wire == "u8" else np.float32),
                [None])
            flow, _, _, _ = eng.infer_cached(
                f[1].astype(np.uint8 if wire == "u8" else np.float32),
                [(fm[0, :4, :4], cn[0, :4, :4], None)])
            outs[wire] = flow
        np.testing.assert_array_equal(outs["f32"], outs["u8"])


class TestCachedParityBasic:
    """Bitwise cached-vs-uncached at the bucket-batch-1 geometry,
    BASIC model (the export arch), integer inputs — across cold,
    warm, and evicted/re-primed rows."""

    @pytest.fixture(scope="class")
    def basic_engines(self):
        cfg = RAFTConfig()
        model = RAFT(cfg)
        img = jnp.zeros((1, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), img, img,
                               iters=1)
        eng = RAFTEngine(variables, cfg, iters=2,
                         envelope=[(1, 32, 32)], precompile=True,
                         warm_start=True, feature_cache=True)
        return cfg, eng

    def test_bitwise_cold_warm_evicted(self, basic_engines):
        cfg, eng = basic_engines
        rng = np.random.RandomState(0)
        f = _frames(rng, 4)
        lh = lw = 4

        # uncached reference chain: cold pair, warm pair (device
        # warp — the same op the cached assembly uses), then a cold
        # restart at (f2, f3)
        ref1, rlow1 = eng.infer_batch(f[0][None], f[1][None],
                                      return_low=True)
        rwarm = forward_interpolate_device(jnp.asarray(rlow1[0]))[None]
        ref2, rlow2 = eng.infer_batch(f[1][None], f[2][None],
                                      flow_init=rwarm, return_low=True)
        ref3 = eng.infer_batch(f[2][None], f[3][None])

        # cached chain: prime f0; pair f1 (cold recurrence); warm pair
        # f2; then "evicted" — re-prime f2 and serve (f2, f3) cold
        _, _, fm0, cn0 = eng.infer_cached(f[0][None], [None])
        c1, clow1, fm1, cn1 = eng.infer_cached(
            f[1][None], [(fm0[0, :lh, :lw], cn0[0, :lh, :lw], None)])
        np.testing.assert_array_equal(c1, ref1)
        np.testing.assert_array_equal(np.asarray(clow1), rlow1)
        cwarm = forward_interpolate_device(clow1[0, :lh, :lw])
        c2, _, fm2, cn2 = eng.infer_cached(
            f[2][None], [(fm1[0, :lh, :lw], cn1[0, :lh, :lw], cwarm)])
        np.testing.assert_array_equal(c2, ref2)
        _, _, fm2b, cn2b = eng.infer_cached(f[2][None], [None])
        c3, _, _, _ = eng.infer_cached(
            f[3][None], [(fm2b[0, :lh, :lw], cn2b[0, :lh, :lw], None)])
        np.testing.assert_array_equal(c3, ref3)
        # the whole drill rode exactly one cached + one plain program
        assert len(eng._compiled_cached) == 1
        assert len(eng._compiled) == 1


class TestCachedScheduler:
    def test_session_stream_warm_and_metrics_schema(
            self, small_cached_engine, tmp_path):
        eng = small_cached_engine
        mpath = os.path.join(str(tmp_path), "metrics.jsonl")
        sched = MicroBatchScheduler(eng, max_batch=2,
                                    gather_window_s=0.0,
                                    feature_cache=True,
                                    feature_cache_capacity=4,
                                    metrics_path=mpath)
        sess = VideoSession(sched, feature_cache=True)
        rng = np.random.RandomState(4)
        futs = []
        for fr in _frames(rng, 5):
            fut = sess.submit_frame(fr)
            if fut is not None:
                futs.append(fut)
        assert len(futs) == 4
        for fut in futs:
            res = fut.result(timeout=600)
            assert res.flow.shape == (32, 32, 2)
            assert res.flow_low is None   # state lives pool-side
        assert sess.warm_submits == 4
        snap = sched.metrics.snapshot(
            executables=sched.executable_count())
        fc = snap["feature_cache"]
        assert {"capacity", "occupancy", "hits", "misses", "stale",
                "evictions", "flushes", "stores",
                "hit_rate"} <= set(fc)
        assert fc["hit_rate"] == 1.0 and fc["misses"] == 0
        assert fc["occupancy"] == 1
        sched.close()
        # close flushes (retired schedulers must not pin device state)
        # and the event landed in the shared metrics.jsonl
        events = [json.loads(ln) for ln in open(mpath)
                  if "cache_flush" in ln]
        assert events and events[-1]["reason"] == "close"
        assert len(sched._fcache) == 0

    def test_video_only_traffic_compiles_no_plain_bucket(
            self, small_setup):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, warm_start=True,
                         feature_cache=True)
        with MicroBatchScheduler(eng, max_batch=2, gather_window_s=0.0,
                                 feature_cache=True) as sched:
            sess = VideoSession(sched, feature_cache=True)
            rng = np.random.RandomState(5)
            for fr in _frames(rng, 3):
                fut = sess.submit_frame(fr)
                if fut is not None:
                    fut.result(timeout=600)
        assert len(eng._compiled) == 0
        assert len(eng._compiled_cached) == 1

    def test_lru_churn_two_streams_capacity_one(self,
                                                small_cached_engine):
        """Capacity 1, two interleaved streams: every pair beyond the
        first interleaving misses, re-primes, and still serves — the
        capacity bound holds and degradation is churn, not failure."""
        eng = small_cached_engine
        with MicroBatchScheduler(eng, max_batch=2, gather_window_s=0.0,
                                 feature_cache=True,
                                 feature_cache_capacity=1) as sched:
            a = VideoSession(sched, feature_cache=True)
            b = VideoSession(sched, feature_cache=True)
            rng = np.random.RandomState(6)
            pairs = 0

            def run(sess, n):
                nonlocal pairs
                for fr in _frames(rng, n):
                    fut = sess.submit_frame(fr)
                    if fut is not None:
                        assert np.isfinite(
                            fut.result(timeout=600).flow).all()
                        pairs += 1

            # phased interleave: each phase evicts the other stream's
            # slot, so every stream switch is a miss -> re-prime ->
            # serve round trip (deterministic — a TIGHT interleave can
            # also fail a queued pair whose slot gets evicted before
            # dispatch; that surfaces as FeatureCacheMiss on the
            # future and the session re-primes, same contract)
            run(a, 3)            # prime + 2 pairs, slot a
            run(b, 2)            # prime + 1 pair, evicts a
            run(a, 2)            # miss -> re-prime f2 -> 2 pairs
            run(b, 1)            # miss -> re-prime -> 1 pair
            snap = sched._fcache.snapshot()
            assert pairs == 6
            assert snap["evictions"] > 0
            assert snap["misses"] >= 2        # the two stream switches
            assert snap["occupancy"] <= 1

    def test_failed_pair_leaves_seq_hole_then_recovers(
            self, small_cached_engine):
        """A failed pair stores nothing; the pool's seq-exact validity
        turns that into a clean miss and the session re-primes — the
        stream never correlates against the wrong frame's features."""
        from raft_tpu.testing import faults

        eng = small_cached_engine
        with MicroBatchScheduler(eng, max_batch=2,
                                 gather_window_s=0.0,
                                 feature_cache=True) as sched:
            sess = VideoSession(sched, feature_cache=True)
            rng = np.random.RandomState(7)
            f = _frames(rng, 4)
            assert sess.submit_frame(f[0]) is None    # prime
            fut1 = sess.submit_frame(f[1])
            fut1.result(timeout=600)
            # fail exactly the next micro-batch (the (f1, f2) pair)
            faults.arm([{"site": "serve.request", "kind": "raise",
                         "count": 1}])
            try:
                fut2 = sess.submit_frame(f[2])
                with pytest.raises(Exception):
                    fut2.result(timeout=600)
            finally:
                faults.disarm()
            # pair (f2, f3): slot is at seq 2 (from fut1's store), the
            # pair needs seq 3 -> miss -> session re-primes f2 and
            # serves the pair; the stream self-heals
            fut3 = sess.submit_frame(f[3])
            assert np.isfinite(fut3.result(timeout=600).flow).all()
            snap = sched._fcache.snapshot()
            assert snap["misses"] >= 1

    def test_weights_swap_flushes_and_stale_never_feeds(
            self, small_setup, tmp_path):
        """scheduler.update_weights: pool flushed + cache_flush event;
        and a DIRECT engine swap (bypassing the flush broom) is caught
        by the weights-version stamp — the queued pair fails with
        FeatureCacheMiss instead of feeding old-weight features to the
        new model, then the stream re-primes."""
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1,
                         envelope=[(2, 32, 32)], precompile=True,
                         warm_start=True, feature_cache=True)
        mpath = os.path.join(str(tmp_path), "metrics.jsonl")
        with MicroBatchScheduler(eng, max_batch=2,
                                 gather_window_s=0.0,
                                 feature_cache=True,
                                 metrics_path=mpath) as sched:
            sess = VideoSession(sched, feature_cache=True)
            rng = np.random.RandomState(8)
            f = _frames(rng, 6)
            sess.submit_frame(f[0])
            sess.submit_frame(f[1]).result(timeout=600)
            # broom: scheduler-level swap flushes the pool
            sched.update_weights(
                jax.tree_util.tree_map(lambda p: p * 1.01, variables))
            assert len(sched._fcache) == 0
            events = [json.loads(ln) for ln in open(mpath)
                      if "cache_flush" in ln]
            assert events[-1]["reason"] == "weights_swap"
            # session recovers: miss at submit -> re-prime -> pair
            fut = sess.submit_frame(f[2])
            assert np.isfinite(fut.result(timeout=600).flow).all()
            # backstop: a DIRECT engine swap (no flush) — the stored
            # slot's version stamp no longer matches, so the pair
            # fails with the cold-restart signal, never stale-feeds
            sess.submit_frame(f[3]).result(timeout=600)
            eng.update_weights(
                jax.tree_util.tree_map(lambda p: p * 1.02, variables))
            fut = sess.submit_frame(f[4])
            with pytest.raises(FeatureCacheMiss):
                fut.result(timeout=600)
            assert sched._fcache.snapshot()["stale"] >= 1
            fut = sess.submit_frame(f[5])
            assert np.isfinite(fut.result(timeout=600).flow).all()


class TestSessionContracts:
    def test_same_route_key_sessions_never_share_a_stream(
            self, small_setup):
        """Two sessions constructed with the SAME sticky route_key
        must not share a pool slot: their independent frame counters
        would collide on seq and silently correlate one video's frame
        against the other's cached features (review-caught)."""
        from raft_tpu.serving.registry import ModelRegistry

        cfg, variables = small_setup
        with ModelRegistry(max_batch=2, gather_window_s=0.0) as reg:
            reg.add_model("m", variables, cfg, iters=1,
                          envelope=[(2, 32, 32)], warm_start=True,
                          feature_cache=True)
            a = VideoSession(reg, model="m", feature_cache=True,
                             route_key="user-42")
            b = VideoSession(reg, model="m", feature_cache=True,
                             route_key="user-42")
            assert a._stream != b._stream
            rng = np.random.RandomState(10)
            fa, fb = _frames(rng, 2), _frames(rng, 2)
            a.submit_frame(fa[0])
            b.submit_frame(fb[0])
            ra = a.submit_frame(fa[1]).result(timeout=600)
            rb = b.submit_frame(fb[1]).result(timeout=600)
            assert np.isfinite(ra.flow).all()
            assert np.isfinite(rb.flow).all()
            # the two streams' flows differ (each correlated against
            # ITS OWN first frame, not a shared slot)
            assert np.abs(ra.flow - rb.flow).max() > 0

    def test_retry_budget_applies_to_cached_submits(self):
        """The cached path honors the session retry budget: transient
        BackpressureError absorbed through backoff, exhaustion
        re-raises the ORIGINAL rejection (review-caught)."""
        from concurrent.futures import Future

        from raft_tpu.serving.scheduler import (BackpressureError,
                                                ServeResult)

        class StubSched:
            def __init__(self, failures):
                self.failures = failures
                self.calls = 0

            def submit_cached(self, frame, **kw):
                self.calls += 1
                if self.failures:
                    self.failures -= 1
                    raise BackpressureError("full")
                fut = Future()
                fut.set_result(ServeResult(None, None))
                return fut

        slept = []
        sched = StubSched(failures=2)
        sess = VideoSession(sched, feature_cache=True, retry_budget=3,
                            retry_jitter=0.0,
                            retry_sleep=slept.append)
        assert sess.submit_frame(np.zeros((32, 32, 3))) is None
        assert sched.calls == 3 and sess.retries_used == 2
        assert len(slept) == 2
        # exhaustion: more failures than remaining budget -> the
        # ORIGINAL exception surfaces
        sched2 = StubSched(failures=10)
        sess2 = VideoSession(sched2, feature_cache=True,
                             retry_budget=2, retry_jitter=0.0,
                             retry_sleep=lambda _s: None)
        with pytest.raises(BackpressureError):
            sess2.submit_frame(np.zeros((32, 32, 3)))

    def test_drain_releases_the_pool_slot(self, small_cached_engine):
        """A finished stream must not occupy pool capacity: drain()
        harvests the last dispatch, drops the slot, and returns None
        (state never materializes to host on the cached path)."""
        with MicroBatchScheduler(small_cached_engine, max_batch=2,
                                 gather_window_s=0.0,
                                 feature_cache=True) as sched:
            sess = VideoSession(sched, feature_cache=True)
            rng = np.random.RandomState(11)
            for fr in _frames(rng, 3):
                sess.submit_frame(fr)
            assert sess.drain() is None
            assert len(sched._fcache) == 0

    def test_submit_cached_on_closed_scheduler_says_closed(
            self, small_cached_engine):
        """Closed-first ordering: a closed scheduler must raise
        SchedulerClosed, never a spurious FeatureCacheMiss (the
        registry re-route catches only the former; review-caught)."""
        from raft_tpu.serving.scheduler import SchedulerClosed

        sched = MicroBatchScheduler(small_cached_engine, max_batch=2,
                                    gather_window_s=0.0,
                                    feature_cache=True)
        sched.close()
        with pytest.raises(SchedulerClosed):
            sched.submit_cached(np.zeros((32, 32, 3)), stream="s",
                                seq=2)


class TestRegistryFlushDrill:
    def test_promote_flushes_and_stream_restarts_clean(
            self, small_setup, tmp_path):
        """The PR-9 variant_version regression, extended to encoder
        state: a same-arch promote must flush the live pool (stamped
        cache_flush event), the session must cold-restart, and the
        post-promote pair must be BITWISE what a fresh stream under
        the new weights computes — stale canary-era features never
        feed the promoted model."""
        from raft_tpu.serving.registry import ModelRegistry

        cfg, variables = small_setup
        v2 = jax.tree_util.tree_map(lambda p: p * 1.05, variables)
        mpath = os.path.join(str(tmp_path), "metrics.jsonl")
        rng = np.random.RandomState(9)
        f = _frames(rng, 4)
        with ModelRegistry(metrics_path=mpath, max_batch=2,
                           gather_window_s=0.0) as reg:
            reg.add_model("m", variables, cfg, iters=1,
                          envelope=[(2, 32, 32)], warm_start=True,
                          feature_cache=True)
            sess = VideoSession(reg, model="m", feature_cache=True)
            assert sess.submit_frame(f[0]) is None
            sess.submit_frame(f[1]).result(timeout=600)
            reg.deploy("m", v2, canary_fraction=0.01)
            reg.promote("m")
            events = [json.loads(ln) for ln in open(mpath)
                      if "cache_flush" in ln]
            assert any(e["reason"] == "promote" and e["model"] == "m"
                       and e["version"] == "v2" for e in events)
            # the session polls variant_version: the promote moved it,
            # so the next frame cold-restarts (returns None, re-primes)
            assert sess.submit_frame(f[2]) is None
            got = sess.submit_frame(f[3]).result(timeout=600).flow
            # reference: a FRESH stream under the promoted weights
            fresh = VideoSession(reg, model="m", feature_cache=True)
            assert fresh.submit_frame(f[2]) is None
            want = fresh.submit_frame(f[3]).result(timeout=600).flow
            np.testing.assert_array_equal(got, want)

    def test_registry_cached_chaos_soak(self, small_setup):
        """Chaos over the cached path: randomized raise/hang plans
        with feature-cache sessions in flight — zero stranded, the
        accounting identity, no leaked slots (bounded pool)."""
        from raft_tpu.cli.serve_bench import run_chaos_drill

        cfg, variables = small_setup
        s = run_chaos_drill(variables, cfg, shapes=[(32, 32)],
                            rounds=2, requests=8, submitters=2,
                            bucket_batch=2, iters=1, sessions=2,
                            session_frames=4, feature_cache=True,
                            cache_capacity=4, recover_s=6.0, seed=3)
        assert s["violations"] == []
        assert s["executables"] == s["documented_buckets"]
        for p in s["per_round"]:
            assert p["cache_occupancy"] <= 4
