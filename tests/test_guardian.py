"""SLO guardian acceptance (ISSUE 10): automated canary judgment with
deterministic bake-window drills (injected clock + synthetic metrics),
the real-stack degraded-canary auto-rollback / clean-canary
auto-promote drills, the registry-wide admission budget's starvation
drill, and per-session retry budgets."""

import json
import time
from concurrent.futures import Future

import numpy as np
import pytest

from tests.test_scheduler import _FakeEngine

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.guardian import (AdmissionBudget, GuardianPolicy,
                                       SLOGuardian, window_stats)
from raft_tpu.serving.metrics import _BOUNDS_MS
from raft_tpu.serving.registry import ModelRegistry
from raft_tpu.serving.resilience import CircuitOpen
from raft_tpu.serving.scheduler import (PRIORITY_BATCH,
                                        PRIORITY_INTERACTIVE,
                                        BackpressureError, ServeResult)
from raft_tpu.serving.session import VideoSession
from raft_tpu.testing import faults
from raft_tpu.testing.faults import FaultInjected

HW = (32, 32)
Z = np.zeros((*HW, 3), np.float32)
_NB = len(_BOUNDS_MS) + 1


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, *HW, 3))
    live = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    canary = model.init(jax.random.PRNGKey(7), img, img, iters=1)
    return cfg, live, canary


@pytest.fixture(scope="module")
def live_engine(small_setup):
    cfg, live, _ = small_setup
    return RAFTEngine(live, cfg, iters=1, envelope=[(2, *HW)],
                      precompile=True, warm_start=True)


@pytest.fixture(scope="module")
def canary_engine(small_setup):
    """Same arch as live, different weights — the rollout artifact."""
    cfg, _, canary = small_setup
    return RAFTEngine(canary, cfg, iters=1, envelope=[(2, *HW)],
                      precompile=True, warm_start=True)


def _pair(rng, h=HW[0], w=HW[1]):
    return (rng.rand(h, w, 3).astype(np.float32) * 255,
            rng.rand(h, w, 3).astype(np.float32) * 255)


# -- synthetic metrics helpers (the injected reader speaks the
# -- registry-snapshot variant-block schema) -------------------------------


def _blk(completed=0, failed=0, bucket=3, wedged=0, opens=0,
         model=None):
    """One variant snapshot block: ``completed`` latency samples all in
    histogram bucket ``bucket`` (p99 == _BOUNDS_MS[bucket])."""
    counts = [0] * _NB
    counts[bucket] = completed
    d = {"completed": completed, "failed": failed,
         "latency": {"counts": counts, "max_ms": float(completed)},
         "resilience": {"wedged": wedged,
                        "breaker_transitions": {"open": opens}}}
    if model is not None:
        d["model"] = model
    return d


def _rep(completed=0, bucket=3):
    """One per-replica metrics block (the fleet scheduler's snapshot
    shape): ``completed`` samples all in histogram bucket ``bucket``."""
    counts = [0] * _NB
    counts[bucket] = completed
    return {"completed": completed, "dispatches": completed,
            "filled": completed, "capacity": completed,
            "occupancy": 1.0, "queue_depth_last": 0,
            "latency": {"counts": counts, "count": completed,
                        "mean_ms": 0.0, "max_ms": float(completed)}}


def _fleet_blk(reps, failed=0, model=None):
    """A variant snapshot whose latency/completed aggregate the given
    ``{replica: (completed, bucket)}`` lanes — the shape a fleet
    scheduler's ServingMetrics emits."""
    counts = [0] * _NB
    total = 0
    for done, bucket in reps.values():
        counts[bucket] += done
        total += done
    d = {"completed": total, "failed": failed,
         "latency": {"counts": counts, "max_ms": float(total)},
         "resilience": {"wedged": 0,
                        "breaker_transitions": {"open": 0}},
         "replicas": {str(k): _rep(done, bucket)
                      for k, (done, bucket) in reps.items()}}
    if model is not None:
        d["model"] = model
    return d


class _FakeRegistry:
    """The registry surface the guardian needs, scripted."""

    metrics_path = None

    def __init__(self):
        self.actions = []
        self.raise_on_action = None

    def promote(self, name):
        if self.raise_on_action is not None:
            raise self.raise_on_action
        self.actions.append(("promote", name))
        return {"model": name, "mode": "weights_swap"}

    def rollback(self, name):
        if self.raise_on_action is not None:
            raise self.raise_on_action
        self.actions.append(("rollback", name))
        return {"model": name}


class TestWindowStats:
    def test_deltas_not_lifetime(self):
        base = _blk(completed=100, failed=10, bucket=2)
        cur = _blk(completed=130, failed=13, bucket=2)
        cur["latency"]["counts"][8] = 0
        base2 = dict(base)
        w = window_stats(cur, base)
        assert w["completed"] == 30 and w["failed"] == 3
        assert w["requests"] == 33
        assert w["err_rate"] == round(3 / 33, 4)
        # p99 comes from the count DELTA, not the lifetime histogram
        cur2 = _blk(completed=100, failed=10, bucket=2)
        cur2["completed"] = 105
        cur2["latency"]["counts"][10] = 5   # 5 new slow samples
        w2 = window_stats(cur2, base2)
        assert w2["p99_ms"] == _BOUNDS_MS[10]


class TestReplicaFleetWindows:
    """window_stats over fleet-scheduler snapshots: per-replica window
    views plus the LatencyHistogram.merge'd aggregate."""

    def test_per_replica_windows_and_merged_p99(self):
        base = _fleet_blk({0: (100, 2), 1: (50, 2)})
        cur = _fleet_blk({0: (130, 2), 1: (80, 2)})
        w = window_stats(cur, base)
        assert w["replicas"]["0"]["completed"] == 30
        assert w["replicas"]["1"]["completed"] == 30
        assert w["replicas"]["0"]["p99_ms"] == _BOUNDS_MS[2]
        assert w["p99_merged_ms"] == _BOUNDS_MS[2]

    def test_replica_absent_from_baseline_windows_from_zero(self):
        """A lane activated mid-bake has no baseline block: its whole
        history IS the window (zeros subtract)."""
        base = _fleet_blk({0: (100, 2)})
        cur = _fleet_blk({0: (120, 2), 1: (15, 10)})
        w = window_stats(cur, base)
        assert w["replicas"]["1"]["completed"] == 15
        assert w["replicas"]["1"]["p99_ms"] == _BOUNDS_MS[10]
        # the merged tail sees the new lane's slow samples
        assert w["p99_merged_ms"] == _BOUNDS_MS[10]

    def test_non_fleet_snapshot_grows_no_replica_keys(self):
        w = window_stats(_blk(completed=10), _blk())
        assert "replicas" not in w and "p99_merged_ms" not in w


class TestReplicaDilutionDrill:
    """The satellite-3 drill: a p99 breach confined to ONE replica of
    a fleet canary must roll the canary back even when the merged
    window dilutes the breach below the aggregate threshold."""

    def _guardian(self, state):
        reg = _FakeRegistry()
        t = [0.0]
        g = SLOGuardian(
            reg,
            GuardianPolicy(bake_window_s=100.0, min_requests=5,
                           p99_ratio=1.5, p99_slack_ms=0.0),
            clock=lambda: t[0], reader=lambda: state["snap"])
        return g, reg, t

    def test_one_sick_replica_rolls_back_despite_dilution(self):
        state = {"snap": {"m": {
            "live": _blk(),
            "canary": _fleet_blk({0: (0, 2), 1: (0, 2)},
                                 model="m@v2")}}}
        g, reg, t = self._guardian(state)
        g.tick()
        t[0] = 3.0
        # r0: 1000 fast samples. r1: 5 samples at bucket 10 — under
        # 1% of the merged window, so the AGGREGATE p99 still reads
        # the fast bucket (the dilution); only r1's own window shows
        # the breach.
        state["snap"] = {"m": {
            "live": _blk(completed=1000, bucket=2),
            "canary": _fleet_blk({0: (1000, 2), 1: (5, 10)},
                                 model="m@v2")}}
        out = g.tick()
        assert len(out) == 1
        ev = out[0]["evidence"]["canary"]
        assert ev["p99_ms"] == _BOUNDS_MS[2]          # diluted
        assert ev["replicas"]["1"]["p99_ms"] == _BOUNDS_MS[10]
        assert out[0]["action"] == "rollback"
        assert "canary_replica_p99 r1" in out[0]["reason"]
        assert reg.actions == [("rollback", "m")]

    def test_sick_replica_below_min_requests_holds(self):
        """Too few samples on the slow lane: statistically
        inadmissible — no verdict yet (the aggregate min_requests is
        met, the lane's is not)."""
        state = {"snap": {"m": {
            "live": _blk(),
            "canary": _fleet_blk({0: (0, 2), 1: (0, 2)},
                                 model="m@v2")}}}
        g, reg, t = self._guardian(state)
        g.tick()
        t[0] = 3.0
        state["snap"] = {"m": {
            "live": _blk(completed=1000, bucket=2),
            "canary": _fleet_blk({0: (1000, 2), 1: (3, 10)},
                                 model="m@v2")}}
        assert g.tick() == []
        assert reg.actions == []

    def test_healthy_fleet_canary_promotes(self):
        state = {"snap": {"m": {
            "live": _blk(),
            "canary": _fleet_blk({0: (0, 2), 1: (0, 2)},
                                 model="m@v2")}}}
        g, reg, t = self._guardian(state)
        g.tick()
        t[0] = 101.0
        state["snap"] = {"m": {
            "live": _blk(completed=1000, bucket=2),
            "canary": _fleet_blk({0: (500, 2), 1: (480, 2)},
                                 model="m@v2")}}
        out = g.tick()
        assert out[0]["action"] == "promote"
        assert reg.actions == [("promote", "m")]


class TestGuardianJudgment:
    """Deterministic bake drills: injected clock + synthetic reader."""

    def _guardian(self, policy, state, reg=None, tmp_path=None):
        reg = reg or _FakeRegistry()
        if tmp_path is not None:
            reg.metrics_path = str(tmp_path / "metrics.jsonl")
        t = [0.0]
        g = SLOGuardian(reg, policy, clock=lambda: t[0],
                        reader=lambda: state["snap"])
        return g, reg, t

    def test_clean_bake_auto_promotes(self, tmp_path):
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=10.0, min_requests=5),
            state, tmp_path=tmp_path)
        assert g.tick() == []        # first sight: bake starts
        t[0] = 5.0                   # mid-window, clean: hold
        state["snap"] = {"m": {"live": _blk(completed=40),
                               "canary": _blk(completed=20,
                                              model="m@v2")}}
        assert g.tick() == []
        t[0] = 10.5                  # window over, clean: promote
        out = g.tick()
        assert len(out) == 1 and out[0]["action"] == "promote"
        assert out[0]["mode"] == "weights_swap"
        assert reg.actions == [("promote", "m")]
        assert g.wait_decision("m", timeout=0.1) is out[0]
        # evidence windows rode into metrics.jsonl with the decision
        events = [json.loads(line)
                  for line in open(reg.metrics_path)]
        kinds = [e["event"] for e in events]
        assert "guardian_bake_start" in kinds
        promote = next(e for e in events
                       if e["event"] == "guardian_promote")
        assert promote["model"] == "m" and promote["version"] == "v2"
        assert promote["evidence"]["canary"]["requests"] == 20
        assert promote["evidence"]["live"]["completed"] == 40
        # a resolved bake leaves no state: next tick is a no-op
        state["snap"] = {"m": {"live": _blk(completed=40),
                               "canary": None}}
        assert g.tick() == []

    def test_err_rate_breach_rolls_back_mid_window(self):
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=100.0, min_requests=5,
                           err_rate_margin=0.05), state)
        g.tick()
        t[0] = 3.0                   # breach fires INSIDE the window
        state["snap"] = {"m": {"live": _blk(completed=40, failed=1),
                               "canary": _blk(completed=10, failed=5,
                                              model="m@v2")}}
        out = g.tick()
        assert len(out) == 1 and out[0]["action"] == "rollback"
        assert "err_rate" in out[0]["reason"]
        assert reg.actions == [("rollback", "m")]

    def test_p99_breach_rolls_back(self):
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=100.0, min_requests=5,
                           p99_ratio=1.5, p99_slack_ms=0.0), state)
        g.tick()
        t[0] = 3.0
        # live p99 at bucket 3, canary at bucket 8 — way past 1.5x
        state["snap"] = {"m": {"live": _blk(completed=40, bucket=3),
                               "canary": _blk(completed=10, bucket=8,
                                              model="m@v2")}}
        out = g.tick()
        assert out[0]["action"] == "rollback"
        assert "p99_ms" in out[0]["reason"]

    def test_p99_ceiling_is_absolute(self):
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=100.0, min_requests=5,
                           p99_ratio=100.0, p99_slack_ms=1e6,
                           p99_ceiling_ms=_BOUNDS_MS[5]), state)
        g.tick()
        t[0] = 3.0
        state["snap"] = {"m": {"live": _blk(completed=40, bucket=6),
                               "canary": _blk(completed=10, bucket=6,
                                              model="m@v2")}}
        out = g.tick()   # relative SLO is wide open; ceiling is not
        assert out[0]["action"] == "rollback"
        assert "ceiling" in out[0]["reason"]

    def test_wedge_and_breaker_counts_breach(self):
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=100.0, min_requests=5),
            state)
        g.tick()
        t[0] = 3.0
        state["snap"] = {"m": {"live": _blk(completed=40),
                               "canary": _blk(completed=10, wedged=1,
                                              model="m@v2")}}
        out = g.tick()
        assert out[0]["action"] == "rollback"
        assert "wedged" in out[0]["reason"]

    def test_empty_live_baseline_never_judges_relative_slos(self):
        """A live window below min_requests reads p99=0/err=0 — the
        relative bounds would collapse to the bare margins and roll
        back a perfectly healthy canary (canary_fraction ~1, or a
        live-traffic lull). Relative SLOs must not judge against a
        baseline that measured nothing; absolute ones still do."""
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=10.0, min_requests=5,
                           p99_ratio=1.5, p99_slack_ms=0.0,
                           err_rate_margin=0.02), state)
        g.tick()
        t[0] = 3.0   # live saw NOTHING; canary is normal-latency
        state["snap"] = {"m": {"live": _blk(completed=0),
                               "canary": _blk(completed=30, failed=1,
                                              bucket=6,
                                              model="m@v2")}}
        assert g.tick() == []        # no spurious breach
        t[0] = 10.5                  # clean window end: promote
        out = g.tick()
        assert out[0]["action"] == "promote"
        # the absolute checks never needed the baseline: a wedge on
        # the canary rolls back even with live silent
        state["snap"] = {"m": {"live": _blk(completed=0),
                               "canary": _blk(completed=10, wedged=2,
                                              model="m@v3")}}
        g.tick()                     # v3 bake opens
        t[0] = 12.0
        state["snap"] = {"m": {"live": _blk(completed=0),
                               "canary": _blk(completed=20, wedged=4,
                                              model="m@v3")}}
        out = g.tick()
        assert out[0]["action"] == "rollback"
        assert "wedged" in out[0]["reason"]

    def test_insufficient_traffic_holds_then_rolls_back(self):
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=10.0, max_bake_s=30.0,
                           min_requests=5), state)
        g.tick()
        t[0] = 15.0                  # window over but only 2 requests
        state["snap"] = {"m": {"live": _blk(completed=40),
                               "canary": _blk(completed=2,
                                              model="m@v2")}}
        assert g.tick() == []        # hold: unjudgeable, not promotable
        t[0] = 31.0                  # max bake: an unjudgeable canary
        out = g.tick()               # must not route forever
        assert out[0]["action"] == "rollback"
        assert "insufficient_traffic" in out[0]["reason"]

    def test_new_version_restarts_bake(self):
        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=10.0, min_requests=1), state)
        g.tick()
        t[0] = 11.0                  # v2's window is over, but v3 is
        state["snap"] = {"m": {"live": _blk(completed=9),
                               "canary": _blk(completed=9,
                                              model="m@v3")}}
        assert g.tick() == []        # fresh bake for v3, no decision
        t[0] = 22.0                  # v3's own window + traffic
        state["snap"] = {"m": {"live": _blk(completed=20),
                               "canary": _blk(completed=15,
                                              model="m@v3")}}
        out = g.tick()
        assert out[0]["version"] == "v3"
        assert out[0]["action"] == "promote"
        # v3's evidence counts from ITS baseline, not v2's
        assert out[0]["evidence"]["canary"]["completed"] == 6

    def test_raced_decision_records_failed_and_clears(self):
        """The registry refusing the verdict (operator resolved the
        rollout first) must not kill the loop or wedge the bake."""
        from raft_tpu.serving.registry import RolloutInProgress

        state = {"snap": {"m": {"live": _blk(),
                                "canary": _blk(model="m@v2")}}}
        reg = _FakeRegistry()
        reg.raise_on_action = RolloutInProgress("no canary to promote")
        g, reg, t = self._guardian(
            GuardianPolicy(bake_window_s=1.0, min_requests=1), state,
            reg=reg)
        g.tick()
        t[0] = 2.0
        state["snap"] = {"m": {"live": _blk(completed=4),
                               "canary": _blk(completed=4,
                                              model="m@v2")}}
        out = g.tick()
        assert out[0]["action"] == "failed"
        assert out[0]["intended"] == "promote"
        assert "RolloutInProgress" in out[0]["error"]
        # the failed verdict still lands and wakes waiters — the
        # rollout IS resolved; sleeping out a timeout to report
        # "undecided" would be strictly less true
        assert g.wait_decision("m", timeout=0.1) is out[0]

    def test_policy_validation(self):
        with pytest.raises(ValueError, match="bake_window_s"):
            GuardianPolicy(bake_window_s=0)
        with pytest.raises(ValueError, match="min_requests"):
            GuardianPolicy(min_requests=0)
        with pytest.raises(ValueError, match="max_bake_s"):
            GuardianPolicy(bake_window_s=10, max_bake_s=5)
        with pytest.raises(ValueError, match="err_rate_margin"):
            GuardianPolicy(err_rate_margin=1.5)


# -- admission budget ------------------------------------------------------


class TestAdmissionBudget:
    def test_acquire_release_round_trip(self):
        b = AdmissionBudget(3, interactive_reserve=1)
        assert b.try_acquire() and b.try_acquire()
        # 2 in use, 1 left == the reserve: batch must not take it
        assert not b.try_acquire(PRIORITY_BATCH)
        assert b.try_acquire(PRIORITY_INTERACTIVE)
        assert not b.try_acquire(PRIORITY_INTERACTIVE)  # truly full
        b.release()
        assert b.try_acquire()
        snap = b.snapshot()
        assert snap["in_use"] == 3
        assert snap["rejected"]["batch"] == 1
        assert snap["rejected"]["interactive"] == 1

    def test_batch_capped_at_capacity_minus_reserve(self):
        b = AdmissionBudget(4, interactive_reserve=2)
        got = sum(b.try_acquire(PRIORITY_BATCH) for _ in range(10))
        assert got == 2      # the flood can never drain the reserve
        assert b.try_acquire(PRIORITY_INTERACTIVE)

    def test_priority_less_draws_as_interactive(self):
        b = AdmissionBudget(2, interactive_reserve=1)
        assert b.try_acquire(PRIORITY_BATCH)
        assert not b.try_acquire(PRIORITY_BATCH)
        assert b.try_acquire(None)   # default traffic = a waiting user

    def test_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            AdmissionBudget(0)
        with pytest.raises(ValueError, match="interactive_reserve"):
            AdmissionBudget(4, interactive_reserve=5)

    def test_starvation_drill_two_models(self):
        """The acceptance drill: a saturating batch flood on model A
        cannot push model B's interactive shed above the drilled bound
        (zero) — the reserve admits every interactive request while
        the flood's rejections land on A as admission_rejected."""
        reg = ModelRegistry(gather_window_s=0.0, max_queue=64,
                            admission_budget=6,
                            admission_interactive_reserve=3)
        reg.add_model("flood", {}, RAFTConfig(),
                      engine=_FakeEngine(infer_delay_s=0.05))
        reg.add_model("inter", {}, RAFTConfig(), engine=_FakeEngine())
        flood_futs, flood_rejected = [], 0
        inter_done = 0
        for i in range(40):
            try:
                flood_futs.append(reg.submit(
                    Z, Z, model="flood", priority=PRIORITY_BATCH))
            except BackpressureError:
                flood_rejected += 1
            if i % 5 == 4:
                # interactive arrivals INTERLEAVED with the flood:
                # every one must admit through the reserve (waited out
                # one at a time — a user, not a second flood)
                f = reg.submit(Z, Z, model="inter",
                               priority=PRIORITY_INTERACTIVE)
                assert f.result(30).flow.shape == (*HW, 2)
                inter_done += 1
        assert flood_rejected > 0, "flood never hit the budget"
        assert inter_done == 8
        for f in flood_futs:
            f.result(30)
        snap = reg.snapshot()
        p = snap["inter"]["live"]["priority"]
        assert p[PRIORITY_INTERACTIVE]["shed"] == 0
        assert p[PRIORITY_INTERACTIVE]["completed"] == inter_done
        # the cross-model interactive latency bound: no queuing behind
        # the flood (its tokens never reach B's queue)
        assert p[PRIORITY_INTERACTIVE]["latency"]["p99_ms"] < 2000.0
        assert snap["flood"]["totals"]["admission_rejected"] \
            == flood_rejected
        assert snap["flood"]["accounting_ok"]
        assert snap["inter"]["accounting_ok"]
        reg.close()
        assert reg.admission_snapshot()["in_use"] == 0

    def test_budget_released_on_failed_submit(self):
        """A submit the variant's queue rejects must hand its token
        back — otherwise sheds leak the budget empty."""
        reg = ModelRegistry(gather_window_s=0.0, max_queue=1,
                            admission_budget=32)
        reg.add_model("m", {}, RAFTConfig(),
                      engine=_FakeEngine(infer_delay_s=0.05))
        futs = []
        for _ in range(12):
            try:
                futs.append(reg.submit(Z, Z))
            except BackpressureError:
                pass        # queue-level shed: token must come back
        for f in futs:
            f.result(30)
        # every queue-level shed released its token; settled futures
        # released theirs via the done callback
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and reg.admission_snapshot()["in_use"]:
            time.sleep(0.01)
        assert reg.admission_snapshot()["in_use"] == 0
        reg.close()


# -- per-session retry budgets ---------------------------------------------


class _FlakyScheduler:
    """Duck-typed scheduler: rejects the first ``fail_n`` submits with
    ``exc_cls``, then serves instantly."""

    def __init__(self, fail_n, exc_cls=BackpressureError):
        self.fail_n = fail_n
        self.exc_cls = exc_cls
        self.calls = []

    def submit(self, i1, i2, **kw):
        self.calls.append(kw)
        if len(self.calls) <= self.fail_n:
            raise self.exc_cls(f"rejection {len(self.calls)}")
        fut = Future()
        fut.set_result(ServeResult(
            np.zeros((*i1.shape[:2], 2), np.float32),
            np.zeros((4, 4, 2), np.float32)))
        return fut


class TestSessionRetryBudget:
    def test_retries_through_backoff_and_cold_restarts(self):
        sleeps = []
        sched = _FlakyScheduler(fail_n=2)
        sess = VideoSession(sched, warm_start=True, retry_budget=4,
                            retry_jitter=0.0, retry_base_s=0.05,
                            retry_sleep=sleeps.append)
        assert sess.submit_frame(Z) is None
        sess._flow_low = np.full((4, 4, 2), 0.5, np.float32)
        fut = sess.submit_frame(Z)
        assert fut.result(5).flow.shape == (*HW, 2)
        assert sess.retries_used == 2
        # jitter 0: the exponential series verbatim
        assert sleeps == [0.05, 0.1]
        # attempt 1 carried the warm start; the retried submits are
        # COLD (stale state must not warm-start a later reality)
        assert sched.calls[0]["flow_init"] is not None
        assert sched.calls[1]["flow_init"] is None
        assert sched.calls[2]["flow_init"] is None
        assert sess.warm_submits == 0
        assert sess._flow_low is None or sched.calls[-1][
            "flow_init"] is None

    def test_exhaustion_surfaces_original_exception(self):
        sched = _FlakyScheduler(fail_n=99, exc_cls=CircuitOpen)
        sess = VideoSession(sched, warm_start=False, retry_budget=3,
                            retry_jitter=0.0, retry_base_s=0.01,
                            retry_sleep=lambda _s: None)
        assert sess.submit_frame(Z) is None
        with pytest.raises(CircuitOpen, match="rejection 1"):
            sess.submit_frame(Z)
        assert sess.retries_used == 3
        assert len(sched.calls) == 4   # 1 original + 3 retries

    def test_budget_spans_the_session(self):
        """The cap is per session, not per pair: a second disruption
        only gets what the first left."""
        sched = _FlakyScheduler(fail_n=2)
        sess = VideoSession(sched, warm_start=False, retry_budget=3,
                            retry_jitter=0.0, retry_base_s=0.01,
                            retry_sleep=lambda _s: None)
        assert sess.submit_frame(Z) is None
        sess.submit_frame(Z).result(5)      # burns 2 retries
        assert sess.retries_used == 2
        sched.fail_n = len(sched.calls) + 5  # next pair: reject 5 more
        with pytest.raises(BackpressureError):
            sess.submit_frame(Z)
        assert sess.retries_used == 3        # hard cap held

    def test_zero_budget_is_the_historical_contract(self):
        sched = _FlakyScheduler(fail_n=1)
        sess = VideoSession(sched, warm_start=False)
        assert sess.submit_frame(Z) is None
        with pytest.raises(BackpressureError):
            sess.submit_frame(Z)
        assert len(sched.calls) == 1         # no retry happened


# -- wedged-guardian contract (deterministic, fake engines) ----------------


class TestWedgedGuardian:
    def _registry_with_canary(self):
        reg = ModelRegistry(gather_window_s=0.0)
        reg.add_model("m", {}, RAFTConfig(), engine=_FakeEngine())
        reg.deploy("m", {}, engine=_FakeEngine(), canary_fraction=0.5)
        return reg

    def test_hung_decision_leaves_routing_whole(self):
        """guardian.decide hang: no decision lands, the guardian
        thread is wedged (accounted by stop() returning False) — and
        the canary is still FULLY routed, every future settles, and
        close() drains with the per-model identity intact."""
        reg = self._registry_with_canary()
        g = SLOGuardian(reg, GuardianPolicy(bake_window_s=0.05,
                                            max_bake_s=30.0,
                                            min_requests=1),
                        poll_s=0.01).start()
        self._wait_bake(g)
        faults.arm([{"site": "guardian.decide", "kind": "hang",
                     "hang_s": 600.0, "count": 1}])
        futs = [reg.submit(Z, Z, route_key=i) for i in range(12)]
        for f in futs:
            f.result(30)
        assert g.wait_decision("m", timeout=1.0) is None
        # routing untouched: the canary is whole, not half-rolled
        canary = reg.health()["m"]["canary"]
        assert canary is not None
        assert canary["state"] == "canary" and canary["fraction"] > 0
        # a fresh submit still routes and serves both sides
        reg.submit(Z, Z, route_key=1).result(30)
        assert not g.stop(timeout=0.3), \
            "stop() claimed a hung guardian exited"
        reg.close()
        assert all(f.done() for f in futs)
        snap = reg.snapshot()["m"]
        assert snap["accounting_ok"], snap["totals"]

    @staticmethod
    def _wait_bake(g, model="m", timeout=5.0):
        """Wait for the guardian to open the bake, so drill traffic
        lands INSIDE the judged window, not in the frozen baseline."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with g._lock:
                if model in g._bakes:
                    return
            time.sleep(0.005)
        raise AssertionError("guardian never opened the bake")

    def test_raised_decision_survives_and_retries(self):
        """guardian.decide raise: the decision aborts with routing
        untouched, the loop survives, and the NEXT tick decides."""
        reg = self._registry_with_canary()
        g = SLOGuardian(reg, GuardianPolicy(bake_window_s=0.05,
                                            max_bake_s=30.0,
                                            min_requests=1),
                        poll_s=0.01).start()
        self._wait_bake(g)
        faults.arm([{"site": "guardian.decide", "kind": "raise",
                     "count": 1}])
        futs = [reg.submit(Z, Z, route_key=i) for i in range(12)]
        for f in futs:
            f.result(30)
        d = g.wait_decision("m", timeout=10.0)
        assert d is not None and d["action"] == "promote"
        assert g.errors >= 1          # the aborted tick was recorded
        assert g.stop(timeout=5.0)
        assert reg.health()["m"]["canary"] is None
        reg.close()
        assert reg.snapshot()["m"]["accounting_ok"]

    def test_manual_tick_raises_fault_to_caller(self):
        """Driving tick() by hand (drills do) surfaces the injected
        decision fault instead of swallowing it."""
        reg = self._registry_with_canary()
        t = [0.0]
        g = SLOGuardian(reg, GuardianPolicy(bake_window_s=1.0,
                                            min_requests=1),
                        clock=lambda: t[0])
        g.tick()                      # bake opens (baseline frozen)
        canary_key = next(k for k in range(50)
                          if reg.routes_to_canary("m", k))
        reg.submit(Z, Z, route_key=canary_key).result(30)
        t[0] = 2.0
        faults.arm([{"site": "guardian.decide", "kind": "raise",
                     "count": 1}])
        with pytest.raises(FaultInjected):
            g.tick()
        faults.disarm()
        canary = reg.health()["m"]["canary"]
        assert canary is not None and canary["fraction"] > 0
        reg.close()


# -- the ISSUE-10 acceptance drills (real stack) ---------------------------


class TestGuardianAcceptanceDrill:
    def test_degraded_canary_auto_rolls_back(self, small_setup,
                                             live_engine,
                                             canary_engine, tmp_path):
        """Deploy a canary whose engine is fault-armed to degrade
        (elevated error rate via serve.request), run traffic through
        the bake window, and assert the guardian auto-rolls-back
        WITHIN the window — with per-model accounting, zero stranded
        futures, and bitwise-unchanged live outputs through it all."""
        cfg, live_vars, canary_vars = small_setup
        rng = np.random.RandomState(11)
        xa, xb = _pair(rng)
        ref_live = live_engine.infer_batch(xa[None], xb[None])[0]

        mpath = str(tmp_path / "metrics.jsonl")
        reg = ModelRegistry(max_batch=2, gather_window_s=0.0,
                            metrics_path=mpath)
        reg.add_model("m", live_vars, cfg, iters=1, engine=live_engine)
        version = reg.deploy("m", canary_vars, canary_fraction=0.5,
                             engine=canary_engine)
        live_keys = [k for k in range(100)
                     if not reg.routes_to_canary("m", k)][:8]
        canary_keys = [k for k in range(100)
                       if reg.routes_to_canary("m", k)][:6]

        t = [0.0]
        g = SLOGuardian(reg, GuardianPolicy(bake_window_s=100.0,
                                            min_requests=4,
                                            err_rate_margin=0.1),
                        clock=lambda: t[0])
        assert g.tick() == []          # bake opens on first sight
        futs = []
        # clean live traffic first (the baseline the canary must beat)
        for k in live_keys:
            f = reg.submit(xa, xb, model="m", route_key=k)
            futs.append(f)
            np.testing.assert_array_equal(f.result(600).flow, ref_live)
        # the degraded canary: its dispatches fail (elevated error
        # rate) — sequential, so the armed count covers exactly the
        # canary-keyed dispatches and live traffic never fires it
        faults.arm([{"site": "serve.request", "kind": "raise",
                     "count": len(canary_keys)}])
        for k in canary_keys:
            f = reg.submit(xa, xb, model="m", route_key=k)
            futs.append(f)
            with pytest.raises(FaultInjected):
                f.result(600)
        faults.disarm()
        t[0] = 5.0                     # well INSIDE the bake window
        out = g.tick()
        assert len(out) == 1 and out[0]["action"] == "rollback"
        assert out[0]["version"] == version
        assert "err_rate" in out[0]["reason"]
        assert out[0]["evidence"]["canary"]["failed"] \
            == len(canary_keys)
        # canary gone, live whole: routing rolled all the way back
        assert reg.health()["m"]["canary"] is None
        # live outputs bitwise untouched through the whole window
        f = reg.submit(xa, xb, model="m", route_key=live_keys[0])
        futs.append(f)
        np.testing.assert_array_equal(f.result(600).flow, ref_live)
        assert all(f.done() for f in futs), "stranded futures"
        reg.close()
        snap = reg.snapshot()["m"]
        assert snap["accounting_ok"], snap["totals"]
        assert snap["totals"]["submitted"] == len(futs)
        assert snap["totals"]["failed"] == len(canary_keys)
        abandoned = sum(s["abandoned_inflight"]
                        for s in [snap["live"]] + snap["retired"])
        assert abandoned == 0
        # the rollback event carried its evidence into metrics.jsonl
        events = [json.loads(line) for line in open(mpath)
                  if "guardian" in line]
        rb = next(e for e in events
                  if e["event"] == "guardian_rollback")
        assert rb["evidence"]["canary"]["err_rate"] == 1.0

    def test_clean_canary_auto_promotes_no_compile_storm(
            self, small_setup, live_engine, canary_engine):
        """The symmetric drill: a clean same-arch canary bakes through
        the window and auto-promotes as a weight swap — the live
        engine keeps its executable OBJECT (no compile storm) and
        post-promote traffic serves the canary's weights bitwise."""
        cfg, live_vars, canary_vars = small_setup
        rng = np.random.RandomState(13)
        xa, xb = _pair(rng)
        ref_canary = canary_engine.infer_batch(xa[None], xb[None])[0]
        exe_before = live_engine._compiled[(2, *HW)]

        reg = ModelRegistry(max_batch=2, gather_window_s=0.0)
        reg.add_model("m", live_vars, cfg, iters=1, engine=live_engine)
        reg.deploy("m", canary_vars, canary_fraction=0.5,
                   engine=canary_engine)
        t = [0.0]
        g = SLOGuardian(reg, GuardianPolicy(bake_window_s=10.0,
                                            min_requests=4,
                                            p99_ratio=50.0,
                                            p99_slack_ms=1e5,
                                            err_rate_margin=0.5),
                        clock=lambda: t[0])
        g.tick()
        futs = [reg.submit(xa, xb, model="m", route_key=k)
                for k in range(16)]
        for f in futs:
            f.result(600)
        t[0] = 5.0
        assert g.tick() == []          # clean but window still open
        t[0] = 10.5
        out = g.tick()
        assert len(out) == 1 and out[0]["action"] == "promote"
        assert out[0]["mode"] == "weights_swap"
        # no compile storm: same executable object, same count
        assert live_engine._compiled[(2, *HW)] is exe_before
        assert len(live_engine._compiled) == 1
        assert len(canary_engine._compiled) == 1
        # live now serves the promoted weights, bitwise
        f = reg.submit(xa, xb, model="m")
        np.testing.assert_array_equal(f.result(600).flow, ref_canary)
        reg.close()
        snap = reg.snapshot()["m"]
        assert snap["accounting_ok"], snap["totals"]
        assert all(x.done() for x in futs)
