"""Checkpoint conversion tests: key mapping, transposes, numeric parity.

Numeric parity is checked layer-by-layer against torch functional ops with
*shared weights* routed through the converter's transpose — this pins the
OIHW→HWIO convention and the explicit-padding semantics without needing a
reference checkpoint (none is downloadable offline).
"""

import os.path as osp

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.models.layers import ResidualBlock, TorchConv, instance_norm
from raft_tpu.tools.convert import convert_state_dict, torch_key_map


@pytest.fixture(scope="module")
def basic_vars():
    model = RAFT(RAFTConfig(small=False))
    img = jnp.zeros((1, 32, 32, 3))
    return model, model.init(jax.random.PRNGKey(0), img, img, iters=1)


@pytest.fixture(scope="module")
def small_vars():
    model = RAFT(RAFTConfig(small=True))
    img = jnp.zeros((1, 32, 32, 3))
    return model, model.init(jax.random.PRNGKey(0), img, img, iters=1)


class TestKeyMap:
    def test_expected_reference_keys_basic(self, basic_vars):
        """Key names observed in the reference source must be derivable."""
        _, variables = basic_vars
        mapping = torch_key_map(variables)
        for key in [
            "fnet.conv1.weight",            # extractor.py:135
            "fnet.conv2.bias",              # extractor.py:144
            "fnet.layer1.0.conv1.weight",   # _make_layer extractor.py:159-165
            "fnet.layer2.0.downsample.0.weight",  # extractor.py:43-45
            "cnet.norm1.weight",            # BatchNorm2d extractor.py:127
            "cnet.norm1.running_mean",
            "cnet.layer3.0.norm3.running_var",
            "update_block.encoder.convc1.weight",  # update.py:83
            "update_block.gru.convz1.weight",      # update.py:36
            "update_block.gru.convq2.bias",        # update.py:42
            "update_block.flow_head.conv2.weight",  # update.py:10
            "update_block.mask.0.weight",   # update.py:122-125
            "update_block.mask.2.bias",
        ]:
            assert key in mapping, key

    def test_expected_reference_keys_small(self, small_vars):
        _, variables = small_vars
        mapping = torch_key_map(variables)
        for key in [
            "fnet.layer1.0.conv3.weight",   # BottleneckBlock extractor.py:66
            "update_block.encoder.conv.weight",  # update.py:69
            "update_block.gru.convz.weight",     # update.py:19
        ]:
            assert key in mapping, key
        # small model: no batch norm anywhere, no mask head
        assert not any("running" in k for k in mapping)
        assert not any(k.startswith("update_block.mask") for k in mapping)

    def test_instance_norm_has_no_params(self, basic_vars):
        """fnet is instance-norm (raft.py:54): no fnet norm params to map."""
        _, variables = basic_vars
        mapping = torch_key_map(variables)
        assert not any(k.startswith("fnet.norm") for k in mapping)
        assert not any(".norm1.weight" in k and k.startswith("fnet")
                       for k in mapping)


def synth_state_dict(variables, seed=0, prefix="module."):
    """Random torch-layout state dict matching a flax variable tree."""
    rng = np.random.RandomState(seed)
    sd = {}
    for tkey, (collection, path) in torch_key_map(variables).items():
        target = variables[collection]
        for comp in path:
            target = target[comp]
        shape = tuple(target.shape)
        if path[-1] == "kernel":
            shape = (shape[3], shape[2], shape[0], shape[1])  # HWIO->OIHW
        if path[-1] == "var":
            sd[prefix + tkey] = rng.rand(*shape).astype(np.float32) + 0.5
        else:
            sd[prefix + tkey] = rng.randn(*shape).astype(np.float32)
    return sd


class TestConvertStateDict:
    def test_roundtrip_fills_all_and_transposes(self, basic_vars):
        model, variables = basic_vars
        sd = synth_state_dict(variables)
        # add reference noise keys that must be ignored
        sd["module.cnet.norm1.num_batches_tracked"] = np.array(5)
        w = sd["module.fnet.layer2.0.downsample.0.weight"]
        sd["module.fnet.layer2.0.downsample.1.weight"] = np.zeros(3)

        out = convert_state_dict(sd, variables)
        got = out["params"]["fnet"]["layer2_0"]["downsample_conv"]["kernel"]
        np.testing.assert_array_equal(np.asarray(got),
                                      np.transpose(w, (2, 3, 1, 0)))
        # batch stats landed
        bs = out["batch_stats"]["cnet"]["norm1"]["norm"]["mean"]
        np.testing.assert_array_equal(
            np.asarray(bs), sd["module.cnet.norm1.running_mean"])

    def test_missing_key_raises(self, small_vars):
        _, variables = small_vars
        sd = synth_state_dict(variables)
        del sd["module.fnet.conv1.weight"]
        with pytest.raises(KeyError, match="missing"):
            convert_state_dict(sd, variables)

    def test_unexpected_key_raises(self, small_vars):
        _, variables = small_vars
        sd = synth_state_dict(variables)
        sd["module.fnet.bogus.weight"] = np.zeros(3, np.float32)
        with pytest.raises(KeyError, match="unmapped"):
            convert_state_dict(sd, variables)

    def test_forward_runs_after_convert(self, small_vars):
        model, variables = small_vars
        out = convert_state_dict(synth_state_dict(variables), variables)
        img = jnp.ones((1, 32, 32, 3)) * 127
        lo, up = model.apply(out, img, img, iters=1, test_mode=True)
        assert bool(jnp.isfinite(up).all())


class TestLayerNumericParity:
    """Shared-weights conv parity: flax TorchConv vs torch F.conv2d."""

    @pytest.mark.parametrize("spec", [
        dict(k=(7, 7), s=2, p=(3, 3), cin=3, cout=8),    # encoder stem
        dict(k=(3, 3), s=1, p=(1, 1), cin=6, cout=8),
        dict(k=(3, 3), s=2, p=(1, 1), cin=6, cout=8),    # strided: the trap
        dict(k=(1, 1), s=1, p=(0, 0), cin=6, cout=8),
        dict(k=(1, 5), s=1, p=(0, 2), cin=6, cout=8),    # SepConvGRU horiz
        dict(k=(5, 1), s=1, p=(2, 0), cin=6, cout=8),    # SepConvGRU vert
    ])
    @pytest.mark.parametrize("hw", [(16, 16), (15, 17)])
    def test_conv_matches_torch(self, rng, spec, hw):
        H, W = hw
        x = rng.randn(2, H, W, spec["cin"]).astype(np.float32)
        w = rng.randn(spec["cout"], spec["cin"], *spec["k"]).astype(np.float32)
        b = rng.randn(spec["cout"]).astype(np.float32)

        conv = TorchConv(spec["cout"], spec["k"], (spec["s"], spec["s"]),
                         spec["p"])
        flax_params = {"params": {"kernel": jnp.asarray(
            np.transpose(w, (2, 3, 1, 0))), "bias": jnp.asarray(b)}}
        got = np.asarray(conv.apply(flax_params, jnp.asarray(x)))

        tx = torch.from_numpy(x).permute(0, 3, 1, 2)
        want = F.conv2d(tx, torch.from_numpy(w), torch.from_numpy(b),
                        stride=spec["s"], padding=spec["p"])
        want = want.permute(0, 2, 3, 1).numpy()

        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    def test_instance_norm_matches_torch(self, rng):
        x = rng.randn(2, 9, 11, 5).astype(np.float32)
        got = np.asarray(instance_norm(jnp.asarray(x)))
        tx = torch.from_numpy(x).permute(0, 3, 1, 2)
        want = F.instance_norm(tx).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)

    def test_residual_block_matches_torch_composition(self, rng):
        """Full block vs torch functional composition, instance norm, s=2."""
        planes, cin = 8, 4
        x = rng.randn(1, 12, 12, cin).astype(np.float32)
        block = ResidualBlock(planes, "instance", stride=2)
        variables = block.init(jax.random.PRNGKey(0), jnp.asarray(x))
        p = variables["params"]

        def t(k):  # flax kernel -> torch weight
            return torch.from_numpy(
                np.transpose(np.asarray(p[k]["kernel"]), (3, 2, 0, 1)))

        def bias(k):
            return torch.from_numpy(np.asarray(p[k]["bias"]))

        tx = torch.from_numpy(x).permute(0, 3, 1, 2)
        y = F.relu(F.instance_norm(F.conv2d(tx, t("conv1"), bias("conv1"),
                                            stride=2, padding=1)))
        y = F.relu(F.instance_norm(F.conv2d(y, t("conv2"), bias("conv2"),
                                            padding=1)))
        xs = F.instance_norm(F.conv2d(tx, t("downsample_conv"),
                                      bias("downsample_conv"), stride=2))
        want = F.relu(xs + y).permute(0, 2, 3, 1).numpy()

        got = np.asarray(block.apply(variables, jnp.asarray(x)))
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


class TestDownloadModels:
    def test_offline_zip_convert(self, small_vars, tmp_path):
        """download_models --zip path: unzip -> convert every .pth to
        msgpack (the zero-egress route of tools/download_models.py)."""
        import zipfile

        from raft_tpu.tools.download_models import main

        _, variables = small_vars
        sd = {k: torch.from_numpy(v)
              for k, v in synth_state_dict(variables).items()}
        pth = tmp_path / "raft-small.pth"
        torch.save(sd, pth)
        z = tmp_path / "models.zip"
        with zipfile.ZipFile(z, "w") as zf:
            zf.write(pth, "models/raft-small.pth")

        out = tmp_path / "out"
        assert main(["--out", str(out), "--zip", str(z)]) == 0
        assert (out / "models" / "raft-small.msgpack").exists()

    def test_models_dir_without_pth_fails(self, tmp_path):
        from raft_tpu.tools.download_models import main

        assert main(["--models-dir", str(tmp_path)]) == 1


class TestGenuineTrainedArtifact:
    """The bundled fixture pair is a REAL torch-saved checkpoint (CPU
    training of the actual reference, tools/train_reference_ckpt.py) and
    its conversion — the converter is pinned against a genuine artifact
    with moved weights and accumulated BN statistics, not just
    synth_state_dict shapes."""

    FIX = osp.join(osp.dirname(__file__), "fixtures")

    def test_pth_converts_and_matches_committed_msgpack(self):
        import jax

        from raft_tpu.tools.convert import load_converted, load_pth

        pth = osp.join(self.FIX, "raft-small-cputrained.pth")
        msg = osp.join(self.FIX, "raft-small-cputrained.msgpack")
        if not (osp.exists(pth) and osp.exists(msg)):
            pytest.skip("trained fixtures not present")
        cfg = RAFTConfig(small=True)
        got = load_pth(pth, cfg)
        want = load_converted(msg, cfg)
        leaves_g = jax.tree_util.tree_leaves_with_path(got)
        leaves_w = dict(
            (jax.tree_util.keystr(k), l)
            for k, l in jax.tree_util.tree_leaves_with_path(want))
        assert len(leaves_g) == len(leaves_w)
        moved = 0.0
        for k, l in leaves_g:
            key = jax.tree_util.keystr(k)
            np.testing.assert_array_equal(np.asarray(l), leaves_w[key],
                                          err_msg=key)
            moved = max(moved, float(np.abs(np.asarray(l)).max()))
        assert moved > 0.1  # genuinely trained weights, not zeros

    def test_trained_weights_produce_sane_flow(self):
        import jax
        import jax.numpy as jnp

        from raft_tpu.models import RAFT
        from raft_tpu.tools.convert import load_converted

        msg = osp.join(self.FIX, "raft-small-cputrained.msgpack")
        if not osp.exists(msg):
            pytest.skip("trained fixture not present")
        cfg = RAFTConfig(small=True)
        variables = load_converted(msg, cfg)
        from PIL import Image

        src = osp.join(osp.dirname(__file__), "..", "demo-frames")
        f1 = np.asarray(Image.open(
            osp.join(src, "frame_0016.png")))[:128, :192].astype(np.float32)
        f2 = np.asarray(Image.open(
            osp.join(src, "frame_0017.png")))[:128, :192].astype(np.float32)
        _, flow = RAFT(cfg).apply(variables, jnp.asarray(f1[None]),
                                  jnp.asarray(f2[None]), iters=8,
                                  test_mode=True)
        flow = np.asarray(flow)[0]
        assert np.isfinite(flow).all()
        # trained weights keep flow in a physical range on real frames —
        # random init emits O(100 px) garbage here (measured, see
        # test_evaluation bucketing-delta docstring)
        assert np.abs(flow).max() < 40.0, np.abs(flow).max()
        assert np.abs(flow).mean() > 0.05
