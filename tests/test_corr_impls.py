"""Parity across corr-lookup backends: gather vs one-hot vs Pallas.

The gather path is already pinned against a torch grid_sample oracle in
test_corr.py, so it serves as the reference here. The Pallas kernel runs in
interpreter mode on CPU (same program, XLA semantics), per the multi-chip
test strategy of SURVEY.md §4(d/e).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.kernels import corr_pallas
from raft_tpu.models.corr import (build_corr_pyramid, corr_lookup,
                                  corr_lookup_onehot)


@pytest.fixture(scope="module")
def setup():
    rng = np.random.RandomState(7)
    B, H, W, C = 2, 8, 12, 16
    fmap1 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    fmap2 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
    pyramid = build_corr_pyramid(fmap1, fmap2, num_levels=3)
    base = np.stack(np.meshgrid(np.arange(W), np.arange(H)), -1)
    coords = (base[None].astype(np.float32)
              + rng.randn(B, H, W, 2).astype(np.float32) * 2.5)
    # exercise integer coords, far OOB, and edge-straddling windows
    coords[0, 0, 0] = [0.0, 0.0]
    coords[0, 0, 1] = [-50.0, 3.0]
    coords[0, 1, 0] = [W + 40.0, H + 40.0]
    coords[1, 0, 0] = [-0.5, H - 0.5]
    return pyramid, jnp.asarray(coords)


RADIUS = 2


class TestOnehotParity:
    def test_matches_gather(self, setup):
        pyramid, coords = setup
        want = np.asarray(corr_lookup(pyramid, coords, RADIUS))
        got = np.asarray(corr_lookup_onehot(pyramid, coords, RADIUS))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_grad_matches_gather(self, setup):
        pyramid, coords = setup

        def loss(fn):
            def f(pyr):
                return jnp.sum(fn(pyr, coords, RADIUS) ** 2)
            return f

        g_want = jax.grad(loss(corr_lookup))(list(pyramid))
        g_got = jax.grad(loss(corr_lookup_onehot))(list(pyramid))
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestPallasInterpretParity:
    @pytest.fixture(autouse=True)
    def interpret_mode(self, monkeypatch):
        monkeypatch.setattr(corr_pallas, "_INTERPRET", True)

    def test_matches_gather(self, setup):
        pyramid, coords = setup
        want = np.asarray(corr_lookup(pyramid, coords, RADIUS))
        got = np.asarray(
            corr_pallas.corr_lookup_pallas(pyramid, coords, RADIUS))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_vjp_matches_gather(self, setup):
        pyramid, coords = setup

        def loss(fn):
            def f(pyr):
                return jnp.sum(fn(pyr, coords, RADIUS) ** 2)
            return f

        g_want = jax.grad(loss(corr_lookup))(tuple(pyramid))
        g_got = jax.grad(
            loss(corr_pallas.corr_lookup_pallas))(tuple(pyramid))
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_nonsquare_and_radius4(self, setup):
        """Basic-model geometry: radius 4, K=9 windows, H != W."""
        rng = np.random.RandomState(3)
        B, H, W, C = 1, 6, 10, 8
        f1 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
        f2 = jnp.asarray(rng.randn(B, H, W, C).astype(np.float32))
        pyr = build_corr_pyramid(f1, f2, num_levels=2)
        base = np.stack(np.meshgrid(np.arange(W), np.arange(H)), -1)
        coords = jnp.asarray(
            base[None].astype(np.float32)
            + rng.randn(B, H, W, 2).astype(np.float32))
        want = np.asarray(corr_lookup(pyr, coords, 4))
        got = np.asarray(corr_pallas.corr_lookup_pallas(pyr, coords, 4))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


class TestCorrDtypeBf16:
    """corr_dtype='bfloat16' stores the volume half-width; selection stays
    exact, so all impls must agree with each other at bf16 exactly as they
    do at fp32, and the bf16-vs-fp32 drift must be storage rounding only."""

    def test_impls_agree_on_bf16_volume(self, setup, monkeypatch):
        monkeypatch.setattr(corr_pallas, "_INTERPRET", True)
        pyramid, coords = setup
        pyr16 = tuple(v.astype(jnp.bfloat16) for v in pyramid)
        want = np.asarray(corr_lookup(pyr16, coords, RADIUS))
        got_oh = np.asarray(corr_lookup_onehot(pyr16, coords, RADIUS))
        got_pl = np.asarray(
            corr_pallas.corr_lookup_pallas(pyr16, coords, RADIUS))
        np.testing.assert_allclose(got_oh, want, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(got_pl, want, atol=1e-5, rtol=1e-5)

    def test_onehot_bf16_selection_is_bit_exact(self, setup):
        """The bf16 fast path (bf16 one-hots, default MXU precision) must
        equal fp32 selection of the same bf16 volume BIT-exactly: each
        output is one volume entry times 1.0 plus zeros, and the lerp runs
        fp32 in both cases. Guards the precision dispatch in
        corr_lookup_onehot against 'simplifying' it back to one path."""
        pyramid, coords = setup
        pyr16 = tuple(v.astype(jnp.bfloat16) for v in pyramid)
        fast = np.asarray(corr_lookup_onehot(pyr16, coords, RADIUS))
        slow = np.asarray(corr_lookup_onehot(
            tuple(v.astype(jnp.float32) for v in pyr16), coords, RADIUS))
        np.testing.assert_array_equal(fast, slow)

    def test_bf16_drift_is_storage_rounding(self, setup):
        pyramid, coords = setup
        pyr16 = tuple(v.astype(jnp.bfloat16) for v in pyramid)
        a = np.asarray(corr_lookup(pyramid, coords, RADIUS))
        b = np.asarray(corr_lookup(pyr16, coords, RADIUS))
        scale = np.abs(a).max()
        # bf16 has an 8-bit mantissa: rel ~2^-8 of the volume's magnitude
        assert np.abs(a - b).max() < scale * 2.0 ** -7

    def test_grad_drift_bounded(self, setup, monkeypatch):
        """Backward at bf16: the volume's cotangent is emitted AND summed
        in bf16, so fmap-side gradients carry extra rounding beyond the
        forward's — pin that it stays at the bf16-epsilon level rather
        than compounding pathologically, for the XLA and Pallas VJPs."""
        monkeypatch.setattr(corr_pallas, "_INTERPRET", True)
        pyramid, coords = setup

        def grad_of(fn, pyr):
            def f(p):
                return jnp.sum(fn(p, coords, RADIUS) ** 2)
            return jax.grad(f)(pyr)

        pyr16 = tuple(v.astype(jnp.bfloat16) for v in pyramid)
        for fn in (corr_lookup, corr_pallas.corr_lookup_pallas):
            g32 = grad_of(fn, tuple(pyramid))
            g16 = grad_of(fn, pyr16)
            for a, b in zip(g32, g16):
                a = np.asarray(a)
                b = np.asarray(b, dtype=np.float32)
                scale = max(np.abs(a).max(), 1e-9)
                # one bf16 rounding of the output cotangent + one of the
                # stored cotangent: ~2^-7 of the gradient's magnitude
                assert np.abs(a - b).max() < scale * 2.0 ** -6, fn

    def test_model_forward_drift_bounded(self):
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        rng = np.random.RandomState(0)
        img1 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        flows = {}
        for dt in ["float32", "bfloat16"]:
            model = RAFT(RAFTConfig(small=True, corr_dtype=dt))
            variables = model.init(jax.random.PRNGKey(0), img1, img2,
                                   iters=1)
            flows[dt] = np.asarray(
                model.apply(variables, img1, img2, iters=4))
        # Per-iteration drift profile, as in TestModelIntegration: the
        # first iteration sees only the volume's bf16 storage rounding
        # (~2^-8 rel); the recurrence then amplifies it (random-init
        # weights are the chaotic worst case — measured profile
        # 0.16% -> 3.7% rel over 4 iters). Pin "rounding in, bounded
        # amplification out" with generous headroom: the measurement is
        # machine/version-sensitive through the recurrence, so the bounds
        # encode orders of magnitude, not this machine's digits.
        per_iter = np.abs(flows["bfloat16"] - flows["float32"]).reshape(
            4, -1).max(axis=1)
        mags = np.abs(flows["float32"]).reshape(4, -1).max(axis=1)
        rel = per_iter / np.maximum(mags, 1e-9)
        assert rel[0] < 2e-2, rel
        assert rel[-1] < 0.2, rel
        growth = rel[1:] / np.maximum(rel[:-1], 1e-12)
        assert growth.max() < 30.0, rel


class TestModelIntegration:
    def test_raft_forward_same_flow_across_impls(self):
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        rng = np.random.RandomState(0)
        img1 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)

        flows = {}
        for impl in ["gather", "onehot"]:
            model = RAFT(RAFTConfig(small=True, corr_impl=impl))
            variables = model.init(jax.random.PRNGKey(0), img1, img2, iters=1)
            # train-mode return: (iters, B, H, W, 2) — all iterations
            flows[impl] = np.asarray(
                model.apply(variables, img1, img2, iters=4))

        # The impls are algebraically identical; they differ only in fp32
        # summation order (4-corner weighted sum vs separable lerp of
        # one-hot GEMM outputs). That rounding difference enters once per
        # iteration and is amplified by the recurrence. Pin the profile:
        # the FIRST iteration diff is pure op-level rounding (must be at
        # the 1e-4 float32 level on ~1e2-magnitude flows), and growth per
        # iteration stays bounded (< 10x/iter), reaching at most ~5e-3 by
        # iteration 4 — drift, not divergence.
        per_iter = np.abs(flows["onehot"] - flows["gather"]).reshape(
            4, -1).max(axis=1)
        assert per_iter[0] < 1e-4, f"op-level mismatch: {per_iter}"
        assert per_iter[-1] < 5e-3, f"drift blow-up: {per_iter}"
        growth = per_iter[1:] / np.maximum(per_iter[:-1], 1e-12)
        assert growth.max() < 10.0, f"non-linear amplification: {per_iter}"


class TestOnehotTParity:
    """The transposed (pixels-on-lanes) volume path must be numerically
    interchangeable with the gather oracle: same dot products (identical
    einsum contraction), same one-hot window select + separable lerp —
    only the storage order differs (see build_corr_pyramid_t)."""

    def test_pyramid_is_transposed_pyramid(self):
        from raft_tpu.models.corr import (build_corr_pyramid,
                                          build_corr_pyramid_t)

        # one set of fmaps, both builders — self-contained on purpose
        # (regenerating "the fixture's" arrays from a copied seed would
        # silently decouple from fixture edits)
        rng = np.random.RandomState(11)
        fmap1 = jnp.asarray(rng.randn(2, 8, 12, 16).astype(np.float32))
        fmap2 = jnp.asarray(rng.randn(2, 8, 12, 16).astype(np.float32))
        pyr = build_corr_pyramid(fmap1, fmap2, num_levels=3)
        pyr_t = build_corr_pyramid_t(fmap1, fmap2, num_levels=3)
        assert len(pyr) == len(pyr_t)
        for v, vt in zip(pyr, pyr_t):
            want = np.asarray(v).transpose(0, 2, 3, 1)   # (B, Hl, Wl, N)
            np.testing.assert_allclose(np.asarray(vt), want,
                                       atol=1e-6, rtol=1e-6)

    def test_matches_gather(self, setup):
        from raft_tpu.models.corr import corr_lookup_onehot_t

        pyramid, coords = setup
        pyr_t = [jnp.transpose(v, (0, 2, 3, 1)) for v in pyramid]
        want = np.asarray(corr_lookup(pyramid, coords, RADIUS))
        got = np.asarray(corr_lookup_onehot_t(pyr_t, coords, RADIUS))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_grad_matches_gather(self, setup):
        from raft_tpu.models.corr import corr_lookup_onehot_t

        pyramid, coords = setup
        pyr_t = [jnp.transpose(v, (0, 2, 3, 1)) for v in pyramid]

        g_want = jax.grad(
            lambda p: jnp.sum(corr_lookup(p, coords, RADIUS) ** 2)
        )(list(pyramid))
        g_got = jax.grad(
            lambda p: jnp.sum(corr_lookup_onehot_t(p, coords, RADIUS) ** 2)
        )(list(pyr_t))
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b).transpose(0, 2, 3, 1),
                atol=1e-4, rtol=1e-4)

    def test_model_forward_same_flow(self):
        """RAFT with corr_impl='onehot_t' produces the same flow as the
        default within fp32 reassociation noise."""
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        rng = np.random.RandomState(3)
        i1 = jnp.asarray(rng.rand(1, 32, 48, 3).astype(np.float32) * 255)
        i2 = jnp.asarray(rng.rand(1, 32, 48, 3).astype(np.float32) * 255)
        outs = {}
        for impl in ("onehot", "onehot_t"):
            cfg = RAFTConfig(small=True, corr_impl=impl)
            variables = RAFT(cfg).init(jax.random.PRNGKey(0), i1, i2,
                                       iters=1)
            _, flow = RAFT(cfg).apply(variables, i1, i2, iters=3,
                                      test_mode=True)
            outs[impl] = np.asarray(flow)
        np.testing.assert_allclose(outs["onehot_t"], outs["onehot"],
                                   atol=1e-4, rtol=1e-4)


class TestSoftselParity:
    """softsel folds the separable bilinear lerp into the selection
    matrices — algebraically identical to the oracle, with no lerp
    intermediates (they burned ~60 ms/step of tile-padded traffic)."""

    def test_matches_gather(self, setup):
        from raft_tpu.models.corr import corr_lookup_softsel

        pyramid, coords = setup
        want = np.asarray(corr_lookup(pyramid, coords, RADIUS))
        got = np.asarray(corr_lookup_softsel(pyramid, coords, RADIUS))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_grad_matches_gather(self, setup):
        from raft_tpu.models.corr import corr_lookup_softsel

        pyramid, coords = setup
        g_want = jax.grad(
            lambda p: jnp.sum(corr_lookup(p, coords, RADIUS) ** 2)
        )(list(pyramid))
        g_got = jax.grad(
            lambda p: jnp.sum(corr_lookup_softsel(p, coords, RADIUS) ** 2)
        )(list(pyramid))
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_bf16_volume_close_to_onehot_bf16(self, setup):
        """With a bf16 volume the weights ride the bf16 GEMM — one extra
        rounding vs onehot's fp32 lerp. Pin that the extra drift stays in
        the same class as the volume's own storage rounding."""
        from raft_tpu.models.corr import corr_lookup_softsel

        pyramid, coords = setup
        pyr16 = [v.astype(jnp.bfloat16) for v in pyramid]
        ref = np.asarray(corr_lookup_onehot(pyr16, coords, RADIUS))
        got = np.asarray(corr_lookup_softsel(pyr16, coords, RADIUS))
        scale = np.abs(ref).max()
        assert np.abs(got - ref).max() / scale < 2e-2, (
            np.abs(got - ref).max(), scale)

    def test_model_forward_same_flow(self):
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        rng = np.random.RandomState(5)
        i1 = jnp.asarray(rng.rand(1, 32, 48, 3).astype(np.float32) * 255)
        i2 = jnp.asarray(rng.rand(1, 32, 48, 3).astype(np.float32) * 255)
        outs = {}
        for impl in ("onehot", "softsel"):
            cfg = RAFTConfig(small=True, corr_impl=impl)
            variables = RAFT(cfg).init(jax.random.PRNGKey(0), i1, i2,
                                       iters=1)
            _, flow = RAFT(cfg).apply(variables, i1, i2, iters=3,
                                      test_mode=True)
            outs[impl] = np.asarray(flow)
        np.testing.assert_allclose(outs["softsel"], outs["onehot"],
                                   atol=1e-4, rtol=1e-4)


class TestSoftselTParity:
    """softsel_t = softsel's lerp-folded selections on the transposed
    pixels-on-lanes volume (corr_lookup_softsel_t docstring)."""

    def test_matches_gather(self, setup):
        from raft_tpu.models.corr import corr_lookup_softsel_t

        pyramid, coords = setup
        pyr_t = [jnp.transpose(v, (0, 2, 3, 1)) for v in pyramid]
        want = np.asarray(corr_lookup(pyramid, coords, RADIUS))
        got = np.asarray(corr_lookup_softsel_t(pyr_t, coords, RADIUS))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_grad_matches_gather(self, setup):
        from raft_tpu.models.corr import corr_lookup_softsel_t

        pyramid, coords = setup
        pyr_t = [jnp.transpose(v, (0, 2, 3, 1)) for v in pyramid]
        g_want = jax.grad(
            lambda p: jnp.sum(corr_lookup(p, coords, RADIUS) ** 2)
        )(list(pyramid))
        g_got = jax.grad(
            lambda p: jnp.sum(corr_lookup_softsel_t(p, coords, RADIUS) ** 2)
        )(list(pyr_t))
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b).transpose(0, 2, 3, 1),
                atol=1e-4, rtol=1e-4)

    def test_model_forward_same_flow(self):
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        rng = np.random.RandomState(5)
        i1 = jnp.asarray(rng.rand(1, 32, 48, 3).astype(np.float32) * 255)
        i2 = jnp.asarray(rng.rand(1, 32, 48, 3).astype(np.float32) * 255)
        outs = {}
        for impl in ("onehot", "softsel_t"):
            cfg = RAFTConfig(small=True, corr_impl=impl)
            variables = RAFT(cfg).init(jax.random.PRNGKey(0), i1, i2,
                                       iters=1)
            _, flow = RAFT(cfg).apply(variables, i1, i2, iters=3,
                                      test_mode=True)
            outs[impl] = np.asarray(flow)
        np.testing.assert_allclose(outs["softsel_t"], outs["onehot"],
                                   atol=1e-4, rtol=1e-4)


class TestInterpretFallback:
    """Off-TPU, pallas_call must auto-fall back to interpret mode AND
    warn loudly — an export/AOT trace on a CPU host would otherwise bake
    the pure-XLA path into a TPU-bound artifact silently (round-5
    review). No _INTERPRET monkeypatch here: this pins the fallback
    path itself."""

    def test_lookup_runs_and_warns_off_tpu(self, setup):
        import warnings

        pyramid, coords = setup
        want = np.asarray(corr_lookup(pyramid, coords, RADIUS))
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            got = np.asarray(
                corr_pallas.corr_lookup_pallas(pyramid, coords, RADIUS))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
        assert any("interpret mode" in str(w.message) for w in rec), (
            "fallback must warn so exports can't silently ship the "
            "pure-XLA path")
