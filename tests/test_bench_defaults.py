"""BENCH_DEFAULTS.json plumbing: the on-chip ladder picks the fastest
measured config (tools/pick_bench_defaults.py) and a bare ``python
bench.py`` must fold it in without overriding explicit flags."""

import argparse
import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_PY = os.path.join(REPO, "bench.py")


def load_bench(name="bench_mod"):
    spec = importlib.util.spec_from_file_location(name, BENCH_PY)
    bench = importlib.util.module_from_spec(spec)
    saved = sys.argv
    sys.argv = ["bench.py"]
    try:
        spec.loader.exec_module(bench)
    finally:
        sys.argv = saved
    return bench


@pytest.fixture(scope="module")
def modules():
    bench = load_bench()
    spec2 = importlib.util.spec_from_file_location(
        "pick_mod", os.path.join(REPO, "tools",
                                 "pick_bench_defaults.py"))
    pick = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(pick)
    return bench, pick


class TestFlagsFromMetric:
    def test_parses_every_ladder_shape(self, modules):
        _, pick = modules
        f = pick.flags_from_metric
        assert f("raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip"
                 ) == {"batches": [8]}
        assert f("raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip"
                 "_corrbfloat16") == {"batches": [8],
                                      "corr_dtype": "bfloat16"}
        got = f("raft_basic_train_chairs_368x496_bf16_b10_iters12_1chip"
                "_remat_dots_corrbfloat16")
        assert got == {"batches": [10], "remat": True,
                       "remat_policy": "dots", "corr_dtype": "bfloat16"}
        assert f("raft_basic_train_368x496_failed") is None

    def test_picker_prefers_highest_value(self, modules, tmp_path):
        _, pick = modules
        (tmp_path / "a.json").write_text(json.dumps(
            {"metric": "raft_basic_train_chairs_368x496_bf16_b6_iters12"
                       "_1chip", "value": 11.5}) + "\n")
        (tmp_path / "b.json").write_text(json.dumps(
            {"metric": "raft_basic_train_chairs_368x496_bf16_b8_iters12"
                       "_1chip_corrbfloat16", "value": 21.0}) + "\n")
        (tmp_path / "c.json").write_text(json.dumps(
            {"metric": "raft_basic_train_chairs_368x496_failed",
             "value": 0.0}) + "\n")
        best = None
        for name in sorted(p.name for p in tmp_path.glob("*.json")):
            rec = json.loads((tmp_path / name).read_text())
            if rec["value"] > 0 and (best is None
                                     or rec["value"] > best["value"]):
                best = rec
        assert pick.flags_from_metric(best["metric"]) == {
            "batches": [8], "corr_dtype": "bfloat16"}


class TestApplyMeasuredDefaults:
    def _merge(self, bench, argv):
        args = bench._build_parser().parse_args(argv)
        passed = vars(bench._build_parser(suppress=True)
                      .parse_args(argv)).keys()
        bench._apply_measured_defaults(args, passed)
        return args

    def test_defaults_applied_and_explicit_flags_win(self, modules,
                                                     tmp_path, monkeypatch):
        bench, _ = modules
        defaults = {"batches": [8], "corr_dtype": "bfloat16", "remat": True,
                    "remat_policy": "dots", "_measured": {"value": 21.0}}
        (tmp_path / "BENCH_DEFAULTS.json").write_text(json.dumps(defaults))
        monkeypatch.setattr(bench.os.path, "dirname",
                            lambda _: str(tmp_path))
        args = self._merge(bench, [])
        assert args.batches == [8] and args.corr_dtype == "bfloat16"
        assert args.remat is True and args.remat_policy == "dots"
        assert not hasattr(args, "_measured")

        args2 = self._merge(bench, ["--batches", "4", "2"])
        assert args2.batches == [4, 2]          # explicit wins
        assert args2.corr_dtype == "bfloat16"   # untouched default filled

        # --no-remat must beat the JSON even though False == parser
        # default, and the JSON's now-meaningless policy is dropped
        # rather than tripping the --remat-policy-requires-remat error
        args3 = self._merge(bench, ["--no-remat"])
        assert args3.remat is False
        assert args3.remat_policy is None

    def test_unreadable_or_invalid_file_is_ignored(self, modules, tmp_path,
                                                   monkeypatch):
        bench, _ = modules
        monkeypatch.setattr(bench.os.path, "dirname",
                            lambda _: str(tmp_path))
        (tmp_path / "BENCH_DEFAULTS.json").write_text("{not json")
        assert self._merge(bench, []).batches == [8, 6, 4, 2]
        # schema violations (typo'd policy) reject the whole file: fail
        # at the argparse layer, not deep inside a remote compile
        (tmp_path / "BENCH_DEFAULTS.json").write_text(json.dumps(
            {"batches": [8], "remat_policy": "dot"}))
        args = self._merge(bench, [])
        assert args.batches == [8, 6, 4, 2] and args.remat_policy is None


class TestWorkerCrashClassifier:
    """Round-4 hardening: a transient tunnel-worker death gets one bounded
    retry instead of zeroing the driver bench (BENCH_r01..r03 all 0.0)."""

    def test_crash_messages_detected(self, modules):
        bench, _ = modules
        for msg in (
            "UNAVAILABLE: TPU worker process crashed or restarted",
            "FAILED_PRECONDITION: worker process restarted mid-call",
            "Unavailable: socket closed before response",
            "UNAVAILABLE: connection reset by peer",
        ):
            assert bench.is_worker_crash(RuntimeError(msg)), msg

    def test_non_crash_errors_not_detected(self, modules):
        bench, _ = modules
        for msg in (
            "RESOURCE_EXHAUSTED: out of memory allocating 2.1GiB",
            "INVALID_ARGUMENT: shapes must be equal",
            "Ran out of memory in memory space vmem",
            "some unrelated ValueError",
        ):
            assert not bench.is_worker_crash(RuntimeError(msg)), msg

    def test_crash_is_not_oom(self, modules):
        # the two classifiers must be disjoint: a crash must never trigger
        # the try-smaller-batch ladder, and an OOM must never re-exec
        bench, _ = modules
        crash = RuntimeError("UNAVAILABLE: TPU worker process crashed")
        oom = RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        assert bench.is_worker_crash(crash) and not bench.is_oom(crash)
        assert bench.is_oom(oom) and not bench.is_worker_crash(oom)


class TestFallbackBatches:
    def test_winner_keeps_smaller_rungs(self, modules):
        _, pick = modules
        assert pick.with_fallbacks([10]) == [10, 8, 6, 4, 2]
        assert pick.with_fallbacks([8]) == [8, 6, 4, 2]
        assert pick.with_fallbacks([2]) == [2]


class TestDeadlineCarryover:
    def test_start_shifts_back_by_elapsed_env(self, monkeypatch):
        # the crash-retry re-exec hands its elapsed seconds to the fresh
        # process via RAFT_BENCH_ELAPSED; START must move back by that
        # much so --deadline-s bounds TOTAL wall-clock, not per-process
        import time as _time

        monkeypatch.setenv("RAFT_BENCH_ELAPSED", "1234.5")
        t0 = _time.monotonic()
        mod = load_bench("bench_elapsed_mod")
        t1 = _time.monotonic()
        # START was stamped between t0 and t1, shifted back 1234.5 s —
        # bound it from both sides with no hidden import-time budget
        assert t0 - mod.START <= 1234.5 + 1e-3
        assert t1 - mod.START >= 1234.5


class TestCrashResumeBatches:
    """RAFT_BENCH_BATCHES round-trip: the re-exec producer serializes the
    surviving rungs space-separated; the consumer must parse them back
    over every other default, and a malformed value must fall back to the
    CLI/JSON batches (the env var is self-produced, but a serialization
    refactor must not silently break resume)."""

    def test_env_overrides_batches(self, modules, monkeypatch):
        bench, _ = modules
        monkeypatch.setenv("RAFT_BENCH_CRASH_RETRIED", "1")
        monkeypatch.setenv("RAFT_BENCH_BATCHES", "6 4")
        ns = argparse.Namespace(batches=[8, 6, 4])
        bench._apply_crash_resume(ns)
        assert ns.batches == [6, 4]

    def test_producer_serialization_roundtrips(self, modules, monkeypatch):
        # exactly the expression the crash handler uses to build the env:
        # a positional slice of the ladder from the crashed rung onward
        bench, _ = modules
        ladder = [12, 10, 8]
        env_val = " ".join(map(str, ladder[1:]))
        monkeypatch.setenv("RAFT_BENCH_CRASH_RETRIED", "1")
        monkeypatch.setenv("RAFT_BENCH_BATCHES", env_val)
        ns = argparse.Namespace(batches=ladder)
        bench._apply_crash_resume(ns)
        assert ns.batches == [10, 8]

    def test_batches_without_retry_flag_ignored(self, modules, monkeypatch):
        # only the script's own re-exec sets BOTH vars; a stale manual
        # export of the list alone must not override --batches
        bench, _ = modules
        monkeypatch.delenv("RAFT_BENCH_CRASH_RETRIED", raising=False)
        monkeypatch.setenv("RAFT_BENCH_BATCHES", "2")
        ns = argparse.Namespace(batches=[12])
        bench._apply_crash_resume(ns)
        assert ns.batches == [12]

    def test_malformed_empty_or_nonpositive_keep_cli_batches(
            self, modules, monkeypatch):
        bench, _ = modules
        monkeypatch.setenv("RAFT_BENCH_CRASH_RETRIED", "1")
        for bad in ("zap", "8,6", "", "0", "-4 2"):
            monkeypatch.setenv("RAFT_BENCH_BATCHES", bad)
            ns = argparse.Namespace(batches=[8, 6])
            bench._apply_crash_resume(ns)
            assert ns.batches == [8, 6], bad

    def test_absent_env_is_noop(self, modules, monkeypatch):
        bench, _ = modules
        monkeypatch.delenv("RAFT_BENCH_BATCHES", raising=False)
        ns = argparse.Namespace(batches=[8])
        bench._apply_crash_resume(ns)
        assert ns.batches == [8]


class TestBenchDefaultFlags:
    """tools/bench_default_flags.py — the shared BENCH_DEFAULTS -> CLI
    flags mapping both shell runbooks consume. Pin the mapping for a
    fully-loaded defaults dict and the degraded no-file case."""

    def _flags(self, tmp_path, defaults, with_batch):
        import shutil
        tools = tmp_path / "tools"
        tools.mkdir(exist_ok=True)
        shutil.copy("/root/repo/tools/bench_default_flags.py", tools)
        if defaults is not None:
            (tmp_path / "BENCH_DEFAULTS.json").write_text(
                json.dumps(defaults))
        spec = importlib.util.spec_from_file_location(
            "bdf_mod", tools / "bench_default_flags.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.flags(with_batch)

    def test_full_defaults_roundtrip(self, tmp_path):
        flags = self._flags(tmp_path, {
            "batches": [10, 8], "corr_dtype": "bfloat16",
            "corr_impl": "softsel", "fused_loss": True, "scan_unroll": 2,
        }, with_batch=True)
        assert flags == ["--batch", "10", "--corr_dtype", "bfloat16",
                         "--corr_impl", "softsel", "--fused_loss",
                         "--scan_unroll", "2"]

    def test_gru_impl_mapped(self, tmp_path):
        # a fused-GRU ladder winner must trace/profile as the fused step
        flags = self._flags(tmp_path, {
            "batches": [8], "gru_impl": "fused",
        }, with_batch=False)
        assert flags == ["--gru_impl", "fused"]

    def test_remat_defaults_mapped(self, tmp_path):
        # a remat ladder winner must trace as the remat step, not the
        # plain one (profile_step grew --remat_policy for this)
        flags = self._flags(tmp_path, {
            "batches": [8], "remat": True, "remat_policy": "dots",
        }, with_batch=False)
        assert flags == ["--remat", "--remat_policy", "dots"]

    def test_no_file_and_no_batch(self, tmp_path):
        assert self._flags(tmp_path, None,
                           with_batch=True) == ["--batch", "8"]
        assert self._flags(tmp_path, None,
                           with_batch=False) == []


class TestScanUnrollPlumbing:
    def test_metric_tag_roundtrip(self, modules):
        _, pick = modules
        f = pick.flags_from_metric
        assert f("raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip"
                 "_corrbfloat16_unroll2") == {
            "batches": [8], "corr_dtype": "bfloat16", "scan_unroll": 2}
        # the unroll tag must not break the trailing corr_impl match
        assert f("raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip"
                 "_softsel_corrbfloat16_unroll4") == {
            "batches": [8], "corr_dtype": "bfloat16", "scan_unroll": 4,
            "corr_impl": "softsel"}

    def test_defaults_schema_accepts_unroll(self, modules):
        bench, _ = modules
        assert bench._DEFAULTS_SCHEMA["scan_unroll"](2)
        assert not bench._DEFAULTS_SCHEMA["scan_unroll"](0)
        assert not bench._DEFAULTS_SCHEMA["scan_unroll"]("2")

    def test_defaults_schema_rejects_bool(self, modules):
        # isinstance(True, int) is True: a copy-pasted JSON `true` must
        # fail the schema, not silently measure unroll=1 behind an
        # "applied" log line
        bench, _ = modules
        assert not bench._DEFAULTS_SCHEMA["scan_unroll"](True)


class TestGruImplPlumbing:
    """gru_impl A/B rungs (round 6): the metric tag, defaults schema and
    runbook flag mapping must round-trip so a measured fused-GRU win can
    set the bare-bench default through pick_bench_defaults."""

    def test_metric_tag_roundtrip(self, modules):
        _, pick = modules
        f = pick.flags_from_metric
        assert f("raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip"
                 "_grufused") == {"batches": [8], "gru_impl": "fused"}
        # composed with the full r5-winner tag set; the gru suffix must
        # not break the trailing corr_impl match
        assert f("raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip"
                 "_softsel_corrbfloat16_fusedloss_grufused") == {
            "batches": [8], "corr_impl": "softsel",
            "corr_dtype": "bfloat16", "fused_loss": True,
            "gru_impl": "fused"}
        assert f("raft_basic_train_chairs_368x496_bf16_b8_iters12_1chip"
                 "_softsel_corrbfloat16_unroll2_gruxla") == {
            "batches": [8], "corr_impl": "softsel",
            "corr_dtype": "bfloat16", "scan_unroll": 2, "gru_impl": "xla"}

    def test_defaults_schema_accepts_impls_only(self, modules):
        bench, _ = modules
        assert bench._DEFAULTS_SCHEMA["gru_impl"]("fused")
        assert bench._DEFAULTS_SCHEMA["gru_impl"]("xla")
        assert not bench._DEFAULTS_SCHEMA["gru_impl"]("mosaic")
        assert not bench._DEFAULTS_SCHEMA["gru_impl"](True)

    def test_defaults_applied_to_args(self, modules, tmp_path, monkeypatch):
        bench, _ = modules
        (tmp_path / "BENCH_DEFAULTS.json").write_text(json.dumps(
            {"batches": [8], "gru_impl": "fused"}))
        monkeypatch.setattr(bench.os.path, "dirname",
                            lambda _: str(tmp_path))
        args = bench._build_parser().parse_args([])
        passed = vars(bench._build_parser(suppress=True)
                      .parse_args([])).keys()
        bench._apply_measured_defaults(args, passed)
        assert args.gru_impl == "fused"
        # explicit flag still wins
        args2 = bench._build_parser().parse_args(["--gru-impl", "xla"])
        passed2 = vars(bench._build_parser(suppress=True)
                       .parse_args(["--gru-impl", "xla"])).keys()
        bench._apply_measured_defaults(args2, passed2)
        assert args2.gru_impl == "xla"


class TestHangWatch:
    """start_hang_watch: the half-up-tunnel wedge must become a recorded
    0.0 JSON, not a silent hang until the driver's timeout."""

    def test_fires_on_staleness_and_emits_failure_json(self, modules,
                                                       monkeypatch, capsys):
        bench, _ = modules
        calls = {}
        monkeypatch.setattr(bench.os, "_exit",
                            lambda code: calls.setdefault("exit", code))
        # stamp progress far in the past, then let one watch tick run
        bench.LAST_PROGRESS = bench.time.monotonic() - 999.0
        t = bench.start_hang_watch("chairs368x496", hang_s=1.0,
                                   interval=0.05)
        t.join(timeout=5.0)
        # the SHARED wedged code (watchdog.WEDGED_EXIT_CODE), not a
        # bench-private integer: one failure mode, one exit code
        assert calls.get("exit") == bench.WEDGED_EXIT_CODE == 3
        out = capsys.readouterr().out.strip().splitlines()[-1]
        rec = json.loads(out)
        assert rec["metric"] == \
            "raft_basic_train_chairs368x496_backend_wedged"
        assert rec["value"] == 0.0

    def test_does_not_fire_while_progress_is_fresh(self, modules,
                                                   monkeypatch, capsys):
        import threading

        bench, _ = modules
        fired = {}
        monkeypatch.setattr(bench.os, "_exit",
                            lambda code: fired.setdefault("exit", code))
        bench.log("progress")  # stamps LAST_PROGRESS = now
        stop = threading.Event()
        t = bench.start_hang_watch("chairs368x496", hang_s=60.0,
                                   interval=0.05, stop=stop)
        bench.time.sleep(0.3)  # several ticks, none stale
        assert "exit" not in fired
        # end the watcher before monkeypatch restores the real os._exit
        stop.set()
        t.join(timeout=5.0)
        assert not t.is_alive()

    def test_nonpositive_hang_s_disables(self, modules):
        bench, _ = modules
        assert bench.start_hang_watch("chairs368x496", hang_s=0.0) is None
        assert bench.start_hang_watch("chairs368x496", hang_s=-1.0) is None

    def test_probe_requires_a_real_execute(self):
        # the probe source must jit-EXECUTE, not merely enumerate: the
        # half-up tunnel answers devices() but hangs execute
        src = open(BENCH_PY).read()
        probe = src.split("probe = (")[1].split("print(d[0].platform)")[0]
        assert "jax.jit" in probe and "block_until_ready" in probe
