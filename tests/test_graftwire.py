"""graftwire: the wire-protocol static-analysis gate (tools/graftwire/).

Mirrors test_graftthread's layers, plus the union/fault-coverage units
this tier's cross-file rules need:

- per-rule fixture tests: each per-file-checkable rule W1-W6 has a
  positive fixture (must fire) and a negative fixture (must stay
  silent) under ``tests/graftwire_fixtures/``;
- cross-file drift: the ``w1_client.py`` / ``w1_server.py`` pair is
  clean per-file and dirty only in the ``lint_paths`` union — W1
  method drift AND the W2 idempotency declaration living on the
  server module (graftthread's T3-only-in-union discipline);
- W7 units over SYNTHETIC mini-repos (``check_repo`` with
  parameterized roots): armed-but-unknown, known-but-never-armed,
  armed-but-undrilled, docstrings never count as "drawn";
- mechanism tests: per-line pragmas, baseline grandfathering +
  stale-entry failure, the declaration error surface (E2), the shared
  content-hash parse cache (facts survive cache hits so the union
  pass still sees cross-file drift), the schema digest folded into
  the cache signature;
- the repo gate: ``python -m tools.graftwire --json`` (default paths:
  serving + parallel + the fault seam, shipped EMPTY baseline) must
  exit 0 under the 30 s warm budget — first-scan findings were FIXED
  (the undeclared ``aot_evicted`` emitter, the undrilled
  ``host.infer`` site), never grandfathered — and the meta-gate
  (``tools.graft``) runs graftwire as its sixth tier with per-tier
  wall time and finding counts.

graftwire is pure-stdlib ``ast``; nothing here touches jax.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "graftwire_fixtures")
BASELINE = os.path.join(REPO, "tools", "graftwire", "baseline.json")
SCHEMA = os.path.join(REPO, "raft_tpu", "serving", "schema.py")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import lintcache  # noqa: E402
from tools.graftwire import (DEFAULT_PATHS, apply_baseline,  # noqa: E402
                             lint_file, lint_paths, load_baseline,
                             write_baseline)
from tools.graftwire import schema_registry  # noqa: E402
from tools.graftwire.core import (collect_files, main,  # noqa: E402
                                  _rules_signature)
from tools.graftwire.rules import fault_coverage  # noqa: E402

RULES = ("W1", "W2", "W3", "W4", "W5", "W6")


def fixture(name):
    return os.path.join(FIXTURES, name)


def rules_hit(path):
    return {f.rule for f in lint_file(path)}


class TestRuleFixtures:
    @pytest.mark.parametrize("rule", RULES)
    def test_positive_fixture_fires(self, rule):
        path = fixture(f"{rule.lower()}_pos.py")
        assert rule in rules_hit(path), \
            f"{rule} positive fixture produced no {rule} finding"

    @pytest.mark.parametrize("rule", RULES)
    def test_negative_fixture_is_silent(self, rule):
        path = fixture(f"{rule.lower()}_neg.py")
        findings = lint_file(path)
        assert not findings, \
            f"{rule} negative fixture is not clean: " \
            + "; ".join(f.render() for f in findings)

    @pytest.mark.parametrize("rule", RULES)
    def test_pragma_suppresses_each_rule(self, rule, tmp_path,
                                         monkeypatch):
        """Detection -> pragma round trip per rule: the positive
        fixture with a pragma on every finding line goes silent for
        that rule; a pragma naming a DIFFERENT rule does not."""
        monkeypatch.chdir(REPO)   # tmp copies resolve the real schema
        src_path = fixture(f"{rule.lower()}_pos.py")
        findings = [f for f in lint_file(src_path) if f.rule == rule]
        lines = open(src_path, encoding="utf-8").read().splitlines()
        for f in findings:
            lines[f.line - 1] += f"  # graftwire: disable={rule}"
        p = tmp_path / f"{rule.lower()}_pos.py"
        p.write_text("\n".join(lines) + "\n")
        assert rule not in {f.rule for f in lint_file(str(p))}
        # a pragma for an unrelated rule must NOT suppress
        wrong = "W1" if rule != "W1" else "W2"
        for i, line in enumerate(lines):
            lines[i] = line.replace(f"disable={rule}",
                                    f"disable={wrong}")
        p.write_text("\n".join(lines) + "\n")
        assert rule in {f.rule for f in lint_file(str(p))}

    @pytest.mark.parametrize("rule", RULES)
    def test_baseline_roundtrip_each_rule(self, rule, tmp_path):
        """Detection -> baseline round trip per rule: grandfathered
        findings don't fail, a fixed finding leaves a stale entry."""
        findings = lint_file(fixture(f"{rule.lower()}_pos.py"))
        assert findings
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), findings)
        new, stale = apply_baseline(findings, load_baseline(str(bl)))
        assert new == [] and stale == []
        new, stale = apply_baseline([], load_baseline(str(bl)))
        assert new == [] and len(stale) == len(findings)


class TestCrossFileUnion:
    """W1/W2's verdict is the union of every scanned file's wire facts
    — client and worker are different modules, so drift (and the
    idempotency declarations that excuse it) only resolve globally."""

    PAIR = ("w1_client.py", "w1_server.py")

    def test_drift_only_fires_in_the_union(self):
        client, server = (fixture(n) for n in self.PAIR)
        assert "W1" not in rules_hit(client)
        assert "W1" not in rules_hit(server)
        union = lint_paths([client, server])
        w1 = [f for f in union if f.rule == "W1"]
        assert {("route" in f.message or "drop" in f.message)
                for f in w1} == {True}
        assert len(w1) == 2
        # the missing-handler half anchors at the CLIENT call site,
        # the dead-handler half at the SERVER table entry
        assert {os.path.basename(f.path) for f in w1} \
            == {"w1_client.py", "w1_server.py"}

    def test_idempotency_declarations_union_across_files(self):
        """Alone, the client fires W2 (its module declares nothing);
        with the server module's GRAFTWIRE['idempotent'] in the union,
        the same calls are covered."""
        client, server = (fixture(n) for n in self.PAIR)
        assert "W2" in rules_hit(client)
        union = lint_paths([client, server])
        assert "W2" not in {f.rule for f in union}


class TestDeclarations:
    def test_bad_declaration_is_a_finding(self, tmp_path):
        p = tmp_path / "bad.py"
        p.write_text("GRAFTWIRE = {'not_a_key': ()}\n")
        findings = lint_file(str(p))
        assert any(f.rule == "E2" and "not_a_key" in f.message
                   for f in findings)
        p.write_text("GRAFTWIRE = 'oops'\n")
        assert any(f.rule == "E2" for f in lint_file(str(p)))
        # non-literal values must not crash the scan
        p.write_text("GRAFTWIRE = {'idempotent': make()}\n")
        assert any(f.rule == "E2" for f in lint_file(str(p)))
        p.write_text("GRAFTWIRE = {'idempotent': (1, 2)}\n")
        assert any(f.rule == "E2" for f in lint_file(str(p)))

    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        findings = lint_file(str(p))
        assert len(findings) == 1 and findings[0].rule == "E1"

    def test_wire_lock_exemption_is_the_declaration(self, tmp_path,
                                                    monkeypatch):
        """The SAME lock-across-I/O shape flips from finding to
        contract with one GRAFTWIRE['wire_locks'] line — the PR-18
        SocketTransport design made declarable."""
        monkeypatch.chdir(REPO)
        body = ("import threading\n"
                "{decl}"
                "class T:\n"
                "    def __init__(self, w):\n"
                "        self._lock = threading.Lock()\n"
                "        self._w = w\n"
                "    def beat(self):\n"
                "        with self._lock:\n"
                "            return self._w.call('ping',\n"
                "                                request_id='r')\n")
        p = tmp_path / "t.py"
        p.write_text(body.format(decl=""))
        assert "W3" in {f.rule for f in lint_file(str(p))}
        p.write_text(body.format(
            decl="GRAFTWIRE = {'wire_locks': ('_lock',)}\n"))
        assert "W3" not in {f.rule for f in lint_file(str(p))}


class TestSchemaRegistry:
    def test_parses_assign_and_annassign_key_sets(self, tmp_path):
        root = tmp_path / "repo"
        sdir = root / "raft_tpu" / "serving"
        sdir.mkdir(parents=True)
        (sdir / "schema.py").write_text(
            "EVENT_FIELDS: Dict[str, frozenset] = {\n"
            "    'ev_a': frozenset({'x'}),\n"
            "    'breaker_open': frozenset(),\n"
            "}\n"
            "WIRE_METHODS = {'m1': frozenset({'k'})}\n")
        found = schema_registry.find_schema(str(sdir / "probe.py"))
        reg = schema_registry.load(found)
        assert reg.events == {"ev_a", "breaker_open"}
        assert reg.methods == {"m1"}
        assert reg.event_declared(("exact", "ev_a"))
        assert reg.event_declared(("prefix", "breaker_"))
        assert not reg.event_declared(("exact", "ev_b"))
        assert not reg.event_declared(("prefix", "zzz_"))

    def test_schema_digest_feeds_the_cache_signature(self, monkeypatch):
        """Editing serving/schema.py must kill cached W6 verdicts: the
        registry digest is folded into the tier's cache signature."""
        monkeypatch.chdir(REPO)
        sig = _rules_signature()
        assert sig.endswith(":" + lintcache.file_digest(SCHEMA))


class TestFaultCoverage:
    """W7 over synthetic mini-repos — check_repo with parameterized
    roots, no dependence on the real tree."""

    @staticmethod
    def _mini_repo(tmp_path, known, armed, drill_src):
        root = tmp_path / "repo"
        (root / "raft_tpu" / "testing").mkdir(parents=True)
        (root / "raft_tpu" / "testing" / "faults.py").write_text(
            "KNOWN_SITES = {\n"
            + "".join(f"    {s!r}: 'desc',\n" for s in known)
            + "}\n"
            "def fault_point(site):\n"
            "    pass\n")
        (root / "raft_tpu" / "serving").mkdir(parents=True)
        (root / "raft_tpu" / "serving" / "mod.py").write_text(
            "from ..testing.faults import fault_point\n"
            "def f():\n"
            + "".join(f"    fault_point({s!r})\n" for s in armed))
        (root / "tests").mkdir()
        (root / "tests" / "drill.py").write_text(drill_src)
        return str(root)

    def test_three_way_cross_reference(self, tmp_path):
        root = self._mini_repo(
            tmp_path,
            known=["loader.sample", "serve.request", "ghost.site"],
            armed=["loader.sample", "serve.request", "rogue.site"],
            drill_src="CHAOS_SITES = ('loader.sample',)\n")
        findings = fault_coverage.check_repo(root)
        by_site = {f.message.split("'")[1]: f for f in findings}
        assert set(by_site) == {"rogue.site", "serve.request",
                                "ghost.site"}
        assert "missing from KNOWN_SITES" in by_site["rogue.site"].message
        assert by_site["rogue.site"].path.endswith("mod.py")
        assert "undrilled" in by_site["serve.request"].message
        assert by_site["serve.request"].path.endswith("mod.py")
        assert "never armed" in by_site["ghost.site"].message
        assert by_site["ghost.site"].path.endswith("faults.py")

    def test_clean_mini_repo_is_silent(self, tmp_path):
        root = self._mini_repo(
            tmp_path, known=["a.b"], armed=["a.b"],
            drill_src="import x\n"
                      "x.arm([{'site': 'a.b', 'kind': 'raise'}])\n")
        assert fault_coverage.check_repo(root) == []

    def test_docstring_mention_does_not_count_as_drawn(self, tmp_path):
        root = self._mini_repo(
            tmp_path, known=["a.b"], armed=["a.b"],
            drill_src='"""This drill discusses a.b in prose only."""\n')
        findings = fault_coverage.check_repo(root)
        assert len(findings) == 1 and "undrilled" in findings[0].message

    def test_arming_inside_faults_py_is_machinery_not_a_site(
            self, tmp_path):
        """fault_point calls in faults.py itself (the machinery and
        its doctests) are not armed sites."""
        root = self._mini_repo(tmp_path, known=[], armed=[],
                               drill_src="x = 1\n")
        faults_py = os.path.join(root, "raft_tpu", "testing",
                                 "faults.py")
        with open(faults_py, "a") as f:
            f.write("def _selftest():\n"
                    "    fault_point('self.test')\n")
        assert fault_coverage.check_repo(root) == []

    def test_real_repo_cross_reference_is_clean(self):
        """The in-process twin of the gate's W7 slice: every armed
        site registered, every KNOWN_SITES row armed, every site
        drawn by some drill."""
        assert fault_coverage.check_repo(REPO) == []


class TestMechanisms:
    def test_pragma_inside_string_literal_does_not_suppress(
            self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text('def f(t):\n'
                     '    t.call("zap_state"); '
                     's = "# graftwire: disable=all"\n')
        assert "W2" in {f.rule for f in lint_file(str(p))}

    def test_pragma_disable_all(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text('def f(t):\n'
                     '    t.call("zap_state")'
                     '  # graftwire: disable=all (drill-only fake)\n')
        assert lint_file(str(p)) == []

    def test_stale_baseline_entry_fails_the_gate(self, tmp_path,
                                                 capsys):
        p = tmp_path / "legacy.py"
        p.write_text('def f(t):\n    t.call("zap_state")\n')
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), lint_file(str(p)))
        assert main([str(p), "--baseline", str(bl),
                     "--no-cache"]) == 0      # grandfathered
        p.write_text("def f(t):\n    pass\n")
        assert main([str(p), "--baseline", str(bl),
                     "--no-cache"]) == 1      # stale entry must burn
        assert "stale baseline" in capsys.readouterr().err

    def test_write_baseline_refuses_rule_filter(self, tmp_path):
        bl = tmp_path / "baseline.json"
        rc = main([fixture("w2_pos.py"), "--rules", "W1",
                   "--write-baseline", str(bl), "--no-cache"])
        assert rc == 2 and not bl.exists()

    def test_rules_filter_and_unknown_rule_errors(self, capsys):
        rc = main([fixture("w4_pos.py"), "--rules", "W3",
                   "--no-cache"])
        assert rc == 0          # W4 violations invisible to a W3 run
        rc = main([fixture("w4_pos.py"), "--rules", "W9",
                   "--no-cache"])
        assert rc == 2

    def test_walk_excludes_fixture_dir_but_explicit_file_wins(self):
        walked = collect_files([os.path.join(REPO, "tests")])
        assert not any("graftwire_fixtures" in p for p in walked)
        explicit = collect_files([fixture("w2_pos.py")])
        assert explicit == [fixture("w2_pos.py")]

    def test_other_tiers_exclude_graftwire_fixtures(self):
        """The fixture tree is intentionally-violating code for THIS
        tier — every other tier's walk (shared lintcache exclusion
        list) must skip it too."""
        from tools.graftlint.core import collect_files as lint_collect
        from tools.graftthread.core import collect_files as thr_collect
        for collect in (lint_collect, thr_collect):
            walked = collect([os.path.join(REPO, "tests")])
            assert not any("graftwire_fixtures" in p for p in walked)


class TestParseCache:
    """The shared tools/lintcache machinery under graftwire: content
    hashed, rules-aware, invalidated by any edit to the checker
    package or the schema registry — and the global W1/W2/W7 passes
    re-run on cache HITS too."""

    BAD = ("import threading\n"
           "class T:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "    def f(self, transport):\n"
           "        with self._lock:\n"
           "            transport.call('ping')\n")

    def test_cache_replays_then_content_hash_invalidates(
            self, tmp_path, monkeypatch):
        monkeypatch.chdir(REPO)
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        first = lint_paths([str(p)], cache_path=str(cache))
        assert {f.rule for f in first} == {"W3", "W2"} \
            and cache.exists()
        # prove the second run is a HIT: doctor the stored finding
        data = json.loads(cache.read_text())
        (key,) = data["files"]
        data["files"][key]["findings"][0]["message"] = "FROM-CACHE"
        cache.write_text(json.dumps(data))
        warm = lint_paths([str(p)], cache_path=str(cache))
        assert [f.message for f in warm if f.rule == "W3"] \
            == ["FROM-CACHE"]
        # any edit changes the content hash: the entry is dead
        p.write_text(self.BAD + "# touched\n")
        fresh = lint_paths([str(p)], cache_path=str(cache))
        assert "FROM-CACHE" not in [f.message for f in fresh]
        assert {f.rule for f in fresh} == {"W3", "W2"}
        assert len(json.loads(cache.read_text())["files"]) == 1

    def test_cached_facts_still_feed_union_pass(self, tmp_path,
                                                monkeypatch):
        """A cache hit must not hide cross-file drift: facts are
        cached per file, but the W1/W2 union runs every time."""
        monkeypatch.chdir(REPO)
        files = []
        for name in ("w1_client.py", "w1_server.py"):
            src = open(fixture(name), encoding="utf-8").read()
            p = tmp_path / name
            p.write_text(src)
            files.append(str(p))
        cache = tmp_path / "cache.json"
        cold = lint_paths(files, cache_path=str(cache))
        warm = lint_paths(files, cache_path=str(cache))
        assert [f.rule for f in cold] == ["W1", "W1"]
        assert [(f.rule, f.path, f.line) for f in warm] \
            == [(f.rule, f.path, f.line) for f in cold]

    def test_jobs_parallel_matches_serial(self, tmp_path, monkeypatch):
        monkeypatch.chdir(REPO)
        files = []
        for i, body in enumerate([self.BAD, "x = 1\n", self.BAD,
                                  "def f(:\n"]):
            p = tmp_path / f"f{i}.py"
            p.write_text(body)
            files.append(str(p))
        assert lint_paths(files, jobs=3) == lint_paths(files)

    def test_signature_invalidates_whole_cache(self, tmp_path,
                                               monkeypatch):
        monkeypatch.chdir(REPO)
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        lint_paths([str(p)], cache_path=str(cache))
        data = json.loads(cache.read_text())
        data["sig"] = "some-older-graftwire-or-schema"
        (key,) = data["files"]
        data["files"][key]["findings"][0]["message"] = "FROM-STALE"
        cache.write_text(json.dumps(data))
        findings = lint_paths([str(p)], cache_path=str(cache))
        assert "FROM-STALE" not in [f.message for f in findings]
        assert json.loads(cache.read_text())["sig"] != \
            "some-older-graftwire-or-schema"


class TestRepoGate:
    """The actual gate: `python -m tools.graftwire --json` (default
    paths + shipped baseline) clean, warm, and under budget — and the
    six-tier meta-gate integration."""

    def test_repo_clean_with_empty_baseline_under_budget(self):
        t0 = time.monotonic()
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftwire", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        dt = time.monotonic() - t0
        assert r.returncode == 0, \
            f"new graftwire findings:\n{r.stdout}\n{r.stderr}"
        assert json.loads(r.stdout) == []
        assert dt < 30.0, f"gate took {dt:.1f}s (budget 30s)"

    def test_baseline_is_empty_and_stays_empty(self):
        """The shipped baseline starts EMPTY: the first-scan findings
        were FIXED at the site (the undeclared aot_evicted emitter in
        registry.py, the undrilled host.infer chaos site) — never
        grandfathered. An entry appearing here means someone took the
        shortcut this gate exists to block."""
        with open(BASELINE) as f:
            entries = json.load(f)["findings"]
        assert entries == [], (
            "graftwire baseline regrew — fix or pragma the finding "
            f"instead of grandfathering it: {entries}")

    def test_default_paths_cover_the_wire_stack(self):
        files = collect_files([os.path.join(REPO, p)
                               for p in DEFAULT_PATHS])
        names = {os.path.basename(p) for p in files}
        assert {"transport.py", "hosts.py", "scheduler.py",
                "registry.py", "schema.py", "placement.py",
                "faults.py"} <= names

    def test_json_mode_is_machine_readable(self):
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftwire",
             os.path.join("tests", "graftwire_fixtures",
                          "w2_pos.py"),
             "--json", "--no-cache"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 1
        findings = json.loads(r.stdout)
        assert findings and all(
            set(f) >= {"path", "line", "col", "rule", "name", "message"}
            for f in findings)
        assert any(f["rule"] == "W2" for f in findings)

    def test_meta_gate_runs_graftwire_as_sixth_tier(self):
        """tools.graft --tiers graftwire: the tier is wired into the
        meta-gate, and its summary block carries the wall time and
        finding count the merged output promises."""
        from tools.graft import TIERS
        assert "graftwire" in TIERS and len(TIERS) == 6
        r = subprocess.run(
            [sys.executable, "-m", "tools.graft", "--json",
             "--tiers", "graftwire"],
            cwd=REPO, capture_output=True, text=True, timeout=180)
        assert r.returncode == 0, r.stdout + r.stderr
        summary = json.loads(r.stdout)
        blk = summary["tiers"]["graftwire"]
        assert blk["exit"] == 0 and blk["count"] == 0
        assert isinstance(blk["seconds"], float)
        assert summary["ok"] is True
