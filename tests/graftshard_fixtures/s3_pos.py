"""S3 planted violation: ``jax.device_put`` traced INSIDE the mesh
program — in-program placement is a hidden reshard; it belongs in the
dispatch layer (or as a declarative with_sharding_constraint)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.graftshard import ShardTarget


def _build():
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    rep = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P("data"))

    def f(x):
        y = jax.device_put(x, rep)      # traced into the program
        return y.sum()

    xs = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sharded)
    return f, (xs,), mesh


TARGETS = [ShardTarget(name="s3_fixture", build=_build)]
