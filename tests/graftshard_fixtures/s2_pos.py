"""S2 planted violations: large values resolved to full replication.

Two of the rule's three surfaces in one tiny program: a 256 KiB
boundary arg declared replicated though the 'data' axis divides it,
and a with_sharding_constraint pinning a big intermediate to
``P()``."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.graftshard import ShardTarget


def _build():
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    rep = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P("data"))

    def f(big_rep, x):
        y = x * 2.0
        # a big intermediate explicitly constrained to replication
        z = jax.lax.with_sharding_constraint(
            jnp.broadcast_to(y.sum(), (64, 1024)), rep)
        return (big_rep * 1.5).sum() + z.sum()

    big = jax.ShapeDtypeStruct((64, 1024), jnp.float32, sharding=rep)
    x = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sharded)
    return f, (big, x), mesh


TARGETS = [ShardTarget(name="s2_fixture", build=_build)]
