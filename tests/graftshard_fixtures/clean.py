"""The negative fixture: a well-partitioned program every rule stays
silent on — specs declared and naming real axes, extents dividing
their axes, donation honored (same sharding in and out), reductions
OUTSIDE the loop, no callbacks, no in-program placement."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.graftshard import ShardTarget


def _build():
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    sharded = NamedSharding(mesh, P("data"))

    def f(state, x):
        def body(c, _):
            # per-shard work only — no cross-device op in the loop
            return c * 1.01 + x * 0.5, ()
        c, _ = jax.lax.scan(body, x, None, length=3)
        # the reduction happens ONCE, outside the loop
        return state + c, c.sum()

    st = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sharded)
    xs = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sharded)
    return f, (st, xs), mesh


TARGETS = [
    ShardTarget(
        name="clean_fixture",
        build=_build,
        donate_argnums=(0,),
        declared_specs=(("rows", ("data", None)),),
        shard_geometry=(
            {"name": "rows 8", "extent": 8, "axis": "data",
             "row_bytes": 64},
        )),
]
