"""S1 planted violation: a collective inside the scan body.

A per-iteration mean over the batch-sharded input forces GSPMD to put
an all-reduce INSIDE the compiled while body — the comm-in-loop hazard
(at iters=20 this is 20 reductions per call)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.graftshard import ShardTarget


def _build():
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))

    def step(x):
        def body(c, _):
            # mean over the sharded dim, per iteration -> all-reduce
            # in the loop body after partitioning
            return c + jnp.mean(x * c), ()
        c, _ = jax.lax.scan(body, jnp.float32(1.0), None, length=5)
        return c

    xs = jax.ShapeDtypeStruct((8, 16), jnp.float32,
                              sharding=NamedSharding(mesh, P("data")))
    return step, (xs,), mesh


TARGETS = [ShardTarget(name="s1_fixture", build=_build)]
