"""S6 planted violation: a donation dropped by resharding.

``state`` is donated and sharded over 'data'; the matching output is
constrained replicated, so the value physically moves between devices
and XLA silently DEGRADES the donation (``buffer_donor`` instead of an
``input_output_alias`` entry) — the program pays an input-sized copy
every call. Shapes are kept tiny so this plants ONLY the S6 hazard
(the resharded value stays under the S2 threshold)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.graftshard import ShardTarget


def _build():
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    sharded1 = NamedSharding(mesh, P("data"))
    sharded2 = NamedSharding(mesh, P(None, "data"))
    rep = NamedSharding(mesh, P())

    def f(state, x):
        out = state + x.sum(0)
        # resharding the donated input's successor kills the alias
        return jax.lax.with_sharding_constraint(out, rep), x * 2.0

    st = jax.ShapeDtypeStruct((16,), jnp.float32, sharding=sharded1)
    xs = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sharded2)
    return f, (st, xs), mesh


TARGETS = [ShardTarget(name="s6_fixture", build=_build,
                       donate_argnums=(0,))]
