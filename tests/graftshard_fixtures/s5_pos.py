"""S5 planted violation: a declared shard geometry whose extent does
not divide its mesh axis — GSPMD pads the trailing shard and every
device computes the dead rows (the ragged-tail lesson at the shard
level, reported as waste bytes)."""

import jax
import numpy as np
from jax.sharding import Mesh

from tools.graftshard import ShardTarget


def _build():
    return Mesh(np.array(jax.devices()[:4]), ("data",))


TARGETS = [
    ShardTarget(
        name="s5_fixture",
        kind="decl",
        build=_build,
        shard_geometry=(
            {"name": "feature-height 6", "extent": 6, "axis": "data",
             "row_bytes": 4096},
        )),
]
