"""S4 planted violations, both halves of the rule:

- a DECLARED spec naming a mesh axis ('model') the mesh doesn't have
  — the declaration layer drifted from the deployment mesh;
- a boundary arg entering the program with NO sharding at all — XLA
  silently replicates it (the with_sharding_constraint discipline)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tools.graftshard import ShardTarget


def _mesh():
    return Mesh(np.array(jax.devices()[:4]), ("data",))


def _build_decl():
    return _mesh()


def _build_unconstrained():
    mesh = _mesh()
    sharded = NamedSharding(mesh, P("data"))

    def f(a, b):
        return a.sum() + b.sum()

    a = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sharded)
    b = jax.ShapeDtypeStruct((16, 128), jnp.float32)   # no sharding
    return f, (a, b), mesh


TARGETS = [
    ShardTarget(
        name="s4_decl_fixture",
        kind="decl",
        build=_build_decl,
        declared_specs=(("activations", ("data", "model")),)),
    ShardTarget(
        name="s4_unconstrained_fixture",
        build=_build_unconstrained),
]
