"""Serialized-executable cache (serving/aot.py): the zero-compile
warm-start seam and its trust model.

Every pin here uses the engine's/cache's OWN counters, never timing —
conftest enables the jax persistent compile cache, so a "fast second
compile" proves nothing. ``compiles == 0`` + ``aot_hits == 1`` is the
claim serving/aot.py makes; bitwise-equal flow is what makes a loaded
executable interchangeable with a compiled one. The other half of the
suite is the trust model: every corruption/skew/stale-key shape must
read as a clean MISS (load returns None, caller recompiles) — no
failure mode may load a wrong executable or raise into serving.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.serving import aot
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.registry import ModelRegistry
from raft_tpu.testing import faults

from tests.conftest import mesh_subprocess_env

_HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return cfg, variables


@pytest.fixture
def images(rng):
    i1 = rng.rand(1, 32, 32, 3).astype(np.float32) * 255
    i2 = rng.rand(1, 32, 32, 3).astype(np.float32) * 255
    return i1, i2


def _full_key(**overrides):
    """A complete 12-field key for direct-AOTCache tests."""
    key = {
        "format": aot.AOT_FORMAT,
        "program": "test",
        "weights": "w" * 16,
        "geometry": [1, 8],
        "wire": "f32",
        "iters": 1,
        "config": "c" * 16,
        "donations": [],
        "partition": "single",
        "jax": jax.__version__,
        "jaxlib": __import__("jaxlib").__version__,
        "platform": jax.default_backend(),
    }
    key.update(overrides)
    return key


def _store_tiny(root):
    """Compile + store a tiny program; returns (cache, key, entry_dir,
    example input). fresh_compile: conftest enables jax's persistent
    compile cache, and a cache-deserialized executable serializes to a
    stillborn payload — the exact hazard aot.fresh_compile exists for."""
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    x = jnp.arange(8, dtype=jnp.float32)
    with aot.fresh_compile():
        lowered = fn.lower(x)
        compiled = lowered.compile()
    cache = aot.AOTCache(root)
    key = _full_key()
    edir = cache.store(key, compiled, lowered=lowered, args=(x,))
    assert edir is not None
    return cache, key, edir, x


# -- the engine seam ------------------------------------------------------


class TestEngineWarmStart:
    def test_in_process_warm_start_zero_compiles_bitwise(
            self, small_setup, images, tmp_path):
        cfg, variables = small_setup
        i1, i2 = images
        eng1 = RAFTEngine(variables, cfg, iters=1, envelope=[],
                          precompile=False, aot_cache=str(tmp_path))
        out1 = np.asarray(eng1.infer_batch(i1, i2))
        s1 = eng1.aot_stats()
        assert s1["enabled"] == 1
        assert s1["compiles"] == 1 and s1["aot_misses"] == 1
        assert s1["aot_hits"] == 0

        # a second engine over the same dir = the restarted replica.
        # aot.fresh_compile made eng1's artifact a first-generation
        # payload, so this load is deterministic even in-process.
        eng2 = RAFTEngine(variables, cfg, iters=1, envelope=[],
                          precompile=False, aot_cache=str(tmp_path))
        out2 = np.asarray(eng2.infer_batch(i1, i2))
        s2 = eng2.aot_stats()
        assert s2["compiles"] == 0, (s2, eng2._aot.last_miss)
        assert s2["aot_hits"] == 1 and s2["compiles_avoided"] == 1
        assert np.array_equal(out1, out2)   # bitwise, not allclose

    def test_weights_swap_invalidates_then_old_artifact_rehits(
            self, small_setup, tmp_path):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[],
                         precompile=False, aot_cache=str(tmp_path))
        bucket = eng.ensure_bucket(1, 32, 32)
        assert eng.aot_stats()["compiles"] == 1

        # same structure/shapes, different content: a genuinely new
        # checkpoint must MISS (content-addressed key), never load the
        # old model's artifact
        swapped = jax.tree_util.tree_map(lambda a: a + 1e-3, variables)
        eng.update_weights(swapped)
        assert eng.drop_bucket(bucket)
        eng.ensure_bucket(1, 32, 32)
        s = eng.aot_stats()
        assert s["compiles"] == 2 and s["aot_misses"] == 2
        entries = os.listdir(os.path.join(str(tmp_path), "objects"))
        assert len(entries) == 2    # two checkpoints, two artifacts

        # swapping BACK re-keys to the first artifact: the old
        # checkpoint's entry is reachable again (content addressing —
        # pinned at the key/manifest level: an in-process reload of a
        # program whose identical twin was ALREADY compiled here trips
        # a CPU-backend symbol-registry quirk, a sequence the real
        # engine never runs — a twin in the bucket table means no AOT
        # load happens at all; cross-process reload is the test above)
        eng.update_weights(variables)
        key_back = eng._aot_key(bucket)
        edir = eng._aot.entry_dir(key_back)
        assert os.path.isdir(edir)
        with open(os.path.join(edir, "manifest.json"),
                  encoding="utf-8") as f:
            assert json.load(f)["key"] == key_back

    def test_cross_process_warm_start(self, tmp_path):
        """The scenario the cache exists for: a fresh interpreter loads
        the artifact a previous process compiled — zero compiles,
        bitwise-identical flow."""
        worker = os.path.join(_HERE, "aot_warm_worker.py")
        cache = str(tmp_path / "artifacts")
        env = mesh_subprocess_env(local_devices=1)
        stats, outs = [], []
        for leg in ("cold", "warm"):
            out_npy = str(tmp_path / f"{leg}.npy")
            proc = subprocess.run(
                [sys.executable, worker, "--cache", cache,
                 "--out", out_npy],
                capture_output=True, text=True, env=env, timeout=600)
            assert proc.returncode == 0, proc.stderr[-2000:]
            line = [ln for ln in proc.stdout.splitlines()
                    if ln.startswith("AOT_WORKER ")]
            assert line, proc.stdout
            stats.append(json.loads(line[-1][len("AOT_WORKER "):]))
            outs.append(np.load(out_npy))
        cold, warm = stats
        assert cold["compiles"] == 1 and cold["aot_misses"] == 1
        assert warm["compiles"] == 0, warm
        assert warm["aot_hits"] == 1 and warm["compiles_avoided"] == 1
        assert np.array_equal(outs[0], outs[1])

    def test_disabled_engine_reports_disabled(self, small_setup):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[],
                         precompile=False)
        s = eng.aot_stats()
        assert s["enabled"] == 0
        assert s["aot_hits"] == 0 and s["aot_misses"] == 0


# -- the trust model (direct AOTCache) ------------------------------------


class TestVerifiedLoad:
    def test_roundtrip_hits_and_runs(self, tmp_path):
        """Store here, load in a FRESH interpreter (deterministic —
        in-process reloads roll the CPU twin-symbol dice) and run."""
        _, key, _, x = _store_tiny(str(tmp_path))
        prog = (
            "import sys, json\n"
            f"sys.path.insert(0, {os.path.dirname(_HERE)!r})\n"
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import jax, jax.numpy as jnp, numpy as np\n"
            "try:\n"
            "    from jax._src import xla_bridge as _xb\n"
            "    _xb._backend_factories.pop('axon', None)\n"
            "except Exception: pass\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from raft_tpu.serving import aot\n"
            "cache = aot.AOTCache(sys.argv[1])\n"
            "key = json.loads(sys.argv[2])\n"
            "runner = cache.load(key)\n"
            "assert runner is not None, cache.last_miss\n"
            "out = np.asarray(runner(jnp.arange(8, dtype=jnp.float32)))\n"
            "assert np.array_equal(out, np.arange(8) * 2.0 + 1.0), out\n"
            "print('ROUNDTRIP OK')\n")
        proc = subprocess.run(
            [sys.executable, "-c", prog, str(tmp_path),
             json.dumps(key)],
            capture_output=True, text=True,
            env=mesh_subprocess_env(local_devices=1), timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "ROUNDTRIP OK" in proc.stdout

    @pytest.mark.parametrize("tamper,reason", [
        ("blob-truncate", "blob hash mismatch"),
        ("blob-bit-flip", "blob hash mismatch"),
        ("manifest-torn", "JSONDecodeError"),
        ("manifest-version-skew", "format skew"),
        ("manifest-key-swap", "key mismatch"),
    ])
    def test_corruption_routes_to_miss(self, tmp_path, tamper, reason):
        """Every damage shape reads as a clean miss with the RIGHT
        diagnosis — and the pristine entry still loads afterwards."""
        _, key, edir, _ = _store_tiny(str(tmp_path))
        backup = str(tmp_path / "backup")
        shutil.copytree(edir, backup)
        blob = os.path.join(edir, "executable.bin")
        manifest = os.path.join(edir, "manifest.json")
        if tamper == "blob-truncate":
            with open(blob, "rb") as f:
                data = f.read()
            with open(blob, "wb") as f:
                f.write(data[:len(data) // 2])
        elif tamper == "blob-bit-flip":
            with open(blob, "rb") as f:
                data = bytearray(f.read())
            data[len(data) // 2] ^= 0x40
            with open(blob, "wb") as f:
                f.write(bytes(data))
        elif tamper == "manifest-torn":
            with open(manifest, encoding="utf-8") as f:
                text = f.read()
            with open(manifest, "w", encoding="utf-8") as f:
                f.write(text[:len(text) // 2])
        elif tamper == "manifest-version-skew":
            with open(manifest, encoding="utf-8") as f:
                m = json.load(f)
            m["format"] = "jax_serialize_executable_v0"
            with open(manifest, "w", encoding="utf-8") as f:
                json.dump(m, f)
        elif tamper == "manifest-key-swap":
            with open(manifest, encoding="utf-8") as f:
                m = json.load(f)
            m["key"] = dict(m["key"], weights="f" * 16)
            with open(manifest, "w", encoding="utf-8") as f:
                json.dump(m, f)
        fresh = aot.AOTCache(str(tmp_path))
        assert fresh.load(key) is None
        assert reason in fresh.last_miss, fresh.last_miss
        # restore: the pristine bytes verify again. Checked at the
        # manifest/hash layer, not via a full deserialize — repeated
        # in-process deserializes of twin programs trip a CPU-backend
        # symbol-registry quirk (fresh-process loads, the real
        # scenario, are pinned by test_cross_process_warm_start)
        shutil.rmtree(edir)
        shutil.copytree(backup, edir)
        assert fresh._entry_valid(edir, key)

    def test_stale_key_is_absent_miss(self, tmp_path):
        cache, key, _, _ = _store_tiny(str(tmp_path))
        fresh = aot.AOTCache(str(tmp_path))
        assert fresh.load(dict(key, weights="f" * 16)) is None
        assert fresh.last_miss == "absent"

    def test_store_refuses_incomplete_key(self, tmp_path):
        fn = jax.jit(lambda x: x + 1.0)
        x = jnp.zeros(4)
        compiled = fn.lower(x).compile()
        key = _full_key()
        del key["weights"]
        with pytest.raises(ValueError, match="weights"):
            aot.AOTCache(str(tmp_path)).store(key, compiled)

    def test_unserializable_program_stores_none(self, tmp_path):
        """Host-callback programs can't serialize; store must decline
        (None), never raise — the cache accelerates, it never gates."""
        def fn(x):
            return jax.pure_callback(
                lambda v: np.asarray(v) * 2.0,  # graftlint: disable=R1
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        x = jnp.zeros(4, jnp.float32)
        compiled = jax.jit(fn).lower(x).compile()
        assert aot.AOTCache(str(tmp_path)).store(_full_key(),
                                                 compiled) is None

    def test_store_replaces_invalid_entry(self, tmp_path):
        _, key, edir, x = _store_tiny(str(tmp_path))
        with open(os.path.join(edir, "executable.bin"), "wb") as f:
            f.write(b"\0" * 64)
        cache2, _, edir2, _ = _store_tiny(str(tmp_path))
        assert edir2 == edir
        assert aot.AOTCache(str(tmp_path))._entry_valid(edir, key)


# -- artifact-store GC ----------------------------------------------------


class TestEvict:
    def _seed(self, root, n=3):
        """Store ``n`` entries under distinct weights fingerprints,
        mtimes staggered oldest-first (entry 0 oldest)."""
        fn = jax.jit(lambda x: x * 2.0 + 1.0)
        x = jnp.arange(8, dtype=jnp.float32)
        with aot.fresh_compile():
            lowered = fn.lower(x)
            compiled = lowered.compile()
        cache = aot.AOTCache(root)
        keys = [_full_key(weights=f"w{i}" * 8) for i in range(n)]
        dirs = []
        now = __import__("time").time()
        for i, key in enumerate(keys):
            edir = cache.store(key, compiled, lowered=lowered, args=(x,))
            assert edir is not None
            mtime = now - 1000.0 + 100.0 * i
            os.utime(os.path.join(edir, "manifest.json"),
                     (mtime, mtime))
            dirs.append(edir)
        return cache, keys, dirs

    def test_weights_policy_removes_only_matching(self, tmp_path):
        cache, keys, dirs = self._seed(str(tmp_path))
        out = cache.evict(weights=keys[1]["weights"])
        assert out["removed"] == 1 and out["remaining"] == 2
        assert out["removed_bytes"] > 0
        assert not os.path.isdir(dirs[1])
        assert os.path.isdir(dirs[0]) and os.path.isdir(dirs[2])
        # the survivors still verify
        assert cache._entry_valid(dirs[0], keys[0])

    def test_max_age_removes_stale_entries(self, tmp_path):
        cache, keys, dirs = self._seed(str(tmp_path))
        # entries sit at ages ~1000s/900s/800s: cut at 850
        out = cache.evict(max_age_s=850.0)
        assert out["removed"] == 2 and out["remaining"] == 1
        assert os.path.isdir(dirs[2])

    def test_max_bytes_evicts_oldest_first(self, tmp_path):
        cache, keys, dirs = self._seed(str(tmp_path))
        per = os.path.getsize(os.path.join(dirs[0], "executable.bin"))
        out = cache.evict(max_bytes=int(per * 1.5))
        assert out["removed"] == 2
        assert out["remaining"] == 1
        assert out["remaining_bytes"] <= per * 1.5
        # the NEWEST entry (the one a warm restart wants) survived
        assert os.path.isdir(dirs[2])
        assert not os.path.isdir(dirs[0]) and not os.path.isdir(dirs[1])

    def test_torn_entry_reads_as_oldest_garbage(self, tmp_path):
        cache, keys, dirs = self._seed(str(tmp_path))
        torn = os.path.join(str(tmp_path), "objects", "deadbeef")
        os.makedirs(torn)
        with open(os.path.join(torn, "manifest.json"), "w",
                  encoding="utf-8") as f:
            f.write("{not json")
        out = cache.evict(max_age_s=3600.0)
        assert not os.path.isdir(torn)      # mtime 0.0: first to go
        assert out["remaining"] == 3        # real entries untouched

    def test_empty_store_is_a_noop(self, tmp_path):
        out = aot.AOTCache(str(tmp_path)).evict(max_bytes=0)
        assert out == {"removed": 0, "removed_bytes": 0,
                       "remaining": 0, "remaining_bytes": 0}


class TestRegistryRetirementGC:
    def test_rollback_evicts_canary_artifacts_keeps_live(
            self, small_setup, tmp_path):
        """The registry half of the GC satellite: a rolled-back
        canary's serialized executables leave the shared store with
        it; the live fingerprint's artifacts stay (a warm restart
        still loads them) and the eviction is an auditable event."""
        cfg, variables = small_setup
        adir = str(tmp_path / "artifacts")
        mpath = str(tmp_path / "metrics.jsonl")
        objs = os.path.join(adir, "objects")
        reg = ModelRegistry(metrics_path=mpath, gather_window_s=0.0)
        try:
            reg.add_model("m", variables, cfg, iters=1,
                          envelope=[(1, 32, 32)], artifact_dir=adir)
            swapped = jax.tree_util.tree_map(lambda a: a + 1e-3,
                                             variables)
            reg.deploy("m", swapped, canary_fraction=0.5,
                       artifact_dir=adir)
            assert len(os.listdir(objs)) == 2
            live = reg._models["m"].live.engine
            reg.rollback("m")
            remaining = os.listdir(objs)
            assert len(remaining) == 1
            with open(os.path.join(objs, remaining[0],
                                   "manifest.json"),
                      encoding="utf-8") as f:
                survivor = json.load(f)["key"]["weights"]
            assert survivor == live._weights_fp
            events = [json.loads(line) for line in open(mpath)]
            gone = [e for e in events
                    if e.get("event") == "aot_evicted"]
            assert len(gone) == 1 and gone[0]["removed"] == 1
        finally:
            reg.close()


# -- the chaos surface ----------------------------------------------------


class TestFaultSite:
    def test_fault_point_raise_reads_as_miss(self, tmp_path):
        _, key, _, _ = _store_tiny(str(tmp_path))
        cache = aot.AOTCache(str(tmp_path))
        faults.arm([{"site": "aot.load", "kind": "raise"}])
        try:
            assert cache.load(key) is None
            assert "FaultInjected" in cache.last_miss
        finally:
            faults.disarm()
        # disarmed: the entry itself was never damaged
        assert cache._entry_valid(cache.entry_dir(key), key)

    def test_fault_file_corrupt_reads_as_miss(self, tmp_path):
        _, key, _, _ = _store_tiny(str(tmp_path))
        cache = aot.AOTCache(str(tmp_path))
        faults.arm([{"site": "aot.load", "kind": "corrupt",
                     "at": 1, "count": 1}])
        try:
            assert cache.load(key) is None
            assert cache.last_miss == "blob hash mismatch"
        finally:
            faults.disarm()

    def test_engine_recompiles_cleanly_through_corrupt_artifact(
            self, small_setup, images, tmp_path):
        """The chaos-drill round in miniature: a corrupted artifact
        mid-run reads as miss, the engine recompiles, and the
        re-stored entry is valid again."""
        cfg, variables = small_setup
        i1, i2 = images
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[],
                         precompile=False, aot_cache=str(tmp_path))
        out1 = np.asarray(eng.infer_batch(i1, i2))
        bucket = eng.ensure_bucket(1, 32, 32)
        assert eng.drop_bucket(bucket)
        faults.arm([{"site": "aot.load", "kind": "corrupt",
                     "at": 1, "count": 1}])
        try:
            out2 = np.asarray(eng.infer_batch(i1, i2))
        finally:
            faults.disarm()
        s = eng.aot_stats()
        assert s["compiles"] == 2 and s["aot_misses"] == 2, s
        assert np.array_equal(out1, out2)
        # the recompile RE-STORED over the corrupted entry: the digest
        # verifies again (a fresh replica loads it — the cross-process
        # test pins that path; an in-process reload would roll the
        # CPU-backend twin-symbol dice, see TestVerifiedLoad)
        key = eng._aot_key(bucket)
        assert eng._aot._entry_valid(eng._aot.entry_dir(key), key)


# -- the registry seam ----------------------------------------------------


class TestRegistryArtifactDir:
    def test_add_model_threads_artifact_dir(self, small_setup,
                                            tmp_path):
        """The wiring: ``artifact_dir=`` arms the engine the registry
        builds (zero-compile proof is the cross-process test below —
        in-process reloads roll the CPU twin-symbol dice)."""
        cfg, variables = small_setup
        adir = str(tmp_path / "artifacts")
        reg = ModelRegistry(gather_window_s=0.0)
        try:
            reg.add_model("m", variables, cfg, iters=1,
                          envelope=[(1, 32, 32)], artifact_dir=adir)
            live = reg._models["m"].live.engine
            s = live.aot_stats()
            assert s["enabled"] == 1
            assert live._aot.root == os.path.abspath(adir)
            # precompiling the envelope published the artifact
            assert len(os.listdir(os.path.join(adir, "objects"))) == 1
        finally:
            reg.close()

    @pytest.mark.slow
    def test_registry_cross_process_warm_start(self, tmp_path):
        """The restarting supervisor: a fresh process re-registers the
        same checkpoint against a warm dir — the live variant AND a
        re-deploy of known weights load with zero compiles."""
        worker = os.path.join(_HERE, "aot_warm_worker.py")
        cache = str(tmp_path / "artifacts")
        env = mesh_subprocess_env(local_devices=1)

        cold = subprocess.run(
            [sys.executable, worker, "--cache", cache,
             "--out", str(tmp_path / "cold.npy")],
            capture_output=True, text=True, env=env, timeout=600)
        assert cold.returncode == 0, cold.stderr[-2000:]

        warm = subprocess.run(
            [sys.executable, worker, "--cache", cache, "--registry"],
            capture_output=True, text=True, env=env, timeout=600)
        assert warm.returncode == 0, warm.stderr[-2000:]
        line = [ln for ln in warm.stdout.splitlines()
                if ln.startswith("AOT_WORKER ")]
        assert line, warm.stdout
        stats = json.loads(line[-1][len("AOT_WORKER "):])
        assert stats["live"]["compiles"] == 0, stats
        assert stats["live"]["aot_hits"] >= 1
        assert stats["canary"]["compiles"] == 0, stats
        assert stats["canary"]["aot_hits"] >= 1
