"""End-to-end trainer loop, checkpoint/resume, and logger tests (CPU mesh).

The reference has no trainer tests (SURVEY.md §4); these pin the loop's
contract: steps advance, loss is finite, full-state resume restores the
optimizer/step exactly, and metric names match the reference dashboards.
"""

import glob
import json
import os

import numpy as np
import pytest

import jax

from raft_tpu.config import RAFTConfig, TrainConfig
from raft_tpu.training import checkpoint as ckpt_lib
from raft_tpu.training.logger import Logger
from raft_tpu.training.train_step import create_train_state
from raft_tpu.training.trainer import train


class SyntheticLoader:
    """Tiny deterministic batch source (stands in for PrefetchLoader)."""

    def __init__(self, batch_size=8, hw=(64, 64), n_batches=2, seed=0):
        rng = np.random.RandomState(seed)
        h, w = hw
        self.batches = [{
            "image1": rng.rand(batch_size, h, w, 3).astype(np.float32) * 255,
            "image2": rng.rand(batch_size, h, w, 3).astype(np.float32) * 255,
            "flow": rng.randn(batch_size, h, w, 2).astype(np.float32),
            "valid": np.ones((batch_size, h, w), np.float32),
        } for _ in range(n_batches)]

    def __iter__(self):
        return iter(self.batches)


@pytest.fixture(scope="module")
def small_cfg():
    return RAFTConfig(small=True)


def make_train_cfg(tmpdir, **kw):
    base = dict(name="t", stage="chairs", lr=1e-4, num_steps=3, batch_size=8,
                image_size=(64, 64), iters=2, val_freq=10 ** 9,
                sum_freq=2, checkpoint_dir=os.path.join(tmpdir, "ckpt"),
                log_dir=os.path.join(tmpdir, "runs"))
    base.update(kw)
    return TrainConfig(**base)


class TestTrainLoop:
    def test_runs_and_saves_final(self, tmp_path, small_cfg):
        cfg = make_train_cfg(str(tmp_path))
        state = train(small_cfg, cfg, loader=SyntheticLoader())
        assert int(state.step) == 3
        final = os.path.join(cfg.checkpoint_dir, "t.msgpack")
        assert os.path.exists(final)
        # weights reloadable through the standard path
        from raft_tpu.tools.convert import load_converted
        variables = load_converted(final, small_cfg, image_hw=(64, 64))
        assert "params" in variables

    def test_add_noise_and_metrics_finite(self, tmp_path, small_cfg):
        cfg = make_train_cfg(str(tmp_path), add_noise=True, num_steps=2)
        state = train(small_cfg, cfg, loader=SyntheticLoader())
        assert int(state.step) == 2
        leaves = jax.tree.leaves(state.params)
        assert all(bool(np.isfinite(np.asarray(x)).all()) for x in leaves)

    def test_uint8_wire_step_identical(self, tmp_path, small_cfg):
        """The loader's uint8 wire format must not change the step at all:
        integral-valued float32 batch vs its uint8 twin -> bitwise-equal
        loss and params after one step (the step casts on device)."""
        from raft_tpu.training.train_step import make_train_step

        cfg = make_train_cfg(str(tmp_path), num_steps=1)
        rng = jax.random.PRNGKey(0)
        host = np.random.RandomState(3)
        f32 = {
            "image1": np.floor(
                host.rand(2, 64, 64, 3) * 255).astype(np.float32),
            "image2": np.floor(
                host.rand(2, 64, 64, 3) * 255).astype(np.float32),
            "flow": host.randn(2, 64, 64, 2).astype(np.float32),
            "valid": np.ones((2, 64, 64), np.float32),
        }
        u8 = dict(f32, image1=f32["image1"].astype(np.uint8),
                  image2=f32["image2"].astype(np.uint8),
                  valid=f32["valid"].astype(np.uint8))
        step = jax.jit(make_train_step(small_cfg, cfg))
        state0 = create_train_state(small_cfg, cfg, rng, image_hw=(64, 64))
        s_f32, m_f32 = step(state0, f32, rng)
        state0 = create_train_state(small_cfg, cfg, rng, image_hw=(64, 64))
        s_u8, m_u8 = step(state0, u8, rng)
        assert float(m_f32["loss"]) == float(m_u8["loss"])
        for a, b in zip(jax.tree.leaves(s_f32.params),
                        jax.tree.leaves(s_u8.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rng_folds_step_counter(self, tmp_path, small_cfg):
        """The step derives its key from (base rng, state.step): re-running
        from the same state reproduces bitwise (resume contract), and the
        derived key advances with the counter so add_noise draws differ
        across steps under the constant base key."""
        from raft_tpu.training.train_step import make_train_step

        cfg = make_train_cfg(str(tmp_path), add_noise=True, num_steps=2)
        rng = jax.random.PRNGKey(7)
        batch = SyntheticLoader(batch_size=2, n_batches=1).batches[0]
        step = jax.jit(make_train_step(small_cfg, cfg))
        state0 = create_train_state(small_cfg, cfg, rng, image_hw=(64, 64))
        _, m1 = step(state0, batch, rng)
        state0b = create_train_state(small_cfg, cfg, rng, image_hw=(64, 64))
        _, m1b = step(state0b, batch, rng)
        assert float(m1["loss"]) == float(m1b["loss"])  # same step -> same key
        # IDENTICAL params, same batch and base key, step counter bumped
        # -> the derived key must change the noise draw (comparing against
        # a stepped state would be vacuous: its params differ too)
        import jax.numpy as jnp
        bumped = create_train_state(
            small_cfg, cfg, rng, image_hw=(64, 64)).replace(
                step=jnp.ones((), jnp.int32))
        _, m_b = step(bumped, batch, rng)
        assert float(m_b["loss"]) != float(m1["loss"])


class TestCheckpointResume:
    def test_full_state_roundtrip(self, tmp_path, small_cfg):
        tcfg = make_train_cfg(str(tmp_path), num_steps=2)
        state = train(small_cfg, tcfg, loader=SyntheticLoader())
        stage_dir = os.path.join(tcfg.checkpoint_dir, "t", "chairs")
        ckpt_lib.save_train_state(stage_dir, state, wait=True)

        fresh = create_train_state(small_cfg, tcfg, jax.random.PRNGKey(1),
                                   image_hw=(64, 64))
        restored = ckpt_lib.restore_train_state(stage_dir, fresh)
        assert int(restored.step) == int(state.step)
        for a, b in zip(jax.tree.leaves(restored.params),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # optimizer state (adam moments) restored too — the upgrade the
        # reference lacks (train.py:185-187 saves weights only)
        for a, b in zip(jax.tree.leaves(restored.opt_state),
                        jax.tree.leaves(state.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_latest_step_empty(self, tmp_path):
        assert ckpt_lib.latest_step(str(tmp_path / "nope")) is None


class TestLogger:
    def test_running_mean_and_jsonl(self, tmp_path, capsys):
        log_dir = str(tmp_path / "runs")
        logger = Logger(log_dir, sum_freq=2, lr_fn=lambda s: 1e-4)
        # reference quirk preserved (train.py:119-123): the window closes
        # when total_steps % freq == freq-1, so the FIRST window holds
        # freq-1 pushes but still divides by freq
        logger.push({"epe": 2.0, "loss": 1.0})   # closes window 1
        logger.push({"epe": 4.0, "loss": 3.0})
        logger.push({"epe": 6.0, "loss": 5.0})   # closes window 2
        logger.write_dict({"chairs": 5.0})
        logger.close()
        out = capsys.readouterr().out
        assert "0.0001" in out  # lr printed
        recs = [json.loads(l) for l in
                open(os.path.join(log_dir, "metrics.jsonl"))]
        assert recs[0]["epe"] == pytest.approx(1.0)  # 2.0 / freq
        assert recs[1]["epe"] == pytest.approx(5.0)  # (4+6) / freq
        assert recs[-1]["chairs"] == 5.0
        assert glob.glob(os.path.join(log_dir, "events.*"))  # tensorboard


class TestCurriculum:
    def test_two_stage_chain_restores_previous_weights(self, tmp_path,
                                                       small_cfg,
                                                       monkeypatch):
        """train_curriculum must chain stages the way train_standard.sh
        chains --restore_ckpt: stage N+1 starts from stage N's final
        weights file with a fresh schedule."""
        from raft_tpu.training import trainer

        restored = []
        orig = trainer.load_weights

        def spy(path, config):
            restored.append(path)
            return orig(path, config)

        monkeypatch.setattr(trainer, "load_weights", spy)

        ckpt = os.path.join(str(tmp_path), "ckpt")
        trainer.train_curriculum(
            ["chairs", "things"], small_cfg, name="c",
            loader_factory=lambda cfg: SyntheticLoader(
                batch_size=8, n_batches=2),
            num_steps=2, batch_size=8, image_size=(64, 64), iters=2,
            val_freq=10 ** 9, sum_freq=10, checkpoint_dir=ckpt,
            log_dir=os.path.join(str(tmp_path), "runs"), validation=())

        chairs_final = os.path.join(ckpt, "c-chairs.msgpack")
        things_final = os.path.join(ckpt, "c-things.msgpack")
        assert os.path.exists(chairs_final)
        assert os.path.exists(things_final)
        # the things stage restored exactly the chairs final weights
        assert restored == [chairs_final]


class TestSyntheticTrainCLI:
    def test_rejects_fewer_samples_than_batch(self):
        """--synthetic N with N < batch_size would make the drop-last
        loader yield zero batches and the trainer spin forever — the CLI
        must refuse up front with a readable message."""
        from raft_tpu.cli.train import _synthetic_loader

        cfg = TrainConfig(stage="chairs", batch_size=10)
        with pytest.raises(SystemExit, match="zero batches"):
            _synthetic_loader(8, cfg)

    def test_loader_persists_and_feeds_real_pipeline(self, monkeypatch,
                                                     tmp_path):
        """The generated dataset lands under ~/.cache once (marker file),
        and the loader yields real decoded+augmented+collated batches."""
        monkeypatch.setenv("HOME", str(tmp_path))
        from raft_tpu.cli.train import _synthetic_loader

        cfg = TrainConfig(stage="chairs", batch_size=2, num_workers=2,
                          image_size=(368, 496))
        loader = _synthetic_loader(4, cfg)
        batch = next(iter(loader))
        assert batch["image1"].shape == (2, 368, 496, 3)
        assert batch["flow"].dtype == np.float32
        root = tmp_path / ".cache" / "raft_tpu" / "synthetic_chairs_4"
        assert (root / ".complete").exists()
        # second call reuses the dataset (marker short-circuits the write)
        before = sorted(os.listdir(root))
        _synthetic_loader(4, cfg)
        assert sorted(os.listdir(root)) == before


def test_train_cli_exposes_step_config_knobs():
    """The measured-best step config (bf16 volumes, lookup backend, scan
    unroll) must be reachable from the real training CLI, not just from
    bench.py."""
    from raft_tpu.cli.train import build_parser, configs_from_args

    m, _ = configs_from_args(build_parser().parse_args(
        ["--stage", "chairs", "--corr_dtype", "bfloat16",
         "--corr_impl", "onehot_t", "--scan_unroll", "2"]))
    assert (m.corr_dtype, m.corr_impl, m.scan_unroll) == (
        "bfloat16", "onehot_t", 2)
    # reference-parity defaults stay untouched when the flags are absent
    m2, _ = configs_from_args(build_parser().parse_args(["--stage", "chairs"]))
    assert (m2.corr_dtype, m2.corr_impl, m2.scan_unroll) == (
        "float32", "onehot", 1)


def test_train_cli_fused_loss_tristate():
    """--fused_loss is tri-state: absent -> the config's auto default
    (None: fused where available), and both explicit directions thread
    through to TrainConfig."""
    from raft_tpu.cli.train import build_parser, configs_from_args

    base = ["--stage", "chairs"]
    _, t_auto = configs_from_args(build_parser().parse_args(base))
    assert t_auto.fused_loss is None
    _, t_on = configs_from_args(
        build_parser().parse_args(base + ["--fused_loss"]))
    assert t_on.fused_loss is True
    _, t_off = configs_from_args(
        build_parser().parse_args(base + ["--no-fused_loss"]))
    assert t_off.fused_loss is False
