"""graftlint: the static-analysis gate (tools/graftlint/).

Three layers:

- per-rule fixture tests: each rule R1-R6 has a positive fixture (must
  fire) and a negative fixture (must stay silent) under
  ``tests/graftlint_fixtures/`` — the positives for R5/R6 are distilled
  verbatim from the PRE-FIX round-5 advisor findings (trainer watchdog
  lifecycle, bench exit code), pinning that the satellites fixed in
  this PR are inside the linter's detection envelope;
- mechanism tests: per-line pragmas, baseline grandfathering/burn-down,
  ``--json`` output, the fixture-dir walk exclusion;
- the repo gate: ``python -m tools.graftlint raft_tpu bench.py tools
  tests --baseline tools/graftlint/baseline.json`` must exit 0 — new
  violations anywhere in the linted tree fail tier-1.

graftlint is pure-stdlib ``ast``; nothing here touches jax.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "graftlint_fixtures")
BASELINE = os.path.join(REPO, "tools", "graftlint", "baseline.json")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftlint import (apply_baseline, lint_file, lint_paths,  # noqa: E402
                             load_baseline, write_baseline)
from tools.graftlint.core import collect_files, main  # noqa: E402

RULES = ("R1", "R2", "R3", "R4", "R5", "R6")


def fixture(name):
    return os.path.join(FIXTURES, name)


def rules_hit(path):
    return {f.rule for f in lint_file(path)}


class TestRuleFixtures:
    @pytest.mark.parametrize("rule", RULES)
    def test_positive_fixture_fires(self, rule):
        path = fixture(f"{rule.lower()}_pos.py")
        assert rule in rules_hit(path), \
            f"{rule} positive fixture produced no {rule} finding"

    @pytest.mark.parametrize("rule", RULES)
    def test_negative_fixture_is_silent(self, rule):
        path = fixture(f"{rule.lower()}_neg.py")
        findings = lint_file(path)
        assert not findings, \
            f"{rule} negative fixture is not clean: " \
            + "; ".join(f.render() for f in findings)

    def test_prefix_advisor_findings_in_envelope(self):
        """The two round-5 advisor bugs this PR fixes, as distilled
        pre-fix code shapes, are DETECTED (R5 lifecycle on the trainer
        shape, R6 exit-code on the bench shape) — and the fixed real
        files no longer trip those rules."""
        r5 = [f for f in lint_file(fixture("r5_pos.py"))
              if f.rule == "R5"]
        assert any("hang_watch" in f.message for f in r5)
        r6 = [f for f in lint_file(fixture("r6_pos.py"))
              if f.rule == "R6"]
        assert any("os._exit(2)" in f.message for f in r6)

        trainer = os.path.join(REPO, "raft_tpu", "training",
                               "trainer.py")
        assert "R5" not in rules_hit(trainer)
        assert "R6" not in rules_hit(os.path.join(REPO, "bench.py"))


class TestMechanisms:
    def test_pragma_suppresses_named_rule(self, tmp_path):
        bad = "import os\nos._exit(2)\n"
        p = tmp_path / "bad.py"
        p.write_text(bad)
        assert {f.rule for f in lint_file(str(p))} == {"R6"}
        p.write_text("import os\nos._exit(2)  # graftlint: disable=R6\n")
        assert lint_file(str(p)) == []
        # the pragma names a DIFFERENT rule: finding survives
        p.write_text("import os\nos._exit(2)  # graftlint: disable=R1\n")
        assert {f.rule for f in lint_file(str(p))} == {"R6"}
        p.write_text("import os\nos._exit(2)  # graftlint: disable=all\n")
        assert lint_file(str(p)) == []

    def test_baseline_grandfathers_then_burns_down(self, tmp_path):
        findings = lint_file(fixture("r6_pos.py"))
        assert findings
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), findings)
        new, stale = apply_baseline(findings, load_baseline(str(bl)))
        assert new == [] and stale == []
        # burn-down: a fixed finding leaves a STALE baseline entry,
        # and a fresh violation is NOT hidden by it
        new, stale = apply_baseline(findings[:1],
                                    load_baseline(str(bl)))
        assert new == [] and len(stale) == len(findings) - 1
        # a partial run that never linted the entry's file is merely
        # unchecked, not stale
        new, stale = apply_baseline([], load_baseline(str(bl)),
                                    linted_paths=["some/other.py"])
        assert new == [] and stale == []

    def test_stale_baseline_entry_fails_the_gate(self, tmp_path, capsys):
        """A lingering entry would silently grandfather the NEXT
        reintroduction of that exact line — once the entry's file is
        linted and the finding is gone, the CLI must force a
        regenerate instead of advising one."""
        p = tmp_path / "legacy.py"
        p.write_text("import os\nos._exit(2)\n")
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), lint_file(str(p)))
        rc = main([str(p), "--baseline", str(bl)])
        assert rc == 0                      # grandfathered
        # burn the finding down WITHOUT regenerating the baseline
        p.write_text("import os\n")
        rc = main([str(p), "--baseline", str(bl)])
        assert rc == 1
        assert "stale baseline" in capsys.readouterr().err
        # ...but an entry for a file OUTSIDE this run's paths is
        # merely unchecked, not stale
        other = tmp_path / "other.py"
        other.write_text("x = 1\n")
        rc = main([str(other), "--baseline", str(bl)])
        assert rc == 0

    def test_write_baseline_refuses_rule_filter(self, tmp_path):
        bl = tmp_path / "baseline.json"
        rc = main([fixture("r6_pos.py"), "--rules", "R1",
                   "--write-baseline", str(bl)])
        assert rc == 2 and not bl.exists()

    def test_pragma_inside_string_literal_does_not_suppress(
            self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text('import os\n'
                     'os._exit(2); s = "# graftlint: disable=all"\n')
        assert {f.rule for f in lint_file(str(p))} == {"R6"}

    def test_daemon_after_unrelated_finally_still_flagged(self,
                                                          tmp_path):
        p = tmp_path / "d.py"
        p.write_text(
            "import threading\n"
            "def leaky(path, work):\n"
            "    try:\n"
            "        f = open(path)\n"
            "    finally:\n"
            "        f.close()\n"
            "    t = threading.Thread(target=work, daemon=True)\n"
            "    t.start()\n")
        assert "R5" in {f.rule for f in lint_file(str(p))}
        # the loader.py pattern — armed, THEN a try/finally signals
        # shutdown — stays exempt
        p.write_text(
            "import threading\n"
            "def ok(path, work, stop):\n"
            "    t = threading.Thread(target=work, daemon=True)\n"
            "    t.start()\n"
            "    try:\n"
            "        return 1\n"
            "    finally:\n"
            "        stop.set()\n")
        assert "R5" not in {f.rule for f in lint_file(str(p))}
        # a finally inside a NESTED function can never stop the outer
        # thread — it must not exempt the arming
        p.write_text(
            "import threading\n"
            "def leaky(work, risky):\n"
            "    t = threading.Thread(target=work, daemon=True)\n"
            "    t.start()\n"
            "    risky()\n"
            "    def helper(path):\n"
            "        try:\n"
            "            f = open(path)\n"
            "        finally:\n"
            "            f.close()\n"
            "    return helper\n")
        assert "R5" in {f.rule for f in lint_file(str(p))}

    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        findings = lint_file(str(p))
        assert len(findings) == 1 and findings[0].rule == "E1"

    def test_walk_excludes_fixture_dir_but_explicit_file_wins(self):
        walked = collect_files([os.path.join(REPO, "tests")])
        assert not any("graftlint_fixtures" in p for p in walked)
        explicit = collect_files([fixture("r1_pos.py")])
        assert explicit == [fixture("r1_pos.py")]

    def test_rules_filter_and_unknown_rule_errors(self, capsys):
        rc = main([fixture("r6_pos.py"), "--rules", "R1"])
        assert rc == 0          # R6 violations invisible to an R1 run
        rc = main([fixture("r6_pos.py"), "--rules", "R9"])
        assert rc == 2


class TestParseCache:
    """The per-file parse cache (PR 4): content-hash keyed, rules-aware,
    invalidated by any edit to the linter package itself — an
    accelerator that can never replay stale findings."""

    BAD = "import os\nos._exit(2)\n"

    def test_cache_replays_then_content_hash_invalidates(self, tmp_path):
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        first = lint_paths([str(p)], cache_path=str(cache))
        assert {f.rule for f in first} == {"R6"} and cache.exists()
        # prove the second run is a HIT: doctor the stored finding and
        # watch the doctored copy come back
        data = json.loads(cache.read_text())
        (key,) = data["files"]
        data["files"][key][0]["message"] = "FROM-CACHE"
        cache.write_text(json.dumps(data))
        assert [f.message for f in
                lint_paths([str(p)], cache_path=str(cache))] \
            == ["FROM-CACHE"]
        # any edit changes the content hash: the entry is dead
        p.write_text(self.BAD + "# touched\n")
        fresh = lint_paths([str(p)], cache_path=str(cache))
        assert [f.message for f in fresh] != ["FROM-CACHE"]
        assert {f.rule for f in fresh} == {"R6"}
        # ...and the superseded-content entry is EVICTED, not kept
        # forever (the cache must not grow by one entry per edit)
        assert len(json.loads(cache.read_text())["files"]) == 1

    def test_linter_signature_invalidates_whole_cache(self, tmp_path):
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        lint_paths([str(p)], cache_path=str(cache))
        data = json.loads(cache.read_text())
        data["sig"] = "some-older-graftlint"
        (key,) = data["files"]
        data["files"][key][0]["message"] = "FROM-STALE-CACHE"
        cache.write_text(json.dumps(data))
        # a cache written by a different linter version is ignored
        # wholesale and rewritten under the current signature
        findings = lint_paths([str(p)], cache_path=str(cache))
        assert [f.message for f in findings] != ["FROM-STALE-CACHE"]
        assert json.loads(cache.read_text())["sig"] != \
            "some-older-graftlint"

    def test_rule_filter_keys_entries_separately(self, tmp_path):
        from tools.graftlint.rules import ALL_RULES
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        r1 = [m for m in ALL_RULES if m.RULE == "R1"]
        assert lint_paths([str(p)], rules=r1,
                          cache_path=str(cache)) == []
        # the R1-filtered empty result must not satisfy a full run
        assert {f.rule for f in
                lint_paths([str(p)], cache_path=str(cache))} == {"R6"}

    def test_jobs_parallel_matches_serial(self, tmp_path):
        files = []
        for i, body in enumerate([self.BAD, "x = 1\n", self.BAD,
                                  "def f(:\n"]):
            p = tmp_path / f"f{i}.py"
            p.write_text(body)
            files.append(str(p))
        assert lint_paths(files, jobs=3) == lint_paths(files)

    def test_cli_flags(self, tmp_path, capsys):
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        assert main([str(p), "--jobs", "0"]) == 2
        assert main([str(p), "--no-cache", "--jobs", "2"]) == 1
        capsys.readouterr()
        assert main([str(p), "--cache", str(cache)]) == 1
        assert cache.exists()
        capsys.readouterr()


class TestRepoGate:
    """The actual gate: the linted tree must be clean modulo baseline."""

    PATHS = ["raft_tpu", "bench.py", "tools", "tests"]

    def test_repo_clean_modulo_baseline(self):
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", *self.PATHS,
             "--baseline", BASELINE],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, \
            f"new graftlint findings:\n{r.stdout}\n{r.stderr}"

    def test_rules_filter_coexists_with_baseline(self):
        """A --rules R5 run must not call the untouched R1 baseline
        entries stale (they are out of the filter's scope)."""
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", *self.PATHS,
             "--rules", "R5", "--baseline", BASELINE],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_json_mode_is_machine_readable(self):
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftlint",
             os.path.join("tests", "graftlint_fixtures", "r6_pos.py"),
             "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 1
        findings = json.loads(r.stdout)
        assert findings and all(
            set(f) >= {"path", "line", "col", "rule", "name", "message"}
            for f in findings)
        assert any(f["rule"] == "R6" for f in findings)

    def test_baseline_stays_burned_down(self):
        """The baseline's 6 legacy R1 entries were burned down to EMPTY
        (PR 2: batched post-loop fetch in train_dynamics_parity, hoisted
        decode + justified pragmas in cli/parity). It must stay that
        way: new findings are fixed or pragma'd with justification at
        the site, never grandfathered — a baseline entry reappearing
        means someone took the shortcut this gate exists to block."""
        with open(BASELINE) as f:
            entries = json.load(f)["findings"]
        assert entries == [], (
            "baseline regrew — fix or pragma the finding instead of "
            f"grandfathering it: {entries}")

    def test_library_walk_matches_cli(self):
        findings = lint_paths([os.path.join(REPO, p)
                               for p in self.PATHS])
        # relative vs absolute path spelling differs; rule counts match
        by_rule = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        with open(BASELINE) as f:
            entries = json.load(f)["findings"]
        assert by_rule.get("R1", 0) == len(entries)
        assert "R5" not in by_rule and "R6" not in by_rule
