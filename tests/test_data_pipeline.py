"""Data-pipeline tests: augmentors, dataset indexers on synthetic trees,
loader determinism, flow visualization — the coverage gap SURVEY.md §4
calls out (the reference ships zero tests for its data path)."""

import os

import numpy as np
import pytest
from PIL import Image

from raft_tpu.data import frame_utils
from raft_tpu.data.augmentor import (FlowAugmentor, SparseFlowAugmentor,
                                     adjust_brightness, adjust_contrast)
from raft_tpu.data.datasets import FlyingChairs, KITTI, MpiSintel
from raft_tpu.data.loader import PrefetchLoader
from raft_tpu.utils.flow_viz import flow_to_image, make_colorwheel


class ScriptedRNG:
    """Stands in for RandomState: scripted uniform/rand draws, real ints."""

    def __init__(self, rand_values, uniform_value=0.0, base=None):
        self._rand = list(rand_values)
        self._uniform = uniform_value
        self._base = base or np.random.RandomState(0)

    def rand(self, *a):
        if a:
            return self._base.rand(*a)
        return self._rand.pop(0) if self._rand else 1.0

    def uniform(self, lo, hi, *a, **k):
        return self._uniform

    def randint(self, lo, hi=None, *a, **k):
        return lo  # deterministic: crop at origin, smallest rectangles

    def permutation(self, n):
        return self._base.permutation(n)


class TestFlowAugmentor:
    def test_output_shapes_and_contiguity(self, rng):
        aug = FlowAugmentor(crop_size=(48, 64), do_flip=True,
                            rng=np.random.RandomState(3))
        img1 = rng.randint(0, 255, (80, 100, 3)).astype(np.uint8)
        img2 = rng.randint(0, 255, (80, 100, 3)).astype(np.uint8)
        flow = rng.randn(80, 100, 2).astype(np.float32)
        o1, o2, of = aug(img1, img2, flow)
        assert o1.shape == (48, 64, 3) and o2.shape == (48, 64, 3)
        assert of.shape == (48, 64, 2)
        assert o1.flags.c_contiguous and of.flags.c_contiguous

    def test_hflip_negates_u(self, rng):
        """h-flip: u component negated, v kept (augmentor.py:97-100)."""
        aug = FlowAugmentor(crop_size=(8, 8), do_flip=True)
        # scripted rand() draws, in call order: asymmetric-color off,
        # eraser off, stretch off, spatial-aug off, h-flip ON, v-flip off
        aug.rng = ScriptedRNG([1.0, 1.0, 1.0, 1.0, 0.0, 1.0])
        aug.photo_aug = lambda img, rng: img  # disable color jitter
        img = np.zeros((8, 8, 3), np.uint8)
        flow = np.stack(np.meshgrid(np.arange(8), np.arange(8)),
                        -1).astype(np.float32)
        _, _, of = aug(img.copy(), img.copy(), flow)
        np.testing.assert_array_equal(of[..., 0], -flow[:, ::-1, 0])
        np.testing.assert_array_equal(of[..., 1], flow[:, ::-1, 1])

    def test_scale_multiplies_flow(self, rng):
        """2x resize doubles displacement vectors (augmentor.py:83-88)."""
        aug = FlowAugmentor(crop_size=(16, 16), min_scale=1.0, max_scale=1.0,
                            do_flip=False)
        # draws: asym-color off, eraser off, stretch off, spatial aug ON;
        # uniform -> scale exponent 1.0 => 2x resize
        aug.rng = ScriptedRNG([1.0, 1.0, 1.0, 0.0], uniform_value=1.0)
        aug.photo_aug = lambda img, rng: img
        img = rng.randint(0, 255, (16, 16, 3)).astype(np.uint8)
        flow = np.full((16, 16, 2), 1.5, np.float32)
        _, _, of = aug(img.copy(), img.copy(), flow)
        np.testing.assert_allclose(of, 3.0, rtol=1e-5)

    def test_determinism_via_reseed(self, rng):
        img1 = rng.randint(0, 255, (64, 80, 3)).astype(np.uint8)
        img2 = rng.randint(0, 255, (64, 80, 3)).astype(np.uint8)
        flow = rng.randn(64, 80, 2).astype(np.float32)
        outs = []
        for _ in range(2):
            aug = FlowAugmentor(crop_size=(32, 40), do_flip=True)
            aug.reseed(77)
            outs.append(aug(img1.copy(), img2.copy(), flow.copy()))
        for a, b in zip(outs[0], outs[1]):
            np.testing.assert_array_equal(a, b)


class TestColorOps:
    def test_brightness_zero_blacks_out(self, rng):
        img = rng.randint(0, 255, (4, 4, 3)).astype(np.uint8)
        assert adjust_brightness(img, 0.0).max() == 0
        np.testing.assert_array_equal(adjust_brightness(img, 1.0), img)

    def test_contrast_one_is_identity(self, rng):
        img = rng.randint(0, 255, (4, 4, 3)).astype(np.uint8)
        np.testing.assert_array_equal(adjust_contrast(img, 1.0), img)


class TestSparseAugmentor:
    def test_sparse_rescale_scatter(self):
        """Valid points land at rounded scaled coords with scaled flow
        (augmentor.py:161-193 semantics)."""
        aug = SparseFlowAugmentor(crop_size=(4, 4))
        flow = np.zeros((4, 6, 2), np.float32)
        valid = np.zeros((4, 6), np.float32)
        flow[2, 3] = [1.0, -2.0]
        valid[2, 3] = 1.0
        out_flow, out_valid = aug.resize_sparse_flow_map(flow, valid,
                                                         fx=2.0, fy=2.0)
        assert out_flow.shape == (8, 12, 2)
        assert out_valid[4, 6] == 1
        np.testing.assert_allclose(out_flow[4, 6], [2.0, -4.0])
        assert out_valid.sum() == 1  # nothing else scattered


def make_sintel_tree(root, n=3, hw=(32, 48)):
    h, w = hw
    rng = np.random.RandomState(0)
    img_dir = os.path.join(root, "Sintel/training/clean/alley_1")
    flow_dir = os.path.join(root, "Sintel/training/flow/alley_1")
    os.makedirs(img_dir)
    os.makedirs(flow_dir)
    for i in range(n):
        Image.fromarray(rng.randint(0, 255, (h, w, 3)).astype(np.uint8)
                        ).save(os.path.join(img_dir, f"frame_{i:04d}.png"))
        if i < n - 1:
            frame_utils.write_flow(
                os.path.join(flow_dir, f"frame_{i:04d}.flo"),
                rng.randn(h, w, 2).astype(np.float32))


class TestDatasetsOnSyntheticTrees:
    def test_sintel_training(self, tmp_path):
        make_sintel_tree(str(tmp_path))
        ds = MpiSintel(aug_params=None, split="training",
                       root=str(tmp_path / "Sintel"), dstype="clean")
        assert len(ds) == 2
        img1, img2, flow, valid = ds[0]
        assert img1.shape == (32, 48, 3) and flow.shape == (32, 48, 2)
        assert valid.min() == 1.0  # all synthetic flows < 1000

    def test_sintel_test_mode(self, tmp_path):
        h, w = 32, 48
        rng = np.random.RandomState(0)
        img_dir = tmp_path / "Sintel/test/clean/seq_1"
        os.makedirs(img_dir)
        for i in range(3):
            Image.fromarray(rng.randint(0, 255, (h, w, 3)).astype(np.uint8)
                            ).save(str(img_dir / f"frame_{i:04d}.png"))
        ds = MpiSintel(split="test", root=str(tmp_path / "Sintel"),
                       dstype="clean")
        img1, img2, (seq, frame) = ds[0]
        assert seq == "seq_1" and frame == 0
        assert img1.dtype == np.float32

    def test_chairs_split(self, tmp_path):
        rng = np.random.RandomState(0)
        data = tmp_path / "FlyingChairs_release/data"
        os.makedirs(data)
        for i in range(1, 4):
            for k in (1, 2):
                Image.fromarray(
                    rng.randint(0, 255, (16, 24, 3)).astype(np.uint8)
                ).save(str(data / f"{i:05d}_img{k}.ppm"))
            frame_utils.write_flow(str(data / f"{i:05d}_flow.flo"),
                                   rng.randn(16, 24, 2).astype(np.float32))
        split = tmp_path / "chairs_split.txt"
        split.write_text("1\n2\n1\n")  # samples 1,3 train / 2 val
        train = FlyingChairs(aug_params=None, split="training",
                             root=str(data), split_file=str(split))
        val = FlyingChairs(aug_params=None, split="validation",
                           root=str(data), split_file=str(split))
        assert len(train) == 2 and len(val) == 1

    def test_kitti_sparse(self, tmp_path):
        rng = np.random.RandomState(0)
        img_dir = tmp_path / "KITTI/training/image_2"
        flow_dir = tmp_path / "KITTI/training/flow_occ"
        os.makedirs(img_dir)
        os.makedirs(flow_dir)
        for i in range(2):
            for k in (10, 11):
                Image.fromarray(
                    rng.randint(0, 255, (20, 30, 3)).astype(np.uint8)
                ).save(str(img_dir / f"{i:06d}_{k}.png"))
            frame_utils.write_flow_kitti(
                str(flow_dir / f"{i:06d}_10.png"),
                rng.randn(20, 30, 2).astype(np.float32) * 5)
        ds = KITTI(aug_params=None, split="training",
                   root=str(tmp_path / "KITTI"))
        assert len(ds) == 2
        img1, img2, flow, valid = ds[0]
        assert flow.shape == (20, 30, 2)
        assert set(np.unique(valid)) <= {0.0, 1.0}


class TestPrefetchLoader:
    class TinyDataset:
        def __init__(self, n=10):
            self.n = n

        def __len__(self):
            return self.n

        def __getitem__(self, i):
            x = np.full((2, 2, 3), i, np.float32)
            return x, x, np.zeros((2, 2, 2), np.float32), np.ones((2, 2),
                                                                  np.float32)

    def test_batching_and_determinism(self):
        ds = self.TinyDataset(10)
        batches1 = [b["image1"][:, 0, 0, 0] for b in
                    PrefetchLoader(ds, 3, seed=5, num_workers=2)]
        batches2 = [b["image1"][:, 0, 0, 0] for b in
                    PrefetchLoader(ds, 3, seed=5, num_workers=2)]
        assert len(batches1) == 3  # drop_last
        for a, b in zip(batches1, batches2):
            np.testing.assert_array_equal(a, b)

    def test_worker_exception_propagates(self):
        class Bad(self.TinyDataset):
            def __getitem__(self, i):
                raise ValueError("boom")

        with pytest.raises(ValueError, match="boom"):
            list(PrefetchLoader(Bad(4), 2, num_workers=2))

    def test_uint8_wire_dtypes_and_losslessness(self):
        ds = self.TinyDataset(6)
        f32 = next(iter(PrefetchLoader(ds, 2, seed=5, num_workers=1)))
        u8 = next(iter(PrefetchLoader(ds, 2, seed=5, num_workers=1,
                                      wire_dtype="uint8")))
        assert u8["image1"].dtype == np.uint8
        assert u8["valid"].dtype == np.uint8
        assert u8["flow"].dtype == np.float32  # real-valued GT stays f32
        # integral-valued images survive the wire exactly
        np.testing.assert_array_equal(
            u8["image1"].astype(np.float32), f32["image1"])
        np.testing.assert_array_equal(
            u8["valid"].astype(np.float32), f32["valid"])

    def test_wire_dtype_validated(self):
        with pytest.raises(ValueError, match="wire_dtype"):
            PrefetchLoader(self.TinyDataset(4), 2, wire_dtype="int4")

    def test_uint8_wire_rejects_nonintegral_images(self):
        class FloatImages(self.TinyDataset):
            def __getitem__(self, i):
                x = np.full((2, 2, 3), 0.5, np.float32)  # normalized [0,1]
                return x, x, np.zeros((2, 2, 2), np.float32), np.ones(
                    (2, 2), np.float32)

        with pytest.raises(ValueError, match="integral"):
            list(PrefetchLoader(FloatImages(4), 2, num_workers=1,
                                wire_dtype="uint8"))

    def test_uint8_wire_rejects_fractional_valid(self):
        class SoftValid(self.TinyDataset):
            def __getitem__(self, i):
                img1, img2, flow, _ = super().__getitem__(i)
                return img1, img2, flow, np.full((2, 2), 0.7, np.float32)

        with pytest.raises(ValueError, match="valid mask"):
            list(PrefetchLoader(SoftValid(4), 2, num_workers=1,
                                wire_dtype="uint8"))


class TestFlowViz:
    def test_colorwheel_layout(self):
        wheel = make_colorwheel()
        assert wheel.shape == (55, 3)
        np.testing.assert_array_equal(wheel[0], [255, 0, 0])  # RY start

    def test_fixed_rad_normalization_is_frame_consistent(self):
        """The fork pins rad_max=3 (flow_viz.py:128-130): the same vector
        maps to the same color regardless of other content."""
        a = np.zeros((4, 4, 2), np.float32)
        a[0, 0] = [1.0, 0.0]
        b = a.copy()
        b[3, 3] = [300.0, 0.0]  # would change per-frame-max normalization
        ia = flow_to_image(a)
        ib = flow_to_image(b)
        np.testing.assert_array_equal(ia[0, 0], ib[0, 0])
        # upstream behavior restored with rad_max=None
        ja = flow_to_image(a, rad_max=None)
        jb = flow_to_image(b, rad_max=None)
        assert not np.array_equal(ja[0, 0], jb[0, 0])


class TestAdjustHue:
    def test_circular_shift_exact_both_signs(self):
        """Hue add must be modular on cv2's [0,180) circle (the analog of
        PIL's full-range uint8 wrap that torchvision rides). The previous
        implementation added the shift in uint8, wrapping at 256 BEFORE
        the %180 and corrupting hues whenever h+shift >= 256 — which every
        negative factor (shift in (90,180) after %180) hit."""
        import cv2

        from raft_tpu.data.augmentor import adjust_hue

        rng = np.random.RandomState(0)
        img = rng.randint(0, 255, (48, 64, 3), dtype=np.uint8)
        for factor in (-0.159, -0.01, 0.0, 0.07, 0.159, 0.5):
            hsv = cv2.cvtColor(img, cv2.COLOR_RGB2HSV)
            h = hsv[..., 0].astype(np.int32)
            hsv[..., 0] = ((h + int(factor * 180.0) % 180) % 180
                           ).astype(np.uint8)
            want = cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)
            got = adjust_hue(img.copy(), factor)
            assert np.array_equal(got, want), factor


def test_loader_worker_clamp(monkeypatch):
    """Worker threads beyond the host's spare cores only buy GIL/queue
    contention (1-core host measured: 1 worker 52.2 pairs/s vs 4 workers
    44.6) — the loader clamps to cpu_count-1 with a floor of 1."""
    import raft_tpu.data.loader as L

    class _DS:
        def __len__(self):
            return 4

    monkeypatch.setattr(L.os, "sched_getaffinity", lambda pid: {0},
                        raising=False)
    assert L.PrefetchLoader(_DS(), 2, num_workers=4).num_workers == 1
    # clamp=False is the bench's escape hatch for re-measuring contention
    assert L.PrefetchLoader(_DS(), 2, num_workers=4,
                            clamp=False).num_workers == 4
    monkeypatch.setattr(L.os, "sched_getaffinity",
                        lambda pid: set(range(8)), raising=False)
    assert L.PrefetchLoader(_DS(), 2, num_workers=4).num_workers == 4
    assert L.PrefetchLoader(_DS(), 2, num_workers=0).num_workers == 1
