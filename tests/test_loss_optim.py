"""Loss math vs hand computation; OneCycle schedule vs torch's OneCycleLR.

Pins the parity surface the reference defines at train.py:42-86: γ-weighted
sequence loss with the MAX_FLOW cutoff, EPE/inlier metrics, and the
AdamW + OneCycleLR(pct_start=0.05, anneal='linear') optimizer.
"""

import jax
import numpy as np
import pytest
import torch

import jax.numpy as jnp

from raft_tpu.training.loss import sequence_loss
from raft_tpu.training.optim import onecycle_linear_schedule


class TestSequenceLoss:
    def test_matches_hand_computation(self, rng):
        T, B, H, W = 3, 2, 4, 5
        preds = rng.randn(T, B, H, W, 2).astype(np.float32)
        gt = rng.randn(B, H, W, 2).astype(np.float32)
        valid = (rng.rand(B, H, W) > 0.3).astype(np.float32)
        gamma = 0.8

        loss, metrics = sequence_loss(jnp.asarray(preds), jnp.asarray(gt),
                                      jnp.asarray(valid), gamma)

        mask = valid >= 0.5  # all mags < 400 here
        want = 0.0
        for i in range(T):
            w = gamma ** (T - 1 - i)
            i_loss = np.abs(preds[i] - gt)
            # reference averages over ALL elements (train.py:60)
            want += w * (mask[..., None] * i_loss).mean()
        np.testing.assert_allclose(float(loss), want, rtol=1e-6)

        epe = np.sqrt(((preds[-1] - gt) ** 2).sum(-1))
        np.testing.assert_allclose(float(metrics["epe"]),
                                   epe[mask].mean(), rtol=1e-6)
        np.testing.assert_allclose(float(metrics["3px"]),
                                   (epe[mask] < 3).mean(), rtol=1e-6)

    def test_max_flow_cutoff(self, rng):
        """GT displacements >= 400 px are excluded (train.py:42,53-55)."""
        preds = np.zeros((1, 1, 2, 2, 2), np.float32)
        gt = np.zeros((1, 2, 2, 2), np.float32)
        gt[0, 0, 0] = [500.0, 0.0]  # excluded
        gt[0, 0, 1] = [3.0, 4.0]    # epe 5 at zero prediction
        valid = np.ones((1, 2, 2), np.float32)
        loss, metrics = sequence_loss(jnp.asarray(preds), jnp.asarray(gt),
                                      jnp.asarray(valid), 0.8)
        # loss averages |pred-gt| over all elems but only valid∧(<400) count
        want = (3 + 4) / preds[0].size
        np.testing.assert_allclose(float(loss), want, rtol=1e-6)
        np.testing.assert_allclose(float(metrics["epe"]), 5.0 / 3, rtol=1e-6)

    def test_gamma_weights_recent_iterations_more(self, rng):
        preds = rng.randn(4, 1, 3, 3, 2).astype(np.float32)
        gt = np.zeros((1, 3, 3, 2), np.float32)
        valid = np.ones((1, 3, 3), np.float32)
        # make the last iteration perfect: loss should drop by the largest
        # weight's share
        perfect = preds.copy()
        perfect[-1] = 0.0
        l_all, _ = sequence_loss(jnp.asarray(preds), jnp.asarray(gt),
                                 jnp.asarray(valid), 0.8)
        l_per, _ = sequence_loss(jnp.asarray(perfect), jnp.asarray(gt),
                                 jnp.asarray(valid), 0.8)
        drop = float(l_all) - float(l_per)
        assert drop == pytest.approx(np.abs(preds[-1]).mean(), rel=1e-5)


class TestOneCycle:
    @pytest.mark.parametrize("lr,steps", [(4e-4, 1000), (1.25e-4, 333)])
    def test_matches_torch_onecycle(self, lr, steps):
        """train.py:83-84: OneCycleLR(lr, steps+100, pct_start=0.05,
        cycle_momentum=False, anneal_strategy='linear')."""
        total = steps + 100
        sched = onecycle_linear_schedule(lr, total)

        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.AdamW([p], lr=lr)
        tsched = torch.optim.lr_scheduler.OneCycleLR(
            opt, lr, total_steps=total, pct_start=0.05,
            cycle_momentum=False, anneal_strategy="linear")

        got, want = [], []
        for step in range(total - 1):
            # torch's get_last_lr after n step() calls == lr used at step n
            tsched.step()
            want.append(tsched.get_last_lr()[0])
            got.append(float(sched(step + 1)))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=1e-9)


class TestSequenceLossSubpixel:
    """sequence_loss_subpixel must be numerically interchangeable with
    sequence_loss fed the standard-layout stack: all reductions are over
    full element sets (or valid-masked sums), so the layout transform
    cannot change the values — only the 560 MB materialization goes away."""

    def _inputs(self, rng):
        T, B, H, W = 3, 2, 4, 6
        flows = jnp.asarray(rng.randn(T, B, H, W, 2).astype(np.float32))
        masks = jnp.asarray(rng.randn(T, B, H, W, 576).astype(np.float32))
        gt = jnp.asarray(rng.randn(B, 8 * H, 8 * W, 2).astype(np.float32)
                         * 5)
        valid = jnp.asarray(
            (rng.rand(B, 8 * H, 8 * W) > 0.3).astype(np.float32))
        return flows, masks, gt, valid

    def test_loss_and_metrics_match_standard(self):
        from raft_tpu.ops.flow_ops import (convex_upsample_batched,
                                           convex_upsample_batched_raw)
        from raft_tpu.training.loss import (sequence_loss,
                                            sequence_loss_subpixel)

        rng = np.random.RandomState(3)
        flows, masks, gt, valid = self._inputs(rng)
        loss_std, m_std = sequence_loss(
            convex_upsample_batched(flows, masks), gt, valid, 0.8)
        loss_fused, m_fused = sequence_loss_subpixel(
            convex_upsample_batched_raw(flows, masks), gt, valid, 0.8)
        np.testing.assert_allclose(float(loss_fused), float(loss_std),
                                   rtol=1e-6)
        for k in m_std:
            np.testing.assert_allclose(float(m_fused[k]), float(m_std[k]),
                                       rtol=1e-5, atol=1e-7)

    def test_grads_match_standard(self):
        from raft_tpu.ops.flow_ops import (convex_upsample_batched,
                                           convex_upsample_batched_raw)
        from raft_tpu.training.loss import (sequence_loss,
                                            sequence_loss_subpixel)

        rng = np.random.RandomState(4)
        flows, masks, gt, valid = self._inputs(rng)

        g_std = jax.grad(lambda f, m: sequence_loss(
            convex_upsample_batched(f, m), gt, valid, 0.8)[0],
            argnums=(0, 1))(flows, masks)
        g_fus = jax.grad(lambda f, m: sequence_loss_subpixel(
            convex_upsample_batched_raw(f, m), gt, valid, 0.8)[0],
            argnums=(0, 1))(flows, masks)
        for a, b in zip(g_fus, g_std):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)

    def test_train_step_fused_matches_standard(self):
        """End to end through make_train_step: same batch, same state,
        fused vs standard — loss/metrics/grad-norm must agree."""
        from raft_tpu.config import RAFTConfig, TrainConfig
        from raft_tpu.training.train_step import (create_train_state,
                                                  make_train_step)

        rng = np.random.RandomState(5)
        batch = {
            "image1": jnp.asarray(
                rng.rand(2, 64, 64, 3).astype(np.float32) * 255),
            "image2": jnp.asarray(
                rng.rand(2, 64, 64, 3).astype(np.float32) * 255),
            "flow": jnp.asarray(rng.randn(2, 64, 64, 2).astype(np.float32)),
            "valid": jnp.ones((2, 64, 64), np.float32),
        }
        model_cfg = RAFTConfig(small=False)
        key = jax.random.PRNGKey(0)
        outs = {}
        for fused in (False, True):
            train_cfg = TrainConfig(stage="chairs", batch_size=2, iters=2,
                                    fused_loss=fused)
            state = create_train_state(model_cfg, train_cfg,
                                       jax.random.PRNGKey(7),
                                       image_hw=(64, 64))
            step = make_train_step(model_cfg, train_cfg)
            _, metrics = step(state, batch, key)
            outs[fused] = {k: float(v) for k, v in metrics.items()}
        for k in outs[False]:
            np.testing.assert_allclose(outs[True][k], outs[False][k],
                                       rtol=1e-4, atol=1e-6, err_msg=k)


def test_fused_loss_with_small_model_warns_and_falls_back():
    """--fused_loss with the small model has no fused path (ADVICE r3):
    the builder must say so instead of silently using the standard loss."""
    import warnings as _warnings

    from raft_tpu.config import RAFTConfig, stage_config
    from raft_tpu.training.train_step import make_train_step

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        make_train_step(RAFTConfig(small=True),
                        stage_config("chairs", batch_size=1,
                                     fused_loss=True))
    assert any("fused_loss" in str(w.message) for w in caught)

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        make_train_step(RAFTConfig(small=False),
                        stage_config("chairs", batch_size=1,
                                     fused_loss=True))
    assert not any("fused_loss" in str(w.message) for w in caught)


def test_fused_loss_auto_default_is_silent_for_small():
    """The tri-state default (None = auto) must NOT warn for the small
    model — the standard-loss fallback is the expected behavior there,
    not an ineffective user request (which is what the warning above
    guards)."""
    import warnings as _warnings

    from raft_tpu.config import RAFTConfig, stage_config
    from raft_tpu.training.train_step import make_train_step

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        make_train_step(RAFTConfig(small=True),
                        stage_config("chairs", batch_size=1))
    assert not [w for w in caught if "fused_loss" in str(w.message)]
