"""Subprocess body for the serving crash-class chaos drill.

``kind="crash"`` is ``os._exit`` — nothing can be asserted in-process
afterwards, so the test (tests/test_scheduler.py) runs THIS worker as a
child with a crash plan armed at a serving site and asserts the
process dies with ``CRASH_EXIT_CODE`` (the PR-3 exit-code discipline:
the supervisor layer, not the scheduler, owns crash recovery). Uses a
stub engine so the child never compiles anything."""

import sys
import time

import numpy as np

from raft_tpu.serving.scheduler import MicroBatchScheduler
from raft_tpu.testing import faults


def _pad8(x):
    return -(-x // 8) * 8


class _StubEngine:
    warm_start = False

    def __init__(self):
        self._compiled = {}

    def bucket_capacity(self, h, w):
        fits = [s[0] for s in self._compiled
                if s[1] == _pad8(h) and s[2] == _pad8(w)]
        return max(fits) if fits else None

    def ensure_bucket(self, b, h, w):
        shape = (b, _pad8(h), _pad8(w))
        self._compiled[shape] = object()
        return shape

    def route_bucket(self, b, h, w):
        return (b, _pad8(h), _pad8(w))

    def drop_bucket(self, shape):
        return self._compiled.pop(shape, None) is not None

    def infer_batch(self, i1, i2, **kw):
        return np.zeros(i1.shape[:3] + (2,), np.float32)


def main():
    site = sys.argv[1] if len(sys.argv) > 1 else "serve.dispatch_exec"
    faults.arm([{"site": site, "kind": "crash"}])
    sched = MicroBatchScheduler(_StubEngine(), gather_window_s=0.0,
                                dispatch_timeout_s=5.0)
    img = np.zeros((16, 16, 3), np.float32)
    sched.submit(img, img)
    # the armed crash fires os._exit(CRASH_EXIT_CODE) on the dispatch
    # path; if it somehow doesn't, exit 0 and let the test fail on the
    # return code
    time.sleep(10)
    sched.close()


if __name__ == "__main__":
    main()
