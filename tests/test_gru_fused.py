"""Parity for the fused (lane-major + Pallas epilogue) update block.

The oracle is the reference-shaped NHWC path (``gru_impl='xla'``), itself
pinned against torch (test_reference_parity). Per-iteration parity is the
meaningful pin — the refinement recurrence amplifies ANY fp32
accumulation-order noise at random-init weights (see
test_model.test_corr_dtype_bf16_model_drift), so the model-level check is
deliberately loose while the single-application fwd/grad checks are
tight. Kernels run in interpret mode on CPU, following
tests/test_corr_alt_pallas.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.kernels import gru_pallas
from raft_tpu.models.update import BasicUpdateBlock, FusedBasicUpdateBlock


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setattr(gru_pallas, "_INTERPRET", True)


@pytest.fixture(scope="module")
def block_setup():
    rng = np.random.RandomState(3)
    B, H, W = 2, 6, 8

    def arr(c, scale=0.1):
        return jnp.asarray(rng.randn(B, H, W, c).astype(np.float32) * scale)

    net, inp, corr = arr(128), arr(128), arr(324)
    flow = arr(2, scale=1.0)
    variables = BasicUpdateBlock(128).init(
        jax.random.PRNGKey(7), net, inp, corr, flow)
    return variables, (net, inp, corr, flow)


class TestGruPallasKernels:
    """Kernel-level oracle: the fused epilogues vs their jnp formulas,
    forward and VJP, at a tile-exact and a pad-requiring shape."""

    # single-tile, exact-divisor-tiled, and padded (near-prime N) regimes
    shapes = [(2, 37, 16), (1, 600, 128), (1, 1021, 8)]

    def _data(self, shape, n):
        rng = np.random.RandomState(sum(shape))
        return [jnp.asarray(rng.randn(*shape).astype(np.float32))
                for _ in range(n)]

    @pytest.mark.parametrize("shape", shapes)
    def test_gates_fwd_and_vjp(self, shape):
        zl, rl, h = self._data(shape, 3)
        z, rh = gru_pallas.gru_gates(zl, rl, h)
        np.testing.assert_allclose(np.asarray(z),
                                   np.asarray(jax.nn.sigmoid(zl)),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(rh), np.asarray(jax.nn.sigmoid(rl) * h),
            atol=1e-6, rtol=1e-6)

        def loss(fn):
            def f(args):
                a, b = fn(*args)
                return jnp.sum(a ** 2) + jnp.sum(jnp.abs(b))
            return f

        oracle = loss(lambda zl, rl, h: (jax.nn.sigmoid(zl),
                                         jax.nn.sigmoid(rl) * h))
        g_want = jax.grad(oracle)((zl, rl, h))
        g_got = jax.grad(loss(gru_pallas.gru_gates))((zl, rl, h))
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    @pytest.mark.parametrize("shape", shapes)
    def test_blend_fwd_and_vjp(self, shape):
        z, h, ql = self._data(shape, 3)
        z = jax.nn.sigmoid(z)  # blend's z input is a sigmoid output
        out = gru_pallas.gru_blend(z, h, ql)
        want = (1.0 - z) * h + z * jnp.tanh(ql)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-6, rtol=1e-6)

        def loss(fn):
            return lambda args: jnp.sum(fn(*args) ** 2)

        oracle = loss(lambda z, h, ql: (1.0 - z) * h + z * jnp.tanh(ql))
        g_want = jax.grad(oracle)((z, h, ql))
        g_got = jax.grad(loss(gru_pallas.gru_blend))((z, h, ql))
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)


class TestRowTile:
    def test_production_geometry_needs_no_pad(self):
        """46x62 -> N=2852: the tile must divide it exactly — padding
        every operand with a copy on the hot path is what the kernels
        exist to avoid."""
        rows, pad = gru_pallas._row_tile(2852)
        assert pad == 0 and 2852 % rows == 0
        assert gru_pallas._MIN_ROWS <= rows <= gru_pallas._ROWS

    def test_small_and_prime_cases(self):
        assert gru_pallas._row_tile(37) == (37, 0)      # single tile
        assert gru_pallas._row_tile(512) == (512, 0)
        rows, pad = gru_pallas._row_tile(1021)          # prime -> pad
        assert rows == gru_pallas._ROWS and (1021 + pad) % rows == 0


class TestConvLaneMajor:
    """The shifted-tap contraction vs the NHWC conv it restructures, over
    every kernel geometry the fused block uses (incl. the tiny-cin FMA
    path of the 7x7-on-flow conv and the 1x1 pure-GEMM shortcut)."""

    @pytest.mark.parametrize("k,pad,cin,cout", [
        ((1, 5), (0, 2), 24, 16),   # SepConvGRU horizontal
        ((5, 1), (2, 0), 24, 16),   # SepConvGRU vertical
        ((3, 3), (1, 1), 24, 16),   # motion-encoder 3x3
        ((7, 7), (3, 3), 2, 16),    # flow conv: cin=2 -> broadcast FMAs
        ((1, 1), (0, 0), 24, 16),   # pointwise -> single GEMM
    ])
    def test_matches_nhwc_conv(self, k, pad, cin, cout):
        import flax.linen as nn

        from raft_tpu.models.layers import TorchConv, conv_lane_major

        B, H, W = 2, 5, 7
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(B, H, W, cin).astype(np.float32))

        class Nhwc(nn.Module):
            @nn.compact
            def __call__(self, x):
                return TorchConv(cout, k, (1, 1), pad, name="c")(x)

        class Lane(nn.Module):
            @nn.compact
            def __call__(self, xf):
                return conv_lane_major(
                    TorchConv(cout, k, (1, 1), pad, name="c"), xf, (H, W))

        v = Nhwc().init(jax.random.PRNGKey(0), x)
        want = np.asarray(Nhwc().apply(v, x)).reshape(B, H * W, cout)
        got = np.asarray(Lane().apply(v, x.reshape(B, H * W, cin)))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


class TestFusedUpdateBlock:
    def test_param_tree_identical(self, block_setup):
        """gru_impl swaps the implementation, never the parameters:
        identical tree structure AND init draws -> checkpoints are
        interchangeable between the two paths."""
        variables, (net, inp, corr, flow) = block_setup
        v_f = FusedBasicUpdateBlock(128).init(
            jax.random.PRNGKey(7), net, inp, corr, flow)
        assert (jax.tree_util.tree_structure(variables)
                == jax.tree_util.tree_structure(v_f))
        for a, b in zip(jax.tree.leaves(variables), jax.tree.leaves(v_f)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_per_iteration_fwd_matches_xla(self, block_setup):
        variables, args = block_setup
        want = BasicUpdateBlock(128).apply(variables, *args)
        got = FusedBasicUpdateBlock(128).apply(variables, *args)
        for name, a, b in zip(("net", "mask", "delta"), got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=1e-5,
                                       err_msg=name)

    def test_per_iteration_grad_matches_xla(self, block_setup):
        variables, (net, inp, corr, flow) = block_setup

        def loss(block):
            def f(params):
                n, m, d = block.apply({"params": params}, net, inp, corr,
                                      flow)
                return (jnp.sum(n ** 2) + 1e-3 * jnp.sum(m ** 2)
                        + jnp.sum(d ** 2))
            return f

        g_want = jax.grad(loss(BasicUpdateBlock(128)))(variables["params"])
        g_got = jax.grad(loss(FusedBasicUpdateBlock(128)))(
            variables["params"])
        flat_w = jax.tree_util.tree_flatten_with_path(g_want)[0]
        flat_g = jax.tree_util.tree_flatten_with_path(g_got)[0]
        for (pa, a), (pb, b) in zip(flat_g, flat_w):
            assert pa == pb
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=jax.tree_util.keystr(pa))


class TestFusedModel:
    def test_model_fused_matches_xla(self):
        """End-to-end on CPU via interpret mode (the acceptance run).
        Loose tolerance by design: the recurrence amplifies fp32
        accumulation-order noise at random init (measured 5.5e-4 px at
        iters=3 on this geometry; a real semantic mismatch is orders
        beyond that)."""
        from raft_tpu.models import RAFT

        rng = np.random.RandomState(0)
        img1 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)

        m_xla = RAFT(RAFTConfig(small=False))
        m_fused = RAFT(RAFTConfig(small=False, gru_impl="fused"))
        variables = m_xla.init(jax.random.PRNGKey(0), img1, img2, iters=1)
        want = np.asarray(m_xla.apply(variables, img1, img2, iters=3))
        got = np.asarray(m_fused.apply(variables, img1, img2, iters=3))
        np.testing.assert_allclose(got, want, atol=5e-3, rtol=1e-4)

    def test_mixed_precision_fused_runs(self):
        """bf16 compute dtype flows through the lane-major convs and the
        Pallas epilogues (weak-typed constants must not upcast)."""
        from raft_tpu.models import RAFT

        model = RAFT(RAFTConfig(small=False, gru_impl="fused",
                                mixed_precision=True))
        img = jnp.ones((1, 32, 32, 3)) * 100
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
        out = model.apply(variables, img, img, iters=2)
        assert out.dtype == jnp.float32  # upsample is an fp32 island
        assert bool(jnp.isfinite(out).all())

    def test_small_model_rejects_fused(self):
        with pytest.raises(ValueError, match="no fused path"):
            RAFTConfig(small=True, gru_impl="fused")
        with pytest.raises(ValueError, match="gru_impl"):
            RAFTConfig(gru_impl="mosaic")
