"""Model-level tests: parameter parity, shapes, autodiff structure, BN modes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT


def n_params(tree):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(tree))


@pytest.fixture(scope="module")
def small_model():
    model = RAFT(RAFTConfig(small=True))
    img = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return model, variables


@pytest.fixture(scope="module")
def basic_model():
    model = RAFT(RAFTConfig(small=False))
    img = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return model, variables


class TestParameterParity:
    def test_small_param_count(self, small_model):
        """~1.0M params (BASELINE.md; exact count pinned here)."""
        _, variables = small_model
        assert n_params(variables["params"]) == 990_162

    def test_basic_param_count(self, basic_model):
        """~5.3M params (BASELINE.md; exact count pinned here)."""
        _, variables = basic_model
        assert n_params(variables["params"]) == 5_257_536

    def test_basic_has_batch_stats(self, basic_model):
        """cnet uses BatchNorm (core/raft.py:55) -> batch_stats collection."""
        _, variables = basic_model
        assert "batch_stats" in variables

    def test_small_has_no_batch_stats(self, small_model):
        """small cnet is norm-free, fnet instance (core/raft.py:49-50)."""
        _, variables = small_model
        assert "batch_stats" not in variables

    def test_expected_top_level_modules(self, basic_model):
        _, variables = basic_model
        assert set(variables["params"].keys()) == {
            "fnet", "cnet", "update_block"}


class TestForward:
    def test_train_mode_returns_all_iterations(self, small_model):
        model, variables = small_model
        img = jnp.ones((2, 32, 32, 3)) * 128
        out = model.apply(variables, img, img, iters=3)
        assert out.shape == (3, 2, 32, 32, 2)

    def test_test_mode_returns_low_and_up(self, small_model):
        model, variables = small_model
        img = jnp.ones((1, 32, 32, 3)) * 128
        lo, up = model.apply(variables, img, img, iters=2, test_mode=True)
        assert lo.shape == (1, 4, 4, 2)
        assert up.shape == (1, 32, 32, 2)

    def test_flow_init_shifts_prediction(self, small_model):
        model, variables = small_model
        rng = np.random.RandomState(0)
        img1 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        lo0, _ = model.apply(variables, img1, img2, iters=1, test_mode=True)
        init = jnp.ones((1, 4, 4, 2)) * 2.0
        lo1, _ = model.apply(variables, img1, img2, iters=1, test_mode=True,
                             flow_init=init)
        assert float(jnp.abs(lo1 - lo0).max()) > 0.1

    def test_identical_images_near_zero_flow(self, basic_model):
        """Same image both sides at init weights -> tiny flow magnitudes."""
        model, variables = basic_model
        rng = np.random.RandomState(1)
        img = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        _, up = model.apply(variables, img, img, iters=4, test_mode=True)
        assert bool(jnp.isfinite(up).all())

    def test_mixed_precision_forward(self):
        model = RAFT(RAFTConfig(small=True, mixed_precision=True))
        img = jnp.ones((1, 32, 32, 3)) * 100
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
        out = model.apply(variables, img, img, iters=2)
        assert out.dtype == jnp.float32  # upsample is an fp32 island
        assert bool(jnp.isfinite(out).all())

    def test_corr_dtype_bf16_model_drift(self, basic_model):
        """Model-level characterization of corr_dtype='bfloat16'.

        At RANDOM-INIT weights the refinement recurrence is chaotic: a
        measured control that injects bf16-scale (2^-9 relative) noise
        into the FP32 volume produces the same compounding drift curve
        (0.22 → 24 → 170 px at iters 1/4/12 on this geometry) as bf16
        storage does. So the meaningful pins are (a) single-iteration
        drift is at perturbation scale, and (b) bf16's amplification is
        COMPARABLE to the fp32-noise control, i.e. the path adds nothing
        beyond its storage rounding. End-to-end inference parity is a
        trained-weights question (EPE on a converted checkpoint)."""
        _, variables = basic_model
        model16 = RAFT(RAFTConfig(small=False, corr_dtype="bfloat16"))
        model32 = RAFT(RAFTConfig(small=False))
        rng = np.random.RandomState(7)
        img1 = jnp.asarray(rng.rand(1, 32, 40, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 32, 40, 3).astype(np.float32) * 255)

        def drift(iters):
            _, up32 = model32.apply(variables, img1, img2, iters=iters,
                                    test_mode=True)
            _, up16 = model16.apply(variables, img1, img2, iters=iters,
                                    test_mode=True)
            return float(jnp.abs(up32 - up16).max())

        assert drift(1) < 1.0, "iter-1 drift beyond storage-rounding scale"
        # compounding must stay within an order of the measured fp32-noise
        # control (~170 px at iters=12 on this geometry/seed)
        assert drift(12) < 1000.0, "bf16 path amplifies beyond its control"


class TestAutodiff:
    def test_gradients_finite_and_nonzero(self, small_model):
        model, variables = small_model
        rng = np.random.RandomState(0)
        img1 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        gt = jnp.asarray(rng.randn(1, 32, 32, 2).astype(np.float32))

        def loss_fn(params):
            preds = model.apply({"params": params}, img1, img2, iters=2)
            return jnp.abs(preds - gt[None]).mean()

        grads = jax.grad(loss_fn)(variables["params"])
        leaves = jax.tree.leaves(grads)
        assert all(bool(jnp.isfinite(g).all()) for g in leaves)
        # every major module receives gradient
        for key in ("fnet", "cnet", "update_block"):
            sub = jax.tree.leaves(grads[key])
            assert any(float(jnp.abs(g).max()) > 0 for g in sub), key


class TestBatchNormModes:
    def test_train_updates_stats_freeze_does_not(self, basic_model):
        model, variables = basic_model
        rng = np.random.RandomState(0)
        img = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)

        _, mutated = model.apply(variables, img, img, iters=1, train=True,
                                 mutable=["batch_stats"])
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                             mutated["batch_stats"],
                             variables["batch_stats"])
        assert max(jax.tree.leaves(diffs)) > 0

        _, frozen = model.apply(variables, img, img, iters=1, train=True,
                                freeze_bn=True, mutable=["batch_stats"])
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                             frozen["batch_stats"],
                             variables["batch_stats"])
        assert max(jax.tree.leaves(diffs)) == 0


class TestScanUnroll:
    def test_unroll_is_math_identical(self, small_model):
        """RAFTConfig.scan_unroll only changes XLA scheduling (body
        replication for cross-iteration pipelining) — predictions must be
        identical to the rolled scan for the same params."""
        model, variables = small_model
        rng = np.random.RandomState(0)
        img1 = jnp.asarray(rng.rand(1, 32, 32, 3) * 255.0, jnp.float32)
        img2 = jnp.asarray(rng.rand(1, 32, 32, 3) * 255.0, jnp.float32)
        base = model.apply(variables, img1, img2, iters=5)
        for unroll in (2, 5):
            m = RAFT(RAFTConfig(small=True, scan_unroll=unroll))
            out = m.apply(variables, img1, img2, iters=5)
            np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                                       rtol=1e-6, atol=1e-6)

    def test_unroll_validation(self):
        with pytest.raises(ValueError):
            RAFTConfig(scan_unroll=0)
        with pytest.raises(ValueError):
            RAFTConfig(scan_unroll=1.5)


class TestFusedConvPair:
    """fused_conv_pair = two same-geometry convs as one double-width conv
    (models/layers.py): per-channel dot products identical, param tree
    identical to the separate convs."""

    def test_matches_separate_convs_and_param_tree(self):
        import flax.linen as nn

        from raft_tpu.models.layers import TorchConv, fused_conv_pair

        class Sep(nn.Module):
            @nn.compact
            def __call__(self, x):
                a = TorchConv(8, (3, 3), (1, 1), (1, 1), name="ca")(x)
                b = TorchConv(4, (3, 3), (1, 1), (1, 1), name="cb")(x)
                return a, b

        class Fused(nn.Module):
            @nn.compact
            def __call__(self, x):
                return fused_conv_pair(
                    TorchConv(8, (3, 3), (1, 1), (1, 1), name="ca"),
                    TorchConv(4, (3, 3), (1, 1), (1, 1), name="cb"), x)

        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2, 6, 7, 5).astype(np.float32))
        vs = Sep().init(jax.random.PRNGKey(1), x)
        vf = Fused().init(jax.random.PRNGKey(1), x)
        # identical param trees (same names, shapes, and init draws)
        assert (jax.tree_util.tree_structure(vs)
                == jax.tree_util.tree_structure(vf))
        for a, b in zip(jax.tree.leaves(vs), jax.tree.leaves(vf)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        sa, sb = Sep().apply(vs, x)
        fa, fb = Fused().apply(vs, x)
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(fa))
        np.testing.assert_array_equal(np.asarray(sb), np.asarray(fb))

    def test_mismatched_geometry_asserts(self):
        import flax.linen as nn

        from raft_tpu.models.layers import TorchConv, fused_conv_pair

        class Bad(nn.Module):
            @nn.compact
            def __call__(self, x):
                return fused_conv_pair(
                    TorchConv(8, (3, 3), (1, 1), (1, 1), name="ca"),
                    TorchConv(4, (1, 5), (1, 1), (0, 2), name="cb"), x)

        x = jnp.zeros((1, 6, 7, 5), jnp.float32)
        with pytest.raises(AssertionError, match="fusable"):
            Bad().init(jax.random.PRNGKey(0), x)
