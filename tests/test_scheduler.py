"""Serving front-end acceptance: async micro-batch coalescing,
deadlines, backpressure, drain-on-shutdown, warm-start sessions, and
the metrics surface — the ISSUE-6 ragged-traffic drill plus the
fault-injection matrix for the ``serve.request`` site — and the
ISSUE-7 resilience layer: dispatch watchdog (wedge verdicts,
quarantine-and-replace), per-bucket circuit breakers, engine
drop + lazy recompile, the ``health()`` surface, and the chaos soak."""

import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.resilience import (CircuitBreaker, CircuitOpen,
                                         DispatchWedged)
from raft_tpu.serving.scheduler import (BackpressureError, DeadlineExceeded,
                                        MicroBatchScheduler, SchedulerClosed,
                                        ServeResult)
from raft_tpu.serving.session import VideoSession
from raft_tpu.testing import faults

SHAPES = [(32, 32), (40, 40)]
BUCKET_BATCH = 3


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return cfg, variables


@pytest.fixture(scope="module")
def engine(small_setup):
    """One warm-start engine for the whole module: two documented
    buckets, one per drill shape — every test below must leave
    ``len(_compiled)`` at exactly these two."""
    cfg, variables = small_setup
    return RAFTEngine(variables, cfg, iters=1,
                      envelope=[(BUCKET_BATCH, h, w) for h, w in SHAPES],
                      precompile=True, warm_start=True)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


def _pair(rng, h=32, w=32):
    return (rng.rand(h, w, 3).astype(np.float32) * 255,
            rng.rand(h, w, 3).astype(np.float32) * 255)


def _no_leaked_workers(before, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive()
                  and t.name.startswith("MicroBatchScheduler")]
        if not leaked:
            return []
        time.sleep(0.05)
    return leaked


class TestRaggedTrafficDrill:
    def test_acceptance_drill(self, engine, small_setup, tmp_path):
        """The ISSUE-6 acceptance criterion: mixed shapes + ragged
        tails, two concurrent submitters — every non-shed request
        served, executable count pinned at the documented bucket
        count, occupancy strictly above one-request-per-dispatch,
        zero deadline-abandoned in-flight, and a metrics.jsonl
        snapshot carrying the full surface."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_drill

        mpath = str(tmp_path / "metrics.jsonl")
        summary = run_drill(variables, cfg, shapes=SHAPES, requests=14,
                            submitters=2, bucket_batch=BUCKET_BATCH,
                            deadline_s=120.0, gather_window_s=0.05,
                            metrics_path=mpath, engine=engine)
        # all non-shed requests served (14 across both shapes: 7 per
        # shape — ragged against the batch-3 buckets)
        assert summary["shed"] == 0 and summary["errors"] == 0
        assert summary["deadline_missed"] == 0
        assert summary["served"] == summary["submitted"] == 14
        # cross-caller coalescing kept the executable count at the
        # documented bucket count (the H3 invariant, scheduler layer)
        assert summary["executables"] == len(SHAPES)
        assert sorted(engine._compiled) == [
            (BUCKET_BATCH, h, w) for h, w in SHAPES]
        # mean batch occupancy strictly above the one-request-per-
        # dispatch baseline: the batch dim filled with OTHER callers'
        # work, not padding
        assert summary["mean_occupancy"] > summary["baseline_occupancy"]
        assert summary["dispatches"] < summary["served"]
        # no in-flight request was deadline-abandoned
        assert summary["abandoned_inflight"] == 0

        recs = [json.loads(line) for line in open(mpath)]
        rec = recs[-1]
        # the trainer Logger's jsonl contract + the serving surface
        assert rec["step"] >= 1 and rec["kind"] == "serving"
        assert rec["shed"] == 0 and rec["executables"] == len(SHAPES)
        assert rec["queue_depth"]["max"] >= 1
        assert (rec["occupancy"]["mean"]
                > rec["occupancy"]["one_per_dispatch_baseline"])
        used = [b for b in rec["buckets"].values() if b["dispatches"]]
        assert used, "no per-bucket records"
        for b in used:
            for stage in ("queue", "device", "total"):
                assert b[stage]["count"] == b["filled"]
                assert b[stage]["p50_ms"] <= b[stage]["p99_ms"]
            # the BUCKETED path reports the padding-waste gauge too —
            # comparable against a ragged A/B line by construction
            assert 0 < b["real_px"] <= b["padded_px"]
        assert (0 < rec["padding_waste"]["real_px"]
                <= rec["padding_waste"]["padded_px"])
        assert rec["ragged"]["dispatches"] == 0  # bucketed drill

    def test_sessions_coalesce_with_oneshot_traffic(self, engine,
                                                    small_setup):
        """Warm-start sessions and one-shot submitters share buckets:
        the drill with sessions on still pins the executable count."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_drill

        summary = run_drill(variables, cfg, shapes=SHAPES, requests=6,
                            submitters=2, bucket_batch=BUCKET_BATCH,
                            sessions=2, session_frames=3,
                            gather_window_s=0.02, engine=engine)
        assert summary["errors"] == 0 and summary["shed"] == 0
        assert summary["session_pairs"] == 2 * 3
        # up to 2 warm pairs per stream; random-weight flows that blow
        # out of the low-res frame (correctly) degrade to cold starts
        assert 1 <= summary["warm_submits"] <= 2 * 2
        assert summary["executables"] == len(SHAPES)
        assert summary["abandoned_inflight"] == 0


class TestSchedulerBasics:
    def test_single_request_matches_engine_direct(self, engine, rng):
        i1, i2 = _pair(rng)
        direct = engine.infer_batch(i1[None], i2[None])[0]
        with MicroBatchScheduler(engine,
                                 gather_window_s=0.0) as sched:
            res = sched.submit(i1, i2).result(timeout=120)
        assert isinstance(res, ServeResult)
        # same executable, batch fill is per-sample neutral (measured
        # ~3e-5 px; tests/test_serving.py ragged-tail test)
        np.testing.assert_allclose(res.flow, direct, atol=1e-3,
                                   rtol=1e-4)
        assert res.flow_low is None  # not requested

    def test_submit_validates_inputs(self, engine, rng):
        i1, i2 = _pair(rng)
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            with pytest.raises(ValueError, match="one \\(H, W, 3\\)"):
                sched.submit(i1[None], i2[None])
            with pytest.raises(ValueError, match="shapes differ"):
                sched.submit(i1, i2[:24])

    def test_drain_on_close_serves_everything(self, engine, rng):
        before = set(threading.enumerate())
        sched = MicroBatchScheduler(engine, gather_window_s=0.01)
        futs = [sched.submit(*_pair(rng)) for _ in range(5)]
        sched.close(drain=True)
        assert all(f.done() for f in futs)
        for f in futs:
            assert f.result().flow.shape == (32, 32, 2)
        with pytest.raises(SchedulerClosed):
            sched.submit(*_pair(rng))
        sched.close()  # idempotent
        assert not _no_leaked_workers(before)

    def test_no_drain_close_fails_pending_loudly(self, engine, rng):
        """A no-drain close must FAIL queued work, not strand it."""
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.4}])
        sched = MicroBatchScheduler(engine, gather_window_s=0.0)
        first = sched.submit(*_pair(rng))   # dispatched, hangs 0.4s
        time.sleep(0.2)
        queued = sched.submit(*_pair(rng))  # still queued behind it
        sched.close(drain=False)
        # the dispatched request still completes — never abandoned
        assert first.result(timeout=120).flow.shape == (32, 32, 2)
        with pytest.raises(SchedulerClosed):
            queued.result(timeout=120)


class TestBackpressureAndDeadlines:
    def test_full_queue_sheds_new_never_inflight(self, engine, rng):
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.8}])
        sched = MicroBatchScheduler(engine, max_queue=2,
                                    gather_window_s=0.0)
        accepted = [sched.submit(*_pair(rng))]
        time.sleep(0.3)  # worker popped it and is hanging in dispatch
        accepted += [sched.submit(*_pair(rng)) for _ in range(2)]
        with pytest.raises(BackpressureError, match="queue full"):
            sched.submit(*_pair(rng))
        sched.close(drain=True)
        # shedding rejected the NEW request only: every accepted one
        # was served
        for f in accepted:
            assert f.result(timeout=0).flow.shape == (32, 32, 2)
        snap = sched.metrics.snapshot()
        assert snap["shed"] == 1
        assert snap["abandoned_inflight"] == 0
        assert snap["completed"] == 3

    def test_queued_deadline_expires_inflight_completes(self, engine,
                                                        rng):
        """A deadline is enforced while QUEUED only: the dispatched
        request outliving its deadline mid-device still completes;
        the one expiring behind it fails fast."""
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.6}])
        sched = MicroBatchScheduler(engine, gather_window_s=0.0)
        first = sched.submit(*_pair(rng), deadline_s=0.2)
        time.sleep(0.25)  # first is mid-hang, its deadline now past
        late = sched.submit(*_pair(rng), deadline_s=0.1)
        assert first.result(timeout=120).flow.shape == (32, 32, 2)
        with pytest.raises(DeadlineExceeded, match="never dispatched"):
            late.result(timeout=120)
        sched.close()
        snap = sched.metrics.snapshot()
        assert snap["deadline_missed"] == 1
        assert snap["abandoned_inflight"] == 0


class _UnservableShapeEngine:
    """Duck-typed engine stub: capacity probes raise for one poisoned
    spatial shape (what a mesh-invalid extent or compile failure looks
    like), everything else serves a trivial flow — fast and
    deterministic for the dispatcher-survival test."""

    warm_start = False

    def __init__(self):
        self._compiled = {(2, 32, 32): object()}

    def bucket_capacity(self, h, w):
        if (h, w) == (24, 24):
            raise RuntimeError("unservable shape (mesh extent)")
        return 2

    def route_bucket(self, b, h, w):
        return (2, -(-h // 8) * 8, -(-w // 8) * 8)

    def infer_batch(self, i1, i2):
        return np.zeros(i1.shape[:3] + (2,), np.float32)


class TestDispatcherResilience:
    def test_unservable_shape_fails_its_requests_not_the_worker(self,
                                                                rng):
        """A shape whose capacity probe raises (mesh-invalid extent,
        compile failure) must fail THOSE futures — not kill the
        dispatcher thread and strand every queued request behind a
        dead worker."""
        sched = MicroBatchScheduler(_UnservableShapeEngine(),
                                    gather_window_s=0.0)
        bad = sched.submit(rng.rand(24, 24, 3).astype(np.float32),
                           rng.rand(24, 24, 3).astype(np.float32))
        with pytest.raises(RuntimeError, match="unservable shape"):
            bad.result(timeout=30)
        # the worker survived and keeps serving other shapes
        ok = sched.submit(*_pair(rng))
        assert ok.result(timeout=30).flow.shape == (32, 32, 2)
        sched.close()
        assert sched.metrics.snapshot()["failed"] == 1

    def test_malformed_flow_init_fails_at_submit(self, engine, rng):
        """A wrong-shape warm start is rejected at submit — at dispatch
        the row assignment would fail (or, if broadcastable, silently
        corrupt) the whole coalesced micro-batch, other callers
        included."""
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            with pytest.raises(ValueError, match="flow_init shape"):
                sched.submit(*_pair(rng),
                             flow_init=np.zeros((2,), np.float32))
            with pytest.raises(ValueError, match="flow_init shape"):
                # broadcastable-but-wrong must also be rejected
                sched.submit(*_pair(rng),
                             flow_init=np.zeros((1, 1, 2), np.float32))
            ok = sched.submit(
                *_pair(rng), flow_init=np.zeros((4, 4, 2), np.float32))
            assert ok.result(timeout=120).flow.shape == (32, 32, 2)


class TestServeRequestFaults:
    def test_raise_fails_batch_not_worker(self, engine, rng):
        faults.arm([{"site": "serve.request", "kind": "raise"}])
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            bad = sched.submit(*_pair(rng))
            with pytest.raises(faults.FaultInjected):
                bad.result(timeout=120)
            # the worker survived the injected failure
            ok = sched.submit(*_pair(rng))
            assert ok.result(timeout=120).flow.shape == (32, 32, 2)
            snap = sched.metrics.snapshot()
        assert snap["failed"] == 1 and snap["completed"] == 1

    def test_hang_drill_no_leaked_threads(self, engine, rng):
        """The satellite drill: a hung dispatch backs traffic up into
        shed + deadline misses, and shutdown still drains clean with
        no leaked worker threads (the PR-3 loader-semaphore lesson)."""
        before = set(threading.enumerate())
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.7}])
        sched = MicroBatchScheduler(engine, max_queue=2,
                                    gather_window_s=0.0)
        futs = [sched.submit(*_pair(rng))]
        time.sleep(0.2)  # the worker is now wedged mid-dispatch
        futs.append(sched.submit(*_pair(rng), deadline_s=0.1))
        futs.append(sched.submit(*_pair(rng)))
        shed = 0
        try:
            sched.submit(*_pair(rng))
        except BackpressureError:
            shed = 1
        sched.close(drain=True)
        outcomes = {"served": 0, "missed": 0}
        for f in futs:
            try:
                f.result(timeout=0)
                outcomes["served"] += 1
            except DeadlineExceeded:
                outcomes["missed"] += 1
        snap = sched.metrics.snapshot()
        assert shed == 1 and snap["shed"] == 1
        assert outcomes["missed"] == snap["deadline_missed"] == 1
        assert outcomes["served"] == snap["completed"] == 2
        assert snap["abandoned_inflight"] == 0
        leaked = _no_leaked_workers(before)
        assert not leaked, f"leaked scheduler threads: {leaked}"


class TestVideoSessions:
    def test_warm_start_recurrence(self, engine, rng):
        frames = [rng.rand(32, 32, 3).astype(np.float32) * 255
                  for _ in range(4)]
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            sess = VideoSession(sched)
            futs = [sess.submit_frame(f) for f in frames]
            assert futs[0] is None and all(f is not None
                                           for f in futs[1:])
            results = [f.result(timeout=120) for f in futs[1:]]
            assert sess.drain() is not None
        assert all(r.flow.shape == (32, 32, 2) for r in results)
        # a warm-start session asks for flow_low back on every pair
        assert all(r.flow_low is not None for r in results)
        assert all(r.flow_low.shape == (4, 4, 2) for r in results)
        # pairs 2 and 3 can warm-start from the previous pair's flow;
        # >= 1, not == 2: random-init weights produce flows that can
        # blow out of the 4x4 low-res frame, and the session then
        # (correctly) degrades that pair to a cold start
        assert 1 <= sess.warm_submits <= 2

    def test_device_state_session_keeps_flow_low_on_device(self,
                                                           engine, rng):
        """ISSUE-8 satellite: device_state=True carries the recurrence
        state between pairs as a DEVICE array — the result's flow_low
        is a jax array, the on-device forward splat feeds it back
        (warm_submits counts it), and drain() still hands the caller a
        host array."""
        import jax

        frames = [rng.rand(32, 32, 3).astype(np.float32) * 255
                  for _ in range(4)]
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            sess = VideoSession(sched, device_state=True)
            futs = [sess.submit_frame(f) for f in frames]
            results = [f.result(timeout=120) for f in futs[1:]]
            # the state never round-tripped: flow_low rides on device
            assert all(isinstance(r.flow_low, jax.Array)
                       for r in results)
            # the device splat has no blow-out degrade (holes are
            # locally cold, not NaN), so every chained pair warm-starts
            assert sess.warm_submits == 2
            final = sess.drain()
        assert isinstance(final, np.ndarray)   # drain materializes
        assert final.shape == (4, 4, 2)
        assert all(r.flow.shape == (32, 32, 2) for r in results)
        # no NaN escaped the device recurrence into served flow
        assert all(np.isfinite(r.flow).all() for r in results)

    def test_device_forward_splat_matches_host_warp_semantics(self):
        """The on-device forward splat vs the scipy host path on a
        controlled flow: identical values where a warped point lands
        (nearest-scatter), zeros in the holes (locally cold instead of
        griddata's global nearest fill), and NaN input degrades to an
        all-cold (all-zero) init instead of poisoning the stream."""
        from raft_tpu.ops.interp import (forward_interpolate,
                                         forward_interpolate_device)

        # uniform (+1, +1) shift: every interior target receives
        # exactly the value (1, 1) — no scatter-tie ambiguity — and
        # the vacated first row/column becomes the hole case
        flow = np.ones((6, 8, 2), np.float32)
        dev = np.asarray(forward_interpolate_device(flow))
        host = forward_interpolate(flow)
        np.testing.assert_array_equal(dev[1:, 1:],
                                      np.ones((5, 7, 2), np.float32))
        np.testing.assert_array_equal(host[1:, 1:],
                                      np.ones((5, 7, 2), np.float32))
        # the documented divergence: holes stay ZERO on device
        # (locally cold), while griddata nearest-fills them
        np.testing.assert_array_equal(dev[0, :], np.zeros((8, 2)))
        np.testing.assert_array_equal(dev[:, 0], np.zeros((6, 2)))
        np.testing.assert_array_equal(host[0, 1:],
                                      np.ones((7, 2), np.float32))
        # NaN flow: every point fails the validity window -> all-zero
        # (cold) init, no host sync, no NaN
        bad = np.full((6, 8, 2), np.nan, np.float32)
        dev_bad = np.asarray(forward_interpolate_device(bad))
        np.testing.assert_array_equal(dev_bad, np.zeros_like(bad))

    def test_flow_init_moves_the_refinement_start(self, engine, rng):
        """The warm-start mechanism itself, deterministically: the same
        pair with a nonzero flow_init differs from the cold dispatch
        (same weights, same executable — the flow_init row is the only
        difference)."""
        i1, i2 = _pair(rng)
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            cold = sched.submit(i1, i2).result(timeout=120).flow
            warm = sched.submit(
                i1, i2,
                flow_init=np.full((4, 4, 2), 0.5, np.float32)).result(
                timeout=120).flow
        assert not np.array_equal(cold, warm)
        assert np.isfinite(warm).all()

    def test_shape_change_restarts_stream(self, engine, rng):
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            sess = VideoSession(sched)
            assert sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32)) is None
            f1 = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32))
            assert f1.result(timeout=120).flow.shape == (32, 32, 2)
            # resolution change: the pair is meaningless — restart
            assert sess.submit_frame(
                rng.rand(40, 40, 3).astype(np.float32)) is None
            f2 = sess.submit_frame(
                rng.rand(40, 40, 3).astype(np.float32))
            # first pair of the restarted stream is a cold start
            assert sess.warm_submits == 0
            assert f2.result(timeout=120).flow.shape == (40, 40, 2)

    def test_blown_out_warm_start_cold_restarts(self, engine, rng):
        """Found by the verification drive: when the previous pair's
        flow is larger than the frame, every forward-warped point
        lands outside it — griddata has an empty scatter and returns
        NaN ('nearest' ignores fill_value), which would poison the
        stream. The session must cold-restart instead."""
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            sess = VideoSession(sched)
            sess.submit_frame(rng.rand(32, 32, 3).astype(np.float32))
            # the degenerate state: all motion out of the 4x4 low-res
            # frame (what random weights / a garbage pair produce)
            sess._flow_low = np.full((4, 4, 2), 99.0, np.float32)
            fut = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32))
            res = fut.result(timeout=120)
            assert np.isfinite(res.flow).all()
            assert sess.warm_submits == 0  # degraded to a cold start
        # the scheduler rejects a caller's non-finite warm start with a
        # cause instead of returning NaN flow from the device
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            bad = np.full((4, 4, 2), np.nan, np.float32)
            with pytest.raises(ValueError, match="non-finite"):
                sched.submit(*_pair(rng), flow_init=bad)

    def test_failed_pair_cold_restarts_not_poisons(self, engine, rng):
        """A deadline-missed pair surfaces on ITS future; the session
        cold-restarts the recurrence instead of dying on harvest."""
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.5}])
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            blocker = sched.submit(*_pair(rng))  # wedges the worker
            time.sleep(0.2)
            sess = VideoSession(sched)
            sess.submit_frame(rng.rand(32, 32, 3).astype(np.float32))
            doomed = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32), deadline_s=0.05)
            ok = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32))
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=120)
            assert ok.result(timeout=120).flow.shape == (32, 32, 2)
            assert sess.warm_submits == 0  # cold restart, not stale warm
            blocker.result(timeout=120)


def _pad8(x):
    return -(-x // 8) * 8


class _FakePending:
    """_FakeEngine's PendingBatch analog: ``fetch_delay_s`` models the
    device compute the host only waits for at fetch — the deterministic
    substrate for the pipelining A/B (no XLA timing noise)."""

    def __init__(self, eng, shape, bucket):
        self._eng = eng
        self._shape = shape
        self.bucket = bucket
        self.h2d_bytes = int(np.prod(shape)) * 2
        self.t_ready = None

    def fetch(self):
        faults.fault_point("serve.fetch")
        if self._eng.fetch_delay_s:
            time.sleep(self._eng.fetch_delay_s)
        if self._shape[1:3] in self._eng.fail_fetch_shapes:
            raise RuntimeError(f"fetch error at {self._shape[1:3]}")
        out = np.zeros(self._shape[:3] + (2,), np.float32)
        self.t_ready = time.monotonic()
        return out


class _FakeEngine:
    """Duck-typed engine for fast, deterministic resilience drills:
    per-shape hang/fail behavior without XLA. Mirrors the real engine's
    scheduler-facing surface (capacity/route/ensure/drop/_compiled +
    the async dispatch split)."""

    warm_start = False

    def __init__(self, infer_delay_s=0.0, fetch_delay_s=0.0):
        self._compiled = {}
        self.infer_delay_s = infer_delay_s
        self.fetch_delay_s = fetch_delay_s
        self.compile_calls = 0
        self.hang_shapes = {}     # (h, w) -> sleep seconds in infer
        self.fail_shapes = set()  # (h, w) -> raise in infer
        self.fail_fetch_shapes = set()  # (h, w) -> raise in fetch

    def bucket_capacity(self, h, w):
        hp, wp = _pad8(h), _pad8(w)
        fits = [s[0] for s in self._compiled
                if s[1] == hp and s[2] == wp]
        return max(fits) if fits else None

    def ensure_bucket(self, b, h, w):
        self.compile_calls += 1
        shape = (b, _pad8(h), _pad8(w))
        self._compiled[shape] = object()
        return shape

    def route_bucket(self, b, h, w):
        cap = self.bucket_capacity(h, w)
        return (cap or b, _pad8(h), _pad8(w))

    def drop_bucket(self, shape):
        return self._compiled.pop(shape, None) is not None

    def infer_batch_async(self, i1, i2, **kw):
        key = (i1.shape[1], i1.shape[2])
        if key in self.hang_shapes:
            time.sleep(self.hang_shapes[key])
        if key in self.fail_shapes:
            raise RuntimeError(f"device error at {key}")
        if self.infer_delay_s:
            time.sleep(self.infer_delay_s)
        return _FakePending(self, i1.shape, self.route_bucket(
            i1.shape[0], *key))

    def infer_batch(self, i1, i2, **kw):
        return self.infer_batch_async(i1, i2, **kw).fetch()


def _wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _retry_until_served(sched, rng, h=32, w=32, timeout=10.0):
    """Probe a shape until it serves (drives the half-open probe);
    returns the result or None on budget exhaustion."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            return sched.submit(
                rng.rand(h, w, 3).astype(np.float32),
                rng.rand(h, w, 3).astype(np.float32)).result(
                timeout=timeout)
        except (CircuitOpen, DispatchWedged):
            time.sleep(0.05)
    return None


class TestCircuitBreakerUnit:
    def test_round_trip_with_injected_clock(self):
        t = [0.0]
        seen = []
        br = CircuitBreaker(failures=2, base_s=10.0, max_s=40.0,
                            jitter=0.0, clock=lambda: t[0],
                            on_transition=lambda o, n: seen.append(
                                (o, n)))
        assert br.state() == "closed"
        br.record_failure()
        assert br.state() == "closed"      # K=2: one failure holds
        br.record_failure(wedged=True)
        assert br.peek() == "open" and br.opens == 1 and br.wedges == 1
        snap = br.snapshot()
        assert snap["state"] == "open" and snap["retry_in_s"] == 10.0
        t[0] = 9.9
        assert br.peek() == "open"
        t[0] = 10.1
        # peek reports the promotion without firing it; state commits it
        assert br.peek() == "half_open"
        assert ("open", "half_open") not in seen
        assert br.state() == "half_open"
        # failed probe: re-open with the NEXT (doubled) backoff
        br.record_failure()
        assert br.peek() == "open" and br.opens == 2
        t[0] = 10.1 + 19.9
        assert br.peek() == "open"
        t[0] = 10.1 + 20.1
        assert br.state() == "half_open"
        br.record_success()
        assert br.state() == "closed" and br.consecutive == 0
        assert seen == [("closed", "open"), ("open", "half_open"),
                        ("half_open", "open"), ("open", "half_open"),
                        ("half_open", "closed")]
        # a recovery resets the backoff series: the next trip starts
        # from base again
        br.record_failure()
        br.record_failure()
        assert br.snapshot()["retry_in_s"] == 10.0

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(failures=3)
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state() == "closed"   # never 3 consecutive

    def test_validation(self):
        with pytest.raises(ValueError, match="failures"):
            CircuitBreaker(failures=0)


class TestDispatchWatchdog:
    """The wedge verdict on the fast stub engine: deterministic
    timing, no XLA."""

    def _sched(self, eng, **kw):
        kw.setdefault("gather_window_s", 0.0)
        kw.setdefault("max_batch", 2)
        kw.setdefault("dispatch_timeout_s", 0.3)
        kw.setdefault("breaker_failures", 1)
        kw.setdefault("breaker_backoff_s", 0.2)
        kw.setdefault("breaker_backoff_max_s", 0.2)
        kw.setdefault("breaker_rng", random.Random(0))
        return MicroBatchScheduler(eng, **kw)

    def test_wedge_fails_futures_within_timeout_and_recovers(self, rng):
        before = set(threading.enumerate())
        eng = _FakeEngine()
        eng.hang_shapes[(32, 32)] = 1.0
        sched = self._sched(eng)
        t0 = time.monotonic()
        fut = sched.submit(*_pair(rng))
        with pytest.raises(DispatchWedged):
            fut.result(timeout=5)
        # the verdict fired at the timeout, not at the end of the hang
        assert time.monotonic() - t0 < 0.9
        h = sched.health()
        assert h["state"] == "degraded"
        assert h["buckets"]["32x32"]["state"] in ("open", "half_open")
        assert h["buckets"]["32x32"]["wedges"] == 1
        assert h["quarantined_threads"] == 1
        # while 32x32 is open, the healthy shape keeps serving
        ok = sched.submit(rng.rand(40, 40, 3).astype(np.float32),
                          rng.rand(40, 40, 3).astype(np.float32))
        assert ok.result(timeout=5).flow.shape == (40, 40, 2)
        # the suspect executable was dropped; the half-open probe
        # recompiles it lazily and closes the breaker
        assert (2, 32, 32) not in eng._compiled
        eng.hang_shapes.clear()
        assert _retry_until_served(sched, rng) is not None
        assert (2, 32, 32) in eng._compiled
        assert _wait_for(lambda: sched.health()["state"] == "healthy")
        snap = sched.metrics.snapshot()
        assert snap["resilience"]["wedged"] == 1
        assert snap["resilience"]["quarantined_threads"] == 1
        assert snap["resilience"]["breaker_transitions"]["open"] >= 1
        assert snap["resilience"]["breaker_transitions"]["closed"] >= 1
        assert snap["abandoned_inflight"] == 0
        # the accounting identity: every accepted request settled once
        assert snap["submitted"] == (snap["completed"] + snap["failed"]
                                     + snap["deadline_missed"]
                                     + snap["cancelled"])
        sched.close(drain=True)
        # the replacement worker joined; the quarantined thread exits
        # once its hang ends (leak accounted, then gone)
        assert not _no_leaked_workers(before)

    def test_submit_fails_fast_while_open(self, rng):
        eng = _FakeEngine()
        eng.fail_shapes.add((32, 32))
        sched = self._sched(eng, breaker_backoff_s=30.0,
                            breaker_backoff_max_s=30.0)
        bad = sched.submit(*_pair(rng))
        with pytest.raises(RuntimeError, match="device error"):
            bad.result(timeout=5)
        assert _wait_for(
            lambda: sched.health()["buckets"].get("32x32", {}).get(
                "state") == "open", timeout=5)
        with pytest.raises(CircuitOpen, match="failing fast"):
            sched.submit(*_pair(rng))
        assert sched.metrics.circuit_rejected == 1
        sched.close(drain=True)

    def test_open_breaker_fails_queued_work_fast(self, rng):
        """Requests already queued when the breaker opens fail with
        CircuitOpen instead of starving until their deadline."""
        eng = _FakeEngine()
        eng.hang_shapes[(32, 32)] = 0.8
        sched = self._sched(eng, breaker_backoff_s=30.0,
                            breaker_backoff_max_s=30.0)
        wedged = sched.submit(*_pair(rng))   # dispatched, wedges
        time.sleep(0.05)
        queued = [sched.submit(*_pair(rng)) for _ in range(3)]
        with pytest.raises(DispatchWedged):
            wedged.result(timeout=5)
        for q in queued:
            with pytest.raises(CircuitOpen):
                q.result(timeout=5)
        sched.close(drain=True)
        snap = sched.metrics.snapshot()
        assert snap["failed"] == 4
        assert snap["submitted"] == (snap["completed"] + snap["failed"]
                                     + snap["deadline_missed"]
                                     + snap["cancelled"])

    def test_wedged_compile_fails_shape_requests(self, rng):
        """A hang in the capacity probe (engine.compile) wedges before
        any request is taken: the shape's queued requests must still
        fail with DispatchWedged — never stranded behind the stuck
        thread."""
        eng = _FakeEngine()
        real_ensure = eng.ensure_bucket

        def slow_ensure(b, h, w):
            if (h, w) == (32, 32):
                time.sleep(0.8)
            return real_ensure(b, h, w)

        eng.ensure_bucket = slow_ensure
        sched = self._sched(eng)
        fut = sched.submit(*_pair(rng))
        with pytest.raises(DispatchWedged):
            fut.result(timeout=5)
        sched.close(drain=True)

    def test_deadline_fires_while_dispatch_inflight(self, rng):
        """The satellite bound: a queued deadline fires within the
        supervision poll tick even while a slow dispatch is in flight —
        not after it."""
        eng = _FakeEngine(infer_delay_s=1.0)
        sched = self._sched(eng, dispatch_timeout_s=10.0,
                            breaker_failures=0)
        first = sched.submit(*_pair(rng))    # dispatched: 1.0s on device
        time.sleep(0.1)
        late = sched.submit(*_pair(rng), deadline_s=0.15)
        # pinned lag bound: expiry at +0.15s, surfaced well inside
        # 0.6s — the in-flight dispatch (1.0s) did not gate it
        exc = late.exception(timeout=0.6)
        assert isinstance(exc, DeadlineExceeded)
        assert not first.done()              # the dispatch is still out
        assert first.result(timeout=5).flow.shape == (32, 32, 2)
        sched.close(drain=True)
        snap = sched.metrics.snapshot()
        assert snap["deadline_missed"] == 1
        assert snap["abandoned_inflight"] == 0

    def test_submit_sweeps_expired_queue_inline_mode(self, rng):
        """Without a watchdog (inline dispatch — the default), submit
        itself is an expiry edge: an expired queued request fails when
        the next submit arrives, not when the busy worker resumes."""
        eng = _FakeEngine(infer_delay_s=0.8)
        sched = MicroBatchScheduler(eng, gather_window_s=0.0,
                                    max_batch=2)
        blocker = sched.submit(*_pair(rng))  # worker busy 0.8s
        time.sleep(0.1)
        doomed = sched.submit(*_pair(rng), deadline_s=0.05)
        time.sleep(0.15)                     # now expired, still queued
        sched.submit(*_pair(rng))            # the sweeping edge
        exc = doomed.exception(timeout=0.2)
        assert isinstance(exc, DeadlineExceeded)
        assert not blocker.done()
        sched.close(drain=True)

    def test_no_drain_close_after_traffic(self, rng):
        """Regression (ISSUE-7 satellite): close(drain=False) after
        dispatches have rewritten the queue must fail pending work via
        the one queue representation — not crash on a type change."""
        eng = _FakeEngine()
        sched = MicroBatchScheduler(eng, gather_window_s=0.0,
                                    max_batch=2)
        warm = sched.submit(*_pair(rng))
        assert warm.result(timeout=5).flow.shape == (32, 32, 2)
        eng.infer_delay_s = 0.5              # wedge the worker briefly
        blocker = sched.submit(*_pair(rng))
        time.sleep(0.1)
        queued = [sched.submit(*_pair(rng)) for _ in range(2)]
        sched.close(drain=False)             # must not raise
        assert blocker.result(timeout=5).flow.shape == (32, 32, 2)
        for q in queued:
            with pytest.raises(SchedulerClosed):
                q.result(timeout=5)
        snap = sched.metrics.snapshot()
        assert snap["submitted"] == (snap["completed"] + snap["failed"]
                                     + snap["deadline_missed"]
                                     + snap["cancelled"])


class TestPipelinedDispatch:
    """ISSUE-8 tentpole (b): pipeline_depth splits dispatch into
    stages over JAX async dispatch — assembly of batch N+1 overlaps
    device compute of batch N, the blocking fetch moves to a
    completion stage, and every PR-6/7 invariant (accounting identity,
    per-request result routing, wedge verdicts, drain) holds across
    in-flight batches."""

    def test_depth2_accounting_and_result_routing(self, engine, rng):
        """Each future gets ITS pair's flow (results cross the
        completion stage without mixing batches), the accounting
        identity holds, and the hot-path metrics block lands in the
        snapshot."""
        pairs = [_pair(rng, h, w)
                 for h, w in (SHAPES * 4)[:8]]
        direct = [engine.infer_batch(i1[None], i2[None])[0]
                  for i1, i2 in pairs]
        with MicroBatchScheduler(engine, max_batch=BUCKET_BATCH,
                                 gather_window_s=0.01,
                                 pipeline_depth=2) as sched:
            futs = [sched.submit(i1, i2) for i1, i2 in pairs]
            res = [f.result(timeout=120) for f in futs]
            for got, want in zip(res, direct):
                # batch fill is per-sample neutral (~3e-5 px)
                np.testing.assert_allclose(got.flow, want, atol=1e-3,
                                           rtol=1e-4)
            h = sched.health()
            assert h["pending_completions"] == 0
            assert h["completion_worker_alive"] is True
        snap = sched.metrics.snapshot()
        assert snap["submitted"] == 8 == snap["completed"]
        assert snap["submitted"] == (snap["completed"] + snap["failed"]
                                     + snap["deadline_missed"]
                                     + snap["cancelled"])
        assert snap["abandoned_inflight"] == 0
        hot = snap["hot_path"]
        assert hot["h2d_bytes"] > 0 and hot["h2d_bytes_per_req"] > 0
        assert hot["dispatch_gap"]["count"] >= 1
        assert 0.0 <= hot["assembly"]["overlap_ratio"] <= 1.0
        # the module invariant: no bucket leaked through the pipeline
        assert sorted(engine._compiled) == [
            (BUCKET_BATCH, h, w) for h, w in SHAPES]

    def test_depth2_gap_strictly_below_depth1(self, rng):
        """THE ISSUE-8 acceptance shape, deterministic: with device
        compute modeled as a fetch-side delay, depth 2 ships batch
        N+1 while N still computes — its mean dispatch gap must sit
        strictly below depth 1's on the same traffic."""
        gaps = {}
        for depth in (1, 2):
            eng = _FakeEngine(fetch_delay_s=0.03)
            eng.ensure_bucket(1, 32, 32)
            sched = MicroBatchScheduler(eng, max_batch=1,
                                        gather_window_s=0.0,
                                        pipeline_depth=depth)
            futs = [sched.submit(*_pair(rng)) for _ in range(6)]
            for f in futs:
                f.result(timeout=30)
            sched.close(drain=True)
            snap = sched.metrics.snapshot()
            gaps[depth] = snap["hot_path"]["dispatch_gap"]["mean_ms"]
            assert snap["completed"] == 6
            if depth == 2:
                assert snap["hot_path"]["assembly"]["overlap_ratio"] > 0
        # depth 1 serializes ready->next-dispatch (gap > 0 always);
        # depth 2 ships during the 30ms compute window -> gap 0 for
        # every overlapped dispatch
        assert gaps[2] < gaps[1], gaps

    def test_depth2_wedge_acceptance_and_recovery(self, small_setup,
                                                  rng):
        """The PR-7 wedge drill at depth 2, on the real stack: a hang
        in the COMPLETION stage (serve.fetch — device compute/D2H that
        never returns) gets the verdict within the timeout, with the
        consequences-before-futures-fail ordering spanning in-flight
        batches: bucket dropped, breaker open, completion worker
        quarantined, THEN DispatchWedged; recovery recompiles and the
        accounting identity survives — no stranded futures, no
        abandoned in-flight work."""
        before = set(threading.enumerate())
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1,
                         envelope=[(2, 32, 32)], precompile=True,
                         warm_start=True)
        faults.arm([{"site": "serve.fetch", "kind": "hang",
                     "hang_s": 1.5}])
        sched = MicroBatchScheduler(
            eng, max_batch=2, gather_window_s=0.0,
            dispatch_timeout_s=0.4, breaker_failures=1,
            breaker_backoff_s=0.2, breaker_backoff_max_s=0.2,
            breaker_rng=random.Random(0), pipeline_depth=2)
        try:
            t0 = time.monotonic()
            wedged = sched.submit(*_pair(rng))
            with pytest.raises(DispatchWedged,
                               match="dispatch_timeout_s"):
                wedged.result(timeout=10)
            assert time.monotonic() - t0 < 1.3  # verdict, not hang-end
            # consequences landed before the future failed
            assert (2, 32, 32) not in eng._compiled
            h = sched.health()
            assert h["state"] == "degraded"
            assert h["buckets"]["32x32"]["state"] in ("open",
                                                      "half_open")
            assert h["quarantined_threads"] == 1
            faults.disarm()
            # recovery: the half-open probe recompiles the dropped
            # bucket and serves
            res = _retry_until_served(sched, rng, timeout=60)
            assert res is not None and res.flow.shape == (32, 32, 2)
            assert (2, 32, 32) in eng._compiled
            assert _wait_for(
                lambda: sched.health()["state"] == "healthy")
        finally:
            faults.disarm()
            sched.close(drain=True)
        snap = sched.metrics.snapshot()
        assert snap["resilience"]["wedged"] == 1
        assert snap["resilience"]["quarantined_threads"] == 1
        assert snap["abandoned_inflight"] == 0
        assert snap["submitted"] == (snap["completed"] + snap["failed"]
                                     + snap["deadline_missed"]
                                     + snap["cancelled"])
        assert not _no_leaked_workers(before)

    def test_depth2_trailing_completion_survives_wedge(self, rng):
        """A wedged completion must not strand the batches queued
        BEHIND it: the verdict re-queues them on the replacement
        worker and they settle normally."""
        eng = _FakeEngine()
        eng.ensure_bucket(1, 32, 32)
        eng.ensure_bucket(1, 40, 40)
        sched = MicroBatchScheduler(eng, max_batch=1,
                                    gather_window_s=0.0,
                                    dispatch_timeout_s=0.3,
                                    breaker_failures=1,
                                    breaker_backoff_s=0.2,
                                    breaker_backoff_max_s=0.2,
                                    breaker_rng=random.Random(0),
                                    pipeline_depth=3)
        # first fetch hangs (fault scoped to one fire); the two
        # batches behind it ride the SAME completion worker
        faults.arm([{"site": "serve.fetch", "kind": "hang",
                     "hang_s": 1.0, "count": 1}])
        try:
            doomed = sched.submit(*_pair(rng))
            ok = [sched.submit(rng.rand(40, 40, 3).astype(np.float32),
                               rng.rand(40, 40, 3).astype(np.float32))
                  for _ in range(2)]
            with pytest.raises(DispatchWedged):
                doomed.result(timeout=10)
            for f in ok:
                assert f.result(timeout=10).flow.shape == (40, 40, 2)
        finally:
            faults.disarm()
            sched.close(drain=True)
        snap = sched.metrics.snapshot()
        assert snap["completed"] == 2 and snap["failed"] == 1
        assert snap["submitted"] == (snap["completed"] + snap["failed"]
                                     + snap["deadline_missed"]
                                     + snap["cancelled"])


@pytest.fixture(scope="module")
def resilience_engine(small_setup):
    """Exact-shapes warm-start engine for the real-stack wedge drill:
    two 32x32 buckets (batch 3 and 6) so the half-open probe after the
    (3,32,32) drop recovers through the surviving same-shape bucket
    without a multi-second recompile gating the drill, plus the
    healthy 40x40 shape."""
    cfg, variables = small_setup
    return RAFTEngine(variables, cfg, iters=1,
                      envelope=[(BUCKET_BATCH, 32, 32), (6, 32, 32),
                                (BUCKET_BATCH, 40, 40)],
                      precompile=True, warm_start=True,
                      exact_shapes=True)


class TestWedgeRecoveryAcceptance:
    def test_serve_request_hang_no_longer_wedges_frontend(
            self, resilience_engine, rng, tmp_path):
        """THE ISSUE-7 acceptance criterion, on the real stack: with
        dispatch_timeout_s set, a serve.request hang fails its batch
        with DispatchWedged within the timeout, healthy buckets keep
        serving, the wedged bucket's breaker opens and recovers via the
        half-open probe, health() reports degraded during and healthy
        after, and close(drain=True) returns without leaking the
        replacement worker."""
        before = set(threading.enumerate())
        mpath = str(tmp_path / "metrics.jsonl")
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 1.2}])
        sched = MicroBatchScheduler(
            resilience_engine, max_batch=BUCKET_BATCH,
            gather_window_s=0.0, dispatch_timeout_s=0.4,
            breaker_failures=1, breaker_backoff_s=0.3,
            breaker_backoff_max_s=0.3, breaker_rng=random.Random(0),
            metrics_path=mpath)
        t0 = time.monotonic()
        wedged = sched.submit(*_pair(rng))
        with pytest.raises(DispatchWedged, match="dispatch_timeout_s"):
            wedged.result(timeout=10)
        assert time.monotonic() - t0 < 1.1   # verdict, not hang-end
        h = sched.health()
        assert h["state"] == "degraded"
        assert h["buckets"]["32x32"]["state"] in ("open", "half_open")
        assert h["quarantined_threads"] == 1
        # the suspect executable was dropped
        assert (BUCKET_BATCH, 32, 32) not in resilience_engine._compiled
        # healthy bucket serves while 32x32 is open
        ok = sched.submit(rng.rand(40, 40, 3).astype(np.float32),
                          rng.rand(40, 40, 3).astype(np.float32))
        assert ok.result(timeout=120).flow.shape == (40, 40, 2)
        # recovery: the half-open probe serves through the surviving
        # same-shape bucket and closes the breaker
        res = _retry_until_served(sched, rng, timeout=30)
        assert res is not None and res.flow.shape == (32, 32, 2)
        assert _wait_for(lambda: sched.health()["state"] == "healthy")
        snap = sched.metrics.snapshot()
        assert snap["resilience"]["wedged"] == 1
        assert snap["resilience"]["quarantined_threads"] == 1
        assert snap["abandoned_inflight"] == 0
        sched.close(drain=True)
        # transitions landed as events in the shared metrics.jsonl
        recs = [json.loads(line) for line in open(mpath)]
        events = [r["event"] for r in recs if "event" in r]
        assert "dispatch_wedged" in events
        assert "thread_quarantined" in events
        assert "breaker_open" in events and "breaker_closed" in events
        states = [r for r in recs if r.get("event") == "serving_state"]
        assert any(r["state"] == "degraded" for r in states)
        assert any(r["state"] == "healthy" for r in states)
        # the final write_snapshot line carries the resilience counters
        snap_recs = [r for r in recs if r.get("kind") == "serving"]
        assert snap_recs[-1]["resilience"]["wedged"] == 1
        # no leaked threads once the 1.2s hang releases the
        # quarantined worker
        assert not _no_leaked_workers(before)


class TestChaosDrills:
    def test_chaos_soak(self, small_setup):
        """ISSUE-7 satellite: randomized raise/hang plans (fixed seed)
        at serve.request / serve.dispatch_exec / engine.compile through
        the full resilience stack — no stranded futures, exact
        accounting, abandoned_inflight == 0, breaker open ->
        half-open -> closed round-trip, and the clean recovery round
        back at the documented executable count."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_chaos_drill

        summary = run_chaos_drill(
            variables, cfg, shapes=SHAPES, rounds=2, requests=8,
            submitters=2, bucket_batch=BUCKET_BATCH, iters=1,
            dispatch_timeout_s=0.4, hang_s=0.8, breaker_failures=1,
            breaker_backoff_s=0.15, breaker_backoff_max_s=0.6,
            recover_s=30.0, seed=7)
        assert summary["violations"] == []
        # the drill actually exercised the machinery it claims to
        assert summary["totals"]["wedged_dispatches"] >= 1
        assert summary["totals"]["quarantined_threads"] >= 1
        assert summary["breaker_transitions"]["open"] >= 1
        assert summary["breaker_transitions"]["closed"] >= 1
        assert summary["executables"] == summary["documented_buckets"]
        clean = summary["per_round"][-1]
        assert clean["health_state"] == "healthy"
        assert clean["served"] == clean["accepted"]

    def test_chaos_soak_pipelined(self, small_setup):
        """ISSUE-8: the chaos soak at pipeline_depth=2 — the wedge
        watchdog, breaker verdicts, and accounting identity must hold
        with in-flight batches spanning the dispatch and completion
        stages (plans now draw the serve.fetch site too). No stranded
        futures, abandoned_inflight == 0, clean-round recovery at the
        documented executable count."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_chaos_drill

        summary = run_chaos_drill(
            variables, cfg, shapes=SHAPES, rounds=2, requests=8,
            submitters=2, bucket_batch=BUCKET_BATCH, iters=1,
            dispatch_timeout_s=0.4, hang_s=0.8, breaker_failures=1,
            breaker_backoff_s=0.15, breaker_backoff_max_s=0.6,
            recover_s=30.0, seed=11, pipeline_depth=2)
        assert summary["violations"] == []
        assert summary["totals"]["wedged_dispatches"] >= 1
        assert summary["executables"] == summary["documented_buckets"]
        clean = summary["per_round"][-1]
        assert clean["health_state"] == "healthy"
        assert clean["served"] == clean["accepted"]
        assert clean["pipeline_depth"] == 2

    def test_crash_plan_kills_subprocess_with_drill_code(self):
        """The crash class can't be asserted in-process (os._exit):
        drill it as a child — the serving path must die with
        CRASH_EXIT_CODE (exit-code discipline: the PR-3 supervisor
        layer owns crash recovery, and it keys on this code)."""
        repo = os.path.dirname(os.path.dirname(__file__))
        worker = os.path.join(repo, "tests", "chaos_serve_worker.py")
        proc = subprocess.run(
            [sys.executable, worker, "serve.dispatch_exec"],
            timeout=120, cwd=repo,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo})
        assert proc.returncode == faults.CRASH_EXIT_CODE


class TestFaultScopingUnit:
    """The ISSUE-7 faults.py extensions: per-site probability and
    nth-call/count scoping (the chaos plans' vocabulary)."""

    def test_count_scopes_total_fires(self):
        faults.arm([{"site": "c", "kind": "raise", "at": 2,
                     "count": 2}])
        faults.fault_point("c")                  # occurrence 1: early
        for _ in range(2):                       # occurrences 2, 3 fire
            with pytest.raises(faults.FaultInjected):
                faults.fault_point("c")
        faults.fault_point("c")                  # exhausted
        assert not faults.armed("c")

    def test_probability_is_plan_seeded_and_reproducible(self):
        def fires(seed):
            faults.arm({"seed": seed, "faults": [
                {"site": "p", "kind": "raise", "p": 0.4, "count": 0}]})
            n = 0
            for _ in range(200):
                try:
                    faults.fault_point("p")
                except faults.FaultInjected:
                    n += 1
            return n

        a, b = fires(3), fires(3)
        assert a == b                  # same plan+seed => same fires
        assert 40 < a < 160            # p=0.4 over 200 calls
        assert fires(4) != a or fires(5) != a

    def test_unlimited_count_keeps_firing(self):
        faults.arm([{"site": "u", "kind": "raise", "count": 0}])
        for _ in range(5):
            with pytest.raises(faults.FaultInjected):
                faults.fault_point("u")
        assert faults.armed("u")

    def test_invalid_p_and_count_rejected(self):
        with pytest.raises(ValueError, match="p="):
            faults.arm([{"site": "x", "kind": "raise", "p": 0.0}])
        with pytest.raises(ValueError, match="p="):
            faults.arm([{"site": "x", "kind": "raise", "p": 1.5}])
        with pytest.raises(ValueError, match="count="):
            faults.arm([{"site": "x", "kind": "raise", "count": -1}])


class TestServingMetricsUnit:
    def test_histogram_ladder_and_percentiles(self):
        from raft_tpu.serving.metrics import LatencyHistogram

        h = LatencyHistogram()
        for v in (0.05, 1.0, 3.0, 40.0, 70000.0):
            h.observe(v)
        s = h.snapshot()
        assert s["count"] == 5
        assert s["max_ms"] == 70000.0
        assert s["p50_ms"] <= s["p99_ms"] <= s["max_ms"]
        assert sum(s["counts"]) == 5
        assert h.quantile(0.0) > 0

    def test_snapshot_shape_and_jsonl_append(self, tmp_path):
        from raft_tpu.serving.metrics import ServingMetrics

        path = str(tmp_path / "m" / "metrics.jsonl")
        m = ServingMetrics(path)
        m.record_submit(depth=1)
        m.record_submit(depth=2)
        m.record_dispatch("3x32x32", filled=2, capacity=3, depth=0,
                          real_px=2 * 30 * 30, padded_px=3 * 32 * 32)
        m.record_complete("3x32x32", queue_ms=1.0, device_ms=2.0)
        m.record_complete("3x32x32", queue_ms=4.0, device_ms=2.0)
        m.record_shed()
        rec = m.write_snapshot(executables=1)
        again = m.write_snapshot(executables=1)
        lines = [json.loads(line) for line in open(path)]
        assert [r["step"] for r in lines] == [1, 2]
        assert rec["submitted"] == 2 and rec["shed"] == 1
        assert rec["queue_depth"]["max"] == 2
        b = rec["buckets"]["3x32x32"]
        assert b["occupancy"] == round(2 / 3, 4)
        assert b["total"]["count"] == 2
        # padding-waste gauge schema (both paths record through this
        # one dispatch hook; the ragged block stays zeroed here)
        assert b["real_px"] == 1800 and b["padded_px"] == 3072
        assert b["padding_waste"] == round(1 - 1800 / 3072, 4)
        pw = rec["padding_waste"]
        assert pw["real_px"] == 1800 and pw["padded_px"] == 3072
        assert pw["waste_ratio"] == round(1 - 1800 / 3072, 4)
        assert rec["ragged"] == {"dispatches": 0,
                                 "cross_shape_dispatches": 0,
                                 "cross_shape_coalesce_rate": 0.0,
                                 "capacity_fill": 0.0}
        assert rec["occupancy"]["mean"] > \
            rec["occupancy"]["one_per_dispatch_baseline"]
        assert again["step"] == 2

    def test_write_without_path_raises(self):
        from raft_tpu.serving.metrics import ServingMetrics

        with pytest.raises(ValueError, match="no metrics path"):
            ServingMetrics().write_snapshot()

    def test_resilience_counters_schema_and_events(self, tmp_path):
        """ISSUE-7 satellite: quarantined-thread and
        breaker-transition counters ride the same snapshot, and the
        transitions append as supervisor-style events to the same
        metrics.jsonl the dashboard tails."""
        from raft_tpu.serving.metrics import ServingMetrics

        path = str(tmp_path / "metrics.jsonl")
        m = ServingMetrics(path)
        m.record_wedge("3x32x32", failed=2, timeout_s=0.4)
        m.record_quarantined("3x32x32", alive=1)
        m.record_breaker_transition("32x32", "closed", "open")
        m.record_breaker_transition("32x32", "open", "half_open")
        m.record_breaker_transition("32x32", "half_open", "closed")
        m.record_state_change("healthy", "degraded", "breaker open")
        m.record_circuit_rejected(3)
        rec = m.write_snapshot(executables=1)
        res = rec["resilience"]
        assert res["wedged"] == 1
        assert res["quarantined_threads"] == 1
        assert res["circuit_rejected"] == 3
        assert res["breaker_transitions"] == {"open": 1,
                                              "half_open": 1,
                                              "closed": 1}
        assert rec["failed"] == 2      # the wedge failed its futures
        lines = [json.loads(line) for line in open(path)]
        events = [r for r in lines if r.get("kind") == "serving_event"]
        assert [e["event"] for e in events] == [
            "dispatch_wedged", "thread_quarantined", "breaker_open",
            "breaker_half_open", "breaker_closed", "serving_state"]
        for e in events:
            assert "time" in e     # the supervisor event contract
        assert events[0]["bucket"] == "3x32x32"
        assert events[-1] == {**events[-1], "state": "degraded",
                              "previous": "healthy"}
        # events without a path are a no-op, not an error
        ServingMetrics().record_event("x")


class TestSettleFuture:
    """raft_tpu/serving/futures.settle_future — the ONE blessed settle
    idiom (graftthread T2). Every scheduler settle site now routes
    through it; these units pin the contract the accounting identity
    rides on: exactly one counted outcome per future, whoever wins the
    race."""

    def test_result_and_exception_paths(self):
        from concurrent.futures import Future

        from raft_tpu.serving.futures import settle_future

        fut = Future()
        assert settle_future(fut, 41) is True
        assert fut.result(timeout=0) == 41
        fut = Future()
        assert settle_future(fut, RuntimeError("boom")) is True
        assert isinstance(fut.exception(timeout=0), RuntimeError)

    def test_exception_class_vs_instance(self):
        """Only INSTANCES fail the future — an exception CLASS is a
        result like any other object (callers always pass built
        exceptions; a class slipping through would surface at
        .result() as a confusing non-raise)."""
        from concurrent.futures import Future

        from raft_tpu.serving.futures import settle_future

        fut = Future()
        assert settle_future(fut, RuntimeError) is True
        assert fut.result(timeout=0) is RuntimeError

    def test_raced_hook_fires_exactly_on_loss(self):
        from concurrent.futures import Future

        from raft_tpu.serving.futures import settle_future

        calls = []
        fut = Future()
        fut.set_result("winner")
        assert settle_future(fut, "loser",
                             raced=lambda: calls.append(1)) is False
        assert calls == [1]
        assert fut.result(timeout=0) == "winner"   # loser never lands
        fut = Future()
        assert settle_future(fut, "winner",
                             raced=lambda: calls.append(2)) is True
        assert calls == [1]                        # no hook on a win

    def test_cancelled_future_counts_as_raced(self):
        """The _expire-vs-cancel race shape: a caller cancel between
        the sweep's check and the settle must be a counted outcome,
        never an InvalidStateError killing the dispatcher."""
        from concurrent.futures import Future

        from raft_tpu.serving.futures import settle_future

        fut = Future()
        assert fut.cancel()
        raced = []
        assert settle_future(fut, DeadlineExceeded("late"),
                             raced=lambda: raced.append(1)) is False
        assert raced == [1]
