"""Serving front-end acceptance: async micro-batch coalescing,
deadlines, backpressure, drain-on-shutdown, warm-start sessions, and
the metrics surface — the ISSUE-6 ragged-traffic drill plus the
fault-injection matrix for the ``serve.request`` site."""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.scheduler import (BackpressureError, DeadlineExceeded,
                                        MicroBatchScheduler, SchedulerClosed,
                                        ServeResult)
from raft_tpu.serving.session import VideoSession
from raft_tpu.testing import faults

SHAPES = [(32, 32), (40, 40)]
BUCKET_BATCH = 3


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return cfg, variables


@pytest.fixture(scope="module")
def engine(small_setup):
    """One warm-start engine for the whole module: two documented
    buckets, one per drill shape — every test below must leave
    ``len(_compiled)`` at exactly these two."""
    cfg, variables = small_setup
    return RAFTEngine(variables, cfg, iters=1,
                      envelope=[(BUCKET_BATCH, h, w) for h, w in SHAPES],
                      precompile=True, warm_start=True)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


def _pair(rng, h=32, w=32):
    return (rng.rand(h, w, 3).astype(np.float32) * 255,
            rng.rand(h, w, 3).astype(np.float32) * 255)


def _no_leaked_workers(before, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive()
                  and t.name.startswith("MicroBatchScheduler")]
        if not leaked:
            return []
        time.sleep(0.05)
    return leaked


class TestRaggedTrafficDrill:
    def test_acceptance_drill(self, engine, small_setup, tmp_path):
        """The ISSUE-6 acceptance criterion: mixed shapes + ragged
        tails, two concurrent submitters — every non-shed request
        served, executable count pinned at the documented bucket
        count, occupancy strictly above one-request-per-dispatch,
        zero deadline-abandoned in-flight, and a metrics.jsonl
        snapshot carrying the full surface."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_drill

        mpath = str(tmp_path / "metrics.jsonl")
        summary = run_drill(variables, cfg, shapes=SHAPES, requests=14,
                            submitters=2, bucket_batch=BUCKET_BATCH,
                            deadline_s=120.0, gather_window_s=0.05,
                            metrics_path=mpath, engine=engine)
        # all non-shed requests served (14 across both shapes: 7 per
        # shape — ragged against the batch-3 buckets)
        assert summary["shed"] == 0 and summary["errors"] == 0
        assert summary["deadline_missed"] == 0
        assert summary["served"] == summary["submitted"] == 14
        # cross-caller coalescing kept the executable count at the
        # documented bucket count (the H3 invariant, scheduler layer)
        assert summary["executables"] == len(SHAPES)
        assert sorted(engine._compiled) == [
            (BUCKET_BATCH, h, w) for h, w in SHAPES]
        # mean batch occupancy strictly above the one-request-per-
        # dispatch baseline: the batch dim filled with OTHER callers'
        # work, not padding
        assert summary["mean_occupancy"] > summary["baseline_occupancy"]
        assert summary["dispatches"] < summary["served"]
        # no in-flight request was deadline-abandoned
        assert summary["abandoned_inflight"] == 0

        recs = [json.loads(line) for line in open(mpath)]
        rec = recs[-1]
        # the trainer Logger's jsonl contract + the serving surface
        assert rec["step"] >= 1 and rec["kind"] == "serving"
        assert rec["shed"] == 0 and rec["executables"] == len(SHAPES)
        assert rec["queue_depth"]["max"] >= 1
        assert (rec["occupancy"]["mean"]
                > rec["occupancy"]["one_per_dispatch_baseline"])
        used = [b for b in rec["buckets"].values() if b["dispatches"]]
        assert used, "no per-bucket records"
        for b in used:
            for stage in ("queue", "device", "total"):
                assert b[stage]["count"] == b["filled"]
                assert b[stage]["p50_ms"] <= b[stage]["p99_ms"]

    def test_sessions_coalesce_with_oneshot_traffic(self, engine,
                                                    small_setup):
        """Warm-start sessions and one-shot submitters share buckets:
        the drill with sessions on still pins the executable count."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_drill

        summary = run_drill(variables, cfg, shapes=SHAPES, requests=6,
                            submitters=2, bucket_batch=BUCKET_BATCH,
                            sessions=2, session_frames=3,
                            gather_window_s=0.02, engine=engine)
        assert summary["errors"] == 0 and summary["shed"] == 0
        assert summary["session_pairs"] == 2 * 3
        # up to 2 warm pairs per stream; random-weight flows that blow
        # out of the low-res frame (correctly) degrade to cold starts
        assert 1 <= summary["warm_submits"] <= 2 * 2
        assert summary["executables"] == len(SHAPES)
        assert summary["abandoned_inflight"] == 0


class TestSchedulerBasics:
    def test_single_request_matches_engine_direct(self, engine, rng):
        i1, i2 = _pair(rng)
        direct = engine.infer_batch(i1[None], i2[None])[0]
        with MicroBatchScheduler(engine,
                                 gather_window_s=0.0) as sched:
            res = sched.submit(i1, i2).result(timeout=120)
        assert isinstance(res, ServeResult)
        # same executable, batch fill is per-sample neutral (measured
        # ~3e-5 px; tests/test_serving.py ragged-tail test)
        np.testing.assert_allclose(res.flow, direct, atol=1e-3,
                                   rtol=1e-4)
        assert res.flow_low is None  # not requested

    def test_submit_validates_inputs(self, engine, rng):
        i1, i2 = _pair(rng)
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            with pytest.raises(ValueError, match="one \\(H, W, 3\\)"):
                sched.submit(i1[None], i2[None])
            with pytest.raises(ValueError, match="shapes differ"):
                sched.submit(i1, i2[:24])

    def test_drain_on_close_serves_everything(self, engine, rng):
        before = set(threading.enumerate())
        sched = MicroBatchScheduler(engine, gather_window_s=0.01)
        futs = [sched.submit(*_pair(rng)) for _ in range(5)]
        sched.close(drain=True)
        assert all(f.done() for f in futs)
        for f in futs:
            assert f.result().flow.shape == (32, 32, 2)
        with pytest.raises(SchedulerClosed):
            sched.submit(*_pair(rng))
        sched.close()  # idempotent
        assert not _no_leaked_workers(before)

    def test_no_drain_close_fails_pending_loudly(self, engine, rng):
        """A no-drain close must FAIL queued work, not strand it."""
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.4}])
        sched = MicroBatchScheduler(engine, gather_window_s=0.0)
        first = sched.submit(*_pair(rng))   # dispatched, hangs 0.4s
        time.sleep(0.2)
        queued = sched.submit(*_pair(rng))  # still queued behind it
        sched.close(drain=False)
        # the dispatched request still completes — never abandoned
        assert first.result(timeout=120).flow.shape == (32, 32, 2)
        with pytest.raises(SchedulerClosed):
            queued.result(timeout=120)


class TestBackpressureAndDeadlines:
    def test_full_queue_sheds_new_never_inflight(self, engine, rng):
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.8}])
        sched = MicroBatchScheduler(engine, max_queue=2,
                                    gather_window_s=0.0)
        accepted = [sched.submit(*_pair(rng))]
        time.sleep(0.3)  # worker popped it and is hanging in dispatch
        accepted += [sched.submit(*_pair(rng)) for _ in range(2)]
        with pytest.raises(BackpressureError, match="queue full"):
            sched.submit(*_pair(rng))
        sched.close(drain=True)
        # shedding rejected the NEW request only: every accepted one
        # was served
        for f in accepted:
            assert f.result(timeout=0).flow.shape == (32, 32, 2)
        snap = sched.metrics.snapshot()
        assert snap["shed"] == 1
        assert snap["abandoned_inflight"] == 0
        assert snap["completed"] == 3

    def test_queued_deadline_expires_inflight_completes(self, engine,
                                                        rng):
        """A deadline is enforced while QUEUED only: the dispatched
        request outliving its deadline mid-device still completes;
        the one expiring behind it fails fast."""
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.6}])
        sched = MicroBatchScheduler(engine, gather_window_s=0.0)
        first = sched.submit(*_pair(rng), deadline_s=0.2)
        time.sleep(0.25)  # first is mid-hang, its deadline now past
        late = sched.submit(*_pair(rng), deadline_s=0.1)
        assert first.result(timeout=120).flow.shape == (32, 32, 2)
        with pytest.raises(DeadlineExceeded, match="never dispatched"):
            late.result(timeout=120)
        sched.close()
        snap = sched.metrics.snapshot()
        assert snap["deadline_missed"] == 1
        assert snap["abandoned_inflight"] == 0


class _UnservableShapeEngine:
    """Duck-typed engine stub: capacity probes raise for one poisoned
    spatial shape (what a mesh-invalid extent or compile failure looks
    like), everything else serves a trivial flow — fast and
    deterministic for the dispatcher-survival test."""

    warm_start = False

    def __init__(self):
        self._compiled = {(2, 32, 32): object()}

    def bucket_capacity(self, h, w):
        if (h, w) == (24, 24):
            raise RuntimeError("unservable shape (mesh extent)")
        return 2

    def route_bucket(self, b, h, w):
        return (2, -(-h // 8) * 8, -(-w // 8) * 8)

    def infer_batch(self, i1, i2):
        return np.zeros(i1.shape[:3] + (2,), np.float32)


class TestDispatcherResilience:
    def test_unservable_shape_fails_its_requests_not_the_worker(self,
                                                                rng):
        """A shape whose capacity probe raises (mesh-invalid extent,
        compile failure) must fail THOSE futures — not kill the
        dispatcher thread and strand every queued request behind a
        dead worker."""
        sched = MicroBatchScheduler(_UnservableShapeEngine(),
                                    gather_window_s=0.0)
        bad = sched.submit(rng.rand(24, 24, 3).astype(np.float32),
                           rng.rand(24, 24, 3).astype(np.float32))
        with pytest.raises(RuntimeError, match="unservable shape"):
            bad.result(timeout=30)
        # the worker survived and keeps serving other shapes
        ok = sched.submit(*_pair(rng))
        assert ok.result(timeout=30).flow.shape == (32, 32, 2)
        sched.close()
        assert sched.metrics.snapshot()["failed"] == 1

    def test_malformed_flow_init_fails_at_submit(self, engine, rng):
        """A wrong-shape warm start is rejected at submit — at dispatch
        the row assignment would fail (or, if broadcastable, silently
        corrupt) the whole coalesced micro-batch, other callers
        included."""
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            with pytest.raises(ValueError, match="flow_init shape"):
                sched.submit(*_pair(rng),
                             flow_init=np.zeros((2,), np.float32))
            with pytest.raises(ValueError, match="flow_init shape"):
                # broadcastable-but-wrong must also be rejected
                sched.submit(*_pair(rng),
                             flow_init=np.zeros((1, 1, 2), np.float32))
            ok = sched.submit(
                *_pair(rng), flow_init=np.zeros((4, 4, 2), np.float32))
            assert ok.result(timeout=120).flow.shape == (32, 32, 2)


class TestServeRequestFaults:
    def test_raise_fails_batch_not_worker(self, engine, rng):
        faults.arm([{"site": "serve.request", "kind": "raise"}])
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            bad = sched.submit(*_pair(rng))
            with pytest.raises(faults.FaultInjected):
                bad.result(timeout=120)
            # the worker survived the injected failure
            ok = sched.submit(*_pair(rng))
            assert ok.result(timeout=120).flow.shape == (32, 32, 2)
            snap = sched.metrics.snapshot()
        assert snap["failed"] == 1 and snap["completed"] == 1

    def test_hang_drill_no_leaked_threads(self, engine, rng):
        """The satellite drill: a hung dispatch backs traffic up into
        shed + deadline misses, and shutdown still drains clean with
        no leaked worker threads (the PR-3 loader-semaphore lesson)."""
        before = set(threading.enumerate())
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.7}])
        sched = MicroBatchScheduler(engine, max_queue=2,
                                    gather_window_s=0.0)
        futs = [sched.submit(*_pair(rng))]
        time.sleep(0.2)  # the worker is now wedged mid-dispatch
        futs.append(sched.submit(*_pair(rng), deadline_s=0.1))
        futs.append(sched.submit(*_pair(rng)))
        shed = 0
        try:
            sched.submit(*_pair(rng))
        except BackpressureError:
            shed = 1
        sched.close(drain=True)
        outcomes = {"served": 0, "missed": 0}
        for f in futs:
            try:
                f.result(timeout=0)
                outcomes["served"] += 1
            except DeadlineExceeded:
                outcomes["missed"] += 1
        snap = sched.metrics.snapshot()
        assert shed == 1 and snap["shed"] == 1
        assert outcomes["missed"] == snap["deadline_missed"] == 1
        assert outcomes["served"] == snap["completed"] == 2
        assert snap["abandoned_inflight"] == 0
        leaked = _no_leaked_workers(before)
        assert not leaked, f"leaked scheduler threads: {leaked}"


class TestVideoSessions:
    def test_warm_start_recurrence(self, engine, rng):
        frames = [rng.rand(32, 32, 3).astype(np.float32) * 255
                  for _ in range(4)]
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            sess = VideoSession(sched)
            futs = [sess.submit_frame(f) for f in frames]
            assert futs[0] is None and all(f is not None
                                           for f in futs[1:])
            results = [f.result(timeout=120) for f in futs[1:]]
            assert sess.drain() is not None
        assert all(r.flow.shape == (32, 32, 2) for r in results)
        # a warm-start session asks for flow_low back on every pair
        assert all(r.flow_low is not None for r in results)
        assert all(r.flow_low.shape == (4, 4, 2) for r in results)
        # pairs 2 and 3 can warm-start from the previous pair's flow;
        # >= 1, not == 2: random-init weights produce flows that can
        # blow out of the 4x4 low-res frame, and the session then
        # (correctly) degrades that pair to a cold start
        assert 1 <= sess.warm_submits <= 2

    def test_flow_init_moves_the_refinement_start(self, engine, rng):
        """The warm-start mechanism itself, deterministically: the same
        pair with a nonzero flow_init differs from the cold dispatch
        (same weights, same executable — the flow_init row is the only
        difference)."""
        i1, i2 = _pair(rng)
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            cold = sched.submit(i1, i2).result(timeout=120).flow
            warm = sched.submit(
                i1, i2,
                flow_init=np.full((4, 4, 2), 0.5, np.float32)).result(
                timeout=120).flow
        assert not np.array_equal(cold, warm)
        assert np.isfinite(warm).all()

    def test_shape_change_restarts_stream(self, engine, rng):
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            sess = VideoSession(sched)
            assert sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32)) is None
            f1 = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32))
            assert f1.result(timeout=120).flow.shape == (32, 32, 2)
            # resolution change: the pair is meaningless — restart
            assert sess.submit_frame(
                rng.rand(40, 40, 3).astype(np.float32)) is None
            f2 = sess.submit_frame(
                rng.rand(40, 40, 3).astype(np.float32))
            # first pair of the restarted stream is a cold start
            assert sess.warm_submits == 0
            assert f2.result(timeout=120).flow.shape == (40, 40, 2)

    def test_blown_out_warm_start_cold_restarts(self, engine, rng):
        """Found by the verification drive: when the previous pair's
        flow is larger than the frame, every forward-warped point
        lands outside it — griddata has an empty scatter and returns
        NaN ('nearest' ignores fill_value), which would poison the
        stream. The session must cold-restart instead."""
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            sess = VideoSession(sched)
            sess.submit_frame(rng.rand(32, 32, 3).astype(np.float32))
            # the degenerate state: all motion out of the 4x4 low-res
            # frame (what random weights / a garbage pair produce)
            sess._flow_low = np.full((4, 4, 2), 99.0, np.float32)
            fut = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32))
            res = fut.result(timeout=120)
            assert np.isfinite(res.flow).all()
            assert sess.warm_submits == 0  # degraded to a cold start
        # the scheduler rejects a caller's non-finite warm start with a
        # cause instead of returning NaN flow from the device
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            bad = np.full((4, 4, 2), np.nan, np.float32)
            with pytest.raises(ValueError, match="non-finite"):
                sched.submit(*_pair(rng), flow_init=bad)

    def test_failed_pair_cold_restarts_not_poisons(self, engine, rng):
        """A deadline-missed pair surfaces on ITS future; the session
        cold-restarts the recurrence instead of dying on harvest."""
        faults.arm([{"site": "serve.request", "kind": "hang",
                     "hang_s": 0.5}])
        with MicroBatchScheduler(engine, gather_window_s=0.0) as sched:
            blocker = sched.submit(*_pair(rng))  # wedges the worker
            time.sleep(0.2)
            sess = VideoSession(sched)
            sess.submit_frame(rng.rand(32, 32, 3).astype(np.float32))
            doomed = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32), deadline_s=0.05)
            ok = sess.submit_frame(
                rng.rand(32, 32, 3).astype(np.float32))
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=120)
            assert ok.result(timeout=120).flow.shape == (32, 32, 2)
            assert sess.warm_submits == 0  # cold restart, not stale warm
            blocker.result(timeout=120)


class TestServingMetricsUnit:
    def test_histogram_ladder_and_percentiles(self):
        from raft_tpu.serving.metrics import LatencyHistogram

        h = LatencyHistogram()
        for v in (0.05, 1.0, 3.0, 40.0, 70000.0):
            h.observe(v)
        s = h.snapshot()
        assert s["count"] == 5
        assert s["max_ms"] == 70000.0
        assert s["p50_ms"] <= s["p99_ms"] <= s["max_ms"]
        assert sum(s["counts"]) == 5
        assert h.quantile(0.0) > 0

    def test_snapshot_shape_and_jsonl_append(self, tmp_path):
        from raft_tpu.serving.metrics import ServingMetrics

        path = str(tmp_path / "m" / "metrics.jsonl")
        m = ServingMetrics(path)
        m.record_submit(depth=1)
        m.record_submit(depth=2)
        m.record_dispatch("3x32x32", filled=2, capacity=3, depth=0)
        m.record_complete("3x32x32", queue_ms=1.0, device_ms=2.0)
        m.record_complete("3x32x32", queue_ms=4.0, device_ms=2.0)
        m.record_shed()
        rec = m.write_snapshot(executables=1)
        again = m.write_snapshot(executables=1)
        lines = [json.loads(line) for line in open(path)]
        assert [r["step"] for r in lines] == [1, 2]
        assert rec["submitted"] == 2 and rec["shed"] == 1
        assert rec["queue_depth"]["max"] == 2
        b = rec["buckets"]["3x32x32"]
        assert b["occupancy"] == round(2 / 3, 4)
        assert b["total"]["count"] == 2
        assert rec["occupancy"]["mean"] > \
            rec["occupancy"]["one_per_dispatch_baseline"]
        assert again["step"] == 2

    def test_write_without_path_raises(self):
        from raft_tpu.serving.metrics import ServingMetrics

        with pytest.raises(ValueError, match="no metrics path"):
            ServingMetrics().write_snapshot()
