"""The negative fixture: one well-behaved program per audit dimension.

bf16 discipline holds (the weight is cast at the site), the donated
state threads back out (aliasable), weights ride as arguments, no
callbacks, and the canary's sweep lands on its documented bucket
count — every H-rule must stay silent here."""

import jax
import jax.numpy as jnp

from tools.graftaudit import CanaryResult, Target


def _build_step():
    def step(state, x, w):
        y = jnp.dot(x, w.astype(jnp.bfloat16))   # cast AT the site
        return state + y.astype(jnp.float32).sum(), y

    return step, (jnp.zeros((), jnp.float32),
                  jnp.ones((8, 8), jnp.bfloat16),
                  jnp.ones((8, 8), jnp.float32))


def _build_canary():
    jf = jax.jit(lambda x: x * 2.0)
    for _ in range(3):             # same shape: one executable
        jf(jnp.ones((8,), jnp.float32))
    return CanaryResult(observed_compiles=jf._cache_size(),
                        detail="same-shape calls x3")


TARGETS = [
    Target(name="clean_step", build=_build_step, donate_argnums=(0,),
           compute_dtype="bfloat16"),
    Target(name="clean_canary", kind="canary", build=_build_canary,
           expect_compiles=1),
]

BUDGETS = {
    "targets": {
        "clean_step": [
            # generous: the point is that a budget EXISTS and holds
            {"band": "whole-step", "match": "", "max_bytes": 10 ** 9},
        ],
    },
}
