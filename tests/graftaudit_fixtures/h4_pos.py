"""H4 planted violation: a donated buffer no output can reuse.

The state arg is donated but the step returns only a scalar — the
donation is declared in source (graftlint's R4 is satisfied!) yet XLA
has nothing to alias it to, so the buffer is silently copied/dropped."""

import warnings

import jax.numpy as jnp

from tools.graftaudit import Target


def _build():
    def step(state, x):
        return (x * 2.0).sum()   # state never threads back out

    # jax itself warns about the unusable donation at lower time —
    # that warning IS the planted condition, not test noise
    warnings.filterwarnings(
        "ignore", message=".*donated.*", category=UserWarning)
    return step, (jnp.ones((64,), jnp.float32),
                  jnp.ones((8,), jnp.float32))


TARGETS = [Target(name="h4_fixture", build=_build, donate_argnums=(0,))]
