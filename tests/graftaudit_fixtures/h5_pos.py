"""H5 planted violation: the fixture's own budgets file allows 1 KiB
for the whole module; the program moves far more."""

import jax.numpy as jnp

from tools.graftaudit import Target


def _build():
    def step(x):
        y = jnp.tanh(x) + 1.0
        return (y @ y.T).sum()

    return step, (jnp.ones((64, 64), jnp.float32),)


TARGETS = [Target(name="h5_fixture", build=_build)]

BUDGETS = {
    "targets": {
        "h5_fixture": [
            {"band": "whole-step", "match": "", "max_bytes": 1024},
        ],
    },
}
