"""H3 planted violation: a shape sweep compiles per request while the
documentation promises one bucket."""

import jax
import jax.numpy as jnp

from tools.graftaudit import CanaryResult, Target


def _build():
    jf = jax.jit(lambda x: x * 2.0)
    for n in (4, 8, 16):       # no bucketing: every shape recompiles
        jf(jnp.ones((n,), jnp.float32))
    return CanaryResult(
        observed_compiles=jf._cache_size(),
        detail="unbucketed 1-d sweep over lengths 4/8/16")


TARGETS = [Target(name="h3_fixture", kind="canary", build=_build,
                  expect_compiles=1)]
