"""H6 planted violation: weights captured by closure, baked into the
executable as a multi-MB literal instead of riding as an argument."""

import jax
import jax.numpy as jnp

from tools.graftaudit import Target

# real-looking weights: a splat (all-ones) would be rewritten to
# broadcast(constant(1)) and dodge the trap this fixture plants
_WEIGHTS = jax.random.normal(jax.random.PRNGKey(0), (512, 1024),
                             jnp.float32)         # 2 MiB literal


def _build():
    def step(x):
        return (x @ _WEIGHTS).sum()

    return step, (jnp.ones((4, 512), jnp.float32),)


TARGETS = [Target(name="h6_fixture", build=_build)]
