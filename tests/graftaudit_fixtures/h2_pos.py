"""H2 planted violation: promotion widens a bf16 dot to f32.

The weight was left f32 (a forgotten cast); jax's promotion silently
runs the hot dot in f32 — invisible in source, visible in the jaxpr."""

import jax.numpy as jnp

from tools.graftaudit import Target


def _build():
    def step(x, w):
        return jnp.dot(x, w).sum()

    return step, (jnp.ones((8, 8), jnp.bfloat16),
                  jnp.ones((8, 8), jnp.float32))


TARGETS = [Target(name="h2_fixture", build=_build,
                  compute_dtype="bfloat16", compiled=False)]
