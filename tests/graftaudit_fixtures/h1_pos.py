"""H1 planted violation: a debug callback traced into the step.

The AST linter can't see this when the print hides inside a helper —
the artifact tier catches the `debug_callback` eqn (and, compiled, the
host custom-call)."""

import jax
import jax.numpy as jnp

from tools.graftaudit import Target


def _noisy_helper(x):
    jax.debug.print("step norm {n}", n=jnp.linalg.norm(x))
    return x * 2.0


def _build():
    def step(x):
        return _noisy_helper(x).sum()

    return step, (jnp.ones((8, 8), jnp.float32),)


TARGETS = [Target(name="h1_fixture", build=_build)]
