"""Replica fleet acceptance (ISSUE 17): the placement layer's
decisions (replicate-vs-shard, capacity fit, scaling policy), the
fleet dispatcher's fan-out (concurrency > 1, least-loaded balance,
queue-depth scale-up / idle retirement), the per-replica failure
domain (one wedged replica quarantines alone while its siblings keep
serving — zero stranded futures, accounting identity intact), the
fleet-atomic weight swap (the ``scheduler.swap`` chaos site must
never leave a half-rolled fleet), the construction-time
``ConfigError`` contracts, and the real-engine pin: replicas 2..N
warm from the shared AOT artifact store with ZERO extra XLA compiles
and bitwise-identical flow vs the single-engine oracle."""

import threading
import time

import numpy as np
import pytest

from tests.test_scheduler import _FakeEngine, _pad8, _wait_for

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.parallel.placement import SHARD_PX_THRESHOLD, Placement
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.resilience import DispatchWedged
from raft_tpu.serving.scheduler import ConfigError, MicroBatchScheduler
from raft_tpu.testing import faults
from raft_tpu.testing.faults import FaultInjected


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


def _pair(rng, h=32, w=32):
    return (rng.rand(h, w, 3).astype(np.float32) * 255,
            rng.rand(h, w, 3).astype(np.float32) * 255)


class _FleetEngine(_FakeEngine):
    """The scheduler-facing fake, fleet-capable: ``spawn_replica``
    mirrors the compiled-key table (the real engine's placeholder
    contract) and ``update_weights`` records the tree for the
    swap-epoch drills."""

    def __init__(self, infer_delay_s=0.0, fetch_delay_s=0.0):
        super().__init__(infer_delay_s, fetch_delay_s)
        self.variables = {"gen": 0}
        self.spawned = 0

    def spawn_replica(self):
        rep = _FleetEngine(self.infer_delay_s, self.fetch_delay_s)
        rep._compiled = dict.fromkeys(self._compiled)
        rep.variables = self.variables
        self.spawned += 1
        return rep

    def update_weights(self, variables):
        self.variables = variables


# -- the placement layer ---------------------------------------------------


class TestPlacement:
    def test_replicate_is_default_without_mesh(self):
        p = Placement(_FleetEngine(), replicas=2)
        assert p.decide((32, 32)) == "replicate"
        assert p.decide((2160, 3840)) == "replicate"   # no partitioner

    def test_shard_for_4k_class_on_mesh_armed_primary(self):
        eng = _FleetEngine()
        eng.partitioner = object()
        p = Placement(eng, replicas=2)
        assert p.decide((32, 32)) == "replicate"
        assert p.decide((2160, 3840)) == "shard"
        assert 2160 * 3840 >= SHARD_PX_THRESHOLD

    def test_spawn_builds_floor_and_assigns_devices(self):
        eng = _FleetEngine()
        eng.ensure_bucket(2, 32, 32)
        p = Placement(eng, replicas=3)
        assert len(p.engines) == 3 and p.engines[0] is eng
        assert eng.spawned == 2
        # replicas mirror the primary's bucket keys (routing parity)
        for rep in p.engines[1:]:
            assert set(rep._compiled) == set(eng._compiled)
        snap = p.snapshot()
        assert snap["replicas"] == 3 and snap["floor"] == 3
        assert sorted(snap["assignments"]) == ["r0", "r1", "r2"]

    def test_grow_stops_at_ceiling(self):
        p = Placement(_FleetEngine(), replicas=1, ceiling=2)
        p.grow()
        assert len(p.engines) == 2
        with pytest.raises(ValueError, match="ceiling"):
            p.grow()

    def test_engines_list_validation(self):
        eng = _FleetEngine()
        with pytest.raises(ValueError, match="primary first"):
            Placement(eng, replicas=2, engines=[_FleetEngine(),
                                                _FleetEngine()])
        with pytest.raises(ValueError, match="entries"):
            Placement(eng, replicas=2, engines=[eng])

    def test_spawnless_engine_needs_explicit_list(self):
        class Duck:
            pass
        with pytest.raises(ValueError, match="spawn_replica"):
            Placement(Duck(), replicas=2)

    def test_scaling_policy(self):
        p = Placement(_FleetEngine(), replicas=1, ceiling=3)
        assert p.want_scale_up(queue_depth=9, active=1, max_batch=4)
        assert not p.want_scale_up(queue_depth=3, active=1, max_batch=4)
        assert not p.want_scale_up(queue_depth=99, active=3, max_batch=4)
        assert not p.want_retire(idle_s=99.0, active=1,
                                 idle_retire_s=1.0)     # at the floor
        p2 = Placement(_FleetEngine(), replicas=1, ceiling=3)
        p2.grow()
        assert p2.want_retire(idle_s=2.0, active=2, idle_retire_s=1.0)
        assert not p2.want_retire(idle_s=0.5, active=2,
                                  idle_retire_s=1.0)

    def test_bucket_fit_matches_single_engine_path(self):
        eng = _FleetEngine()
        # cold: warms one bucket at max_batch, exactly _shape_capacity
        assert Placement.bucket_fit(eng, (32, 32), 4) == 4
        assert eng.compile_calls == 1
        # warm: probes, no second compile
        assert Placement.bucket_fit(eng, (32, 32), 4) == 4
        assert eng.compile_calls == 1


# -- the fleet dispatcher (fake engines: deterministic timing) -------------


class TestFleetServing:
    def _sched(self, eng, **kw):
        kw.setdefault("gather_window_s", 0.0)
        kw.setdefault("max_batch", 2)
        return MicroBatchScheduler(eng, **kw)

    def test_fanout_concurrency_and_balance(self, rng):
        """The tentpole gauge: mixed-shape traffic over 4 replicas
        shows dispatch concurrency > 1 and per-replica load within 2x
        of each other (least-loaded pick)."""
        eng = _FleetEngine(infer_delay_s=0.02)
        sched = self._sched(eng, replicas=4, dispatch_timeout_s=10.0)
        try:
            futs = [sched.submit(*_pair(rng, *s))
                    for s in [(32, 32), (40, 40)] * 20]
            for f in futs:
                assert f.result(timeout=60).flow.shape[-1] == 2
            h = sched.health()
            fleet = h["fleet"]
            assert fleet["replicas"] == 4 and fleet["active"] == 4
            assert fleet["concurrency_max"] > 1
            loads = [blk["dispatches"]
                     for blk in fleet["lanes"].values()]
            assert min(loads) >= 1
            assert max(loads) <= 2 * min(loads), loads
            snap = sched.metrics.snapshot()
            assert snap["submitted"] == 40 and snap["completed"] == 40
            # per-replica metrics blocks rode into the snapshot
            reps = snap["replicas"]
            assert len(reps) == 4
            assert sum(b["completed"] for b in reps.values()) == 40
            occ = [b["occupancy"] for b in reps.values()]
            assert min(occ) > 0
            assert max(occ) <= 2 * min(occ), occ
        finally:
            sched.close()

    def test_queue_pressure_grows_then_idle_retires(self, rng):
        """replicas=1 with a ceiling: sustained queue depth activates
        replicas up to the ceiling; idleness retires them back to the
        floor (never the primary)."""
        eng = _FleetEngine(infer_delay_s=0.05)
        sched = self._sched(eng, replicas=1, replica_ceiling=3,
                            max_batch=1, max_queue=64,
                            replica_idle_retire_s=0.15)
        try:
            futs = [sched.submit(*_pair(rng)) for _ in range(30)]
            for f in futs:
                f.result(timeout=60)
            h = sched.health()
            assert h["fleet"]["replicas"] > 1          # grew
            assert h["fleet"]["concurrency_max"] > 1   # and fanned out
            assert _wait_for(
                lambda: sched.health()["fleet"]["active"] == 1,
                timeout=10.0), sched.health()["fleet"]
        finally:
            sched.close()

    def test_wedged_replica_quarantines_alone_rest_serve(self, rng):
        """The chaos round: one replica's dispatch hangs past the
        watchdog. ONLY that lane is quarantined; its siblings keep
        serving the queue; every future settles (zero stranded) and
        the accounting identity holds."""
        eng = _FleetEngine()
        sched = self._sched(eng, replicas=3, max_batch=1,
                            dispatch_timeout_s=0.3,
                            breaker_failures=1)
        try:
            victim = sched._lanes[1].engine
            orig = victim.infer_batch_async
            armed = {"on": True}

            def hang(i1, i2, **kw):
                if armed.pop("on", None):
                    time.sleep(3.0)
                return orig(i1, i2, **kw)

            victim.infer_batch_async = hang
            futs = [sched.submit(*_pair(rng)) for _ in range(20)]
            outs = [f.exception(timeout=60) for f in futs]  # all settle
            failed = [e for e in outs if e is not None]
            assert len(failed) >= 1
            assert all(isinstance(e, DispatchWedged) for e in failed)
            h = sched.health()
            quarantined = [k for k, blk in h["fleet"]["lanes"].items()
                           if blk["quarantined"]]
            assert quarantined == ["r1"]
            assert h["state"] == "degraded"
            snap = sched.metrics.snapshot()
            assert snap["completed"] == 20 - len(failed)
            assert snap["submitted"] == (
                snap["completed"] + snap["failed"]
                + snap["deadline_missed"] + snap["cancelled"])
            assert snap["resilience"]["wedged"] == 1
        finally:
            sched.close()

    def test_swap_weights_is_fleet_atomic_under_fault(self, rng):
        """The ``scheduler.swap`` chaos site at lane 2: the epoch
        aborts, the already-swapped lane rolls BACK, and every engine
        still serves the old tree — never a mixed fleet. Disarmed, the
        same swap lands everywhere."""
        eng = _FleetEngine()
        sched = self._sched(eng, replicas=3)
        try:
            old = [lane.engine.variables for lane in sched._lanes]
            assert len(set(map(id, old))) >= 1
            new = {"gen": 1}
            faults.arm([{"site": "scheduler.swap", "at": 2,
                         "kind": "raise"}])
            with pytest.raises(FaultInjected):
                sched.swap_weights(new)
            for lane, before in zip(sched._lanes, old):
                assert lane.engine.variables is before
            faults.disarm()
            sched.swap_weights(new)
            assert all(lane.engine.variables is new
                       for lane in sched._lanes)
            # the epoch left the fleet serviceable
            assert sched.submit(*_pair(rng)).result(timeout=60)
        finally:
            sched.close()

    def test_feature_cache_with_replicas_raises_config_error(self):
        eng = _FleetEngine()
        eng.feature_cache = True
        with pytest.raises(ConfigError, match="replica"):
            MicroBatchScheduler(eng, replicas=2, feature_cache=True)

    def test_pipeline_depth_with_replicas_raises_config_error(self):
        with pytest.raises(ConfigError, match="pipeline_depth"):
            MicroBatchScheduler(_FleetEngine(), replicas=2,
                                pipeline_depth=2)

    def test_replicas_one_builds_no_fleet(self, rng):
        """The migration pin: ``replicas=1`` (the default) constructs
        NO placement and NO lanes — the single-engine path, bitwise
        PR-16."""
        sched = self._sched(_FleetEngine())
        try:
            assert sched.placement is None and sched._lanes == []
            assert "fleet" not in sched.health()
            assert sched.submit(*_pair(rng)).result(timeout=60)
            assert "replicas" not in sched.metrics.snapshot()
        finally:
            sched.close()

    def test_close_stops_every_lane_worker(self, rng):
        before = set(threading.enumerate())
        eng = _FleetEngine()
        sched = self._sched(eng, replicas=3)
        futs = [sched.submit(*_pair(rng)) for _ in range(6)]
        for f in futs:
            f.result(timeout=60)
        sched.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            leaked = [t for t in threading.enumerate()
                      if t not in before and t.is_alive()
                      and t.name.startswith("MicroBatchScheduler")]
            if not leaked:
                break
            time.sleep(0.05)
        assert not leaked, leaked


# -- the real engine: AOT-warmed replicas, bitwise oracle ------------------


class TestFleetRealEngine:
    def test_replicas_warm_zero_compiles_bitwise_oracle(self, tmp_path):
        """Replicas 2..3 spin up against the primary's artifact store:
        ZERO XLA compiles each (AOT counters, never timing), and every
        replica's flow at bucket-batch-1 integer inputs is BITWISE the
        single-engine oracle."""
        cfg = RAFTConfig(small=True)
        model = RAFT(cfg)
        img = jnp.zeros((1, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
        rng = np.random.RandomState(3)
        i1 = (rng.rand(32, 32, 3) * 255).round().astype(np.float32)
        i2 = (rng.rand(32, 32, 3) * 255).round().astype(np.float32)

        primary = RAFTEngine(variables, cfg, iters=1,
                             envelope=[(1, 32, 32)], precompile=True,
                             aot_cache=str(tmp_path / "artifacts"))
        oracle = np.asarray(primary.infer_batch(i1[None], i2[None]))[0]

        sched = MicroBatchScheduler(primary, replicas=3, max_batch=1,
                                    gather_window_s=0.0)
        try:
            futs = [sched.submit(i1, i2) for _ in range(9)]
            for f in futs:
                flow = np.asarray(f.result(timeout=600).flow)
                assert np.array_equal(flow, oracle)   # bitwise
            lanes = sched.health()["fleet"]["lanes"]
            assert all(blk["dispatches"] >= 1
                       for blk in lanes.values()), lanes
            for lane in sched._lanes[1:]:
                s = lane.engine.aot_stats()
                assert s["compiles"] == 0, s
                assert s["aot_hits"] >= 1, s
            assert primary.aot_stats()["compiles"] == 1
        finally:
            sched.close()


class TestServeBenchFleet:
    def test_run_drill_grows_fleet_block(self):
        """serve_bench's drill at --replicas 2: the summary grows the
        per-replica ``fleet`` block (dispatches / occupancy / breaker
        state / queue depth per lane); the same drill at the default
        replicas=1 stays byte-identical — no ``fleet`` key at all."""
        cfg = RAFTConfig(small=True)
        model = RAFT(cfg)
        img = jnp.zeros((1, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
        engine = RAFTEngine(variables, cfg, iters=1,
                            envelope=[(1, 32, 32)], precompile=True)
        from raft_tpu.cli.serve_bench import run_drill

        s = run_drill(variables, cfg, shapes=[(32, 32)], requests=8,
                      submitters=2, bucket_batch=1,
                      gather_window_s=0.0, engine=engine, replicas=2)
        assert s["served"] == 8 and s["accounting_ok"]
        fleet = s["fleet"]
        assert fleet["replicas"] == 2 == fleet["active"]
        assert sorted(fleet["lanes"]) == ["r0", "r1"]
        for blk in fleet["lanes"].values():
            assert {"active", "quarantined", "dispatches", "completed",
                    "occupancy", "queue_depth_last",
                    "open_breakers"} <= set(blk)
            assert blk["open_breakers"] == 0
        assert sum(b["completed"]
                   for b in fleet["lanes"].values()) == 8
        # the single-engine drill on the SAME engine: no fleet key
        s1 = run_drill(variables, cfg, shapes=[(32, 32)], requests=4,
                       submitters=1, bucket_batch=1,
                       gather_window_s=0.0, engine=engine)
        assert "fleet" not in s1
