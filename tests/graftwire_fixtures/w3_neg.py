"""W3 negative: a declared wire lock IS the per-connection
serialization contract (holding it across the I/O is the design), and
ordinary locks release before the RPC leaves."""

import threading

GRAFTWIRE = {
    "idempotent": ("ping",),
    "wire_locks": ("_lock",),
    "framed_helpers": ("_send_msg",),
}


def _send_msg(sock, data):
    sock.sendall(data)


class Transport:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._sock = sock

    def send(self, data):
        with self._lock:                 # serialization IS the contract
            _send_msg(self._sock, data)


class Fleet:
    def __init__(self, transport):
        self._state_lock = threading.Lock()
        self._transport = transport
        self._alive = True

    def beat(self):
        with self._state_lock:
            alive = self._alive
        if alive:
            self._transport.call("ping")   # lock released first
