"""W2 positive: a mutating remote call, neither declared idempotent
nor carrying a request_id — a retry double-applies it."""


class CounterClient:
    def __init__(self, transport):
        self._t = transport

    def bump(self, n):
        return self._t.call("increment", {"by": n})
