"""W4 positive: a host-verdict function settles caller-visible futures
BEFORE any declared consequence — a woken caller can re-submit into
the dead lane."""

GRAFTWIRE = {
    "verdicts": ("wedge_host",),
    "consequences": ("quarantine", "poison"),
}


class Sched:
    def wedge_host(self, name, requests):
        for r in requests:
            r.future.set_result(None)     # settle FIRST: the bug
        self.quarantine(name)
        self.poison(name)

    def quarantine(self, name):
        pass

    def poison(self, name):
        pass
