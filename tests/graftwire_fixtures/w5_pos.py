"""W5 positive: reconnect loops paced by hand-rolled constant sleeps —
the unbounded hammer."""

import time


def reconnect(transport):
    while True:
        try:
            transport.reopen()
            return
        except ConnectionError:
            time.sleep(0.5)               # constant-rate hammer


def poll_until_up(transport):
    for _ in range(100):
        try:
            transport.call("ping")
            return True
        except OSError:
            time.sleep(1.0)               # same hammer, for-loop form
    return False
