"""W4 negative: consequences strictly before the first settle — the
PR-18 `_wedge_host` ordering."""

GRAFTWIRE = {
    "verdicts": ("wedge_host",),
    "consequences": ("quarantine", "poison"),
    "settles": ("fail_requests",),
}


class Sched:
    def wedge_host(self, name, requests):
        self.quarantine(name)
        self.poison(name)
        self.fail_requests(requests)      # settle LAST: the contract

    def quarantine(self, name):
        pass

    def poison(self, name):
        pass

    def fail_requests(self, requests):
        pass
