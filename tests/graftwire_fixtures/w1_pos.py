"""W1 positive: method-table drift in both directions — a client call
with no handler, and a handler with no caller."""


class Worker:
    def handle(self, method, payload):
        return getattr(self, "_m_" + method)(payload)

    def _m_ping(self, payload):
        return True

    def _m_orphan(self, payload):     # registered, never called
        return None


class Client:
    def __init__(self, transport):
        self._t = transport

    def ping(self):
        return self._t.call("ping")

    def frobnicate(self):
        return self._t.call("frobnicate")   # no _m_frobnicate anywhere
