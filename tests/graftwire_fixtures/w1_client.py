"""Cross-file drift, client half: calls "ping" (handled by
w1_server.py) and "route" (NOT handled there — the drift only a union
pass over both files can see). Idempotency declarations live on the
server module, so this file linted ALONE also fires W2 — and goes
silent in the union."""


class FleetClient:
    def __init__(self, transport):
        self._t = transport

    def beat(self):
        return self._t.call("ping")

    def route(self, n, h, w):
        return self._t.call("route", {"n": n, "h": h, "w": w})
