"""W3 positive: wire round-trips lexically inside held scheduler-ish
locks — every contending thread wedges for the full RTT."""

import threading

GRAFTWIRE = {
    "idempotent": ("ping", "stats"),
}


class Fleet:
    def __init__(self, transport):
        self._lock = threading.Lock()
        self._transport = transport

    def beat(self):
        with self._lock:
            return self._transport.call("ping")     # RPC under lock


class Pusher:
    def __init__(self, sock):
        self._reg_lock = threading.Lock()
        self._sock = sock

    def push(self, data):
        with self._reg_lock:
            self._sock.sendall(data)                # socket I/O under lock

    def reap(self, proc):
        with self._reg_lock:
            return proc.wait()                      # subprocess wait under lock
