"""Cross-file drift, server half: handles "ping" (called by
w1_client.py) and "drop" (called by NOBODY — dead protocol surface
only the union pass can see)."""

GRAFTWIRE = {
    "idempotent": ("ping", "route", "drop"),
}


class FleetWorker:
    def handle(self, method, payload):
        return getattr(self, "_m_" + method)(payload)

    def _m_ping(self, payload):
        return True

    def _m_drop(self, payload):
        return None
