"""W1 negative: every call has a handler, every handler a caller."""

GRAFTWIRE = {
    "idempotent": ("ping", "stats"),
}


class Worker:
    def handle(self, method, payload):
        return getattr(self, "_m_" + method)(payload)

    def _m_ping(self, payload):
        return True

    def _m_stats(self, payload):
        return {}


class Client:
    def __init__(self, transport):
        self._t = transport

    def ping(self):
        return self._t.call("ping")

    def stats(self):
        return self._t.call("stats")
