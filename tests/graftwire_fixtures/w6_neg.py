"""W6 negative: declared events (exact and constant-prefix), registered
wire methods, and raw socket verbs only inside blessed framed
helpers."""

import struct

GRAFTWIRE = {
    "idempotent": ("ping", "stats"),
    "framed_helpers": ("_send_msg", "_recv_exact"),
    "event_emitters": ("_emit",),
}

_LEN = struct.Struct(">Q")


def _send_msg(sock, data):
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        buf.extend(chunk)
    return bytes(buf)


class Lane:
    def __init__(self, metrics):
        self._metrics = metrics

    def _emit(self, kind, **fields):
        self._metrics.record_event(kind, **fields)

    def beat(self, transport, state):
        transport.call("ping")
        transport.call("stats")
        self._emit("host_suspect", host="h0", missed=1)
        self._emit("breaker_" + state, bucket="b", previous="open")


class Worker:
    def handle(self, method, payload):
        return getattr(self, "_m_" + method)(payload)

    def _m_ping(self, payload):
        return True

    def _m_stats(self, payload):
        return {}
