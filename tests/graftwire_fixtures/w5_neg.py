"""W5 negative: the same loops, paced through backoff_delays — bounded,
factor-grown, jittered."""

import time

from raft_tpu.utils.retry import backoff_delays

GRAFTWIRE = {
    "idempotent": ("ping",),
}


def reconnect(transport):
    delays = backoff_delays(base_s=0.1, factor=2.0, max_s=5.0)
    while True:
        try:
            transport.reopen()
            return
        except ConnectionError:
            time.sleep(next(delays))      # blessed: visibly backoff-fed


def poll_until_up(transport):
    delays = backoff_delays(base_s=0.1, factor=2.0, max_s=5.0)
    for _ in range(100):
        try:
            transport.call("ping")
            return True
        except OSError:
            delay = next(delays)
            time.sleep(delay)             # blessed via the named delay
    return False
