"""W6 positive: schema drift everywhere — an undeclared event kind, an
undeclared constant prefix, an unregistered wire method (both call and
handler sides), and raw socket verbs outside any framed helper."""


def emit_things(metrics, state):
    metrics.record_event("totally_undeclared_event", x=1)
    metrics.record_event("zzz_" + state, bucket="b")


def call_things(transport):
    return transport.call("not_in_the_registry")


def leak_bytes(sock, payload):
    sock.send(payload)                    # unframed: drift becomes a hang
    return sock.recv(4096)


class Worker:
    def handle(self, method, payload):
        return getattr(self, "_m_" + method)(payload)

    def _m_not_in_the_registry(self, payload):
        return None
