"""W2 negative: the three legal shapes — declared idempotent, a
request_id keyword, and a request_id key in the payload dict."""

GRAFTWIRE = {
    "idempotent": ("ping",),
}


class SafeClient:
    def __init__(self, transport):
        self._t = transport

    def beat(self):
        return self._t.call("ping")

    def infer(self, a, b):
        return self._t.call("infer", {"request_id": "r-1",
                                      "image1": a, "image2": b})

    def stats(self):
        return self._t.call("stats", request_id="r-2")
