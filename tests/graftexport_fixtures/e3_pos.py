"""E3 planted violation: a weight matrix baked into the blob.

``W`` is closure-captured instead of passed as an argument, so the
trace carries it as a 2.25 MiB ``stablehlo.constant`` — over the
1 MiB default budget. The cache key's weights fingerprint cannot see
it: ``update_weights`` would swap the key while the OLD weights ride
along inside the serialized program."""

import jax
import jax.numpy as jnp
import numpy as np

from tools.graftexport import ExportTarget

_W = np.arange(768 * 768, dtype=np.float32).reshape(768, 768) / 1e6


def _build():
    def f(x):
        return x @ jnp.asarray(_W)

    return f, (jax.ShapeDtypeStruct((4, 768), jnp.float32),), ()


TARGETS = [ExportTarget(name="e3_fixture", build=_build, kind="fn")]
