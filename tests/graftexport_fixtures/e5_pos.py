"""E5 planted violation: calling-convention drift.

The manifest's recorded signature is tampered after the write
(``tamper_signature`` rewrites ``in[0]``), modeling a writer whose
key was complete but whose recorded convention is wrong — a loading
engine diffing the artifact against its live recipe must refuse to
trust the blob's calling convention."""

import jax
import jax.numpy as jnp

from tools.graftexport import ExportTarget


def _build():
    def f(state, x):
        return state + x, x - 1.0

    st = jax.ShapeDtypeStruct((16,), jnp.float32)
    xs = jax.ShapeDtypeStruct((16,), jnp.float32)
    return f, (st, xs), ()


TARGETS = [ExportTarget(name="e5_fixture", build=_build, kind="fn",
                        tamper_signature=True)]
