"""E6 planted violation: a loader that skips the integrity checks.

``naive_loader`` probes a manifest-ignoring load path (read blob,
unpickle, deserialize — nothing else) with the manifest-level tampers:
a torn manifest, a jax-version skew, a swapped weights key. The naive
loader survives all three — each survival is a finding, the
counterfactual showing exactly what the verified path's checks
protect against. (Bit-level blob damage is never fed to the naive
loader: unpickling corrupted bytes can kill the process, which is
itself why the verified path hashes before it unpickles.)"""

import jax
import jax.numpy as jnp

from tools.graftexport import ExportTarget


def _build():
    def f(x):
        return x * x + 1.0

    return f, (jax.ShapeDtypeStruct((32,), jnp.float32),), ()


TARGETS = [ExportTarget(name="e6_fixture", build=_build, kind="fn",
                        naive_loader=True)]
