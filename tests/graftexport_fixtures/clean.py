"""Negative control: a well-behaved exportable program.

Complete key, a donation that survives the round trip (same-shaped
in/out alias), no baked literals, no custom calls, honest platform,
untampered signature, verified loader. Zero findings expected."""

import jax
import jax.numpy as jnp

from tools.graftexport import ExportTarget


def _build():
    def f(state, x):
        return state + x, (x * 2.0).sum()

    st = jax.ShapeDtypeStruct((64,), jnp.float32)
    xs = jax.ShapeDtypeStruct((64,), jnp.float32)
    return f, (st, xs), (0,)


TARGETS = [ExportTarget(name="clean_fixture", build=_build, kind="fn")]
