"""E1 planted violation: an incomplete cache key.

The written manifest omits ``weights`` and ``jaxlib`` — the two
components whose absence bites hardest in production: a promoted model
would collide with the old model's entry, and a runtime upgrade would
load last release's blob. The production ``aot.store`` refuses this
key; the fixture writes through the audit's low-level raw writer,
modeling an older or third-party exporter."""

import jax
import jax.numpy as jnp

from tools.graftexport import ExportTarget


def _build():
    def f(x):
        return x * 2.0 + 1.0

    return f, (jax.ShapeDtypeStruct((32,), jnp.float32),), ()


TARGETS = [ExportTarget(name="e1_fixture", build=_build, kind="fn",
                        omit_key_fields=("weights", "jaxlib"))]
